"""Bucketed backward overlap: the Horovod fusion-buffer analogue.

Horovod's fusion buffer batches small gradients into one collective and
dispatches it while the rest of backward still runs.  Here the same idea
appears twice, sized by ``HOROVOD_TPU_BUCKET_BYTES`` (``cfg.bucket_bytes``;
<= 0 means one bucket per dtype group):

- **Eager/engine path** (:func:`bucketed_distributed_gradients`): the
  gradient pytree is grouped into size-targeted buckets; each bucket's
  leaves enqueue on the async engine and the engine is *nudged*
  immediately, so bucket *b*'s (decomposed) reduce-scatter dispatches
  while bucket *b+1* is still being enqueued — comm hides under the
  remaining host work, and the executor's
  ``hvd_sched_overlap_fraction`` gauge shows the realized overlap.
  The entries are ordinary engine entries, so they ride negotiation
  meta (``sc``/``wp``) for join/rebuild exactly like the dense path.

- **In-jit path** (:func:`attach_gradient_reduction`): each bucket
  becomes a ``custom_vjp`` boundary around its parameters — identity on
  the forward; on the backward, the bucket's cotangents are reduced
  through one :func:`~.in_context.overlap_allreduce` chain as soon as
  backward produces them.  Each bucket is an independent rs/ag chain in
  the graph, so XLA's latency-hiding scheduler overlaps chain *b*'s
  collective with chain *b+1*'s backward arithmetic (chain-by-chain,
  instead of one barrier after the whole backward).

The ZeRO-1 optimizer (:mod:`optim.zero`) rides the same bucket grammar
via :mod:`optim.partition` (shared padding/chunk-unit rules), stopping
each bucket's chain at the shard.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp


def _resolved_bucket_bytes(bucket_bytes: Optional[int]) -> int:
    if bucket_bytes is not None:
        return int(bucket_bytes)
    from ...context import global_state
    from ... import config as config_mod
    state = global_state()
    cfg = state.config if state.initialized else config_mod.Config()
    return int(getattr(cfg, "bucket_bytes", 0) or 0)


def plan_buckets(leaves: Sequence[Any],
                 bucket_bytes: Optional[int] = None) -> list:
    """Group leaf *indices* into size-targeted buckets.

    Greedy in pytree order — the order backward produces gradients —
    never mixing dtypes (a fused buffer must share one wire layout).  A
    bucket closes when the next same-dtype leaf would push it past the
    byte target; one oversized leaf still gets its own bucket.  Returns
    ``[[leaf_index, ...], ...]``.
    """
    target = _resolved_bucket_bytes(bucket_bytes)
    open_by_dtype: dict = {}
    order: list = []
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        nbytes = int(arr.size) * arr.dtype.itemsize
        key = str(arr.dtype)
        cur = open_by_dtype.get(key)
        if cur is not None and target > 0 and \
                cur["bytes"] + nbytes > target:
            cur = None
        if cur is None:
            cur = {"idx": [], "bytes": 0}
            open_by_dtype[key] = cur
            order.append(cur)
        cur["idx"].append(i)
        cur["bytes"] += nbytes
    return [b["idx"] for b in order]


def bucketed_distributed_gradients(per_rank_grads: Any,
                                   op=None, *,
                                   compression=None,
                                   process_set=None,
                                   bucket_bytes: Optional[int] = None
                                   ) -> Any:
    """Eager bucket-by-bucket reduction of a per-rank gradient pytree.

    The bucketed twin of :func:`optim.distributed.distributed_gradients`:
    identical results (same engine entries, same fusion/negotiation/
    wire-mode rules), but each bucket's enqueue is followed by an engine
    nudge so its collective dispatches while later buckets are still
    being prepared — per-bucket dispatch as leaves become available,
    instead of one enqueue-everything barrier.
    """
    import horovod_tpu as hvd
    from ..compression import Compression, routes_engine_side
    if op is None:
        op = hvd.Average
    if compression is None:
        compression = Compression.none
    leaves, treedef = jax.tree.flatten(per_rank_grads)
    buckets = plan_buckets(leaves, bucket_bytes)
    kw = {"compression": compression} if routes_engine_side(compression) \
        else {}
    engine = getattr(hvd.global_state(), "engine", None)
    handles = [None] * len(leaves)
    ctxs = [None] * len(leaves)
    for bucket in buckets:
        for i in bucket:
            if kw:
                wire, ctxs[i] = jnp.asarray(leaves[i]), None
            else:
                wire, ctxs[i] = compression.compress(
                    jnp.asarray(leaves[i]))
            handles[i] = hvd.allreduce_async(
                wire, op, process_set=process_set, **kw)
        # Per-bucket dispatch: wake the cycle thread now instead of
        # waiting out cycle_time_ms — bucket b's collective negotiates/
        # dispatches while bucket b+1 enqueues.
        if engine is not None:
            engine.nudge()
    reduced = [h.wait() if kw else compression.decompress(h.wait(), ctx)
               for h, ctx in zip(handles, ctxs)]
    return jax.tree.unflatten(treedef, reduced)


def attach_gradient_reduction(params: Any, axis_name: str = "hvd", *,
                              average: bool = True, mode: str = "fp32",
                              chunks: int = 2, block: int = 512,
                              bucket_bytes: Optional[int] = None) -> Any:
    """In-jit bucket boundaries: identity on ``params``, but gradients
    flowing back through the result are cross-replica reduced per bucket
    via :func:`~.in_context.overlap_allreduce` chains.

    ``jax.grad`` of a loss taken through the returned tree yields
    already-reduced gradients, bucket by bucket, as backward emits each
    bucket's cotangent — each bucket is its own ``custom_vjp`` boundary
    wrapping one rs/ag chain, so XLA can overlap chain *b*'s collective
    with chain *b+1*'s backward compute.  Values (and the forward graph)
    are untouched.
    """
    leaves, treedef = jax.tree.flatten(params)
    buckets = plan_buckets(leaves, bucket_bytes)

    def _reduce_ct(ct):
        from .in_context import overlap_allreduce
        return overlap_allreduce(jnp.asarray(ct), axis_name,
                                 average=average, mode=mode,
                                 chunks=chunks, block=block)

    @jax.custom_vjp
    def _boundary(*bucket_leaves):
        return bucket_leaves

    def _fwd(*bucket_leaves):
        return bucket_leaves, None

    def _bwd(_, cts):
        return tuple(_reduce_ct(ct) for ct in cts)

    _boundary.defvjp(_fwd, _bwd)

    out = list(leaves)
    for bucket in buckets:
        wrapped = _boundary(*(leaves[i] for i in bucket))
        for j, i in enumerate(bucket):
            out[i] = wrapped[j]
    return jax.tree.unflatten(treedef, out)
