"""Compiled GSPMD backend: lower a sched-IR schedule into ONE jitted
NamedSharding program.

The dispatched executor (:mod:`.executor`) walks a lowered schedule unit
by unit — per chunk a reduce-scatter program, a combine program, an
allgather program — and relies on JAX's async dispatch to overlap them.
That buys host-visible overlap windows but pays one host dispatch (and
one XLA executable launch) per unit: on dispatch-bound payloads the walk
itself is the bottleneck (BENCH_r07's 0.06–1.1× decomposed ratios on the
CPU rig).  This module lowers the SAME schedule — same
:func:`~.lower.chunk_layout` boundaries, same per-chunk arithmetic, same
encode/decode algebra — into one ``jax.jit`` program over the
NamedSharding mesh, so the XLA compiler owns collective placement,
fusion and overlap (GC3's compile-don't-interpret thesis; see
PAPERS.md).  One launch, zero per-unit dispatches.

Numerics contract — identical to the dispatched path's, because the
per-chunk chains are the executor's phase-builder bodies inlined:

- fp32: ``prescale -> psum_scatter -> /n (AVERAGE) -> all_gather ->
  postscale`` per chunk, the same per-element float ops in the same
  order as both the monolithic psum and the dispatched walk (bit-exact
  on same-association backends; <=2 ulp normwise across associations);
- int8/fp8: shared-scale block quantization (global pmax), exact
  narrow-accumulator ``psum_scatter``, per-block dequant/average/requant
  with LOCAL scales, wire+scale allgathers, decode — block boundaries
  land on the SAME ``n * block`` units as the monolithic kernel, so the
  result is bit-identical to it (and to the dispatched schedule).

Every process in the mesh MUST execute this same program for a given
collective: under ``jax.distributed`` the collective channel IDs are
assigned per-executable, so the backend choice rides the negotiation
meta (``sc = "compiled:rs_ag:<k>"``) exactly like the wire mode, and the
engine reconciles mixed-mode peers to one common descriptor before
dispatch (see ``engine._run_cycle``).

The cached program is keyed by schedule signature (the same raw lowering
inputs the dispatched path keys on, under a distinct ``"sched_compiled"``
tag) in the shared collectives dispatch cache, so re-dispatching the
same fused group is a table hit — no re-trace, no re-compile.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...obs import REGISTRY as _obs
from ...obs import perfmodel as _perf
from .. import reduction as R
from .lower import chunk_layout, parse_compiled_descriptor

_m_compiled = _obs.counter(
    "hvd_sched_compiled_dispatches_total",
    "single-program compiled-schedule collective dispatches",
    ("schedule",))
_m_compiled_d: dict = {}


def _m_compiled_child(descriptor: str):
    child = _m_compiled_d.get(descriptor)
    if child is None:
        child = _m_compiled_d.setdefault(
            descriptor, _m_compiled.labels(schedule=descriptor))
    return child


def _chunk_fp32(x, axis: str, n: int, average: bool, prescale: float,
                postscale: float):
    """One chunk's fp32 chain — the executor's rs/combine/ag fp32
    builders inlined (same ops, same order, so same bits)."""
    if prescale != 1.0:
        x = x * jnp.asarray(prescale, x.dtype)
    s = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    if average:
        s = s / n
    g = lax.all_gather(s, axis, axis=0, tiled=True)
    if postscale != 1.0:
        g = g * jnp.asarray(postscale, g.dtype)
    return g


def _chunk_quant(x, axis: str, n: int, average: bool, mode: str,
                 block: int, prescale: float, postscale: float):
    """One chunk's quantized chain — rs_quant + combine_quant + ag_quant
    inlined: global-pmax shared scales, exact narrow psum_scatter,
    local-scale requant, wire+scale gathers, decode."""
    alg = R.algebra_for(mode)
    clen = x.shape[0]
    cblocks = clen // block
    sblocks = cblocks // n
    xf = x.astype(jnp.float32)
    if prescale != 1.0:
        xf = xf * prescale
    blocks = xf.reshape(cblocks, block)
    shared = alg.scale_from_absmax(
        lax.pmax(alg.block_absmax(blocks), axis))
    q, _ = alg.wire_encode(blocks, shared_scale=shared)
    acc = lax.psum_scatter(
        q.astype(alg.acc_dtype).reshape(-1), axis,
        scatter_dimension=0, tiled=True)                  # [clen // n]
    me = lax.axis_index(axis)
    my_scale = lax.dynamic_slice_in_dim(shared, me * sblocks, sblocks)
    accf = alg.wire_decode(acc.reshape(sblocks, block), my_scale)
    if average:
        accf = accf / n
    w2, s2 = alg.wire_encode(accf)
    gw = lax.all_gather(w2.reshape(-1), axis, axis=0, tiled=True)
    gs = lax.all_gather(s2, axis, axis=0, tiled=True)
    out = alg.wire_decode(gw.reshape(cblocks, block), gs).reshape(-1)
    if postscale != 1.0:
        out = out * postscale
    return out


def _build_compiled(mesh: Mesh, axis: str, average: bool, mode: str,
                    numels: tuple, shapes: tuple, dtype, prescale: float,
                    postscale: float, block: int, layout: tuple):
    """The whole schedule as ONE jitted program: prepare (flatten /
    concat / zero-pad), every chunk's chain inside a single shard_map
    (XLA sees all k chunks at once and pipelines their collectives
    itself), finish (truncate / split / reshape), replicated outputs."""
    n = mesh.shape[axis]
    total = int(sum(numels))
    plen = int(sum(layout))
    quant = mode in R.QUANT_MODES
    repl = NamedSharding(mesh, P())

    def kernel(v):  # [1, plen] per device — this rank's padded row
        x = v[0]
        outs = []
        off = 0
        for clen in layout:
            xc = lax.dynamic_slice_in_dim(x, off, clen)
            off += clen
            if quant:
                outs.append(_chunk_quant(xc, axis, n, average, mode,
                                         block, prescale, postscale))
            else:
                outs.append(_chunk_fp32(xc, axis, n, average, prescale,
                                        postscale))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs)

    kern = shard_map(kernel, mesh=mesh, in_specs=P(axis), out_specs=P(),
                     check_vma=False)

    def fn(xs):
        rows = xs[0].shape[0]
        flat = (xs[0].reshape(rows, -1) if len(xs) == 1 else
                jnp.concatenate([x.reshape(rows, -1) for x in xs],
                                axis=1))
        if plen != total:
            flat = jnp.concatenate(
                [flat, jnp.zeros((rows, plen - total), flat.dtype)],
                axis=1)
        full = kern(flat)[:total]
        outs = []
        off = 0
        for numel, shape in zip(numels, shapes):
            outs.append(lax.dynamic_slice_in_dim(full, off, numel)
                        .reshape(shape).astype(dtype))
            off += numel
        return outs

    return jax.jit(fn, out_shardings=[repl] * len(numels))


def execute_allreduce(xs: Sequence[Any], op, *, descriptor: str,
                      precision: str = "fp32", prescale: float = 1.0,
                      postscale: float = 1.0, process_set=None,
                      name: str = "allreduce") -> list:
    """Run a (possibly fused) allreduce group through the compiled
    single-program backend named by ``descriptor``
    (``"compiled:rs_ag:<k>"``).

    Same call contract as :func:`.executor.execute_allreduce`; the
    difference is purely backend — one cached jitted program, zero
    per-unit dispatches (``hvd_sched_dispatches_total`` never moves on
    this path; ``hvd_sched_compiled_dispatches_total`` counts instead).
    """
    from .. import collectives as C
    from ... import context as ctx_mod
    chunks = parse_compiled_descriptor(descriptor)
    if chunks is None:
        raise ValueError(
            f"unknown compiled schedule descriptor {descriptor!r}")
    if precision in ("bf16", "fp16"):
        # Same backstop as the dispatched executor: resolve_schedule
        # never admits cast modes into any decomposed family.
        raise ValueError(
            f"compiled schedule does not support cast wire mode "
            f"{precision!r}; resolve_schedule should have fallen back")
    mesh, axis = C._mesh_axis(process_set)
    n = mesh.shape[axis]
    state = ctx_mod.global_state()
    cfg = state.config
    block = cfg.quant_block_size
    mode = precision or "fp32"
    arrs = [C.as_per_rank(x, process_set) for x in xs]
    dtype = arrs[0].dtype
    shapes = tuple(a.shape[1:] for a in arrs)
    numels = tuple(int(np.prod(s, dtype=np.int64)) if s else 1
                   for s in shapes)
    total = int(sum(numels))
    layout = tuple(chunk_layout(total, n, chunks, mode, block))
    key = C._sig(mesh, axis, "sched_compiled", descriptor, op, dtype.name,
                 numels, shapes, mode, block,
                 float(prescale), float(postscale))
    average = op is C.ReduceOp.AVERAGE
    prog = C._cache.get_or_build(
        key, lambda: _build_compiled(mesh, axis, average, mode, numels,
                                     shapes, dtype, float(prescale),
                                     float(postscale), block, layout))
    if mode != "fp32":
        R.account_wire(mode, total * dtype.itemsize, n, block,
                       itemsize=dtype.itemsize)
    _m_compiled_child(descriptor).inc()

    tl = state.timeline
    tl_on = tl is not None and tl.enabled
    lane = f"{name}/compiled"
    if tl_on:
        tl.start_activity(lane, "SCHED_COMPILED")
    t0 = time.monotonic()
    results = prog(list(arrs))
    t1 = time.monotonic()
    if tl_on:
        tl.end_activity(lane)
    # One program, one window: the whole pipeline's host dispatch time.
    # Overlap is invisible from the host here — it happens inside the
    # executable — so the comm window carries everything and the perf
    # model's compiled arm (steps = one ring, not k rings) supplies the
    # matching expectation.
    _perf.MODEL.observe_schedule(
        descriptor=descriptor, mode=mode,
        payload_bytes=total * dtype.itemsize, n=n, chunks=len(layout),
        comm_windows=[(t0, t1)], compute_windows=[],
        block=block, itemsize=dtype.itemsize)
    return list(results)
