"""Engine-side schedule executor: walk a lowered schedule, dispatch steps
asynchronously so later chunks' communication overlaps earlier chunks'
compute.

Execution model
---------------
The schedule's dispatch units — per chunk: a *reduce-scatter* unit (wire
encode folded in), a *combine* unit (the fp32 dequant-accumulate /
average / requant arithmetic), an *allgather* unit (decode folded in) —
each compile to one jitted program (cached in the collectives dispatch
table by schedule signature).  The walk follows
:meth:`~horovod_tpu.ops.sched.ir.Schedule.interleaved_order`: every
chunk's reduce-scatter is dispatched before any chunk's combine, so with
JAX's async dispatch the device is free to run chunk *c+1*'s collective
while chunk *c*'s arithmetic executes.  Nothing blocks until the caller
synchronizes the returned arrays.

Timeline spans (Timeline v2)
----------------------------
Each dispatched unit opens a span on its own lane
(``<tensor>/rs.c0``, ``/combine.c0``, ``/ag.c0``) at dispatch time and
closes it when the step's consumer unit is dispatched — i.e. the span is
the step's **in-flight window**: the host has issued it and no later
dispatch has demanded its result yet.  That window is exactly where the
device may overlap it with other in-flight work, so a communication span
overlapping a compute span in the trace is the *schedule's* overlap
opportunity made visible (on a bandwidth-bound interconnect the device
realizes it; the CPU rig serializes — see docs/performance.md).  Flow
arrows link RS -> COMBINE -> AG per chunk, and
``hvd_sched_overlap_fraction`` integrates the same windows into a gauge:
the fraction of communication in-flight time overlapped by compute
in-flight time.
"""

from __future__ import annotations

import os
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...obs import REGISTRY as _obs
from ...obs import perfmodel as _perf
from .. import reduction as R
from .lower import (chunk_layout, parse_compiled_descriptor,
                    parse_descriptor, parse_hier_descriptor)

_m_overlap = _obs.gauge(
    "hvd_sched_overlap_fraction",
    "fraction of communication-step in-flight time overlapped by "
    "compute-step in-flight time in the last decomposed collective "
    "(host dispatch windows; 0 = fully serialized schedule)")
_m_sched = _obs.counter(
    "hvd_sched_dispatches_total",
    "decomposed-schedule collective dispatches", ("schedule",))
# Pre-resolved per-descriptor children (engine.py keeps its per-verb
# counters allocation-free the same way): one locked float add per
# dispatch, no labels() lookup on the cycle-thread hot path.
_m_sched_d: dict = {}


def _m_sched_child(descriptor: str):
    child = _m_sched_d.get(descriptor)
    if child is None:
        child = _m_sched_d.setdefault(
            descriptor, _m_sched.labels(schedule=descriptor))
    return child


#: HVDTPU_SCHED_FENCE_DISPATCH=1 blocks on every dispatched unit instead
#: of pipelining them.  Escape hatch for the in-process XLA:CPU rig: its
#: cross_module rendezvous runs device executions on a shared pool sized
#: by host cores, and two *independent* in-flight programs (chunk c's
#: cross hop under chunk c+1's scatter — the overlap this executor
#: exists to create) can each hold threads the other's rendezvous needs;
#: on few-core hosts that intermittently deadlocks ("This thread has
#: been waiting..." spew).  Fencing forfeits overlap (gauge reads 0), so
#: only benchmarks/collective_bench --hierarchy sets it by default —
#: real multi-process transports (gloo/TPU) never need it.
_FENCE_DISPATCH = os.environ.get(
    "HVDTPU_SCHED_FENCE_DISPATCH", "") not in ("", "0")


def _fence_unit(v):
    if _FENCE_DISPATCH and v is not None:
        jax.block_until_ready(v)
    return v


# ---------------------------------------------------------------------------
# Phase program builders (one jitted program per dispatch unit, cached by
# the collectives dispatch table under the schedule signature)
# ---------------------------------------------------------------------------

def _build_prepare(mesh: Mesh, axis: str, layout: tuple, total: int,
                   plen: int):
    """Flatten + concat + zero-pad the group payloads, split into chunk
    buffers (the IR's leading ``chunk`` step)."""
    shard = NamedSharding(mesh, P(axis))

    def fn(xs):
        n = xs[0].shape[0]
        flat = (xs[0].reshape(n, -1) if len(xs) == 1 else
                jnp.concatenate([x.reshape(n, -1) for x in xs], axis=1))
        if plen != total:
            flat = jnp.concatenate(
                [flat, jnp.zeros((n, plen - total), flat.dtype)], axis=1)
        outs = []
        off = 0
        for clen in layout:
            outs.append(lax.dynamic_slice_in_dim(flat, off, clen, axis=1))
            off += clen
        return outs

    return jax.jit(fn, out_shardings=[shard] * len(layout))


def _build_finish(mesh: Mesh, numels: tuple, shapes: tuple, dtype,
                  total: int):
    """Concat chunk results, drop padding, split back per group entry
    (the IR's trailing ``concat`` step)."""
    repl = NamedSharding(mesh, P())

    def fn(chunks):
        flat = (chunks[0] if len(chunks) == 1
                else jnp.concatenate(chunks))[:total]
        outs = []
        off = 0
        for numel, shape in zip(numels, shapes):
            outs.append(lax.dynamic_slice_in_dim(flat, off, numel)
                        .reshape(shape).astype(dtype))
            off += numel
        return outs

    return jax.jit(fn, out_shardings=[repl] * len(numels))


def _build_rs_fp32(mesh: Mesh, axis: str, prescale: float):
    def kernel(v):  # [1, clen] per device
        x = v[0]
        if prescale != 1.0:
            x = x * jnp.asarray(prescale, x.dtype)
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def _build_combine_fp32(mesh: Mesh, axis: str, n: int):
    # The AVERAGE divide on the owning shard.  Elementwise, so dividing
    # the shard then gathering is bit-identical to the monolithic
    # psum-then-divide (same per-element float ops in the same order).
    def kernel(s):  # [clen // n] per device
        return s / n

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False))


def _build_ag_fp32(mesh: Mesh, axis: str, postscale: float):
    def kernel(s):  # [clen // n] per device
        g = lax.all_gather(s, axis, axis=0, tiled=True)
        if postscale != 1.0:
            g = g * jnp.asarray(postscale, g.dtype)
        return g

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False))


def _build_rs_quant(mesh: Mesh, axis: str, mode: str, clen: int,
                    block: int, prescale: float):
    """Encode + reduce-scatter unit: shared-scale block quantization
    (pmax of raw absmax, then the zero-block sentinel — the same order
    :func:`reduction._build_quant_allreduce` uses, for the same poisoned-
    sentinel reason) and a ``psum_scatter`` of the narrow accumulator in
    which sums are exact (int8/int16) or fp16-rounded (fp8)."""
    n = mesh.shape[axis]
    alg = R.algebra_for(mode)
    cblocks = clen // block
    sblocks = cblocks // n

    def kernel(v):  # [1, clen] per device
        x = v[0].astype(jnp.float32)
        if prescale != 1.0:
            x = x * prescale
        blocks = x.reshape(cblocks, block)
        shared = alg.scale_from_absmax(
            lax.pmax(alg.block_absmax(blocks), axis))
        q, _ = alg.wire_encode(blocks, shared_scale=shared)
        acc = lax.psum_scatter(
            q.astype(alg.acc_dtype).reshape(-1), axis,
            scatter_dimension=0, tiled=True)              # [clen // n]
        me = lax.axis_index(axis)
        my_scale = lax.dynamic_slice_in_dim(shared, me * sblocks, sblocks)
        return acc, my_scale

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=P(axis),
                             out_specs=(P(axis), P(axis)),
                             check_vma=False))


def _build_combine_quant(mesh: Mesh, axis: str, mode: str, block: int,
                         n: int, average: bool):
    """Compute unit: fp32 dequant-accumulate (+average) on the owning
    shard, then requantize with LOCAL per-block scales.  Per-block and
    order-independent (exact narrow sums), so the result is bit-identical
    to the monolithic quantized kernel regardless of chunking."""
    alg = R.algebra_for(mode)

    def kernel(acc_sh, scale_sh):  # [clen//n], [cblocks//n] per device
        accf = alg.wire_decode(
            acc_sh.reshape(scale_sh.shape[0], block), scale_sh)
        if average:
            accf = accf / n
        w2, s2 = alg.wire_encode(accf)
        return w2.reshape(-1), s2

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(P(axis), P(axis)),
                             out_specs=(P(axis), P(axis)),
                             check_vma=False))


def _build_ag_quant(mesh: Mesh, axis: str, mode: str, block: int,
                    postscale: float):
    """Allgather + decode unit: 1-byte payload + 4B/block scales on the
    wire, fp32 decode on arrival."""
    alg = R.algebra_for(mode)

    def kernel(w_sh, s_sh):
        gw = lax.all_gather(w_sh, axis, axis=0, tiled=True)
        gs = lax.all_gather(s_sh, axis, axis=0, tiled=True)
        out = alg.wire_decode(gw.reshape(gs.shape[0], block), gs).reshape(-1)
        if postscale != 1.0:
            out = out * postscale
        return out

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(P(axis), P(axis)),
                             out_specs=P(), check_vma=False))


def _build_programs(mesh, axis, average, mode, numels, shapes, dtype,
                    prescale, postscale, block, layout):
    """All dispatch-unit programs for one schedule signature."""
    n = mesh.shape[axis]
    total = int(sum(numels))
    plen = int(sum(layout))
    quant = mode in R.QUANT_MODES
    progs: dict = {
        "prepare": _build_prepare(mesh, axis, tuple(layout), total, plen),
        "finish": _build_finish(mesh, tuple(numels), tuple(shapes), dtype,
                                total),
        "rs": {}, "combine": {}, "ag": {},
    }
    for clen in sorted(set(layout)):
        if quant:
            progs["rs"][clen] = _build_rs_quant(mesh, axis, mode, clen,
                                                block, prescale)
            progs["combine"][clen] = _build_combine_quant(
                mesh, axis, mode, block, n, average)
            progs["ag"][clen] = _build_ag_quant(mesh, axis, mode, block,
                                                postscale)
        else:
            progs["rs"][clen] = _build_rs_fp32(mesh, axis, prescale)
            if average:
                progs["combine"][clen] = _build_combine_fp32(mesh, axis, n)
            progs["ag"][clen] = _build_ag_fp32(mesh, axis, postscale)
    return progs


# ---------------------------------------------------------------------------
# Tiered phase builders (hier:<n_local>:<k> — chunked + two-tier).  Three
# dispatch units per chunk on the 2-D (hvd_cross, hvd_local) mesh:
#
#   rs     — fast-tier (ICI) reduce-scatter of the chunk over n_local;
#   cross  — slow-tier (DCN) allreduce of the 1/n_local shard over
#            n_cross, with its own wire mode (the EQuARX placement: the
#            bandwidth-starved hop is where quantization pays), combine
#            (average / dequant-requant) folded in;
#   ag     — fast-tier allgather back to the full chunk.
#
# Quantized base mode stays bit-identical to the flat quantized kernel:
# the shared scale is a pmax over BOTH axes (associative max == the flat
# axis pmax), the narrow accumulator sums exactly under either grouping,
# and the cross-then-local gathers reassemble the identical element
# order.  fp32 changes the n-way sum's association (local ring then
# cross) — the <=2 ulp contract, same as flat rs_ag at np>=4.
# ---------------------------------------------------------------------------

_HIER_AXES = ("hvd_cross", "hvd_local")
_HIER_SPEC = P(_HIER_AXES)
_HIER_MESHES: dict = {}


def _hier_mesh(state, n_cross: int, n_local: int) -> Mesh:
    devs = tuple(state.devices)
    ent = _HIER_MESHES.get((n_cross, n_local))
    if ent is not None and ent[0] == devs:
        return ent[1]
    mesh = Mesh(np.array(devs).reshape(n_cross, n_local), _HIER_AXES)
    _HIER_MESHES[(n_cross, n_local)] = (devs, mesh)
    return mesh


def _build_hier_rs_fp32(mesh: Mesh, prescale: float):
    def kernel(v):  # [1, clen] per device
        x = v[0]
        if prescale != 1.0:
            x = x * jnp.asarray(prescale, x.dtype)
        return lax.psum_scatter(x, "hvd_local", scatter_dimension=0,
                                tiled=True)                # [clen/n_local]

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=_HIER_SPEC,
                             out_specs=_HIER_SPEC, check_vma=False))


def _build_hier_cross_fp32(mesh: Mesh, average: bool, n_total: int):
    def kernel(s):  # [clen/n_local] per device
        r = lax.psum(s, "hvd_cross")
        if average:
            r = r / n_total
        return r

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=_HIER_SPEC,
                             out_specs=_HIER_SPEC, check_vma=False))


def _build_hier_cross_quant(mesh: Mesh, cross_mode: str, clen: int,
                            block: int, average: bool, n_total: int):
    """Slow-tier hop under an fp32 fast tier: quantize the 1/n_local
    shard with cross-group shared scales, exchange the narrow
    accumulator (psum_scatter + requantized allgather), decode back to
    fp32 — the only hop whose bytes cross DCN carries ~1/4 the width."""
    alg = R.algebra_for(cross_mode)
    n_local = mesh.shape["hvd_local"]
    n_cross = mesh.shape["hvd_cross"]
    sb = clen // (n_local * block)      # blocks per local shard
    sbc = sb // n_cross

    def kernel(s):  # [clen/n_local] fp32 per device
        blocks = s.reshape(sb, block)
        shared = alg.scale_from_absmax(
            lax.pmax(alg.block_absmax(blocks), "hvd_cross"))
        q, _ = alg.wire_encode(blocks, shared_scale=shared)
        acc = lax.psum_scatter(
            q.astype(alg.acc_dtype).reshape(-1), "hvd_cross",
            scatter_dimension=0, tiled=True)               # [clen/n]
        me = lax.axis_index("hvd_cross")
        my_scale = lax.dynamic_slice_in_dim(shared, me * sbc, sbc)
        accf = alg.wire_decode(acc.reshape(sbc, block), my_scale)
        if average:
            accf = accf / n_total
        w2, s2 = alg.wire_encode(accf)
        gw = lax.all_gather(w2.reshape(-1), "hvd_cross", axis=0, tiled=True)
        gs = lax.all_gather(s2, "hvd_cross", axis=0, tiled=True)
        return alg.wire_decode(gw.reshape(sb, block), gs).reshape(-1)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=_HIER_SPEC,
                             out_specs=_HIER_SPEC, check_vma=False))


def _build_hier_ag_fp32(mesh: Mesh, postscale: float):
    def kernel(s):  # [clen/n_local] per device, cross-replicated
        g = lax.all_gather(s, "hvd_local", axis=0, tiled=True)
        if postscale != 1.0:
            g = g * jnp.asarray(postscale, g.dtype)
        return g

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=_HIER_SPEC,
                             out_specs=P(), check_vma=False))


def _build_hier_rs_quant(mesh: Mesh, mode: str, clen: int, block: int,
                         prescale: float):
    """Quantized base mode, fast-tier half: shared-scale encode with the
    GLOBAL pmax (both axes — identical to the flat kernel's flat-axis
    pmax, max being associative) and an exact narrow psum_scatter over
    the local tier only."""
    alg = R.algebra_for(mode)
    n_local = mesh.shape["hvd_local"]
    cblocks = clen // block
    sbl = cblocks // n_local

    def kernel(v):  # [1, clen] per device
        x = v[0].astype(jnp.float32)
        if prescale != 1.0:
            x = x * prescale
        blocks = x.reshape(cblocks, block)
        shared = alg.scale_from_absmax(
            lax.pmax(alg.block_absmax(blocks), _HIER_AXES))
        q, _ = alg.wire_encode(blocks, shared_scale=shared)
        acc = lax.psum_scatter(
            q.astype(alg.acc_dtype).reshape(-1), "hvd_local",
            scatter_dimension=0, tiled=True)           # [clen/n_local]
        me = lax.axis_index("hvd_local")
        my_scale = lax.dynamic_slice_in_dim(shared, me * sbl, sbl)
        return acc, my_scale

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=_HIER_SPEC,
                             out_specs=(_HIER_SPEC, _HIER_SPEC),
                             check_vma=False))


def _build_hier_cross_quant_acc(mesh: Mesh, mode: str, block: int,
                                average: bool, n_total: int):
    """Quantized base mode, slow-tier hop: finish the exact narrow sum
    over the cross tier (total == the flat kernel's n-way sum, integer
    addition under either grouping), dequant/average/requant with LOCAL
    per-block scales — bit-identical to the flat combine — then gather
    the re-encoded wire back across the cross tier, still 1 byte/elem."""
    alg = R.algebra_for(mode)
    n_cross = mesh.shape["hvd_cross"]

    def kernel(acc, scale):  # [clen/n_local] acc_dtype, [sbl] fp32
        sbl = scale.shape[0]
        sbc = sbl // n_cross
        acc2 = lax.psum_scatter(acc, "hvd_cross", scatter_dimension=0,
                                tiled=True)                # [clen/n]
        me = lax.axis_index("hvd_cross")
        my_scale = lax.dynamic_slice_in_dim(scale, me * sbc, sbc)
        accf = alg.wire_decode(acc2.reshape(sbc, block), my_scale)
        if average:
            accf = accf / n_total
        w2, s2 = alg.wire_encode(accf)
        gw = lax.all_gather(w2.reshape(-1), "hvd_cross", axis=0, tiled=True)
        gs = lax.all_gather(s2, "hvd_cross", axis=0, tiled=True)
        return gw, gs                    # [clen/n_local] wire, [sbl] scales

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(_HIER_SPEC, _HIER_SPEC),
                             out_specs=(_HIER_SPEC, _HIER_SPEC),
                             check_vma=False))


def _build_hier_ag_quant(mesh: Mesh, mode: str, block: int,
                         postscale: float):
    alg = R.algebra_for(mode)

    def kernel(w, s):  # [clen/n_local] wire, [sbl] scales per device
        gw = lax.all_gather(w, "hvd_local", axis=0, tiled=True)
        gs = lax.all_gather(s, "hvd_local", axis=0, tiled=True)
        out = alg.wire_decode(gw.reshape(gs.shape[0], block),
                              gs).reshape(-1)
        if postscale != 1.0:
            out = out * postscale
        return out

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(_HIER_SPEC, _HIER_SPEC),
                             out_specs=P(), check_vma=False))


def _build_hier_programs(mesh, average, mode, cross_mode, numels, shapes,
                         dtype, prescale, postscale, block, layout,
                         n_total):
    """All dispatch-unit programs for one hier schedule signature."""
    total = int(sum(numels))
    plen = int(sum(layout))
    quant = mode in R.QUANT_MODES
    progs: dict = {
        "prepare": _build_prepare(mesh, _HIER_AXES, tuple(layout), total,
                                  plen),
        "finish": _build_finish(mesh, tuple(numels), tuple(shapes), dtype,
                                total),
        "rs": {}, "cross": {}, "ag": {},
    }
    for clen in sorted(set(layout)):
        if quant:
            progs["rs"][clen] = _build_hier_rs_quant(
                mesh, mode, clen, block, prescale)
            progs["cross"][clen] = _build_hier_cross_quant_acc(
                mesh, mode, block, average, n_total)
            progs["ag"][clen] = _build_hier_ag_quant(
                mesh, mode, block, postscale)
        else:
            progs["rs"][clen] = _build_hier_rs_fp32(mesh, prescale)
            if cross_mode in R.QUANT_MODES:
                progs["cross"][clen] = _build_hier_cross_quant(
                    mesh, cross_mode, clen, block, average, n_total)
            else:
                progs["cross"][clen] = _build_hier_cross_fp32(
                    mesh, average, n_total)
            progs["ag"][clen] = _build_hier_ag_fp32(mesh, postscale)
    return progs


# ---------------------------------------------------------------------------
# The walk
# ---------------------------------------------------------------------------

_UNIT_ACTIVITY = {"rs": "SCHED_RS", "combine": "SCHED_COMBINE",
                  "ag": "SCHED_AG", "cross": "SCHED_CROSS"}


def _overlap_fraction(comm: list, compute: list) -> float:
    """Fraction of total comm in-flight time covered by the union of
    compute in-flight windows (both lists of (t0, t1) host timestamps)."""
    total = sum(t1 - t0 for t0, t1 in comm)
    if total <= 0.0 or not compute:
        return 0.0
    # Merge compute windows first: the engine walk's windows are disjoint
    # today, but summing pairwise intersections would double-count any
    # future walk with concurrently-open compute spans.
    merged: list = []
    for k0, k1 in sorted(compute):
        if merged and k0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], k1)
        else:
            merged.append([k0, k1])
    covered = 0.0
    for c0, c1 in comm:
        for k0, k1 in merged:
            lo, hi = max(c0, k0), min(c1, k1)
            if hi > lo:
                covered += hi - lo
    return min(1.0, covered / total)


def execute_allreduce(xs: Sequence[Any], op, *, descriptor: str,
                      precision: str = "fp32", prescale: float = 1.0,
                      postscale: float = 1.0, process_set=None,
                      name: str = "allreduce") -> list:
    """Run a (possibly fused) allreduce group through the decomposed
    reduce-scatter/allgather schedule named by ``descriptor``.

    ``xs`` are per-rank tensors ([n, *shape] sharded over the collective
    axis); results are replicated, one per input, in input order —
    bit-identical to the monolithic path (fp32: identical per-element
    float ops; quantized: identical block layout + exact narrow sums; see
    the phase builders).
    """
    from .. import collectives as C
    from ... import context as ctx_mod
    chunks = parse_descriptor(descriptor)
    if chunks is None:
        if parse_compiled_descriptor(descriptor) is not None:
            # Single-program GSPMD backend: same schedule, no dispatch
            # walk — _m_sched stays untouched on this path (the CI
            # zero-dispatch guard rests on that).
            from . import compiled as CP
            return CP.execute_allreduce(
                xs, op, descriptor=descriptor, precision=precision,
                prescale=prescale, postscale=postscale,
                process_set=process_set, name=name)
        if parse_hier_descriptor(descriptor) is not None:
            return _execute_hier_allreduce(
                xs, op, descriptor=descriptor, precision=precision,
                prescale=prescale, postscale=postscale,
                process_set=process_set, name=name)
        raise ValueError(f"unknown schedule descriptor {descriptor!r}")
    if precision in ("bf16", "fp16"):
        # resolve_schedule never admits cast modes (they keep the
        # single-psum shape — see its docstring); running them here
        # would silently execute fp32 programs while accounting cast
        # savings.  Fail loudly instead.
        raise ValueError(
            f"decomposed schedule does not support cast wire mode "
            f"{precision!r}; resolve_schedule should have fallen back")
    mesh, axis = C._mesh_axis(process_set)
    n = mesh.shape[axis]
    state = ctx_mod.global_state()
    cfg = state.config
    block = cfg.quant_block_size
    mode = precision or "fp32"
    arrs = [C.as_per_rank(x, process_set) for x in xs]
    dtype = arrs[0].dtype
    shapes = tuple(a.shape[1:] for a in arrs)
    numels = tuple(int(np.prod(s, dtype=np.int64)) if s else 1
                   for s in shapes)
    total = int(sum(numels))
    layout = tuple(chunk_layout(total, n, chunks, mode, block))
    # Cache key: the raw lowering inputs.  Lowering is deterministic in
    # exactly these (plus mesh/axis), so the cheap tuple IS the schedule
    # signature — no per-dispatch Schedule rebuild or string formatting
    # on the cycle-thread hot path (lower_allreduce stays the source of
    # truth for IR consumers and tests/test_sched.py asserts the
    # executor's walk matches its interleaved_order).
    key = C._sig(mesh, axis, "sched", descriptor, op, dtype.name,
                 numels, shapes, mode, block,
                 float(prescale), float(postscale))
    average = op is C.ReduceOp.AVERAGE
    progs = C._cache.get_or_build(
        key, lambda: _build_programs(mesh, axis, average, mode, numels,
                                     shapes, dtype, float(prescale),
                                     float(postscale), block, layout))
    if mode != "fp32":
        R.account_wire(mode, total * dtype.itemsize, n, block,
                       itemsize=dtype.itemsize)
    _m_sched_child(descriptor).inc()

    # -- dispatch walk ------------------------------------------------------
    tl = state.timeline
    tl_on = tl is not None and tl.enabled
    chunk_bufs = progs["prepare"](list(arrs))
    quant = mode in R.QUANT_MODES
    k = len(layout)
    vals: list = [None] * k           # per-chunk in-flight value(s)
    outs: list = [None] * k           # per-chunk gathered result
    opened: dict = {}                 # (unit, c) -> (lane, t_open)
    windows: dict = {"comm": [], "compute": []}
    flows: dict = {}

    def _open(unit: str, c: int) -> None:
        t = time.monotonic()
        lane = f"{name}/{unit}.c{c}"
        opened[(unit, c)] = (lane, t)
        if tl_on:
            tl.start_activity(lane, _UNIT_ACTIVITY[unit])
            if unit == "rs":
                fid = tl.new_flow()
                flows[c] = fid
                tl.flow_start(lane, fid)
            elif c in flows:
                # Land the chunk's arrow on this span, then re-open it so
                # the chain RS -> COMBINE -> AG stays connected.
                tl.flow_end(lane, flows[c])
                if unit != "ag":
                    fid = tl.new_flow()
                    flows[c] = fid
                    tl.flow_start(lane, fid)

    def _close(unit: str, c: int) -> None:
        ent = opened.pop((unit, c), None)
        if ent is None:
            return
        lane, t0 = ent
        windows["comm" if unit in ("rs", "ag") else "compute"].append(
            (t0, time.monotonic()))
        if tl_on:
            tl.end_activity(lane)

    has_combine = quant or average
    order = [(u, c) for c in range(k) for u in ("rs", "combine", "ag")
             if u != "combine" or has_combine]
    # Interleave exactly as Schedule.interleaved_order does for rs_ag:
    # all reduce-scatters first, then combine/allgather per chunk —
    # asserted equivalent in tests/test_sched.py.
    order.sort(key=lambda uc: (0 if uc[0] == "rs" else 1, uc[1],
                               0 if uc[0] == "combine" else 1))
    for unit, c in order:
        clen = layout[c]
        if unit == "rs":
            _open("rs", c)
            vals[c] = _fence_unit(progs["rs"][clen](chunk_bufs[c]))
        elif unit == "combine":
            _close("rs", c)          # its consumer is now dispatched
            _open("combine", c)
            v = vals[c]
            vals[c] = _fence_unit(progs["combine"][clen](*v) if quant
                                  else progs["combine"][clen](v))
        else:  # ag
            _close("combine" if has_combine else "rs", c)
            _open("ag", c)
            v = vals[c]
            outs[c] = _fence_unit(progs["ag"][clen](*v) if quant
                                  else progs["ag"][clen](v))
    results = progs["finish"](outs)
    for c in range(k):
        _close("ag", c)
    _m_overlap.set(_overlap_fraction(windows["comm"], windows["compute"]))
    # Feed the same dispatch windows into the expected-vs-achieved model:
    # the union span is the host-observed in-flight time of the whole
    # pipeline, the per-chunk comm windows give straggler attribution.
    _perf.MODEL.observe_schedule(
        descriptor=descriptor, mode=mode,
        payload_bytes=total * dtype.itemsize, n=n, chunks=k,
        comm_windows=windows["comm"], compute_windows=windows["compute"],
        block=block, itemsize=dtype.itemsize)
    return list(results)


def _union_seconds(windows: list) -> float:
    """Total covered time of a set of (t0, t1) host windows (union, not
    sum — concurrently-open spans count once)."""
    merged: list = []
    for t0, t1 in sorted(windows):
        if merged and t0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t1)
        else:
            merged.append([t0, t1])
    return sum(t1 - t0 for t0, t1 in merged)


def resolve_cross_mode(mode: str, cfg) -> str:
    """Wire mode on the cross-tier hop, from synchronized config.

    A quantized base mode keeps its own algebra end to end (the exact
    narrow accumulator must survive both tiers for the bit-exactness
    contract); an fp32 base mode takes ``hierarchical_cross_precision``
    on the slow hop only.  Deterministic in (mode, config) — every rank
    derives the same answer, so the descriptor need not carry it.
    """
    if mode in R.QUANT_MODES:
        return mode
    cross = getattr(cfg, "hierarchical_cross_precision", "") or ""
    if cross in R.QUANT_MODES:
        return cross
    return "fp32"


def _execute_hier_allreduce(xs: Sequence[Any], op, *, descriptor: str,
                            precision: str = "fp32", prescale: float = 1.0,
                            postscale: float = 1.0, process_set=None,
                            name: str = "allreduce") -> list:
    """Run a fused allreduce group through the chunked+tiered
    ``hier:<n_local>:<k>`` schedule: per chunk, an ICI reduce-scatter
    over the local tier, a DCN allreduce of the 1/n_local shard over the
    cross tier (with its own wire mode), and an ICI allgather back.  All
    local scatters are dispatched before any cross hop, so chunk *c*'s
    slow-tier exchange is in flight while chunk *c+1*'s fast-tier
    scatter runs — the overlap the ``hvd_sched_overlap_fraction`` gauge
    measures here as (cross windows covered by local windows).
    """
    from .. import collectives as C
    from ... import context as ctx_mod
    n_local, chunks = parse_hier_descriptor(descriptor)
    if precision in ("bf16", "fp16"):
        raise ValueError(
            f"tiered schedule does not support cast wire mode "
            f"{precision!r}; resolve_schedule should have fallen back")
    if process_set is not None:
        raise ValueError("tiered schedule requires the global process set "
                         "(subgroup topology unknown)")
    state = ctx_mod.global_state()
    cfg = state.config
    n = state.size
    if n % n_local or not (1 < n_local < n):
        raise ValueError(
            f"descriptor {descriptor!r} does not divide world size {n}")
    n_cross = n // n_local
    mesh = _hier_mesh(state, n_cross, n_local)
    block = cfg.quant_block_size
    mode = precision or "fp32"
    cross_mode = resolve_cross_mode(mode, cfg)
    arrs = [C.as_per_rank(x, process_set) for x in xs]
    dtype = arrs[0].dtype
    shapes = tuple(a.shape[1:] for a in arrs)
    numels = tuple(int(np.prod(s, dtype=np.int64)) if s else 1
                   for s in shapes)
    total = int(sum(numels))
    # Chunk boundaries use the TOTAL rank count and the quantized unit
    # when EITHER tier is quantized: clen % (n * block) == 0 makes the
    # 1/n_local local shard a whole number of n_cross * block units, so
    # the cross hop can scatter on block boundaries — and lands on the
    # same boundaries the flat lowering uses (bit-exactness per chunk).
    mode_eff = mode if mode in R.QUANT_MODES else cross_mode
    layout = tuple(chunk_layout(total, n, chunks, mode_eff, block))
    key = C._sig(mesh, "hier", "sched", descriptor, op, dtype.name,
                 numels, shapes, mode, cross_mode, block,
                 float(prescale), float(postscale))
    average = op is C.ReduceOp.AVERAGE
    progs = C._cache.get_or_build(
        key, lambda: _build_hier_programs(
            mesh, average, mode, cross_mode, numels, shapes, dtype,
            float(prescale), float(postscale), block, layout, n))
    # Per-tier wire accounting: the local tier rings the full payload
    # over n_local, the cross tier rings the 1/n_local shard over
    # n_cross — each at its own wire mode.
    if mode in R.QUANT_MODES:
        R.account_wire(mode, total * dtype.itemsize, n_local, block,
                       itemsize=dtype.itemsize)
    if cross_mode in R.QUANT_MODES:
        R.account_wire(cross_mode, total * dtype.itemsize // n_local,
                       n_cross, block, itemsize=dtype.itemsize)
    _m_sched_child(descriptor).inc()

    # -- dispatch walk ------------------------------------------------------
    tl = state.timeline
    tl_on = tl is not None and tl.enabled
    chunk_bufs = progs["prepare"](list(arrs))
    quant = mode in R.QUANT_MODES
    k = len(layout)
    vals: list = [None] * k
    outs: list = [None] * k
    opened: dict = {}                 # (unit, c) -> (lane, t_open)
    windows: dict = {"local": [], "cross": []}
    flows: dict = {}

    def _open(unit: str, c: int) -> None:
        t = time.monotonic()
        lane = f"{name}/{'local_' if unit != 'cross' else ''}{unit}.c{c}"
        opened[(unit, c)] = (lane, t)
        if tl_on:
            tl.start_activity(lane, _UNIT_ACTIVITY[unit])
            if unit == "rs":
                fid = tl.new_flow()
                flows[c] = fid
                tl.flow_start(lane, fid)
            elif c in flows:
                tl.flow_end(lane, flows[c])
                if unit != "ag":
                    fid = tl.new_flow()
                    flows[c] = fid
                    tl.flow_start(lane, fid)

    def _close(unit: str, c: int) -> None:
        ent = opened.pop((unit, c), None)
        if ent is None:
            return
        lane, t0 = ent
        windows["cross" if unit == "cross" else "local"].append(
            (t0, time.monotonic()))
        if tl_on:
            tl.end_activity(lane)

    order = [(u, c) for c in range(k) for u in ("rs", "cross", "ag")]
    # Same interleave contract as the flat walk vs interleaved_order:
    # every chunk's local scatter first, then (cross, ag) per chunk —
    # chunk c's DCN hop in flight under chunk c+1's ICI scatter.
    order.sort(key=lambda uc: (0 if uc[0] == "rs" else 1, uc[1],
                               0 if uc[0] == "cross" else 1))
    for unit, c in order:
        clen = layout[c]
        if unit == "rs":
            _open("rs", c)
            vals[c] = _fence_unit(progs["rs"][clen](chunk_bufs[c]))
        elif unit == "cross":
            _close("rs", c)
            _open("cross", c)
            v = vals[c]
            vals[c] = _fence_unit(progs["cross"][clen](*v) if quant
                                  else progs["cross"][clen](v))
        else:  # ag
            _close("cross", c)
            _open("ag", c)
            v = vals[c]
            outs[c] = _fence_unit(progs["ag"][clen](*v) if quant
                                  else progs["ag"][clen](v))
    results = progs["finish"](outs)
    for c in range(k):
        _close("ag", c)
    # Overlap here means: how much of the slow tier's in-flight time was
    # hidden under fast-tier work.
    _m_overlap.set(_overlap_fraction(windows["cross"], windows["local"]))
    all_windows = windows["local"] + windows["cross"]
    _perf.MODEL.observe_tiers(
        total * dtype.itemsize, n_local, n_cross,
        _union_seconds(all_windows),
        tier_seconds={"local": _union_seconds(windows["local"]),
                      "cross": _union_seconds(windows["cross"])},
        mode=mode, cross_mode=cross_mode, chunks=k, schedule=descriptor,
        block=block, itemsize=dtype.itemsize)
    return list(results)
