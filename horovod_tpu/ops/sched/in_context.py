"""In-jit schedule entry points: decomposed collectives inside an
already-mapped region (shard_map/pmap body).

The engine-side executor (:mod:`.executor`) owns host-dispatched
collectives; these helpers serve callers that are *already inside* a
compiled program — jitted train steps, the llama decode projections —
where the schedule must be expressed as graph structure and the overlap
is realized by XLA's latency-hiding scheduler (on TPU, async collective
start/done pairs; the CPU rig serializes, same caveat as everywhere).

``matmul_reducescatter`` is the fused computation-collective form (per
"Optimizing Distributed ML Communication with Fused Computation-
Collective Operations", PAPERS.md): a row-parallel projection
``psum(x @ w)`` chunked along the output dim so chunk *c*'s
reduce-scatter can run under chunk *c+1*'s partial matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...jaxcompat import axis_size
from .. import reduction as R
from .ir import Schedule
from .lower import chunk_layout


def overlap_allreduce(x: jax.Array, axis_name: str, *, average: bool = True,
                      mode: str = "fp32", chunks: int = 2,
                      block: int = 512) -> jax.Array:
    """Chunked reduce-scatter/allgather allreduce of one already-mapped
    tensor — the in-graph analogue of the engine executor, composing with
    the wire-precision algebras the same way.

    Each chunk is an independent ``[encode] -> psum_scatter -> combine ->
    all_gather [-> decode]`` chain; XLA is free to overlap chain *c+1*'s
    collective with chain *c*'s arithmetic.  Falls back to the monolithic
    form when the payload is too small to chunk or the mesh axis is
    trivial.  Results are bit-identical to ``lax.psum`` (fp32) /
    :func:`reduction.in_context_allreduce` numerics (quantized modes use
    the identical shared-scale pipeline, per chunk).
    """
    n = axis_size(axis_name)
    if n <= 1:
        return x
    alg = R.algebra_for(mode)
    quant = mode in R.QUANT_MODES
    cast = mode in ("bf16", "fp16")
    out_dtype = x.dtype
    flat = (x.astype(jnp.float32) if quant else x).reshape(-1)
    numel = flat.shape[0]
    layout = chunk_layout(numel, n, max(1, chunks), mode, block)
    plen = sum(layout)
    if plen != numel:
        flat = jnp.concatenate(
            [flat, jnp.zeros((plen - numel,), flat.dtype)])
    outs = []
    off = 0
    for clen in layout:
        ch = lax.dynamic_slice_in_dim(flat, off, clen)
        off += clen
        if quant:
            blocks = ch.reshape(clen // block, block)
            shared = alg.scale_from_absmax(
                lax.pmax(alg.block_absmax(blocks), axis_name))
            q, _ = alg.wire_encode(blocks, shared_scale=shared)
            acc = lax.psum_scatter(
                q.astype(alg.acc_dtype).reshape(-1), axis_name,
                scatter_dimension=0, tiled=True)
            sblocks = (clen // block) // n
            me = lax.axis_index(axis_name)
            my_scale = lax.dynamic_slice_in_dim(
                shared, me * sblocks, sblocks)
            accf = alg.wire_decode(acc.reshape(sblocks, block), my_scale)
            if average:
                accf = accf / n
            w2, s2 = alg.wire_encode(accf)
            gw = lax.all_gather(w2.reshape(-1), axis_name, axis=0,
                                tiled=True)
            gs = lax.all_gather(s2, axis_name, axis=0, tiled=True)
            outs.append(alg.wire_decode(
                gw.reshape(clen // block, block), gs).reshape(-1))
        elif cast:
            sh = lax.psum_scatter(alg.wire_encode(ch)[0], axis_name,
                                  scatter_dimension=0, tiled=True)
            g = alg.wire_decode(
                lax.all_gather(sh, axis_name, axis=0, tiled=True), None)
            outs.append(g / n if average else g)
        else:
            sh = lax.psum_scatter(ch, axis_name, scatter_dimension=0,
                                  tiled=True)
            if average:
                sh = sh / n
            outs.append(lax.all_gather(sh, axis_name, axis=0, tiled=True))
    out = (outs[0] if len(outs) == 1 else jnp.concatenate(outs))[:numel]
    return out.reshape(x.shape).astype(out_dtype)


def overlap_reducescatter(flat: jax.Array, axis_name: str, *,
                          layout, average: bool = True,
                          mode: str = "fp32",
                          block: int = 512) -> jax.Array:
    """The :func:`overlap_allreduce` chain STOPPED at the shard — the
    ZeRO-1 half: per chunk ``[encode] -> psum_scatter -> combine`` with
    **no** gradient allgather; the caller closes the step with one
    *parameter* allgather instead (:mod:`optim.zero`).

    ``flat`` must already be padded to ``sum(layout)`` (fp32 for the
    quant modes, matching ``overlap_allreduce``'s internal cast); each
    ``layout`` entry must divide by the axis size (and by ``n * block``
    for quant modes) — :func:`~.lower.chunk_layout` guarantees both.
    Returns the rank's ``sum(layout)/n`` shard in chunk-major order.

    Numerics are bit-identical to the corresponding elements of
    ``overlap_allreduce``'s output: the quant path re-applies the same
    post-combine requantization roundtrip the dense chain wires through
    its allgather, so a ZeRO step and a dense step see the exact same
    reduced-gradient bits for every element of the shard.
    """
    n = axis_size(axis_name)
    if n <= 1:
        return flat
    alg = R.algebra_for(mode)
    quant = mode in R.QUANT_MODES
    outs = []
    off = 0
    for clen in layout:
        ch = lax.dynamic_slice_in_dim(flat, off, clen)
        off += clen
        if quant:
            blocks = ch.reshape(clen // block, block)
            shared = alg.scale_from_absmax(
                lax.pmax(alg.block_absmax(blocks), axis_name))
            q, _ = alg.wire_encode(blocks, shared_scale=shared)
            acc = lax.psum_scatter(
                q.astype(alg.acc_dtype).reshape(-1), axis_name,
                scatter_dimension=0, tiled=True)
            sblocks = (clen // block) // n
            me = lax.axis_index(axis_name)
            my_scale = lax.dynamic_slice_in_dim(
                shared, me * sblocks, sblocks)
            accf = alg.wire_decode(acc.reshape(sblocks, block), my_scale)
            if average:
                accf = accf / n
            # Dense parity: the dense chain requantizes the combined
            # shard onto the wire for its allgather; replay the same
            # encode/decode roundtrip so shard bits match exactly.
            w2, s2 = alg.wire_encode(accf)
            outs.append(alg.wire_decode(w2, s2).reshape(-1))
        else:
            sh = lax.psum_scatter(ch, axis_name, scatter_dimension=0,
                                  tiled=True)
            if average:
                sh = sh / n
            outs.append(sh)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def matmul_reducescatter(x: jax.Array, w: jax.Array, axis_name: str, *,
                         chunks: int = 2) -> jax.Array:
    """Row-parallel projection ``psum(x @ w, axis)`` as a chunked
    partial-matmul + reduce-scatter fusion, allgathered back.

    ``x``: [..., K_local] (contraction dim sharded over ``axis_name``);
    ``w``: [K_local, D].  The output dim D is split into ``chunks``
    column slices; per slice the partial product reduce-scatters over the
    axis (each rank owns D/(n·chunks) columns of the sum) and an
    allgather rebuilds the replicated slice — elementwise the same sums
    as ``lax.psum``, so results are bit-identical on backends whose
    psum/psum_scatter share the accumulation order (asserted on the CPU
    rig in tests/test_sched.py).  Falls back to the plain ``psum`` when D
    does not split evenly or the axis/chunking is trivial.
    """
    n = axis_size(axis_name)
    d = w.shape[-1]
    if n <= 1 or chunks <= 1 or d % (n * chunks):
        return lax.psum(jnp.matmul(x, w), axis_name)
    csz = d // chunks
    outs = []
    for c in range(chunks):
        wc = lax.slice_in_dim(w, c * csz, (c + 1) * csz, axis=-1)
        pc = jnp.matmul(x, wc)                        # [..., csz]
        sh = lax.psum_scatter(pc, axis_name,
                              scatter_dimension=pc.ndim - 1, tiled=True)
        outs.append(lax.all_gather(sh, axis_name, axis=pc.ndim - 1,
                                   tiled=True))
    return jnp.concatenate(outs, axis=-1)


def run_in_context(schedule: Schedule, x: jax.Array, *,
                   average: bool = False) -> jax.Array:
    """Interpret a (single-chunk) schedule in-graph on a mapped tensor.

    The interpreter for schedules whose steps operate on the whole
    buffer — today the two-tier hierarchical family
    (:func:`~.lower.lower_hierarchical`): reduce-scatter and allgather
    steps pad/scatter over their tier's axis, ``all_reduce`` runs on the
    scattered shard, ``combine`` applies the AVERAGE divide over every
    axis reduced so far.  ``ops/hierarchical.py`` routes through here, so
    the two-level path and the engine's chunked path share one IR.
    """
    shape = x.shape
    flat = x.reshape(-1)
    pad_total = 0
    denom = 1
    for s in schedule.interleaved_order():
        if s.kind == "reduce_scatter":
            n = axis_size(s.axis)
            denom *= n
            pad = (-flat.size) % n
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)])
                pad_total += pad
            flat = lax.psum_scatter(flat, s.axis, scatter_dimension=0,
                                    tiled=True)
        elif s.kind == "all_reduce":
            denom *= axis_size(s.axis)
            flat = lax.psum(flat, s.axis)
        elif s.kind == "combine":
            if average and denom > 1:
                flat = flat / denom
        elif s.kind == "all_gather":
            flat = lax.all_gather(flat, s.axis, axis=0, tiled=True)
        # chunk/concat/barrier/encode/decode: no-ops for this family.
    if pad_total:
        flat = flat[:-pad_total]
    return flat.reshape(shape)
