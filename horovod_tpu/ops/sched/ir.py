"""Collective schedule IR: schedules-as-data for decomposed collectives.

Per GC3 ("GC3: An Optimizing Compiler for GPU Collective Communication")
and "Optimizing Distributed ML Communication with Fused
Computation-Collective Operations" (PAPERS.md), a large allreduce should
not be an opaque verb: it is a *schedule* of primitive steps —
reduce-scatter and allgather halves, chunked so later chunks'
communication overlaps earlier chunks' compute, composed with the wire
precision encode/decode steps of :mod:`horovod_tpu.ops.reduction`.

This module is the data model only: a :class:`Step` is one primitive
operation, a :class:`Schedule` is a validated DAG of steps with a stable
string :meth:`~Schedule.signature`.  Lowering (verb -> schedule) lives in
:mod:`.lower`; execution lives in :mod:`.executor` (engine-side, one
jitted program per phase) and :mod:`.in_context` (inside an existing
mapped region).

Design constraints, in order:

1. **Cross-rank determinism.**  Every rank — including a joined rank
   rebuilding the entry from a negotiation meta — must lower to the
   byte-identical schedule, so signatures are pure functions of
   (verb, shape, dtype, op, wire mode, chunk count, config) and never of
   rank-local state.  The compact descriptor carried in negotiation
   metas (``"rs_ag:4"``) re-derives the full schedule through the same
   lowering.
2. **Precision composes.**  ``Encode``/``Decode`` steps reuse the
   reduction algebras, so the block-scaled int8/fp8 pipeline maps onto
   the same IR as fp32 (quantize -> reduce-scatter -> dequant-accumulate
   -> requant -> 1-byte allgather).
3. **Topology composes.**  The same step vocabulary expresses the
   two-tier hierarchical allreduce (intra-tier reduce-scatter,
   inter-tier allreduce, intra-tier allgather) — see
   :func:`horovod_tpu.ops.sched.lower.lower_hierarchical`.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

#: Step kinds.  COMM steps move bytes over the interconnect; COMPUTE
#: steps are local arithmetic (the overlap target); DATA steps reshape
#: buffers and carry no meaningful wall-clock.
COMM_KINDS = ("reduce_scatter", "all_gather", "all_reduce")
COMPUTE_KINDS = ("encode", "combine", "decode")
DATA_KINDS = ("chunk", "concat", "barrier")
KINDS = COMM_KINDS + COMPUTE_KINDS + DATA_KINDS


@dataclasses.dataclass(frozen=True)
class Step:
    """One primitive operation in a collective schedule.

    ``uid``    — schedule-unique id; dependency edges reference uids.
    ``kind``   — one of :data:`KINDS`.
    ``chunk``  — chunk index this step operates on (-1 = whole buffer).
    ``axis``   — mesh axis a COMM step communicates over ("" for local
    steps; hierarchical schedules use it to place steps on tiers).
    ``mode``   — wire mode for encode/decode steps ("" = fp32/identity).
    ``deps``   — uids of steps that must complete before this one; the
    executor is free to dispatch anything whose deps are satisfied, which
    is exactly where overlap comes from.
    """

    uid: int
    kind: str
    chunk: int = -1
    axis: str = ""
    mode: str = ""
    deps: tuple = ()

    @property
    def is_comm(self) -> bool:
        return self.kind in COMM_KINDS

    @property
    def is_compute(self) -> bool:
        return self.kind in COMPUTE_KINDS

    def sig(self) -> str:
        """Stable per-step signature fragment."""
        parts = [self.kind]
        if self.chunk >= 0:
            parts.append(f"c{self.chunk}")
        if self.axis:
            parts.append(f"@{self.axis}")
        if self.mode and self.mode != "fp32":
            parts.append(self.mode)
        dep = ",".join(str(d) for d in self.deps)
        return f"{self.uid}:" + ".".join(parts) + (f"<-{dep}" if dep else "")


class ScheduleError(ValueError):
    """Malformed schedule (bad deps, unknown kind, cycle)."""


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A validated DAG of :class:`Step`, plus the lowering parameters
    that produced it (enough to rebuild identical compiled programs on
    every rank).

    ``descriptor`` is the compact wire form carried through negotiation
    metas (e.g. ``"rs_ag:4"``); ``signature()`` is the full stable
    string — lowering determinism means descriptor + entry meta implies
    the signature, and the signature doubles as a compile-cache key.
    """

    name: str                       # e.g. "rs_ag", "hier"
    steps: tuple                    # tuple[Step, ...], topologically ordered
    chunks: int = 1                 # effective chunk count
    mode: str = "fp32"              # wire mode the schedule composes with
    descriptor: str = ""            # compact negotiation-meta form

    def __post_init__(self) -> None:
        seen: set = set()
        for s in self.steps:
            if s.kind not in KINDS:
                raise ScheduleError(f"unknown step kind {s.kind!r}")
            if s.uid in seen:
                raise ScheduleError(f"duplicate step uid {s.uid}")
            for d in s.deps:
                if d not in seen:
                    # Steps are declared in topological order, so a dep
                    # on a not-yet-seen uid is either forward (a cycle)
                    # or dangling — both malformed.
                    raise ScheduleError(
                        f"step {s.uid} depends on {d}, which is not an "
                        "earlier step (cycle or dangling edge)")
            seen.add(s.uid)

    def signature(self) -> str:
        """Stable string identity: equal schedules (same lowering inputs)
        produce equal signatures on every rank and across processes."""
        body = ";".join(s.sig() for s in self.steps)
        return f"sched[{self.name}/k{self.chunks}/{self.mode}]{{{body}}}"

    def step(self, uid: int) -> Step:
        for s in self.steps:
            if s.uid == uid:
                return s
        raise KeyError(uid)

    def consumers(self, uid: int) -> list:
        return [s for s in self.steps if uid in s.deps]

    def comm_steps(self) -> list:
        return [s for s in self.steps if s.is_comm]

    def compute_steps(self) -> list:
        return [s for s in self.steps if s.is_compute]

    def interleaved_order(self) -> list:
        """Dispatch order that exposes overlap: a greedy topological walk
        over the ready set with priority ``reduce_scatter`` > pre-comm
        compute (``encode``) > everything downstream of the scatters
        (``combine``/``decode``/``all_gather``/``all_reduce``) > data,
        ties broken by ascending chunk, then uid.

        Ranking the scatters (and the encodes that unlock them) ahead of
        ALL post-scatter steps matters: it issues every chunk's inbound
        communication before any earlier chunk's results are demanded —
        including the no-combine fp32 SUM pipeline, where an earlier
        chunk's ``all_gather`` becomes ready while later scatters are
        still pending and must NOT jump the queue (COMM priority alone
        would serialize the walk into RS(c), AG(c) pairs).  For the
        rs_ag family this yields ``RS(c0), RS(c1), ...,
        [COMBINE(c0),] AG(c0), [COMBINE(c1),] AG(c1), ...`` (encodes/
        decodes interleaved next to their chunk's comm) — the same unit
        order the engine executor dispatches, asserted equivalent in
        tests/test_sched.py — giving the device room to run chunk
        *c+1*'s collective under chunk *c*'s arithmetic.
        """
        def pri(s: Step) -> int:
            if s.kind == "reduce_scatter":
                return 0
            if s.kind == "encode":
                return 1
            if s.is_comm or s.is_compute:
                return 2
            return 3

        done: set = set()
        pending = list(self.steps)
        order: list = []
        while pending:
            ready = [s for s in pending if all(d in done for d in s.deps)]
            if not ready:  # unreachable post-validation; defensive
                raise ScheduleError("schedule has an unsatisfiable step")
            ready.sort(key=lambda s: (pri(s), s.chunk, s.uid))
            nxt = ready[0]
            order.append(nxt)
            done.add(nxt.uid)
            pending.remove(nxt)
        return order


class _Builder:
    """Tiny helper for lowering passes: monotonically numbered steps."""

    def __init__(self) -> None:
        self.steps: list = []
        self._uid = 0

    def add(self, kind: str, *, chunk: int = -1, axis: str = "",
            mode: str = "", deps: Iterable = ()) -> int:
        uid = self._uid
        self._uid += 1
        self.steps.append(Step(uid=uid, kind=kind, chunk=chunk, axis=axis,
                               mode=mode, deps=tuple(deps)))
        return uid

    def build(self, name: str, *, chunks: int, mode: str,
              descriptor: str = "") -> Schedule:
        return Schedule(name=name, steps=tuple(self.steps), chunks=chunks,
                        mode=mode, descriptor=descriptor)
