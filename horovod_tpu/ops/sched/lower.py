"""Lowering passes: collective verb + parameters -> :class:`~.ir.Schedule`.

Everything here is a pure function of values every rank agrees on
(shape, dtype, reduce op, wire mode, chunk count, synchronized config),
so two processes — or a joined rank rebuilding from a negotiation meta —
always produce byte-identical schedules and therefore identical compiled
programs.  That invariant is what lets the engine carry only the compact
descriptor (``"rs_ag:4"``) through negotiation, next to the ``wp`` wire
mode field.
"""

from __future__ import annotations

import math
import re
from typing import Optional

from .ir import Schedule, _Builder

#: Descriptor grammar for negotiation metas: three schedule families
#: ride the ``sc`` field — the chunked reduce-scatter/allgather
#: decomposition (``rs_ag:<k>``), the chunked+tiered two-level allreduce
#: (``hier:<n_local>:<k>``), and the compiled GSPMD lowering of the flat
#: family (``compiled:rs_ag:<k>`` — same schedule, executed as ONE
#: jitted program instead of the executor's dispatch-unit walk).
#: Unknown descriptors from version-skewed peers must be rejected
#: (parse -> None), never guessed at.
_DESC_RE = re.compile(r"^rs_ag:(\d+)$")
_HIER_DESC_RE = re.compile(r"^hier:(\d+):(\d+)$")
_COMPILED_DESC_RE = re.compile(r"^compiled:rs_ag:(\d+)$")

#: Schedule-mode config values (``HOROVOD_TPU_SCHED_MODE``).
SCHED_MODES = ("monolithic", "decomposed", "compiled")


def parse_descriptor(desc: str) -> Optional[int]:
    """``"rs_ag:<k>"`` -> chunk count k, or None when malformed/unknown.

    The joined-rank half of schedule agreement: a meta whose ``sc`` field
    does not parse means a peer runs a lowering this build does not know
    — the entry must be skipped (exactly like an unknown ``wp`` mode),
    not crash the cycle thread.
    """
    m = _DESC_RE.match(desc or "")
    if not m:
        return None
    k = int(m.group(1))
    return k if k >= 1 else None


def descriptor(chunks: int) -> str:
    return f"rs_ag:{int(chunks)}"


def parse_hier_descriptor(desc: str) -> Optional[tuple]:
    """``"hier:<n_local>:<k>"`` -> ``(n_local, chunks)``, or None.

    The tiered sibling of :func:`parse_descriptor`: ``n_local`` is the
    fast-tier (ICI) group size every rank agreed on, ``k`` the chunk
    count.  ``n_local >= 2`` is required — a one-rank "tier" is just the
    flat schedule and must never be encoded as hier (two ranks lowering
    differently for the same meta would desynchronize dispatch).
    """
    m = _HIER_DESC_RE.match(desc or "")
    if not m:
        return None
    n_local, k = int(m.group(1)), int(m.group(2))
    if n_local < 2 or k < 1:
        return None
    return (n_local, k)


def hier_descriptor(n_local: int, chunks: int) -> str:
    return f"hier:{int(n_local)}:{int(chunks)}"


def parse_compiled_descriptor(desc: str) -> Optional[int]:
    """``"compiled:rs_ag:<k>"`` -> chunk count k, or None.

    The compiled sibling of :func:`parse_descriptor`: the schedule lowered
    is byte-identical to the flat ``rs_ag:<k>`` family's, but the backend
    is one jitted NamedSharding program (XLA places and fuses the
    collectives) instead of the executor's per-unit dispatch walk.  The
    backend choice rides the descriptor because every process MUST run
    the same executable — under ``jax.distributed`` the per-collective
    channel IDs are assigned per-program, so a compiled rank and a
    dispatched rank would rendezvous on nothing.
    """
    m = _COMPILED_DESC_RE.match(desc or "")
    if not m:
        return None
    k = int(m.group(1))
    return k if k >= 1 else None


def compiled_descriptor(chunks: int) -> str:
    return f"compiled:rs_ag:{int(chunks)}"


def known_descriptor(desc: str) -> bool:
    """True when ``desc`` belongs to a schedule family this build can
    lower — the negotiation meta's validity check for the ``sc`` field."""
    return (parse_descriptor(desc) is not None or
            parse_hier_descriptor(desc) is not None or
            parse_compiled_descriptor(desc) is not None)


def autotune_sched_arms(chunk_counts=(2, 4)) -> list:
    """The autotuner's schedule-dimension arm set, derived from
    :data:`SCHED_MODES` so the two can never drift apart (adding a mode
    here grows the grid automatically; tests assert the sync).

    ``monolithic`` contributes itself; ``decomposed`` contributes one
    flat ``rs_ag:<k>`` arm per candidate chunk count; ``compiled``
    contributes the compiled twin of each.  Hier arms are seeded
    separately from the split table (topology-, not mode-, derived).
    """
    arms = []
    for mode in SCHED_MODES:
        if mode == "monolithic":
            arms.append("monolithic")
        elif mode == "decomposed":
            arms.extend(descriptor(k) for k in chunk_counts)
        elif mode == "compiled":
            arms.extend(compiled_descriptor(k) for k in chunk_counts)
    return arms


def chunk_layout(numel: int, n: int, chunks: int, mode: str,
                 block: int) -> list:
    """Per-chunk element counts for a decomposed allreduce payload.

    The flat payload is zero-padded to ``plen`` — a multiple of the
    *unit* — and split into at most ``chunks`` contiguous pieces, each a
    whole number of units:

    - fp32/cast modes: unit = ``n`` (psum_scatter shards must divide
      evenly across ranks);
    - quantized modes: unit = ``n * block`` (shard boundaries must also
      land on block-scale boundaries, and — deliberately — on the SAME
      boundaries the monolithic quantized kernel uses, so the decomposed
      result is bit-identical to it: per-block scales, exact narrow-
      accumulator sums and per-block requantization are all independent
      of which chunk a block lands in).

    Returns the chunk lengths (summing to plen >= numel); the effective
    chunk count is ``len(result)`` <= ``chunks`` (a payload with fewer
    units than requested chunks degrades gracefully).
    """
    if numel < 1 or n < 1 or chunks < 1:
        raise ValueError(f"bad chunk layout inputs ({numel}, {n}, {chunks})")
    from ..reduction import QUANT_MODES
    unit = n * block if mode in QUANT_MODES else n
    units_total = max(1, math.ceil(numel / unit))
    k = min(chunks, units_total)
    base, rem = divmod(units_total, k)
    # Deterministic spread: the first ``rem`` chunks get one extra unit.
    return [(base + (1 if c < rem else 0)) * unit for c in range(k)]


def lower_allreduce(numel: int, n: int, *, op_average: bool, mode: str,
                    chunks: int, axis: str, block: int = 512) -> Schedule:
    """Fused-allreduce group -> chunked reduce-scatter/allgather schedule.

    Per chunk *c* the pipeline is::

        [encode(c)] -> reduce_scatter(c) -> combine(c) -> all_gather(c)
                       \\_______ comm ____/   \\ compute /   \\__ comm __/

    where for quantized modes ``encode`` is the shared-scale block
    quantization (folded into the same dispatch as the reduce-scatter —
    XLA fuses them; the IR keeps it explicit so signatures say what the
    wire carries), ``combine`` is the fp32 dequant-accumulate + average +
    local-scale requant, and ``all_gather`` moves the 1-byte payload +
    scales and decodes.  For fp32, ``encode`` is elided and ``combine``
    is the average (elided again for SUM — nothing to compute).

    A leading ``chunk`` DATA step models the flatten/concat/pad split and
    a trailing ``concat`` step models reassembly; ``barrier`` is not
    emitted here (the rs_ag DAG's only joins are per-chunk edges) but the
    executor honors it for hand-built schedules.
    """
    b = _Builder()
    layout = chunk_layout(numel, n, chunks, mode, block)
    k = len(layout)
    quant = mode in ("int8", "fp8")
    split = b.add("chunk")
    tails = []
    for c in range(k):
        prev = split
        if quant:
            prev = b.add("encode", chunk=c, mode=mode, deps=[prev])
        rs = b.add("reduce_scatter", chunk=c, axis=axis, deps=[prev])
        prev = rs
        if quant or op_average:
            # Quantized: dequant-accumulate (+average) + requant.
            # fp32 AVERAGE: the divide.  fp32 SUM: no compute step.
            prev = b.add("combine", chunk=c, mode=mode if quant else "",
                         deps=[prev])
        ag = b.add("all_gather", chunk=c, axis=axis, deps=[prev])
        prev = ag
        if quant:
            prev = b.add("decode", chunk=c, mode=mode, deps=[prev])
        tails.append(prev)
    b.add("concat", deps=tails)
    return b.build("rs_ag", chunks=k, mode=mode,
                   descriptor=descriptor(chunks))


def lower_hierarchical(local_axis: str, cross_axis: str) -> Schedule:
    """Two-tier allreduce as an IR schedule (ROADMAP item 3 seed).

    The reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` shape — NCCL
    reduce-scatter within the node, MPI allreduce across, NCCL allgather
    back — expressed as three steps on two tiers::

        reduce_scatter@local -> all_reduce@cross -> all_gather@local

    ``ops/hierarchical.py`` builds this schedule and interprets it
    in-graph (:func:`horovod_tpu.ops.sched.in_context.run_in_context`),
    so the two-level path and the engine's chunked path share one step
    vocabulary — the prerequisite for a topology-aware lowering that
    chunks *and* tiers.
    """
    b = _Builder()
    rs = b.add("reduce_scatter", chunk=0, axis=local_axis)
    ar = b.add("all_reduce", chunk=0, axis=cross_axis, deps=[rs])
    cb = b.add("combine", chunk=0, deps=[ar])
    b.add("all_gather", chunk=0, axis=local_axis, deps=[cb])
    return b.build("hier", chunks=1, mode="fp32",
                   descriptor=f"hier:{local_axis}/{cross_axis}")


def lower_hierarchical_chunked(
        numel: int, n_local: int, n_cross: int, *, op_average: bool,
        mode: str, cross_mode: str, chunks: int, local_axis: str,
        cross_axis: str, block: int = 512) -> Schedule:
    """Chunked + tiered allreduce: ``rs_ag:k`` chunking composed with the
    two-tier split so chunk *i*'s slow-tier (DCN) allreduce overlaps
    chunk *i+1*'s fast-tier (ICI) reduce-scatter.

    Per chunk *c* the pipeline is::

        [encode(c)] -> reduce_scatter(c)@local -> all_reduce(c)@cross
                    -> combine(c) -> all_gather(c)@local -> [decode(c)]

    The cross-tier ``all_reduce`` moves only the 1/n_local shard and
    carries its own wire mode (``cross_mode`` — e.g. int8 on DCN under
    fp32 ICI, per EQuARX); ``combine`` is the post-cross dequant/average/
    requant.  :meth:`~.ir.Schedule.interleaved_order` ranks all local
    scatters ahead of every post-scatter step, so the dispatch order is
    ``RS(c0), RS(c1), ..., AR(c0), CB(c0), AG(c0), AR(c1), ...`` — chunk
    c's cross hop runs under chunk c+1's local scatter.

    Chunk boundaries reuse :func:`chunk_layout` with ``n = n_local *
    n_cross`` (total ranks): the quantized unit ``n * block`` makes each
    chunk's 1/n_local local shard a whole number of ``n_cross * block``
    units (so the cross hop can itself scatter on block boundaries), and
    — deliberately — lands on the SAME boundaries the flat lowering
    uses, so quantized hier results are bit-identical to flat per chunk.
    """
    if n_local < 2 or n_cross < 2:
        raise ValueError(f"bad tier split ({n_local}, {n_cross})")
    b = _Builder()
    n = n_local * n_cross
    from ..reduction import QUANT_MODES
    mode_eff = mode if mode in QUANT_MODES else (
        cross_mode if cross_mode in QUANT_MODES else mode)
    layout = chunk_layout(numel, n, chunks, mode_eff, block)
    k = len(layout)
    quant = mode in QUANT_MODES
    cross_quant = cross_mode in QUANT_MODES
    split = b.add("chunk")
    tails = []
    for c in range(k):
        prev = split
        if quant:
            prev = b.add("encode", chunk=c, mode=mode, deps=[prev])
        rs = b.add("reduce_scatter", chunk=c, axis=local_axis, deps=[prev])
        ar = b.add("all_reduce", chunk=c, axis=cross_axis,
                   mode=cross_mode if cross_quant else "", deps=[rs])
        prev = ar
        if quant or cross_quant or op_average:
            prev = b.add("combine", chunk=c,
                         mode=mode if quant else
                         (cross_mode if cross_quant else ""),
                         deps=[prev])
        ag = b.add("all_gather", chunk=c, axis=local_axis, deps=[prev])
        prev = ag
        if quant:
            prev = b.add("decode", chunk=c, mode=mode, deps=[prev])
        tails.append(prev)
    b.add("concat", deps=tails)
    return b.build("hier", chunks=k, mode=mode,
                   descriptor=hier_descriptor(n_local, chunks))
