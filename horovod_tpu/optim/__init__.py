"""Distributed optimization: the TPU-native ``DistributedOptimizer``.

† ``horovod/torch/optimizer.py`` / ``horovod/tensorflow/__init__.py``.
"""

from .distributed import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientTransformation,
    distributed_gradients,
)
from .zero import ZeroDistributedOptimizer  # noqa: F401
