"""DistributedOptimizer: synchronous data-parallel gradient averaging.

Reference behavior († ``horovod/torch/optimizer.py`` ``_DistributedOptimizer``,
† ``horovod/tensorflow/__init__.py`` ``DistributedOptimizer`` /
``DistributedGradientTape``, † ``gradient_aggregation.py``):

- per-parameter gradient hooks enqueue async allreduces during backward;
  ``step()`` synchronizes and applies averaged gradients;
- ``backward_passes_per_step=N`` accumulates N micro-batch gradients locally
  before one allreduce (local gradient aggregation);
- optional fp16 compression on the wire; optional Adasum reduction.

TPU-native redesign.  On TPU the training step is one compiled program, so
"hook + background negotiation" would fight the compiler.  Instead the
averaging *is part of the jitted step*, expressed with a collective the
compiler schedules (and fuses/overlaps with backward compute — XLA's latency
hiding replaces Horovod's comm/compute-overlap machinery):

- :func:`DistributedOptimizer` wraps any optax ``GradientTransformation`` so
  its ``update()`` cross-replica-averages gradients first.  Use it inside a
  ``shard_map``/``pmap`` step over the data-parallel axis — the Horovod-style
  explicit-SPMD form.
- For plain-``jit``-with-shardings training (compiler-inserted collectives),
  no wrapper is needed; this module still adds value via
  ``backward_passes_per_step`` accumulation and compression.
- :func:`distributed_gradients` is the eager escape hatch: per-rank gradient
  pytrees reduced through the async engine (fusion, handles) — the direct
  analogue of the reference's hook path, for host-driven loops.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..ops import collectives as C
from ..ops.compression import Compression, Compressor, routes_engine_side


def _in_axis_context(axis_name: str) -> bool:
    """True when tracing inside shard_map/pmap over ``axis_name``."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def _reduce_in_context(g, axis_name: str, op: C.ReduceOp,
                       compression: type[Compressor]):
    """Average/sum/adasum one gradient leaf across the mapped axis.

    Quantized compressors (``Compression.int8`` / ``fp8``) lower to the
    reduction-algebra's in-context form: shared block scales via
    ``pmax``, then one ``psum`` of the narrow accumulator — 2B/elem on
    the wire instead of 4 (see :mod:`ops.reduction`).  Adasum never
    quantizes (dot-product projections amplify the error).  Under
    ``sched_mode="decomposed"`` or ``"compiled"`` (``HVDTPU_SCHED_MODE``
    / ``HOROVOD_TPU_SCHED_MODE``) the fp32 and quant paths route through
    :func:`ops.sched.overlap_allreduce` instead — the allreduce becomes
    chunked reduce-scatter/allgather chains inside the step's one jitted
    program (for ``compiled`` this IS the single-program contract; for
    ``decomposed`` XLA may still overlap them with the surrounding
    arithmetic); bf16/fp16 cast modes stay monolithic, same rule as the
    engine resolver.
    """
    g_arr = jnp.asarray(g)
    quant = routes_engine_side(compression)
    if op in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM) \
            and jnp.issubdtype(g_arr.dtype, jnp.floating) \
            and (quant or not compression.wire_mode):
        from ..context import global_state
        from .. import config as config_mod
        state = global_state()
        # Trace-time constants; dataclass defaults before init().
        cfg = state.config if state.initialized else config_mod.Config()
        big = int(g_arr.size) * g_arr.dtype.itemsize >= cfg.quant_min_bytes
        # Sub-floor leaves ride fp32, same as the engine path's resolver.
        mode = compression.wire_mode if (quant and big) else "fp32"
        if cfg.sched_mode in ("decomposed", "compiled"):
            # Same eligibility rules as the engine's resolve_schedule:
            # only fp32 and the quant wire modes decompose (bf16/fp16
            # cast stays monolithic — see its docstring), so the
            # gradient allreduce inside a jitted train step chunks into
            # reduce-scatter/allgather chains XLA can overlap.  The
            # compiled mode takes the same in-graph chains: inside a
            # jitted train step the whole step ALREADY IS one program —
            # this branch is the compiled path end to end, with zero
            # engine dispatches (the CI compiled-parity job asserts the
            # per-chunk dispatch counter stays at 0), and only the eager
            # engine route differs between the two modes.
            from ..ops.sched import overlap_allreduce
            return overlap_allreduce(
                g_arr, axis_name, average=op is C.ReduceOp.AVERAGE,
                mode=mode, chunks=cfg.sched_chunks,
                block=cfg.quant_block_size)
        if quant and big:
            from ..ops.reduction import in_context_allreduce
            return in_context_allreduce(
                g_arr, axis_name, mode,
                average=op is C.ReduceOp.AVERAGE,
                block=cfg.quant_block_size)
    wire, ctx = compression.compress(g)
    if op is C.ReduceOp.AVERAGE:
        red = lax.pmean(wire, axis_name)
    elif op is C.ReduceOp.SUM:
        red = lax.psum(wire, axis_name)
    elif op is C.ReduceOp.ADASUM:
        red = _adasum_in_context(wire, axis_name)
    else:
        raise ValueError(f"unsupported gradient reduce op {op}")
    return compression.decompress(red, ctx)


def _adasum_in_context(g, axis_name: str):
    """Adasum combination inside a mapped context († ``adasum/adasum.h``):
    gather per-rank copies, combine pairwise (per-tensor dot/norm rule)."""
    from ..ops.adasum import _pair_combine
    stacked = lax.all_gather(g, axis_name, axis=0)  # [n, *shape]
    vecs = [stacked[i].reshape(-1) for i in range(stacked.shape[0])]
    while len(vecs) > 1:
        nxt = [_pair_combine(vecs[i], vecs[i + 1])
               for i in range(0, len(vecs) - 1, 2)]
        if len(vecs) % 2:
            nxt.append(vecs[-1])
        vecs = nxt
    return vecs[0].reshape(g.shape)


class _AggState(NamedTuple):
    """State for local gradient aggregation († ``LocalGradientAggregationHelper``)."""
    inner: Any
    acc: Any
    counter: jnp.ndarray  # int32 scalar


def DistributedGradientTransformation(
    inner: optax.GradientTransformation,
    *,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    axis_name: str = "hvd",
    backward_passes_per_step: int = 1,
    compression: type[Compressor] = Compression.none,
    average_aggregated_gradients: bool = True,
) -> optax.GradientTransformation:
    """Wrap an optax transformation with cross-replica gradient reduction.

    Use inside a ``shard_map``/``pmap``-mapped train step whose data axis is
    ``axis_name``.  With ``backward_passes_per_step > 1``, gradients
    accumulate locally and the (one) collective fires every N-th update;
    off-cycle updates are zero (parameters unchanged), matching the
    reference's aggregation helper semantics.
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    def reduce_grads(grads):
        return jax.tree.map(
            lambda g: _reduce_in_context(g, axis_name, op, compression), grads)

    if backward_passes_per_step == 1:
        def init(params):
            return inner.init(params)

        def update(grads, state, params=None):
            return inner.update(reduce_grads(grads), state, params)

        return optax.GradientTransformation(init, update)

    n = backward_passes_per_step

    def init(params):
        return _AggState(
            inner=inner.init(params),
            acc=jax.tree.map(jnp.zeros_like, params),
            counter=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        # Accumulate in the GRADIENT dtype: ``init`` seeds the
        # accumulator as zeros_like(params), and with bf16 params +
        # fp32 grads a param-dtype accumulator would round every
        # micro-batch's contribution onto the bf16 grid before the sum.
        # The explicit widen keeps the accumulator in the grad dtype
        # from the first pass on (zeros cast losslessly).
        acc = jax.tree.map(lambda a, g: a.astype(g.dtype) + g,
                           state.acc, grads)
        counter = state.counter + 1
        is_step = counter >= n

        def do_step(operand):
            acc_, inner_state = operand
            if average_aggregated_gradients:
                scaled = jax.tree.map(lambda a: a / n, acc_)
            else:
                scaled = acc_
            reduced = reduce_grads(scaled)
            updates, new_inner = inner.update(reduced, inner_state, params)
            return updates, new_inner, jax.tree.map(jnp.zeros_like, acc_), \
                jnp.zeros((), jnp.int32)

        def skip_step(operand):
            acc_, inner_state = operand
            zeros = jax.tree.map(jnp.zeros_like, acc_)
            return zeros, inner_state, acc_, counter

        updates, new_inner, new_acc, new_counter = lax.cond(
            is_step, do_step, skip_step, (acc, state.inner))
        return updates, _AggState(new_inner, new_acc, new_counter)

    return optax.GradientTransformation(init, update)


# Horovod-familiar alias: ``hvd.DistributedOptimizer(opt)``.
DistributedOptimizer = DistributedGradientTransformation


def distributed_gradients(per_rank_grads: Any,
                          op: C.ReduceOp = C.ReduceOp.AVERAGE,
                          *, compression: type[Compressor] = Compression.none,
                          process_set=None) -> Any:
    """Eager reduction of a pytree of per-rank gradients via the async engine.

    The host-loop analogue of the reference's hook path: every leaf (shape
    ``[num_ranks, ...]``) is enqueued async — so the engine fuses them into
    as few compiled collectives as possible — then synchronized, returning
    the reduced pytree.  † ``allreduce_async_`` + ``synchronize()``.
    """
    import horovod_tpu as hvd
    leaves, treedef = jax.tree.flatten(per_rank_grads)
    # Quantized compressors route as wire modes: the engine quantizes
    # inside the fused collective (host-side int8 values with per-rank
    # scales could not be summed by a plain allreduce).
    kw = {"compression": compression} if routes_engine_side(compression) \
        else {}
    compressed, ctxs = [], []
    for leaf in leaves:
        if kw:
            wire, ctx = jnp.asarray(leaf), None
        else:
            wire, ctx = compression.compress(jnp.asarray(leaf))
        compressed.append(wire)
        ctxs.append(ctx)
    handles = [hvd.allreduce_async(leaf, op, process_set=process_set, **kw)
               for leaf in compressed]
    # Engine-side (quantized) compressors dequantize inside the fused
    # collective — the engine output is already fp32, so the host-side
    # decompress must NOT run again (a lossy Compressor whose decompress
    # is not the identity would corrupt the result).
    reduced = [h.wait() if kw else compression.decompress(h.wait(), ctx)
               for h, ctx in zip(handles, ctxs)]
    return jax.tree.unflatten(treedef, reduced)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    """† ``hvd.broadcast_optimizer_state`` — sync optimizer state from root."""
    import horovod_tpu as hvd
    return hvd.broadcast_parameters(opt_state, root_rank=root_rank)
