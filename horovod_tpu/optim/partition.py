"""Flatten/partition plan for the ZeRO-1 sharded optimizer.

The sharded optimizer (:mod:`.zero`) keeps only the 1/n gradient shard
the reduce-scatter produces and runs the inner optax transformation on
that shard.  For the shard to be well defined — and for the quantized
wire modes to stay bit-exact against the dense path — every leaf must be
padded to a *shard-divisible* size whose unit matches the schedule
lowerer's chunk unit (:func:`~..ops.sched.lower.chunk_layout`):

- fp32 / cast leaves pad to a multiple of ``n``;
- quantized leaves pad to a multiple of ``n * block`` so that quant
  *block* boundaries land identically to the dense per-leaf path (each
  leaf starts on a block boundary inside its bucket, so per-block shared
  scales — and therefore every quantized bit — match the dense
  ``overlap_allreduce`` chain).

Leaves are then grouped into size-targeted *buckets* (the Horovod fusion
-buffer analogue, ``HOROVOD_TPU_BUCKET_BYTES``): each bucket is one
contiguous flat buffer = the concatenation of its padded leaves, one
reduce-scatter chain per bucket, and ONE parameter allgather per bucket
closes the step.  Buckets never mix dtypes or wire modes.

Shard layout: a bucket of ``P`` padded elements is chunked by
``chunk_layout`` into ``k`` chunks; ``psum_scatter`` over chunk *c*
hands rank *r* the contiguous slice ``[r*clen/n, (r+1)*clen/n)`` of that
chunk, so the rank's bucket shard is the chunk-major concatenation of
those slices (``P/n`` elements total).  :func:`extract_shard` and
:func:`assemble_from_shards` are the exact inverse pair for that layout.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.sched.lower import chunk_layout


class LeafSpec(NamedTuple):
    """Static geometry of one pytree leaf inside its bucket."""
    index: int          # position in the flattened pytree
    shape: tuple
    dtype: Any
    numel: int
    padded: int         # numel rounded up to the bucket's unit
    offset: int         # offset of this leaf inside the bucket's flat buffer


class BucketSpec(NamedTuple):
    """One fusion bucket: same-dtype, same-wire-mode leaves."""
    leaves: tuple       # tuple[LeafSpec, ...] in pytree order
    numel: int          # sum of padded leaf sizes (multiple of the unit)
    shard: int          # numel // n
    mode: str           # "fp32" or a quant wire mode ("int8"/"fp8")
    dtype: Any          # the common leaf dtype


class Plan(NamedTuple):
    """The full partition plan — static, derived from shapes/dtypes and
    config only, so every rank computes the identical plan."""
    n: int
    block: int
    chunks: int
    treedef: Any
    buckets: tuple      # tuple[BucketSpec, ...]
    numel: int          # total unpadded elements
    padded: int         # total padded elements
    shard_numel: int    # padded // n


def _pad_unit(mode: str, n: int, block: int) -> int:
    return n * block if mode not in ("fp32", "bf16", "fp16") else n


def build_plan(params: Any, n: int, *, modes: Sequence[str],
               block: int = 512, chunks: int = 2,
               bucket_bytes: int = 0) -> Plan:
    """Build the partition plan for ``params`` over ``n`` shards.

    ``modes[i]`` is the resolved wire mode of leaf *i* ("fp32" for
    unquantized, the wire mode for engine-side quant leaves above the
    size floor).  ``bucket_bytes <= 0`` means unbounded buckets — one
    bucket per (dtype, mode) group, i.e. literally one parameter
    allgather per group.
    """
    leaves, treedef = jax.tree.flatten(params)
    if len(modes) != len(leaves):
        raise ValueError(f"modes has {len(modes)} entries for "
                         f"{len(leaves)} leaves")
    buckets: list[BucketSpec] = []
    # Greedy size-targeted grouping in pytree order; a bucket closes when
    # adding the next leaf of its (dtype, mode) group would exceed the
    # byte target (a single oversized leaf still gets its own bucket).
    open_by_key: dict = {}
    order: list = []
    for i, (leaf, mode) in enumerate(zip(leaves, modes)):
        arr = jnp.asarray(leaf)
        dtype = jnp.dtype(arr.dtype)
        unit = _pad_unit(mode, n, block)
        numel = int(np.prod(arr.shape)) if arr.shape else 1
        padded = max(1, -(-numel // unit)) * unit
        key = (str(dtype), mode)
        cur = open_by_key.get(key)
        cur_bytes = (sum(s.padded for s in cur) * dtype.itemsize
                     if cur else 0)
        if cur is None or (bucket_bytes > 0 and cur and
                           cur_bytes + padded * dtype.itemsize
                           > bucket_bytes):
            cur = []
            open_by_key[key] = cur
            order.append((key, cur, mode, dtype))
        off = sum(s.padded for s in cur)
        cur.append(LeafSpec(index=i, shape=tuple(arr.shape), dtype=dtype,
                            numel=numel, padded=padded, offset=off))
    for (_key, specs, mode, dtype) in order:
        total = sum(s.padded for s in specs)
        buckets.append(BucketSpec(leaves=tuple(specs), numel=total,
                                  shard=total // n, mode=mode,
                                  dtype=dtype))
    numel = sum(s.numel for b in buckets for s in b.leaves)
    padded = sum(b.numel for b in buckets)
    return Plan(n=n, block=block, chunks=chunks, treedef=treedef,
                buckets=tuple(buckets), numel=numel, padded=padded,
                shard_numel=padded // n)


def bucket_layout(plan: Plan, bucket: BucketSpec) -> tuple:
    """Chunk layout of one bucket's flat buffer — the exact layout the
    reduce-scatter chain and the shard extract/assemble pair share.
    ``bucket.numel`` is already unit-aligned, so this never re-pads."""
    return tuple(chunk_layout(bucket.numel, plan.n, max(1, plan.chunks),
                              bucket.mode, plan.block))


def flatten_bucket(bucket: BucketSpec, leaves: Sequence[Any]) -> jax.Array:
    """Concatenate a bucket's leaves (from the *full* flattened pytree
    leaf list) into its padded flat buffer."""
    parts = []
    for spec in bucket.leaves:
        flat = jnp.asarray(leaves[spec.index]).reshape(-1)
        if spec.padded != spec.numel:
            flat = jnp.concatenate(
                [flat, jnp.zeros((spec.padded - spec.numel,), flat.dtype)])
        parts.append(flat)
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unflatten_bucket(bucket: BucketSpec, flat: jax.Array) -> list:
    """Inverse of :func:`flatten_bucket`: ``[(leaf_index, array), ...]``
    with each leaf reshaped (padding dropped)."""
    out = []
    for spec in bucket.leaves:
        leaf = lax.dynamic_slice_in_dim(flat, spec.offset, spec.padded)
        out.append((spec.index,
                    leaf[:spec.numel].reshape(spec.shape)))
    return out


def extract_shard(flat: jax.Array, me, layout: Sequence[int],
                  n: int) -> jax.Array:
    """Rank ``me``'s shard of a bucket's flat buffer, chunk-major — the
    same element order ``psum_scatter`` hands that rank per chunk."""
    parts = []
    off = 0
    for clen in layout:
        piece = clen // n
        parts.append(lax.dynamic_slice_in_dim(flat, off + me * piece,
                                              piece))
        off += clen
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def assemble_from_shards(gathered: jax.Array, layout: Sequence[int],
                         n: int) -> jax.Array:
    """Rebuild the full bucket buffer from the tiled allgather of every
    rank's shard (``gathered``: flat ``[n * shard]``, rank-major)."""
    shard = gathered.shape[0] // n
    rows = gathered.reshape(n, shard)
    chunks = []
    soff = 0
    for clen in layout:
        piece = clen // n
        # rows[:, soff:soff+piece] is chunk c's per-rank pieces; rank-
        # major flatten IS the chunk's original element order.
        chunks.append(lax.dynamic_slice_in_dim(
            rows, soff, piece, axis=1).reshape(-1))
        soff += piece
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)


def shard_bytes(tree: Any) -> int:
    """Total bytes of a pytree of (possibly traced) arrays — static
    shape/dtype arithmetic only, safe under jit."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        arr = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
        total += int(np.prod(arr.shape)) * jnp.dtype(arr.dtype).itemsize
    return total
