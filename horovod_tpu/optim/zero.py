"""ZeRO-1 sharded optimizer on the reduce-scatter/allgather decomposition.

The schedule IR (PR 7/16) already lowers every gradient allreduce into
chunked reduce-scatter/allgather chains — but the dense
:func:`~.distributed.DistributedOptimizer` immediately allgathers the
gradient back and keeps FULL Adam state on every rank, throwing away the
1/n shard the reduce-scatter just produced.
:func:`ZeroDistributedOptimizer` keeps it:

1. gradients lower through the same rs chain but STOP at the shard
   (:func:`~..ops.sched.in_context.overlap_reducescatter` — no gradient
   allgather);
2. the inner optax transformation's ``init``/``update`` run on the 1/n
   parameter shard, so m/v (any inner state) is sharded n ways;
3. ONE parameter-delta allgather per bucket closes the step.

Total wire bytes are identical to the dense path (rs + param-ag == rs +
grad-ag) while optimizer-state memory drops to ``1/n`` of dense plus the
shard-divisible padding (:mod:`.partition`); the ``hvd_zero_state_bytes``
gauge publishes the per-rank state footprint.

Parity contract (asserted in tests/test_optimizer.py and the
``zero1-parity`` CI job): updated parameters are bit-exact vs the dense
``DistributedOptimizer`` at np=2 for fp32 and the int8 wire, and within
2 ulp at np>=4, across all three ``HOROVOD_TPU_SCHED_MODE``s.  The quant
modes stay exact because bucket flattening pads every leaf to the same
``n * block`` unit the dense chunk layout uses, so quant *block*
boundaries — and therefore every shared scale — land identically, and
the shard chain replays the dense path's post-combine requantization
roundtrip.  In ``compiled`` mode the whole ZeRO step stays one jitted
program (``hvd_sched_dispatches_total == 0``, same guard as the dense
compiled path).

Restrictions: elementwise inner transformations (Adam/SGD/AdamW-style —
each element's update depends only on that element's grad/param/state);
``op`` must be AVERAGE or SUM (Adasum's dot-product projections need the
full gradient); ``update`` must run inside the mapped context
(shard_map/pmap over ``axis_name``), same as the dense wrapper.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..jaxcompat import axis_size
from ..obs import REGISTRY as _obs
from ..ops import collectives as C
from ..ops.compression import Compression, Compressor, routes_engine_side
from .distributed import _in_axis_context, _reduce_in_context
from . import partition as P

_g_state_bytes = _obs.gauge(
    "hvd_zero_state_bytes",
    "per-rank optimizer-state bytes under the ZeRO-1 sharded optimizer "
    "(sharded inner state; ~1/n of the dense footprint plus padding)")


def _resolved_config():
    from ..context import global_state
    from .. import config as config_mod
    state = global_state()
    return state.config if state.initialized else config_mod.Config()


def _resolve_n(axis_name: str, num_shards: Optional[int]) -> int:
    if num_shards is not None:
        return int(num_shards)
    if _in_axis_context(axis_name):
        return axis_size(axis_name)
    from ..context import global_state
    state = global_state()
    if state.initialized:
        return state.size
    raise ValueError(
        "ZeroDistributedOptimizer.init called outside the mapped context "
        "before hvd.init(); pass num_shards= explicitly")


def _leaf_modes(leaves, compression, cfg) -> list:
    """Resolved wire mode per leaf — the same eligibility rule the dense
    ``_reduce_in_context`` applies (sub-floor leaves ride fp32)."""
    quant = routes_engine_side(compression)
    modes = []
    for leaf in leaves:
        arr = jnp.asarray(leaf)
        big = int(arr.size) * arr.dtype.itemsize >= cfg.quant_min_bytes
        eligible = quant and big and jnp.issubdtype(arr.dtype,
                                                    jnp.floating)
        modes.append(compression.wire_mode if eligible else "fp32")
    return modes


def ZeroDistributedOptimizer(
    inner: optax.GradientTransformation,
    partition: int = 1,
    *,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    axis_name: str = "hvd",
    compression: type[Compressor] = Compression.none,
    bucket_bytes: Optional[int] = None,
    num_shards: Optional[int] = None,
) -> optax.GradientTransformation:
    """Wrap ``inner`` as a ZeRO-1 sharded optimizer (see module docs).

    ``partition=1`` is the supported stage (optimizer-state sharding);
    stages 2/3 (gradient/parameter sharding) are out of scope here.
    ``bucket_bytes`` overrides ``HOROVOD_TPU_BUCKET_BYTES`` (<=0 means
    one bucket per dtype/wire-mode group).  ``num_shards`` pins the
    shard count when ``init`` runs outside the mapped context on a mesh
    smaller than the world (e.g. an np-subset bench mesh).
    """
    if partition != 1:
        raise NotImplementedError(
            f"ZeRO stage {partition} is not supported; only stage 1 "
            "(optimizer-state sharding) is implemented")
    if op not in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM):
        raise ValueError(
            f"ZeroDistributedOptimizer supports AVERAGE/SUM, got {op}")

    # The plan is static (shapes + config), so it is latched once and
    # every rank recomputes the identical object; ``update`` rebuilds it
    # from the gradients when ``init`` never ran (restored state).
    holder: dict = {}

    def _build(tree, n, cfg):
        leaves = jax.tree.flatten(tree)[0]
        bb = cfg.bucket_bytes if bucket_bytes is None else bucket_bytes
        plan = P.build_plan(
            tree, n, modes=_leaf_modes(leaves, compression, cfg),
            block=cfg.quant_block_size,
            chunks=max(1, cfg.sched_chunks), bucket_bytes=int(bb or 0))
        holder["plan"] = plan
        return plan

    def _shard_params(plan, leaves, me):
        shards = []
        for bucket in plan.buckets:
            layout = P.bucket_layout(plan, bucket)
            flat = P.flatten_bucket(bucket, leaves)
            shards.append(P.extract_shard(flat, me, layout, plan.n))
        return tuple(shards)

    def init(params):
        cfg = _resolved_config()
        n = _resolve_n(axis_name, num_shards)
        plan = _build(params, n, cfg)
        leaves = jax.tree.flatten(params)[0]
        if _in_axis_context(axis_name):
            shard = _shard_params(plan, leaves, lax.axis_index(axis_name))
        else:
            # Outside the mapped context the rank is unknown; standard
            # scale_by_* inits are value-independent (zeros_like), so a
            # zero-valued shard template of the right shape/dtype is
            # exact for them.  Value-dependent inits need in-context
            # init (call ``tx.init`` inside the shard_map body).
            shard = tuple(
                jnp.zeros((b.shard,), b.dtype) for b in plan.buckets)
        state = inner.init(shard)
        try:
            _g_state_bytes.set(float(P.shard_bytes(state)))
        except Exception:  # telemetry must never break a step
            pass
        return state

    def update(grads, state, params=None):
        if not _in_axis_context(axis_name):
            raise ValueError(
                "ZeroDistributedOptimizer.update must run inside the "
                f"mapped context (shard_map/pmap over {axis_name!r})")
        cfg = _resolved_config()
        n = axis_size(axis_name)
        plan = holder.get("plan")
        if plan is None or plan.n != n:
            plan = _build(grads, n, cfg)
        me = lax.axis_index(axis_name)
        gleaves, gdef = jax.tree.flatten(grads)
        pleaves = jax.tree.flatten(params)[0] if params is not None \
            else None
        average = op is C.ReduceOp.AVERAGE
        decompose = cfg.sched_mode in ("decomposed", "compiled") and \
            (routes_engine_side(compression) or not compression.wire_mode)
        shard_grads, shard_params, layouts = [], [], []
        for bucket in plan.buckets:
            layout = P.bucket_layout(plan, bucket)
            layouts.append(layout)
            quant = bucket.mode != "fp32"
            flat = P.flatten_bucket(bucket, gleaves)
            gdtype = flat.dtype
            if decompose and jnp.issubdtype(gdtype, jnp.floating):
                # The rs chain stopped at the shard: the ZeRO half of
                # the dense overlap_allreduce, chunk boundaries and
                # quant blocks identical by construction.
                from ..ops.sched import overlap_reducescatter
                if quant:
                    flat = flat.astype(jnp.float32)
                shard = overlap_reducescatter(
                    flat, axis_name, layout=layout, average=average,
                    mode=bucket.mode, block=plan.block)
                shard = shard.astype(gdtype)
            else:
                # Monolithic / cast-wire fallback: the exact dense
                # reduce per leaf, then slice this rank's shard — parity
                # is trivially bit-exact, memory still shards.
                reduced = list(gleaves)
                for spec in bucket.leaves:
                    reduced[spec.index] = _reduce_in_context(
                        gleaves[spec.index], axis_name, op, compression)
                rflat = P.flatten_bucket(bucket, reduced)
                shard = P.extract_shard(rflat, me, layout, plan.n)
            shard_grads.append(shard)
            if pleaves is not None:
                pflat = P.flatten_bucket(bucket, pleaves)
                shard_params.append(
                    P.extract_shard(pflat, me, layout, plan.n))
        sp = tuple(shard_params) if pleaves is not None else None
        shard_updates, new_state = inner.update(
            tuple(shard_grads), state, sp)
        out = [None] * len(gleaves)
        for bucket, layout, ush in zip(plan.buckets, layouts,
                                       shard_updates):
            # The ONE parameter allgather that closes the ZeRO step
            # (per bucket; buckets never mix dtypes or wire modes).
            gathered = lax.all_gather(ush, axis_name, axis=0, tiled=True)
            full = P.assemble_from_shards(gathered, layout, plan.n)
            for idx, arr in P.unflatten_bucket(bucket, full):
                out[idx] = arr
        return jax.tree.unflatten(gdef, out), new_state

    return optax.GradientTransformation(init, update)


def from_config(
    inner: optax.GradientTransformation,
    **kwargs: Any,
) -> optax.GradientTransformation:
    """``HOROVOD_TPU_ZERO`` dispatcher: the ZeRO-1 wrapper when
    ``cfg.zero`` is set, the dense :func:`DistributedOptimizer`
    otherwise — so train-step builders and benches flip between the two
    with one env knob."""
    if _resolved_config().zero:
        return ZeroDistributedOptimizer(inner, **kwargs)
    from .distributed import DistributedGradientTransformation
    kwargs.pop("bucket_bytes", None)
    kwargs.pop("num_shards", None)
    return DistributedGradientTransformation(inner, **kwargs)
