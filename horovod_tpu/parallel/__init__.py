"""Parallelism strategies beyond the reference.

The reference is data-parallel only (SURVEY §2.6: TP/PP/SP/EP all ABSENT —
Horovod scales batch, never model or sequence).  On TPU, the same collective
layer that carries DP gradients (ICI psum) also carries tensor-parallel
activations, ring-attention KV rotation, pipeline hand-offs and MoE dispatch,
so this package makes every strategy first-class:

- :mod:`mesh` — multi-axis device meshes (dp/fsdp/tp/sp/pp/ep) with
  ICI-friendly axis ordering; hierarchical = ICI within slice, DCN across.
- :mod:`sharding` — logical-axis → PartitionSpec rules (GSPMD annotations).
- :mod:`tensor_parallel` — Megatron-style column/row-parallel layers.
- :mod:`ring_attention` — sequence parallelism via blockwise KV rotation
  (``ppermute`` ring) with online-softmax accumulation.
- :mod:`pipeline` — GPipe-style microbatch pipelining over the pp axis.
- :mod:`moe` — expert parallelism: top-k gating + ``all_to_all`` dispatch,
  the DLRM/MoE use of the alltoall verb (BASELINE config 5).
"""

from .mesh import MeshConfig, build_mesh  # noqa: F401
from .sharding import logical_sharding, constrain  # noqa: F401
