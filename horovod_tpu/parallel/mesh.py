"""Multi-axis device mesh construction.

TPU-first design notes: ICI bandwidth dominates DCN by an order of magnitude,
so axes that carry the chattiest collectives must map to ICI neighbors.
Convention (innermost/fastest-varying axis last in the device ordering):

    ('pp', 'dp', 'fsdp', 'ep', 'sp', 'tp')

- ``tp`` innermost: per-layer activation psums every matmul — needs the
  tightest ICI loops.
- ``sp``/``ep`` next: ring permutes / alltoall per attention/MoE layer.
- ``dp``/``fsdp``: one gradient reduce-scatter+all-gather per step.
- ``pp`` outermost: point-to-point hand-offs once per microbatch — the only
  axis that tolerates DCN, which is why multi-slice deployments put the
  slice boundary on pp (or dp) — the hierarchical split the reference
  implements as NCCL-within-node + MPI-across († ``nccl_operations.cc``
  HOROVOD_HIERARCHICAL_ALLREDUCE).

``jax.sharding.Mesh`` over ``mesh_utils.create_device_mesh`` handles the
physical ICI topology mapping; on CPU test rigs the reshape order stands in
for it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis; product must equal device count."""

    dp: int = 1      # data parallel (batch)
    fsdp: int = 1    # sharded-parameter data parallel (ZeRO-3 style)
    tp: int = 1      # tensor (Megatron) parallel
    sp: int = 1      # sequence/context parallel (ring attention / Ulysses)
    pp: int = 1      # pipeline parallel
    ep: int = 1      # expert parallel (MoE)

    @property
    def total(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.pp * self.ep

    def axis_sizes(self) -> dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    @staticmethod
    def auto(n_devices: int) -> "MeshConfig":
        """Factorize ``n_devices`` across axes for a maximal exercise of
        every parallelism style (used by the multi-chip dry run):
        repeatedly assign the smallest prime factor to the axis that most
        needs >1 size, in priority order tp, dp, pp, sp, ep, fsdp.
        """
        factors = _prime_factors(n_devices)
        sizes = {"tp": 1, "dp": 1, "pp": 1, "sp": 1, "ep": 1, "fsdp": 1}
        order = ["tp", "dp", "pp", "sp", "ep", "fsdp"]
        i = 0
        for f in sorted(factors):
            # fill axes round-robin in priority order
            sizes[order[i % len(order)]] *= f
            i += 1
        return MeshConfig(**sizes)


def _prime_factors(n: int) -> list[int]:
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def build_mesh(config: MeshConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the multi-axis mesh in ICI-friendly axis order."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if config.total != len(devs):
        raise ValueError(
            f"mesh sizes {config.axis_sizes()} multiply to {config.total} "
            f"but {len(devs)} devices are available")
    shape = tuple(config.axis_sizes()[a] for a in AXES)
    if devices is None and len(devs) > 1:
        try:
            arr = mesh_utils.create_device_mesh(shape)
        except (ValueError, AssertionError):
            arr = np.array(devs).reshape(shape)
    else:
        arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names=AXES)


def data_axes() -> tuple[str, ...]:
    """Axes a global batch is sharded over (gradient-reduction axes)."""
    return ("dp", "fsdp")
