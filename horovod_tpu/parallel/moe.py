"""Expert parallelism: Switch-style MoE with all_to_all dispatch.

ABSENT as a strategy in the reference, but its ``hvd.alltoall`` verb
(† ``message.h RequestType::ALLTOALL``, ``MPI_Alltoallv``) exists precisely
for this exchange pattern (DLRM embedding swaps, MoE token dispatch) —
BASELINE config 5 makes it a required capability.

Design (Switch Transformer, arXiv:2101.03961, re-expressed for TPU):
top-1 routing with static capacity so every shape is fixed at trace time
(XLA requirement — no dynamic gathers), dispatch/combine as einsums with
one-hot masks (MXU-friendly), and the token exchange as a single
``all_to_all`` over the ``ep`` axis in each direction.  Overflowed tokens
are dropped (standard capacity semantics) and recovered by the residual
connection in the caller.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..jaxcompat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..obs import REGISTRY as _obs

_m_dropped = _obs.counter(
    "hvd_moe_dropped_tokens_total",
    "tokens dropped past expert capacity (the capacity-factor tuning "
    "signal: a persistently nonzero rate means the factor is too low "
    "for the observed routing skew)", ("layer",))


def record_dropped_tokens(count, layer: str = "0") -> None:
    """Count capacity overflow drops into the per-layer counter.

    Host-side (counters are process state, not traced values): callers
    inside jit return the drop count as an output and record it here
    after the step.
    """
    c = float(count)
    if c > 0:
        _m_dropped.labels(layer=str(layer)).inc(c)


def switch_route(router_logits: jax.Array, capacity: int
                 ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-1 routing masks.

    router_logits: [T, E].  Returns (dispatch [T, E, C] float, combine
    [T, E, C] float, aux_loss scalar, dropped [T] bool).

    ``dropped`` marks tokens past their expert's capacity explicitly —
    they contribute nothing to dispatch/combine (the residual recovers
    them), but silent drops made capacity-factor tuning blind; callers
    feed ``dropped.sum()`` to :func:`record_dropped_tokens`.
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # [T]
    expert_onehot = jax.nn.one_hot(expert_idx, E)            # [T, E]
    # Load-balancing auxiliary loss († Switch eq. 4).
    density = expert_onehot.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux_loss = E * jnp.sum(density * density_proxy)
    # Position of each token within its expert's capacity buffer.
    position = (jnp.cumsum(expert_onehot, axis=0) - 1.0) * expert_onehot
    keep = (position < capacity) & (expert_onehot > 0)       # [T, E]
    pos_onehot = jax.nn.one_hot(position.astype(jnp.int32), capacity)
    dispatch = keep[..., None] * pos_onehot                  # [T, E, C]
    gate = (probs * expert_onehot).sum(axis=-1)              # [T]
    combine = dispatch * gate[:, None, None]
    dropped = ~keep.any(axis=-1)                             # [T]
    return dispatch.astype(router_logits.dtype), combine, aux_loss, dropped


def moe_layer_local(tokens: jax.Array,
                    router_kernel: jax.Array,
                    expert_fn: Callable[[Any, jax.Array], jax.Array],
                    expert_params: Any, *,
                    axis_name: str = "ep",
                    capacity_factor: float = 1.25,
                    buffer_constraint: Callable[[jax.Array], jax.Array]
                    = lambda x: x,
                    return_drops: bool = False,
                    ):
    """MoE layer inside a mapped context.

    tokens: local [T, D]; router_kernel: [D, E_total] replicated;
    expert_params: this device's experts, leaves [E_local, ...].
    Returns (output [T, D], aux_loss scalar); with ``return_drops``,
    (output, aux_loss, dropped-token count scalar) — the count is a
    traced value, so jitted callers thread it out and feed
    :func:`record_dropped_tokens` host-side.

    ``buffer_constraint`` pins the expert buffers' sharding on the mesh
    axes that stay automatic inside the caller's ``shard_map`` (the token
    dim is reduced away building them, so they should be replicated over
    dp/fsdp) — without it GSPMD's propagator smears batch shardings onto
    the expert dim of the saved-for-backward buffers and pays an
    involuntary full rematerialization each layer.
    """
    n = axis_size(axis_name)
    T, D = tokens.shape
    E_total = router_kernel.shape[1]
    if E_total % n:
        raise ValueError(f"experts ({E_total}) must divide ep size ({n})")
    E_local = E_total // n
    capacity = max(1, int(T * capacity_factor / E_total))

    logits = tokens @ router_kernel                           # [T, E]
    dispatch, combine, aux, dropped = switch_route(logits, capacity)

    # Gather tokens into expert buffers: [E, C, D].
    expert_inputs = buffer_constraint(
        jnp.einsum("tec,td->ecd", dispatch, tokens))
    # Exchange: send each expert's buffer to its owner device.
    # [E, C, D] -> [n, E_local, C, D] -> a2a -> [n, E_local, C, D] where the
    # leading dim now indexes source rank.
    shaped = expert_inputs.reshape(n, E_local, capacity, D)
    received = lax.all_to_all(shaped, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    # received: [n, E_local, C, D] — tokens from every rank for my experts.
    per_expert = buffer_constraint(received.transpose(1, 0, 2, 3).reshape(
        E_local, n * capacity, D))
    expert_out = buffer_constraint(jax.vmap(expert_fn)(
        expert_params, per_expert))                           # [E_local, n*C, D]
    # Route back: inverse exchange.
    back = expert_out.reshape(E_local, n, capacity, D).transpose(1, 0, 2, 3)
    returned = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    # returned: [n(expert-owner), E_local, C, D] == my tokens' results.
    results = buffer_constraint(returned.reshape(E_total, capacity, D))
    out = jnp.einsum("tec,ecd->td", combine, results)
    if return_drops:
        return (out.astype(tokens.dtype), aux,
                jnp.sum(dropped.astype(jnp.float32)))
    return out.astype(tokens.dtype), aux


def moe_layer(tokens: jax.Array, router_kernel: jax.Array,
              expert_fn: Callable[[Any, jax.Array], jax.Array],
              stacked_expert_params: Any, mesh: Mesh, *,
              axis_name: str = "ep",
              capacity_factor: float = 1.25,
              layer: str = "0") -> tuple[jax.Array, jax.Array]:
    """Standalone entry: tokens [T, D] sharded over ``axis_name`` on dim 0;
    expert params leaves [E_total, ...] sharded over ``axis_name``.

    Capacity overflow drops are counted into
    ``hvd_moe_dropped_tokens_total{layer}`` after the step (the count
    rides out of the jitted region as an output)."""

    def local(tok, rk, params):
        out, aux, drops = moe_layer_local(
            tok, rk, expert_fn,
            jax.tree.map(lambda a: a, params),
            axis_name=axis_name, capacity_factor=capacity_factor,
            return_drops=True)
        return out, lax.pmean(aux, axis_name), lax.psum(drops, axis_name)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P(), P(axis_name)),
        out_specs=(P(axis_name), P(), P()),
        check_vma=False)
    out, aux, drops = jax.jit(fn)(tokens, router_kernel,
                                  stacked_expert_params)
    record_dropped_tokens(jax.device_get(drops), layer)
    return out, aux


def _softmax_np(x):
    import numpy as np
    x = x - x.max(axis=-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


def moe_layer_hvd(tokens, router_kernel, expert_fn, expert_params, *,
                  capacity_factor: float = 1.25, layer: str = "0"):
    """Expert parallelism over the engine's negotiated ``hvd.alltoall``
    — the 4th collective verb at job scale.

    Where :func:`moe_layer` is the in-jit path (static capacity buffers,
    ``lax.all_to_all`` inside one compiled program), this is the
    process-level eager path: routing happens host-side, per-expert
    counts are exchanged FIRST (a tiny uniform alltoall), so the token
    exchange itself ships only the kept rows — the alltoallv form with
    split sizes known on every rank, no padded capacity slots on the
    wire.  Multi-process correct: the same code runs in the
    single-controller rig (one process driving n ranks) and under
    ``hvdrun`` (one rank per process).

    Args: ``tokens`` — list of per-rank [T_k, D] arrays, one entry per
    rank this process drives; ``router_kernel`` [D, E_total] replicated;
    ``expert_params`` — list of per-rank pytrees, leaves [E_local, ...]
    (rank r owns experts ``r*E_local .. (r+1)*E_local-1``).

    Returns ``(outs, aux, dropped)``: per-rank outputs [T_k, D], the
    mean Switch aux loss over local ranks, and the total overflow drops
    (also counted into ``hvd_moe_dropped_tokens_total{layer}``).
    """
    import numpy as np
    import horovod_tpu as hvd

    n = hvd.size()
    toks = [np.asarray(t, np.float32) for t in tokens]
    rk = np.asarray(router_kernel, np.float32)
    local = len(toks)
    E_total = rk.shape[1]
    if E_total % n:
        raise ValueError(f"experts ({E_total}) must divide world ({n})")
    E_local = E_total // n

    counts = np.zeros((local, E_total), np.int32)   # kept per expert
    send_orders, sends, gates, dropped, auxes = [], [], [], 0, []
    for k, tok in enumerate(toks):
        T = tok.shape[0]
        capacity = max(1, int(T * capacity_factor / E_total))
        probs = _softmax_np(tok @ rk)
        eidx = probs.argmax(axis=-1)
        gate = probs[np.arange(T), eidx]
        onehot = np.eye(E_total, dtype=np.float32)[eidx]
        auxes.append(float(
            E_total * (onehot.mean(0) * probs.mean(0)).sum()))
        pos = np.empty(T, np.int64)
        for e in range(E_total):
            sel = eidx == e
            pos[sel] = np.arange(int(sel.sum()))
            counts[k, e] = min(int(sel.sum()), capacity)
        keep = pos < capacity
        dropped += int((~keep).sum())
        kept = np.nonzero(keep)[0]
        order = kept[np.argsort(eidx[kept], kind="stable")]
        send_orders.append(order)
        sends.append(tok[order])
        gates.append(gate)

    # (1) per-expert counts first — destination j learns exactly how many
    # rows each source sends for each of its experts, so every split size
    # below is known before any token moves.
    splits_cnt = np.full((local, n), E_local, np.int32)
    cnt_recv = hvd.alltoall([c for c in counts], splits=splits_cnt)
    # cnt_recv[k]: [n*E_local] — source-major counts for rank k's experts.
    # (2) kept tokens, expert-ascending per destination block.
    splits = np.stack([counts[k].reshape(n, E_local).sum(axis=1)
                       for k in range(local)]).astype(np.int32)
    data_recv = hvd.alltoall(sends, splits=splits)

    # (3) run the local experts on expert-major regroupings.
    results = []
    for k in range(local):
        cnt = np.asarray(cnt_recv[k]).reshape(n, E_local)
        block = np.asarray(data_recv[k])          # source-major rows
        src_off = np.concatenate([[0], cnt.sum(axis=1).cumsum()])
        within = np.concatenate(
            [np.zeros((n, 1), np.int64), cnt.cumsum(axis=1)], axis=1)
        out_rows = np.zeros_like(block)
        params = expert_params[min(k, len(expert_params) - 1)]
        for e in range(E_local):
            rows = [block[src_off[i] + within[i, e]:
                          src_off[i] + within[i, e + 1]] for i in range(n)]
            x_e = np.concatenate(rows, axis=0) if cnt[:, e].sum() else None
            if x_e is None or not len(x_e):
                continue
            p_e = jax.tree.map(lambda a: jnp.asarray(a)[e], params)
            y_e = np.asarray(expert_fn(p_e, jnp.asarray(x_e)))
            off = 0
            for i in range(n):
                m = int(cnt[i, e])
                out_rows[src_off[i] + within[i, e]:
                         src_off[i] + within[i, e + 1]] = y_e[off:off + m]
                off += m
        results.append(out_rows)

    # (4) inverse exchange: each destination returns exactly the rows it
    # received, so the transposed split matrix routes them home.
    splits_back = np.stack([np.asarray(cnt_recv[k]).reshape(
        n, E_local).sum(axis=1) for k in range(local)]).astype(np.int32)
    back = hvd.alltoall(results, splits=splits_back)

    outs = []
    for k, tok in enumerate(toks):
        out = np.zeros_like(tok)
        rows = np.asarray(back[k])   # dest-major == my original send order
        order = send_orders[k]
        out[order] = gates[k][order, None] * rows
        outs.append(out)
    record_dropped_tokens(dropped, layer)
    return outs, float(np.mean(auxes)) if auxes else 0.0, dropped
