"""Expert parallelism: Switch-style MoE with all_to_all dispatch.

ABSENT as a strategy in the reference, but its ``hvd.alltoall`` verb
(† ``message.h RequestType::ALLTOALL``, ``MPI_Alltoallv``) exists precisely
for this exchange pattern (DLRM embedding swaps, MoE token dispatch) —
BASELINE config 5 makes it a required capability.

Design (Switch Transformer, arXiv:2101.03961, re-expressed for TPU):
top-1 routing with static capacity so every shape is fixed at trace time
(XLA requirement — no dynamic gathers), dispatch/combine as einsums with
one-hot masks (MXU-friendly), and the token exchange as a single
``all_to_all`` over the ``ep`` axis in each direction.  Overflowed tokens
are dropped (standard capacity semantics) and recovered by the residual
connection in the caller.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..jaxcompat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def switch_route(router_logits: jax.Array, capacity: int
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing masks.

    router_logits: [T, E].  Returns (dispatch [T, E, C] float, combine
    [T, E, C] float, aux_loss scalar).
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # [T]
    expert_onehot = jax.nn.one_hot(expert_idx, E)            # [T, E]
    # Load-balancing auxiliary loss († Switch eq. 4).
    density = expert_onehot.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux_loss = E * jnp.sum(density * density_proxy)
    # Position of each token within its expert's capacity buffer.
    position = (jnp.cumsum(expert_onehot, axis=0) - 1.0) * expert_onehot
    keep = (position < capacity) & (expert_onehot > 0)       # [T, E]
    pos_onehot = jax.nn.one_hot(position.astype(jnp.int32), capacity)
    dispatch = keep[..., None] * pos_onehot                  # [T, E, C]
    gate = (probs * expert_onehot).sum(axis=-1)              # [T]
    combine = dispatch * gate[:, None, None]
    return dispatch.astype(router_logits.dtype), combine, aux_loss


def moe_layer_local(tokens: jax.Array,
                    router_kernel: jax.Array,
                    expert_fn: Callable[[Any, jax.Array], jax.Array],
                    expert_params: Any, *,
                    axis_name: str = "ep",
                    capacity_factor: float = 1.25,
                    buffer_constraint: Callable[[jax.Array], jax.Array]
                    = lambda x: x,
                    ) -> tuple[jax.Array, jax.Array]:
    """MoE layer inside a mapped context.

    tokens: local [T, D]; router_kernel: [D, E_total] replicated;
    expert_params: this device's experts, leaves [E_local, ...].
    Returns (output [T, D], aux_loss scalar).

    ``buffer_constraint`` pins the expert buffers' sharding on the mesh
    axes that stay automatic inside the caller's ``shard_map`` (the token
    dim is reduced away building them, so they should be replicated over
    dp/fsdp) — without it GSPMD's propagator smears batch shardings onto
    the expert dim of the saved-for-backward buffers and pays an
    involuntary full rematerialization each layer.
    """
    n = axis_size(axis_name)
    T, D = tokens.shape
    E_total = router_kernel.shape[1]
    if E_total % n:
        raise ValueError(f"experts ({E_total}) must divide ep size ({n})")
    E_local = E_total // n
    capacity = max(1, int(T * capacity_factor / E_total))

    logits = tokens @ router_kernel                           # [T, E]
    dispatch, combine, aux = switch_route(logits, capacity)

    # Gather tokens into expert buffers: [E, C, D].
    expert_inputs = buffer_constraint(
        jnp.einsum("tec,td->ecd", dispatch, tokens))
    # Exchange: send each expert's buffer to its owner device.
    # [E, C, D] -> [n, E_local, C, D] -> a2a -> [n, E_local, C, D] where the
    # leading dim now indexes source rank.
    shaped = expert_inputs.reshape(n, E_local, capacity, D)
    received = lax.all_to_all(shaped, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    # received: [n, E_local, C, D] — tokens from every rank for my experts.
    per_expert = buffer_constraint(received.transpose(1, 0, 2, 3).reshape(
        E_local, n * capacity, D))
    expert_out = buffer_constraint(jax.vmap(expert_fn)(
        expert_params, per_expert))                           # [E_local, n*C, D]
    # Route back: inverse exchange.
    back = expert_out.reshape(E_local, n, capacity, D).transpose(1, 0, 2, 3)
    returned = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    # returned: [n(expert-owner), E_local, C, D] == my tokens' results.
    results = buffer_constraint(returned.reshape(E_total, capacity, D))
    out = jnp.einsum("tec,ecd->td", combine, results)
    return out.astype(tokens.dtype), aux


def moe_layer(tokens: jax.Array, router_kernel: jax.Array,
              expert_fn: Callable[[Any, jax.Array], jax.Array],
              stacked_expert_params: Any, mesh: Mesh, *,
              axis_name: str = "ep",
              capacity_factor: float = 1.25) -> tuple[jax.Array, jax.Array]:
    """Standalone entry: tokens [T, D] sharded over ``axis_name`` on dim 0;
    expert params leaves [E_total, ...] sharded over ``axis_name``."""

    def local(tok, rk, params):
        out, aux = moe_layer_local(
            tok, rk, expert_fn,
            jax.tree.map(lambda a: a, params),
            axis_name=axis_name, capacity_factor=capacity_factor)
        return out, lax.pmean(aux, axis_name)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P(), P(axis_name)),
        out_specs=(P(axis_name), P()),
        check_vma=False)
    return jax.jit(fn)(tokens, router_kernel, stacked_expert_params)
