"""Pipeline parallelism: GPipe-style microbatch streaming over the pp axis.

ABSENT in the reference (SURVEY §2.6).  TPU-native design: stage parameters
are stacked along a leading ``[pp, ...]`` dimension sharded over the ``pp``
mesh axis; inside ``shard_map`` every device runs the *same* program (SPMD)
and hands activations to its successor with ``ppermute`` — the point-to-point
collective that tolerates DCN, which is why pp is the outermost mesh axis
(see :mod:`horovod_tpu.parallel.mesh`).

Schedule: GPipe fill-drain with M microbatches over S stages: T = M + S - 1
ticks.  At tick t, the device at stage s processes microbatch ``t - s`` when
``0 <= t - s < M`` and garbage otherwise (masked out).  Bubble fraction
(S-1)/(M+S-1) — callers pick M >= 4·S to keep it small.  The tick loop is a
``lax.scan`` (compiler-friendly control flow; one compiled body regardless
of M).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                         stage_params: Any,
                         microbatches: jax.Array, *,
                         axis_name: str = "pp") -> jax.Array:
    """Run the pipeline inside a mapped context.

    ``stage_params``: this device's stage parameters (leading pp dim already
    stripped to local, i.e. leaves are one stage's params with a leading
    singleton removed by the caller's in_specs).
    ``microbatches``: [M, mb, ...] — the full microbatch set, replicated
    across pp (each stage only *uses* its inputs when scheduled).
    Returns [M, mb, ...] outputs, valid on the last stage.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (when in range); others take the
        # activation handed over from the previous stage.
        mb_idx = jnp.clip(t, 0, M - 1)
        injected = microbatches[mb_idx]
        x = jnp.where(idx == 0, injected, buf)
        y = stage_fn(stage_params, x)
        # The last stage records its result for microbatch t - (n-1).
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        is_valid = (t - (n - 1) >= 0) & (t - (n - 1) < M)
        record = jnp.where((idx == n - 1) & is_valid, 1.0, 0.0)
        outputs = outputs.at[out_idx].set(
            jnp.where(record > 0, y, outputs[out_idx]))
        # Hand activations downstream (ring; stage n-1 → 0 is ignored).
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros(microbatches.shape[:1] + _out_shape(
        stage_fn, stage_params, microbatches[0]), microbatches.dtype)
    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(T))
    # Broadcast final outputs from the last stage to all pp ranks so the
    # caller sees replicated results (one psum, masked).
    outputs = lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name)
    return outputs


def _out_shape(stage_fn, params, x) -> tuple[int, ...]:
    return jax.eval_shape(stage_fn, params, x).shape


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any,
                   microbatches: jax.Array,
                   mesh: Mesh, *,
                   axis_name: str = "pp") -> jax.Array:
    """Standalone entry: ``stacked_params`` leaves have leading dim = pp size
    (stage-major), sharded over ``axis_name``; ``microbatches`` is [M, mb,...]
    replicated.  Returns [M, mb, ...] outputs replicated."""

    def local(params, mb):
        local_params = jax.tree.map(lambda a: a[0], params)
        return pipeline_apply_local(stage_fn, local_params, mb,
                                    axis_name=axis_name)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False)
    return jax.jit(fn)(stacked_params, microbatches)
