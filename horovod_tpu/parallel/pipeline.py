"""Pipeline parallelism: GPipe-style microbatch streaming over the pp axis.

ABSENT in the reference (SURVEY §2.6).  TPU-native design: stage parameters
are stacked along a leading ``[pp, ...]`` dimension sharded over the ``pp``
mesh axis; inside ``shard_map`` every device runs the *same* program (SPMD)
and hands activations to its successor with ``ppermute`` — the point-to-point
collective that tolerates DCN, which is why pp is the outermost mesh axis
(see :mod:`horovod_tpu.parallel.mesh`).

Schedule: GPipe fill-drain with M microbatches over S stages: T = M + S - 1
ticks.  At tick t, the device at stage s processes microbatch ``t - s`` when
``0 <= t - s < M`` and garbage otherwise (masked out).  Bubble fraction
(S-1)/(M+S-1) — callers pick M >= 4·S to keep it small.  The tick loop is a
``lax.scan`` (compiler-friendly control flow; one compiled body regardless
of M).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..jaxcompat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                         stage_params: Any,
                         microbatches: jax.Array, *,
                         axis_name: str = "pp",
                         with_aux: bool = False):
    """Run the pipeline inside a mapped context.

    ``stage_params``: this device's stage parameters (leading pp dim already
    stripped to local, i.e. leaves are one stage's params with a leading
    singleton removed by the caller's in_specs).
    ``microbatches``: [M, mb, ...] — the full microbatch set, replicated
    across pp (each stage only *uses* its inputs when scheduled).
    Returns [M, mb, ...] outputs, valid on the last stage.

    With ``with_aux`` the stage returns ``(y, aux_scalar)``; aux from valid
    ticks is accumulated per stage, psummed over pp (each stage owns
    disjoint layers) and averaged over microbatches; the return becomes
    ``(outputs, aux)``.
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outputs, aux_acc = carry
        # Stage 0 injects microbatch t (when in range); others take the
        # activation handed over from the previous stage.
        mb_idx = jnp.clip(t, 0, M - 1)
        injected = microbatches[mb_idx]
        x = jnp.where(idx == 0, injected, buf)
        res = stage_fn(stage_params, x)
        y, aux = res if with_aux else (res, None)
        if with_aux:
            # This stage processes real data at tick t iff 0 <= t-idx < M.
            # Both the mask and aux ride as shape [1]: rank-0 residuals of
            # a differentiated shard_map trip a spec error on 0.4.x.
            live = ((t - idx >= 0) & (t - idx < M)).reshape(1)
            aux_acc = aux_acc + jnp.where(live, aux.reshape(1), 0.0)
        # The last stage records its result for microbatch t - (n-1).
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        is_valid = (t - (n - 1) >= 0) & (t - (n - 1) < M)
        record = jnp.where((idx == n - 1) & is_valid, 1.0, 0.0)
        outputs = outputs.at[out_idx].set(
            jnp.where(record > 0, y, outputs[out_idx]))
        # Hand activations downstream (ring; stage n-1 → 0 is ignored).
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outputs, aux_acc), None

    buf0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros(microbatches.shape[:1] + _out_shape(
        stage_fn, stage_params, microbatches[0], with_aux),
        microbatches.dtype)
    carry0 = (buf0, out0, jnp.zeros((1,), jnp.float32))
    (_, outputs, aux_acc), _ = lax.scan(tick, carry0, jnp.arange(T))
    # Broadcast final outputs from the last stage to all pp ranks so the
    # caller sees replicated results (one psum, masked).
    outputs = lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name)
    if with_aux:
        # aux stays shape [1] (see tick) — callers index [0] outside the
        # differentiated region.
        return outputs, lax.psum(aux_acc, axis_name) / M
    return outputs


def _out_shape(stage_fn, params, x, with_aux: bool = False) -> tuple[int, ...]:
    shape = jax.eval_shape(stage_fn, params, x)
    return (shape[0] if with_aux else shape).shape


def pipeline_train_local(stage_fn: Callable[[Any, jax.Array], tuple],
                         stage_params: Any,
                         microbatches: jax.Array,
                         loss_head: Callable[[Any, jax.Array, jax.Array],
                                             jax.Array],
                         head_params: Any, *,
                         axis_name: str = "pp",
                         aux_weight: float = 0.0,
                         seed_scale: float = 1.0):
    """1F1B training schedule inside a mapped context.

    The GPipe path (:func:`pipeline_apply_local` under ``jax.grad``) keeps
    every microbatch's forward state live until the whole backward starts —
    activation memory grows with M.  This schedule interleaves: at tick
    ``t`` stage ``s`` runs the FORWARD of microbatch ``t - s`` and the
    BACKWARD of microbatch ``t - 2(n-1) + s`` (the tick its cotangent
    physically arrives from downstream), so in steady state every tick does
    one forward and one backward and at most ``2(n-1)`` microbatch inputs
    are in flight per stage — a ring buffer of ``2(n-1)`` slots replaces
    GPipe's M-deep saved state.  The backward recomputes the stage forward
    from the saved INPUT (``jax.vjp`` per tick, remat-style), the standard
    memory/compute trade of 1F1B pipelines.

    ``stage_fn(params, x) -> (y, aux_scalar)``.
    ``loss_head(head_params, y, m) -> scalar`` — per-microbatch loss,
    evaluated (and differentiated) on the LAST stage; ``m`` indexes any
    per-microbatch data (targets) the closure carries.  Its gradient seed
    is ``seed_scale`` (callers pass 1/n_data_shards so per-shard local
    means add up to the global mean).  ``aux_weight`` seeds each stage's
    aux output cotangent (microbatch-mean semantics after the final /M).

    Returns ``(loss, aux, d_microbatches, d_stage_params, d_head_params)``:
    loss/aux psummed over the pipeline and microbatch-averaged;
    d_microbatches the cotangent w.r.t. the stage-0 inputs (replicated
    over pp), d_stage_params THIS stage's parameter gradients (fp32),
    d_head_params the loss-head gradients (fp32, psummed over pp).  All
    gradients are for the microbatch-MEAN loss, matching the returned
    ``loss`` (i.e. already divided by M).
    """
    n = axis_size(axis_name)
    if n < 2:
        raise ValueError("pipeline_train_local needs a pp axis of size >= 2")
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    K = 2 * (n - 1)
    T = M + K
    perm_down = [(i, (i + 1) % n) for i in range(n)]
    perm_up = [(i, (i - 1) % n) for i in range(n)]
    f32 = jnp.float32

    zeros_f32 = lambda tree: jax.tree.map(
        lambda l: jnp.zeros(l.shape, f32), tree)

    def mask_add(acc, grads, live):
        return jax.tree.map(
            lambda a, g: a + jnp.where(live, g.astype(f32), 0.0), acc, grads)

    y_aval = jax.eval_shape(stage_fn, stage_params, microbatches[0])[0]

    def tick(carry, t):
        fwd_buf, bwd_buf, ring, gacc, hacc, loss_acc, aux_acc, dmbs = carry
        is_last = s == n - 1
        # ---- backward bookkeeping reads BEFORE the forward write: at
        # stage 0 the bwd slot and this tick's fwd slot coincide (mod K).
        m_b = t - K + s
        live_b = (m_b >= 0) & (m_b < M)
        slot_b = jnp.clip(m_b, 0, M - 1) % K
        x_saved_pre = ring[slot_b]
        # ---- forward ----
        m_f = t - s
        live_f = (m_f >= 0) & (m_f < M)
        mclip_f = jnp.clip(m_f, 0, M - 1)
        x_in = jnp.where(s == 0, microbatches[mclip_f], fwd_buf)
        y, aux_f = stage_fn(stage_params, x_in)
        aux_acc = aux_acc + jnp.where(live_f, aux_f, 0.0)
        slot_f = mclip_f % K
        ring = ring.at[slot_f].set(jnp.where(live_f, x_in, ring[slot_f]))
        # ---- loss head (last stage; its bwd microbatch == m_f this tick)
        lval, head_vjp = jax.vjp(
            lambda hp, yy: loss_head(hp, yy, mclip_f), head_params, y)
        live_loss = live_f & is_last
        loss_acc = loss_acc + jnp.where(live_loss, lval, 0.0)
        dhead_t, dy_seed = head_vjp(jnp.asarray(seed_scale, lval.dtype))
        hacc = mask_add(hacc, dhead_t, live_loss)
        # ---- backward (recompute-from-saved-input vjp) ----
        # Last stage: the saved input for m_b IS this tick's x_in.
        x_bwd = jnp.where(is_last, x_in, x_saved_pre)
        cot_in = jnp.where(is_last, dy_seed, bwd_buf)
        _, stage_vjp = jax.vjp(stage_fn, stage_params, x_bwd)
        # Seeded per tick with weight * seed_scale (the final /M turns the
        # accumulated sum into the same microbatch mean as ``aux``).  The
        # seed_scale factor matters: like the CE seed, the aux cotangent is
        # per-data-shard, and the caller's blanket psum of replicated-param
        # grads over the data axes would otherwise count it n_data times
        # (caught by a round-4 review finite-difference probe: router grad
        # 4x the oracle on a pp*ep*dp mesh).
        aux_seed = jnp.where(
            live_b, jnp.asarray(aux_weight * seed_scale, f32), 0.0)
        dparams, dx = stage_vjp((cot_in, aux_seed))
        gacc = mask_add(gacc, dparams, live_b)
        out_slot = jnp.clip(m_b, 0, M - 1)
        rec = live_b & (s == 0)
        dmbs = dmbs.at[out_slot].set(
            jnp.where(rec, dx, dmbs[out_slot]))
        # ---- handoffs ----
        fwd_buf = lax.ppermute(y, axis_name, perm_down)
        bwd_buf = lax.ppermute(dx, axis_name, perm_up)
        return (fwd_buf, bwd_buf, ring, gacc, hacc, loss_acc, aux_acc,
                dmbs), None

    mb0 = microbatches[0]
    carry0 = (
        jnp.zeros(y_aval.shape, y_aval.dtype),            # fwd handoff
        jnp.zeros(mb0.shape, mb0.dtype),                  # bwd handoff
        jnp.zeros((K,) + mb0.shape, mb0.dtype),           # input ring
        zeros_f32(stage_params),                          # stage grads
        zeros_f32(head_params),                           # head grads
        jnp.zeros((), f32),                               # loss
        jnp.zeros((), f32),                               # aux
        jnp.zeros(microbatches.shape, mb0.dtype),         # d_microbatches
    )
    (_, _, _, gacc, hacc, loss_acc, aux_acc, dmbs), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    loss = lax.psum(jnp.where(s == n - 1, loss_acc, 0.0), axis_name) / M
    aux = lax.psum(aux_acc, axis_name) / M
    inv_m = 1.0 / M
    gacc = jax.tree.map(lambda g: g * inv_m, gacc)
    hacc = jax.tree.map(lambda g: lax.psum(g, axis_name) * inv_m, hacc)
    dmbs = lax.psum(
        jnp.where(s == 0, dmbs, jnp.zeros_like(dmbs)), axis_name) * inv_m
    return loss, aux, dmbs, gacc, hacc


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any,
                   microbatches: jax.Array,
                   mesh: Mesh, *,
                   axis_name: str = "pp") -> jax.Array:
    """Standalone entry: ``stacked_params`` leaves have leading dim = pp size
    (stage-major), sharded over ``axis_name``; ``microbatches`` is [M, mb,...]
    replicated.  Returns [M, mb, ...] outputs replicated."""

    def local(params, mb):
        local_params = jax.tree.map(lambda a: a[0], params)
        return pipeline_apply_local(stage_fn, local_params, mb,
                                    axis_name=axis_name)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False)
    return jax.jit(fn)(stacked_params, microbatches)
