"""Ring attention: exact attention over sequences sharded across devices.

Sequence/context parallelism is ABSENT in the reference (SURVEY §2.6) — this
is capability-beyond-parity required for the Llama long-context config.

Algorithm (Liu, Zaharia & Abbeel, "Ring Attention with Blockwise
Transformers", arXiv:2310.01889): the sequence is chunked contiguously
across the ``sp`` mesh axis; Q stays resident while K/V blocks rotate
around the ICI ring via ``ppermute``.  Each hop contributes one block of
scores folded in with online (flash-style) softmax accumulation, so memory
stays O(local_seq²) and the N-1 rotations overlap with block compute —
XLA schedules the ``collective-permute`` concurrently with the matmuls,
which is what makes the ring bandwidth-optimal on the torus.

Causality on the ring: rank *i* owns tokens ``[i*C, (i+1)*C)``.  After *s*
hops the resident KV block originated at rank ``(i - s) mod n``:
- origin < i   → fully visible,
- origin == i  → lower-triangular block mask,
- origin > i   → fully masked (contributes nothing, but the hop still
  happens so every rank stays in lockstep — same reason the reference's
  coordinator keeps collective order identical on all ranks).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..jaxcompat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """Scores for one (local-Q × resident-KV) block.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; mask: [Lq, Lk] bool or None.
    Returns (scores [B, H, Lq, Lk]) pre-softmax, masked.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, _NEG_INF)
    return s


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str = "sp",
                         causal: bool = True,
                         scale: Optional[float] = None) -> jax.Array:
    """Exact attention for locally-sharded q/k/v inside a mapped context.

    Shapes (local shard): ``q,k,v: [batch, local_seq, heads, head_dim]``;
    returns the same shape.  Call inside ``shard_map``/``pjit``-mapped code
    whose ``axis_name`` axis shards the sequence dimension.
    """
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, L, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    # Online-softmax accumulators.
    m = jnp.full((B, H, L), _NEG_INF, jnp.float32)          # running max
    l = jnp.zeros((B, H, L), jnp.float32)                   # running denom
    o = jnp.zeros((B, L, H, D), jnp.float32)                # running numer

    perm = [(i, (i + 1) % n) for i in range(n)]
    tri = jnp.tril(jnp.ones((L, L), bool)) if causal else None

    def fold(carry, kv_origin, k_blk, v_blk):
        m_, l_, o_ = carry
        if causal:
            # Block-level causal visibility (see module docstring).
            full = kv_origin < my
            diag = kv_origin == my
            base = jnp.where(full, True, False)
            mask = jnp.where(diag, tri, jnp.broadcast_to(base, (L, L)))
        else:
            mask = None
        s = _block_attend(q, k_blk, v_blk, scale, mask).astype(jnp.float32)
        blk_max = s.max(axis=-1)                            # [B,H,L]
        m_new = jnp.maximum(m_, blk_max)
        alpha = jnp.exp(m_ - m_new)
        p = jnp.exp(s - m_new[..., None])                   # [B,H,Lq,Lk]
        l_new = l_ * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        o_new = o_ * alpha.transpose(0, 2, 1)[..., None] + pv
        return m_new, l_new, o_new

    carry = (m, l, o)
    k_cur, v_cur = k, v
    for step in range(n):
        origin = (my - step) % n
        carry = fold(carry, origin, k_cur, v_cur)
        if step != n - 1:
            # Rotate KV to the next rank; XLA overlaps this collective-
            # permute with the next block's matmuls.
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    m_, l_, o_ = carry
    out = o_ / jnp.maximum(l_, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Mesh, *, axis_name: str = "sp",
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Standalone entry: q/k/v are global ``[B, S, H, D]`` arrays; the
    sequence dim is sharded over ``axis_name`` and exact attention is
    computed with the ring schedule."""
    fn = shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal,
                scale=scale),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False)
    return jax.jit(fn)(q, k, v)


def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                            axis_name: str = "sp",
                            causal: bool = True,
                            scale: Optional[float] = None) -> jax.Array:
    """Ulysses-style sequence parallelism (DeepSpeed-Ulysses,
    arXiv:2309.14509): all_to_all swaps the sharded dim from sequence to
    heads, runs full-sequence attention on 1/n of the heads, and swaps back.
    Uses the same alltoall primitive the collective layer must provide
    anyway (SURVEY §5.7); preferable when heads % n == 0 and sequence fits.
    """
    n = axis_size(axis_name)
    B, L, H, D = q.shape
    if H % n:
        raise ValueError(
            f"sp size ({n}) must divide heads ({H}) for Ulysses")

    # tiled=True all_to_alls: split_axis chunked across the axis, concat
    # axis grown n-fold, no intermediate block reshapes.  (The tiled=False
    # block formulation had a broken transpose on this jax — the vjp's
    # cotangent came back mis-shaped when split_axis != concat_axis, which
    # only surfaced once the model grew a differentiated Ulysses path.)
    def seq_to_heads(x):
        # [B, L, H, D] local-seq → [B, n*L, H/n, D] local-heads
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    S = qh.shape[1]
    mask = jnp.tril(jnp.ones((S, S), bool)) if causal else None
    s = _block_attend(qh, kh, vh, scale, mask).astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype))
