"""Logical-axis sharding rules (the GSPMD annotation layer).

The scaling recipe: name every tensor dimension logically (``batch``,
``seq``, ``embed``, ``mlp``, ``heads``, ``experts``, ``stage``…), map logical
names to mesh axes once, annotate with ``with_sharding_constraint``, and let
XLA insert the collectives.  This module owns that one mapping so models
never hard-code mesh axes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical dimension name -> mesh axis (or tuple of axes) it shards over
DEFAULT_RULES: dict[str, Union[None, str, tuple[str, ...]]] = {
    "batch": ("dp", "fsdp"),   # data-parallel batch split
    "seq": "sp",               # sequence/context parallel
    "embed": "fsdp",           # ZeRO-3: params sharded over fsdp at rest
    "mlp": "tp",               # column-parallel hidden dim
    "heads": "tp",             # attention heads over tp
    "kv_heads": "tp",
    "head_dim": None,
    "qkv": None,
    "vocab": "tp",             # output projection vocab-parallel
    # Embedding-table rows: sharding the table on its vocab (indexed) dim
    # keeps the token gather partitionable — GSPMD lowers a gather from a
    # row-sharded table to per-shard lookups + psum, whereas a table
    # sharded on the embed (feature) dim forces an involuntary full
    # rematerialization when the output wants batch sharding.
    "vocab_rows": ("tp", "fsdp"),
    "experts": "ep",           # MoE experts over ep
    "expert_mlp": "tp",
    "stage": "pp",             # pipeline stage dimension (stacked params)
    "norm": None,
}


def spec_for(logical_dims: Sequence[Optional[str]],
             rules: Optional[dict] = None) -> P:
    """PartitionSpec for a tensor whose dims have these logical names."""
    rules = {**DEFAULT_RULES, **(rules or {})}
    entries = []
    for dim in logical_dims:
        if dim is None:
            entries.append(None)
            continue
        if dim not in rules:
            raise KeyError(f"unknown logical dim {dim!r}")
        entries.append(rules[dim])
    return P(*entries)


def logical_sharding(mesh: Mesh, logical_dims: Sequence[Optional[str]],
                     rules: Optional[dict] = None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_dims, rules))


def fitted_rules(mesh: Mesh, dim_sizes: dict[str, int],
                 rules: Optional[dict] = None) -> dict:
    """Mesh-aware rule overrides: for each logical dim in ``dim_sizes``,
    keep the longest prefix of its mapped mesh axes whose product divides
    the dim size, degrading to replication when even the first axis does
    not divide (e.g. ``kv_heads=2`` on a ``tp=4`` mesh).

    Sharding a dim over axes that do not divide it is not merely padded by
    GSPMD — jitted init with such out_shardings is rejected outright, and
    the model's grouped-KV dispatch would silently fall off its fast path.
    Returns an override dict to pass as ``rules`` to :func:`spec_for` /
    :func:`constrain` / :func:`logical_sharding`.
    """
    base = {**DEFAULT_RULES, **(rules or {})}
    out = dict(rules or {})
    for dim, size in dim_sizes.items():
        axes = base.get(dim)
        if axes is None:
            continue
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        kept: list[str] = []
        prod = 1
        for a in axes_t:
            n = mesh.shape.get(a, 1)
            if n > 1 and size % (prod * n) != 0:
                break
            kept.append(a)
            prod *= n
        if len(kept) != len(axes_t):
            out[dim] = tuple(kept) if kept else None
    return out


def spec_axes(spec: P) -> set:
    """The set of mesh axis names a PartitionSpec references."""
    out = set()
    for entry in spec:
        if entry is None:
            continue
        out.update((entry,) if isinstance(entry, str) else entry)
    return out


def constrain(x: jax.Array, logical_dims: Sequence[Optional[str]],
              mesh: Optional[Mesh] = None,
              rules: Optional[dict] = None) -> jax.Array:
    """``with_sharding_constraint`` by logical dimension names.

    When every mesh axis the spec references has size 1 the constraint is
    semantically a no-op (the tensor is unsharded either way) and is
    skipped: the annotation is an optimization barrier to XLA fusion, so
    leaving it in costs real step time on single-device meshes.
    """
    spec = spec_for(logical_dims, rules)
    if mesh is not None:
        if all(mesh.shape.get(a, 1) == 1 for a in spec_axes(spec)):
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)
