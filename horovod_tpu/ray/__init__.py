"""Ray integration: place horovod_tpu ranks as Ray actors.

† ``horovod/ray/runner.py`` (v0.20+): upstream's ``RayExecutor`` creates a
placement group of worker actors, wires the rendezvous env into each, and
exposes ``start() / run(fn) / execute(fn) / shutdown()``.  Here Ray is the
process placer; the control plane is the native KV/controller services on
the driver and the collectives are XLA programs, exactly as under
``hvdrun``.

Usage († upstream README example)::

    from horovod_tpu.ray import RayExecutor
    ex = RayExecutor(num_workers=4)
    ex.start()
    results = ex.run(train_fn, args=(cfg,))
    ex.shutdown()
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runner.cluster import (DriverServices, pick_coordinator_port,
                              placement_env)

__all__ = ["RayExecutor"]


def _worker_cls():
    """Build the Ray actor class lazily (ray import deferred)."""
    import ray

    @ray.remote
    class _HvdWorker:
        def __init__(self, rank: int, env: Dict[str, str]) -> None:
            self._rank = rank
            os.environ.update(env)

        def hostname_ip(self) -> str:
            from horovod_tpu.runner.cluster import placement_info
            return placement_info()

        def set_env(self, env: Dict[str, str]) -> None:
            os.environ.update(env)

        def execute(self, fn: Callable, args: Sequence,
                    kwargs: Dict[str, Any]) -> Any:
            return fn(*args, **kwargs)

    return _HvdWorker


class RayExecutor:
    """† ``horovod.ray.RayExecutor``: actor-per-rank launcher."""

    def __init__(self, num_workers: int, *,
                 cpus_per_worker: int = 1,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 env: Optional[Dict[str, str]] = None,
                 platform: Optional[str] = None) -> None:
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.resources_per_worker = resources_per_worker
        self._extra_env = dict(env or {})
        self._platform = platform
        self._services: Optional[DriverServices] = None
        self._workers: List[Any] = []

    def start(self) -> None:
        """Create the services and the actor fleet; wire rendezvous env
        († upstream start(): placement group + per-worker env)."""
        try:
            import ray
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.ray.RayExecutor requires ray; on TPU VM "
                "slices without Ray use `hvdrun` instead") from e
        if self._workers:
            raise RuntimeError("RayExecutor already started")
        if not ray.is_initialized():
            ray.init()

        n = self.num_workers
        self._services = DriverServices(n)
        cls = _worker_cls()
        opts: Dict[str, Any] = {"num_cpus": self.cpus_per_worker}
        if self.resources_per_worker:
            opts["resources"] = self.resources_per_worker
        self._workers = [
            cls.options(**opts).remote(
                r, self._services.worker_env(
                    r, 0, platform=self._platform,
                    extra_env=self._extra_env))
            for r in range(n)
        ]
        # Placement round: learn each actor's host for local_rank and
        # rank 0's IP for the JAX coordinator (≙ spark's barrier allGather).
        infos = ray.get([w.hostname_ip.remote() for w in self._workers])
        coord_port = pick_coordinator_port()
        ray.get([
            w.set_env.remote(placement_env(infos, r, coord_port))
            for r, w in enumerate(self._workers)
        ])

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn`` on every rank; return rank-ordered results
        († upstream run())."""
        import ray
        if not self._workers:
            raise RuntimeError("call start() first")
        return ray.get([w.execute.remote(fn, args, kwargs or {})
                        for w in self._workers])

    # † upstream alias: execute() runs on all workers too (its
    # single-worker `execute_single` is rank 0 here).
    execute = run

    def execute_single(self, fn: Callable, args: Sequence = (),
                       kwargs: Optional[Dict[str, Any]] = None) -> Any:
        import ray
        if not self._workers:
            raise RuntimeError("call start() first")
        return ray.get(self._workers[0].execute.remote(fn, args,
                                                       kwargs or {}))

    def shutdown(self) -> None:
        """Kill the fleet and close driver services († upstream
        shutdown()).  No-op before start(), so ``finally: ex.shutdown()``
        is safe even when start() itself failed."""
        if not self._workers and self._services is None:
            return
        import ray
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        self._workers = []
        if self._services is not None:
            self._services.close()
            self._services = None
