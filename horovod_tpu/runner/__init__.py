"""Launcher: the ``horovodrun`` equivalent for TPU-native jobs.

† ``horovod/runner/`` — CLI (``launch.py``), host parsing, rendezvous server,
per-rank env injection, ssh fan-out, monitor/kill.  Public API parity:
``horovod_tpu.runner.run(fn_cmd, np=...)`` mirrors ``horovod.run``.
"""

from .api import run_func  # noqa: F401
from .hosts import HostSlots, parse_hosts  # noqa: F401
from .launch import main, run  # noqa: F401
