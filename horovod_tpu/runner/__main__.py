from .launch import main
import sys

sys.exit(main())
