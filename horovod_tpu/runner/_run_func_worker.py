"""Worker entry for :func:`horovod_tpu.runner.api.run_func` jobs.

Launched by the driver as ``python -m horovod_tpu.runner._run_func_worker``
on every rank († the role of ``horovod/runner/run_task.py``): fetch the
pickled function from the job KV store, execute it, publish the result.
(Underscore-named so the module never shadows the ``run_func`` function
re-exported on the ``horovod_tpu.runner`` package.)
"""

import sys

from .api import worker_main

if __name__ == "__main__":
    sys.exit(worker_main())
