"""Programmatic function launcher — † ``horovod.run`` (``horovod/runner/
__init__.py run``): call a Python function on ``np`` ranks and get the
per-rank return values back, without writing a script or touching the CLI.

    import horovod_tpu as hvd

    def train(lr):
        hvd.init()
        ...
        return final_loss

    losses = hvd.run_func(train, args=(0.01,), np=4)   # rank-ordered

Design (TPU-native, no shared filesystem assumed): the function, its
arguments, and every rank's return value travel over the job's
authenticated KV store — the same control-plane channel the rendezvous
uses — serialized with cloudpickle (so closures and notebook-defined
functions work, † cloudpickle payloads in ``runner/common/util/codec.py``).

  driver                                  worker (python -m ...run_func)
  ------                                  ------
  put payload blob in KV                  fetch payload blob
  launch workers (launch_workers)         result = func(*args, **kwargs)
  collector thread reads                  put result blob in KV
    runfunc/result/<rank> as each         wait for runfunc/ack/<rank> (so
    lands and sets runfunc/ack/<rank>       the driver's KV server outlives
    immediately                             the read), then exit
  join collector; unpickle; return

  Acks are PER RANK so a worker exits the moment its own result is read —
  a peer hanging in a collective must not hold an already-finished (or
  already-failed) worker for the full ack timeout.

Values larger than the control-plane frame limit are chunked
(:func:`kv_put_blob`).  A worker whose function raises reports the
traceback as its result and exits nonzero, so the launcher tears the job
down and :func:`run_func` raises with every collected failure.
"""

from __future__ import annotations

import dataclasses as _dc
import os
import threading
import time
from typing import Any, List, Optional, Sequence

from .. import chaos
from ..utils import retry as _retry

# Control-plane frames cap at 8 MiB (native/hvdtpu_core.cc recv guard);
# chunk well under it to leave room for HMAC/framing overhead.
_CHUNK = 4 << 20

_PAYLOAD_KEY = "runfunc/payload"
_RESULT_KEY = "runfunc/result/{rank}"
_ACK_KEY = "runfunc/ack/{rank}"


def kv_put_blob(kv, prefix: str, data: bytes, *,
                policy: _retry.RetryPolicy = _retry.KV_POLICY,
                deadline_s: Optional[float] = None) -> None:
    """Store ``data`` under ``prefix`` in ≤4 MiB chunks.

    The meta key goes LAST so a blocking reader that sees it can read
    every chunk without racing the writer; it carries the total length
    so a reader racing a REWRITE of the same prefix (the obs plane
    republishes ``obs/rank/<r>`` every interval; run_func keys are
    write-once and never hit this) detects the torn read instead of
    returning spliced bytes.

    Transient store errors retry under the shared backoff policy with
    ONE overall ``deadline_s`` across every chunk write.  The default
    budget scales with the blob (2s per 4 MiB chunk, 10s floor) so a
    large run_func result is never failed by a flat timeout a small
    blob sized; callers with a real cadence to protect (the obs
    publisher) pass a tight explicit deadline instead."""
    n = max(1, (len(data) + _CHUNK - 1) // _CHUNK)
    if deadline_s is None:
        deadline_s = max(10.0, 2.0 * n)
    policy = _dc.replace(policy, deadline_s=deadline_s)
    deadline = time.monotonic() + deadline_s

    def put(key: str, value: bytes) -> None:
        def attempt():
            chaos.fire("kv_put")
            if time.monotonic() > deadline:
                raise _Expired(
                    f"kv_put_blob({prefix!r}): {deadline_s}s overall "
                    "deadline exceeded")
            kv.set(key, value)
        _retry.retry_call(attempt, op="kv_put", policy=policy)

    for i in range(n):
        put(f"{prefix}/{i}", data[i * _CHUNK:(i + 1) * _CHUNK])
    put(f"{prefix}/meta", f"{n}:{len(data)}".encode())


def kv_get_blob(kv, prefix: str, timeout_ms: int = 10000) -> bytes:
    """Blocking fetch of a chunked blob stored by :func:`kv_put_blob`.

    ``timeout_ms`` is ONE overall deadline shared by the meta wait and
    every chunk wait — each wait gets only the remaining budget, so a
    flaky store can never stretch the call to ``chunks x timeout`` (the
    pre-retry-policy behavior restarted the full timeout per chunk).
    Transient errors inside the window retry on the shared backoff
    policy.

    Raises ``ValueError`` when the assembled length contradicts the
    meta record (concurrent rewrite of the prefix) — callers on
    rewritable keys retry or skip; write-once keys never see it."""
    deadline = time.monotonic() + timeout_ms / 1000.0

    def wait_key(key: str) -> bytes:
        def attempt():
            chaos.fire("kv_get")
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0:
                raise _Expired(
                    f"kv_get_blob({prefix!r}): {timeout_ms}ms overall "
                    f"deadline exceeded waiting for {key!r}")
            return kv.wait(key, timeout_ms=remaining_ms)
        policy = _dc.replace(
            _retry.KV_POLICY,
            deadline_s=max(0.0, deadline - time.monotonic()))
        return _retry.retry_call(attempt, op="kv_get", policy=policy)

    meta = wait_key(f"{prefix}/meta").decode()
    n_str, _, len_str = meta.partition(":")
    n = int(n_str)
    blob = b"".join(wait_key(f"{prefix}/{i}") for i in range(n))
    if len_str and len(blob) != int(len_str):
        raise ValueError(
            f"blob {prefix!r} torn mid-rewrite "
            f"(meta says {len_str} bytes, read {len(blob)})")
    return blob


class _Expired(_retry.Permanent, TimeoutError):
    """Deadline-expired marker: still a ``TimeoutError`` for callers'
    except clauses, but :class:`~horovod_tpu.utils.retry.Permanent`
    vetoes retrying a budget that is already spent."""


def _collect(kv, np_total: int, results: dict, stop: threading.Event) -> None:
    """Driver-side collector: read every rank's result blob as it lands and
    immediately publish that rank's ack, releasing the worker to exit.

    Sweeps ALL outstanding ranks non-blockingly each pass — a rank that
    hangs (e.g. blocked in a collective on a crashed peer) must not hide
    a later rank's already-published failure traceback, nor delay another
    worker's exit."""
    outstanding = set(range(np_total))
    while outstanding and not stop.is_set():
        progressed = False
        for rank in sorted(outstanding):
            key = _RESULT_KEY.format(rank=rank)
            try:
                if kv.get(f"{key}/meta") is None:
                    continue
                results[rank] = kv_get_blob(kv, key, timeout_ms=1000)
                kv.set(_ACK_KEY.format(rank=rank), b"1")
            except TimeoutError:
                continue
            except (ConnectionError, OSError):
                return  # services gone — the job already tore down
            outstanding.discard(rank)
            progressed = True
        if outstanding and not progressed:
            stop.wait(0.05)


def _pickle_module_by_value(mod) -> bool:
    """Should ``mod``'s contents ship by value?  Installed (site-packages /
    stdlib) modules are importable on workers and stay by-reference;
    everything else with a real file (project code, pytest-loaded modules)
    ships by value.  ``__main__`` needs nothing: cloudpickle already
    by-values it."""
    import sysconfig

    if mod is None or mod.__name__ == "__main__":
        return False
    path = getattr(mod, "__file__", None)
    if path is None:  # builtin / C extension — by-reference only
        return False
    path = os.path.abspath(path)
    if "site-packages" in path or "dist-packages" in path:
        return False
    stdlib = os.path.abspath(sysconfig.get_paths()["stdlib"])
    return not path.startswith(stdlib + os.sep)


def run_func(func, args: Sequence[Any] = (), kwargs: Optional[dict] = None,
             np: int = 1, *, hosts: Optional[str] = None,
             extra_env: Optional[dict] = None, ssh_port: int = 22,
             verbose: bool = False) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` ranks; return the rank-ordered
    list of results († ``horovod.run`` signature: func/args/kwargs/np/hosts).

    ``func`` typically calls :func:`horovod_tpu.init` itself, exactly like
    a script launched by ``hvdrun`` would.  Raises ``RuntimeError`` when
    any rank fails, with every collected worker traceback attached.
    """
    import cloudpickle
    import sys

    from .._native import KvClient
    from .launch import launch_workers

    # Ship the function BY VALUE when its module is plausibly not
    # importable on the workers (a notebook cell, a pytest-loaded test
    # module, a sweep script run from elsewhere) — cloudpickle only
    # by-values ``__main__`` automatically.  Installed libraries stay
    # by-reference: by-value would drag module globals (locks, handles)
    # into the payload for no benefit.  Registration is global
    # cloudpickle state — always undone.
    mod = sys.modules.get(getattr(func, "__module__", "") or "")
    register = _pickle_module_by_value(mod)
    if register:
        cloudpickle.register_pickle_by_value(mod)
    try:
        payload = cloudpickle.dumps(
            {"func": func, "args": tuple(args), "kwargs": dict(kwargs or {})})
    finally:
        if register:
            cloudpickle.unregister_pickle_by_value(mod)

    results: dict = {}
    stop = threading.Event()
    state: dict = {}

    def services_hook(services) -> None:
        kv = KvClient("127.0.0.1", services.kv.port, secret=services.secret)
        kv_put_blob(kv, _PAYLOAD_KEY, payload)
        t = threading.Thread(target=_collect, args=(kv, np, results, stop),
                             daemon=True)
        t.start()
        state["kv"], state["thread"] = kv, t

    command = [sys.executable, "-m", "horovod_tpu.runner._run_func_worker"]
    try:
        code = launch_workers(command, np_total=np, hosts_spec=hosts,
                              extra_env=extra_env, ssh_port=ssh_port,
                              verbose=verbose, services_hook=services_hook)
    finally:
        stop.set()
        thread = state.get("thread")
        if thread is not None:
            thread.join(timeout=5)
        if "kv" in state and (thread is None or not thread.is_alive()):
            # Close only once the collector has provably exited: closing
            # under a live collector nulls the native handle mid-call.  A
            # still-alive daemon thread keeps (and leaks) the client; the
            # missing-results check below reports the incomplete snapshot.
            try:
                state["kv"].close()
            except OSError:
                pass

    decoded = {rank: cloudpickle.loads(blob)
               for rank, blob in results.items()}
    failures = {rank: r["error"] for rank, r in decoded.items()
                if not r["ok"]}
    if code != 0 or failures:
        detail = "".join(f"\n[rank {r}]\n{tb}" for r, tb in
                         sorted(failures.items()))
        raise RuntimeError(
            f"run_func job failed (exit code {code}, "
            f"{len(failures)} rank(s) raised){detail}")
    missing = [r for r in range(np) if r not in decoded]
    if missing:
        raise RuntimeError(
            f"run_func: workers exited 0 but results from ranks {missing} "
            "were never collected")
    return [decoded[r]["value"] for r in range(np)]


def worker_main() -> int:
    """Entry point for ``python -m horovod_tpu.runner._run_func_worker``."""
    import traceback

    import cloudpickle

    from .._native import KvClient

    host, port = os.environ["HVDTPU_RENDEZVOUS_ADDR"].rsplit(":", 1)
    rank = int(os.environ.get("HVDTPU_CROSS_RANK", "0"))
    kv = KvClient(host, int(port), secret=os.environ.get("HVDTPU_SECRET"))
    start_timeout_ms = int(float(os.environ.get(
        "HVDTPU_START_TIMEOUT", "30")) * 1000)
    spec = cloudpickle.loads(
        kv_get_blob(kv, _PAYLOAD_KEY, timeout_ms=start_timeout_ms))

    code = 0
    try:
        value = spec["func"](*spec["args"], **spec["kwargs"])
        try:
            out = cloudpickle.dumps({"ok": True, "value": value})
        except Exception:
            raise RuntimeError(
                f"run_func: rank {rank}'s return value of type "
                f"{type(value).__name__} is not picklable")
    except BaseException:
        out = cloudpickle.dumps(
            {"ok": False, "error": traceback.format_exc()})
        code = 1
    kv_put_blob(kv, _RESULT_KEY.format(rank=rank), out)
    try:
        # Hold until the driver has read THIS rank's result (its KV server
        # dies with the job) — the driver acks per rank as soon as it
        # collects, so a hung peer never delays this worker's exit.  A
        # failed worker waits a shorter bound: its exit is what triggers
        # the launcher's teardown, so surfacing the error beats lingering.
        timeout_ms = 60000 if code == 0 else 10000
        kv.wait(_ACK_KEY.format(rank=rank), timeout_ms=timeout_ms)
    except (TimeoutError, ConnectionError, OSError):
        pass
    kv.close()
    return code
