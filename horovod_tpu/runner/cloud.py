"""Cloud TPU-VM slice discovery: synthesize the job host list from the
GCE metadata server instead of hand-written ``-H`` specs.

† ``horovod/runner/driver/driver_service.py`` role (auto host inventory);
on TPU pods the inventory source is the instance metadata every TPU VM
worker serves: ``worker-network-endpoints`` lists each worker's internal
IP — the same source ``jax.distributed`` uses for its own cluster
bootstrap.  One process per host VM is the deployment model (each
process drives all its local chips), so slots default to 1.

The metadata root is overridable via ``HVDTPU_METADATA_ROOT`` so tests
(and non-GCE emulation rigs) can point it at a mock server.
"""

from __future__ import annotations

import os
import re
import urllib.error
import urllib.request
from typing import List, Optional

from .hosts import HostSlots

_DEFAULT_ROOT = "http://metadata.google.internal/computeMetadata/v1"
_IPV4 = re.compile(r"^\d{1,3}(\.\d{1,3}){3}$")


class MetadataUnavailable(RuntimeError):
    """The metadata server is absent/unreachable (not on a TPU VM)."""


def _metadata_root() -> str:
    return os.environ.get("HVDTPU_METADATA_ROOT", _DEFAULT_ROOT)


def get_attribute(name: str, timeout: float = 5.0) -> str:
    """Fetch ``instance/attributes/<name>`` with the required
    ``Metadata-Flavor`` header."""
    url = f"{_metadata_root()}/instance/attributes/{name}"
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode()
    except (urllib.error.URLError, OSError, TimeoutError) as err:
        raise MetadataUnavailable(
            f"cannot read TPU-VM metadata {name!r} from "
            f"{_metadata_root()} ({err}); not on a TPU VM? "
            "Pass -H host:slots explicitly.") from err


def parse_worker_endpoints(raw: str) -> List[str]:
    """Worker internal IPs from ``worker-network-endpoints``.

    Entries are ','-separated, each a ':'-joined record whose fields vary
    by provisioning era; the IPv4-looking field is the worker address
    (matching how jax's cloud bootstrap reads it).
    """
    ips: List[str] = []
    for entry in raw.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        ip = next((f for f in entry.split(":") if _IPV4.match(f)), None)
        if ip:
            ips.append(ip)
    return ips


def tpu_pod_hosts(default_slots: Optional[int] = None) -> List[HostSlots]:
    """Host list for the current TPU pod slice.

    Slots default to 1: the TPU-native deployment model is one process
    per host VM driving all its local chips through ``jax.distributed``
    (see :mod:`horovod_tpu.context`) — the reference's process-per-GPU
    slot model maps to process-per-host here.  ``default_slots`` > 1 is
    for users who partition chips themselves (``TPU_VISIBLE_DEVICES``
    per local rank).
    """
    ips = parse_worker_endpoints(get_attribute("worker-network-endpoints"))
    if not ips:
        raise MetadataUnavailable(
            "worker-network-endpoints metadata was empty")
    return [HostSlots(ip, default_slots or 1) for ip in ips]


def worker_number() -> Optional[int]:
    """This worker's index in the slice (``agent-worker-number``)."""
    try:
        return int(get_attribute("agent-worker-number").strip())
    except (MetadataUnavailable, ValueError):
        return None
