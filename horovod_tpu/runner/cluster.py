"""Shared driver-side plumbing for launchers (ssh fan-out, Spark, Ray).

The reference's Spark and Ray integrations († ``horovod/spark/runner.py``,
``horovod/ray/runner.py``) both follow the same shape: the driver process
starts the rendezvous services, builds per-rank environment blocks, and the
cluster manager (instead of ssh) places the worker processes.  This module
is that shared shape for the TPU-native runtime: the native KV +
controller services, the env-block builder, and the placement-exchange
helpers used by ``runner/launch.py``, ``horovod_tpu/spark`` and
``horovod_tpu/ray``.
"""

from __future__ import annotations

import os
import secrets as _secrets
import socket
from typing import Dict, List, Optional


def local_ip() -> str:
    """Routable address other hosts can reach; localhost jobs don't care."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def pick_coordinator_port() -> int:
    """Port for the JAX coordinator, which binds on rank 0's host — the
    driver cannot probe a remote host's free ports, so pick from a wide
    ephemeral-range slice to make collisions unlikely.  (A conflict fails
    that worker's startup and the monitor/timeout reports it.)"""
    import random
    return random.randint(23000, 29999)


class DriverServices:
    """Native control-plane services bound on the driver.

    Starts the KV rendezvous store and the negotiation controller with a
    per-job HMAC secret († secret.py: one random credential per job), and
    hands out the env block each rank needs to ``hvd.init()``.
    """

    def __init__(self, num_proc: int, *, service_ip: Optional[str] = None,
                 secret: Optional[str] = None,
                 stall_shutdown_s: Optional[float] = None,
                 stall_warn_s: Optional[float] = None) -> None:
        from .._native import ControllerServer, KvServer

        if num_proc < 1:
            raise ValueError(f"num_proc must be >= 1, got {num_proc}")
        self.num_proc = num_proc
        self.secret = secret or os.environ.get("HVDTPU_SECRET") \
            or _secrets.token_hex(16)
        self.service_ip = service_ip or local_ip()
        self.kv = KvServer(secret=self.secret)
        # Round-barrier abort tracks the stall-shutdown opt-in: with
        # shutdown enabled, a rank whose peers stop checking in must be
        # released with an error rather than blocked in recv where its
        # own stall inspector cannot run († error Response to all ranks).
        # Callers whose stall knob does not live in this process's env
        # (hvdrun --config-file puts it only in the WORKER env) must pass
        # ``stall_shutdown_s`` explicitly.
        if stall_shutdown_s is None or stall_warn_s is None:
            from .. import config as config_mod
            cfg = config_mod.from_env()
            if stall_shutdown_s is None:
                stall_shutdown_s = cfg.stall_shutdown_time_s
            if stall_warn_s is None:
                # The controller's stall inspector (straggler attribution:
                # which ranks never submitted a pending tensor) must fire
                # on the same timescale as the workers' own stall checks,
                # not the native default.
                stall_warn_s = cfg.stall_warning_time_s
        round_abort_ms = 0
        if stall_shutdown_s and stall_shutdown_s > 0:
            round_abort_ms = int(stall_shutdown_s * 2 * 1000)
        try:
            self.controller = ControllerServer(
                size=num_proc, secret=self.secret,
                stall_warn_ms=max(1, int(stall_warn_s * 1000)),
                round_abort_ms=round_abort_ms)
        except Exception:
            self.kv.stop()  # construction failed; __exit__ will never run
            raise

    def worker_env(self, rank: int, local_rank: int, *,
                   coordinator_addr: Optional[str] = None,
                   platform: Optional[str] = None,
                   extra_env: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
        """The env block ``runner/launch.py base_env`` injects, minus the
        inherited process env (the cluster manager owns that part)."""
        env = dict(extra_env or {})
        env.update({
            "HVDTPU_CROSS_RANK": str(rank),
            "HVDTPU_CROSS_SIZE": str(self.num_proc),
            "HVDTPU_CONTROLLER_ADDR":
                f"{self.service_ip}:{self.controller.port}",
            "HVDTPU_RENDEZVOUS_ADDR": f"{self.service_ip}:{self.kv.port}",
            "HVDTPU_LOCAL_RANK": str(local_rank),
            "HVDTPU_SECRET": self.secret,
        })
        if coordinator_addr:
            env["HVDTPU_COORDINATOR_ADDR"] = coordinator_addr
        if platform:
            env["HVDTPU_PLATFORM"] = platform
        return env

    def close(self) -> None:
        self.kv.stop()
        self.controller.stop()

    def __enter__(self) -> "DriverServices":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def local_ranks(hostnames: List[str]) -> List[int]:
    """Per-rank local rank, given each rank's hostname in rank order
    († host_hash.py grouping: ranks sharing a host get 0,1,2,...)."""
    seen: Dict[str, int] = {}
    out = []
    for h in hostnames:
        out.append(seen.get(h, 0))
        seen[h] = out[-1] + 1
    return out


# --- placement exchange (worker side) --------------------------------------
# Each rank contributes placement_info(); from the gathered rank-ordered
# list, placement_env() derives what only placement can decide: local rank
# (host grouping) and the JAX coordinator address (rank 0's IP).  Used by
# the Spark barrier allGather and the Ray placement round.

def placement_info() -> str:
    return socket.gethostname() + "|" + local_ip()


def placement_env(infos: List[str], rank: int, coord_port: int
                  ) -> Dict[str, str]:
    # Group on the full "hostname|ip" pair: containerized Spark/Ray
    # clusters can give distinct hosts identical default hostnames, which
    # would mis-assign local ranks if hostname alone were the key.
    rank0_ip = infos[0].split("|", 1)[1]
    return {
        "HVDTPU_LOCAL_RANK": str(local_ranks(infos)[rank]),
        "HVDTPU_COORDINATOR_ADDR": f"{rank0_ip}:{coord_port}",
    }
