"""Elastic driver: host discovery, blacklist, assignment, job supervision.

† ``horovod/runner/elastic/driver.py`` (``ElasticDriver``,
``HostAssignment``), ``discovery.py`` (``HostDiscovery`` script polling),
``registration.py`` (blacklist), ``worker.py`` (notification).

TPU adaptation (SURVEY §5.3): chip/slice failures are coarser than GPU-host
failures and XLA meshes are static, so membership changes restart the *job*
(workers reload from their committed state/checkpoints) rather than patching
a live ring.  The driver supervises that loop: poll discovery → compute
assignment (respecting the blacklist) → launch → on worker death, blacklist
the host and relaunch → on discovery change, bump the membership epoch (the
workers' ``WorkerNotificationClient`` raises ``HostsUpdatedInterrupt`` at
their next commit, letting them exit cleanly for the relaunch).
"""

from __future__ import annotations

import dataclasses
import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence

from .hosts import HostSlots, assign_ranks, parse_hosts
from .. import config as config_mod
from ..obs import REGISTRY as _obs
from ..obs import flightrec as _frec
from ..utils import logging as hvd_logging
from ..utils import retry as _retry

log = hvd_logging.get_logger()

_m_worker_failures = _obs.counter(
    "hvd_elastic_worker_failures_total",
    "worker crashes that blacklisted a host")
_m_rendezvous_rounds = _obs.counter(
    "hvd_elastic_rendezvous_rounds_total",
    "job (re)launch rounds run by the elastic driver")
_m_hosts = _obs.gauge(
    "hvd_elastic_available_hosts",
    "non-blacklisted hosts in the current assignment")
_m_blacklisted = _obs.gauge(
    "hvd_elastic_blacklisted_hosts",
    "hosts currently serving a blacklist cooldown")
_m_epoch = _obs.gauge(
    "hvd_elastic_membership_epoch",
    "membership epoch of the assignment the driver last launched "
    "(aggregated per-rank, a lagging rank shows a stale epoch)")


class HostDiscovery:
    """† ``HostDiscovery`` interface."""

    def find_available_hosts(self) -> List[HostSlots]:
        raise NotImplementedError


class ScriptDiscovery(HostDiscovery):
    """† ``HostDiscoveryScript``: an executable printing ``host[:slots]``
    lines (the ``--host-discovery-script`` contract).  ``default_slots``
    applies to bare hostnames († the ``--slots`` flag)."""

    def __init__(self, script: str, timeout: float = 30.0,
                 default_slots: int = 1) -> None:
        self._script = script
        self._timeout = timeout
        self._default_slots = default_slots

    def find_available_hosts(self) -> List[HostSlots]:
        res = subprocess.run([self._script], capture_output=True, text=True,
                             timeout=self._timeout)
        if res.returncode != 0:
            raise RuntimeError(
                f"discovery script failed ({res.returncode}): {res.stderr}")
        lines = [line.strip() for line in res.stdout.splitlines()
                 if line.strip()]
        if not lines:
            return []
        spec = ",".join(
            line if ":" in line else f"{line}:{self._default_slots}"
            for line in lines)
        return parse_hosts(spec)


class FixedDiscovery(HostDiscovery):
    """Deterministic sequence of host lists (the reference's fake-discovery
    unit-test rig † ``test_elastic_driver.py``); repeats the last entry."""

    def __init__(self, *host_specs: str) -> None:
        self._specs = [parse_hosts(s) if s else [] for s in host_specs]
        self._i = 0

    def find_available_hosts(self) -> List[HostSlots]:
        spec = self._specs[min(self._i, len(self._specs) - 1)]
        self._i += 1
        return spec


@dataclasses.dataclass
class _BlacklistEntry:
    """One host's failure history: probation instead of a life sentence."""
    failures: int = 0
    until: float = 0.0         # monotonic instant the cooldown expires
    last_failure: float = 0.0


class ElasticDriver:
    """Membership brain: current hosts − blacklist → rank assignment.

    The blacklist DECAYS: a host's first crash excludes it for
    ``blacklist_cooldown_s``; when the cooldown lapses the host is
    re-admitted on probation, and a further crash doubles the cooldown
    (capped at ``blacklist_max_cooldown_s``).  A transient failure
    (preemption, OOM kill, flaky NIC) therefore costs bounded capacity,
    while a persistently bad host spends almost all its time excluded —
    the permanent blacklist it replaces ratcheted every transient
    failure toward ``min_np`` forever.  ``cooldown <= 0`` restores the
    permanent behavior.  ``clock`` is injectable so the decay schedule
    unit-tests without sleeping.
    """

    def __init__(self, discovery: HostDiscovery, *, min_np: int,
                 max_np: Optional[int] = None,
                 poll_interval_s: float = 1.0,
                 blacklist_cooldown_s: Optional[float] = None,
                 blacklist_max_cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if min_np < 1:
            raise ValueError("min_np must be >= 1")
        cfg = config_mod.from_env()
        self._discovery = discovery
        self.min_np = min_np
        self.max_np = max_np
        self._poll_interval = poll_interval_s
        self._cooldown = (cfg.blacklist_cooldown_s
                          if blacklist_cooldown_s is None
                          else blacklist_cooldown_s)
        self._max_cooldown = (cfg.blacklist_max_cooldown_s
                              if blacklist_max_cooldown_s is None
                              else blacklist_max_cooldown_s)
        self._clock = clock
        self._blacklist: dict[str, _BlacklistEntry] = {}
        self._lock = threading.Lock()
        self._current_hosts: List[HostSlots] = []
        self.membership_epoch = 0
        #: autoscaler-imposed world size; ``None`` = use full capacity.
        #: A voluntary shrink sets this below capacity so the next
        #: assignment retires ranks even though their hosts are healthy.
        self.target_np: Optional[int] = None

    # -- membership ---------------------------------------------------------
    def blacklist(self, hostname: str) -> None:
        """† ``registration.py``: a host whose worker crashed is excluded
        from future assignments — here for a decaying cooldown, not
        forever (see the class docstring)."""
        now = self._clock()
        with self._lock:
            e = self._blacklist.setdefault(hostname, _BlacklistEntry())
            e.failures += 1
            e.last_failure = now
            if self._cooldown > 0:
                cooldown = min(self._cooldown * (2 ** (e.failures - 1)),
                               self._max_cooldown)
                e.until = now + cooldown
            else:
                cooldown = float("inf")
                e.until = float("inf")
        _m_worker_failures.inc()
        _frec.RECORDER.record("elastic_blacklist", name=hostname,
                              failures=e.failures,
                              cooldown_s=(None if cooldown == float("inf")
                                          else round(cooldown, 3)))
        log.warning(
            "elastic: blacklisted host %s (failure #%d, cooldown %s)",
            hostname, e.failures,
            "permanent" if cooldown == float("inf")
            else f"{cooldown:.0f}s")

    def blacklisted(self) -> set[str]:
        """Hosts currently serving a cooldown.  Hosts whose cooldown
        lapsed are re-admitted (probation) but keep their failure
        count, so the next failure doubles the cooldown."""
        now = self._clock()
        out = set()
        readmitted = []
        with self._lock:
            for host, e in self._blacklist.items():
                if now < e.until:
                    out.add(host)
                elif e.until:      # lapsed since we last looked
                    e.until = 0.0
                    readmitted.append((host, e.failures))
        _m_blacklisted.set(len(out))
        for host, failures in readmitted:
            _frec.RECORDER.record("elastic_probation", name=host,
                                  failures=failures)
            log.warning(
                "elastic: blacklist cooldown lapsed for host %s "
                "(%d failure(s) so far) — re-admitting on probation",
                host, failures)
        return out

    def blacklist_failures(self, hostname: str) -> int:
        """Failure count a host has accrued (0 = never failed)."""
        with self._lock:
            e = self._blacklist.get(hostname)
            return e.failures if e else 0

    def poll_hosts(self) -> bool:
        """Refresh from discovery; returns True if membership changed."""
        hosts = [h for h in self._discovery.find_available_hosts()
                 if h.hostname not in self.blacklisted()]
        with self._lock:
            changed = hosts != self._current_hosts
            if changed:
                self._current_hosts = hosts
                self.membership_epoch += 1
        return changed

    def wait_for_available_slots(self, min_np: Optional[int] = None,
                                 timeout_s: float = 600.0
                                 ) -> List[HostSlots]:
        """† ``ElasticDriver.wait_for_available_slots``: block until at
        least min_np slots exist among non-blacklisted hosts.

        Discovery failures (the script crashing, timing out, or its
        host being briefly unreachable) no longer kill the driver: they
        back off on the shared retry policy — exponential, capped,
        deterministic jitter — and only the overall ``timeout_s``
        budget gives up.  A healthy poll resets the backoff to the
        plain poll interval."""
        need = min_np if min_np is not None else self.min_np
        deadline = time.monotonic() + timeout_s
        backoff = _retry.Backoff(
            _retry.RetryPolicy(max_attempts=None,
                               base_delay_s=max(0.05, self._poll_interval),
                               max_delay_s=max(8 * self._poll_interval,
                                               self._poll_interval)),
            op="elastic_discovery")
        last_err: Optional[Exception] = None
        while True:
            try:
                self.poll_hosts()
                backoff.reset()
                last_err = None
            except Exception as e:
                last_err = e
                log.warning("elastic: host discovery failed (%s); "
                            "retrying with backoff", e)
            with self._lock:
                hosts = list(self._current_hosts)
            if last_err is None and sum(h.slots for h in hosts) >= need:
                return hosts
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(
                    f"not enough hosts for min_np={need} within "
                    f"{timeout_s}s (have {hosts}, "
                    f"blacklist {sorted(self.blacklisted())}"
                    + (f", last discovery error: {last_err}" if last_err
                       else "") + ")")
            delay = (backoff.next_delay() if last_err is not None
                     else self._poll_interval)
            time.sleep(min(delay, max(0.0, deadline - now)))

    def assignment(self, hosts: Optional[Sequence[HostSlots]] = None
                   ) -> List[tuple[int, str, int]]:
        """Rank assignment over current (or given) hosts, capped at
        max_np and at the autoscaler's ``target_np`` when one is set."""
        if hosts is None:
            with self._lock:
                hosts = list(self._current_hosts)
        total = sum(h.slots for h in hosts)
        np_total = min(total, self.max_np or total, self.target_np or total)
        np_total = max(np_total, min(self.min_np, total))
        return assign_ranks(list(hosts), np_total)

    # -- supervision --------------------------------------------------------
    def run_job(self, command: Sequence[str], *,
                max_restarts: int = 10,
                extra_env: Optional[dict] = None,
                launcher: Optional[Callable] = None,
                on_epoch_change: Optional[Callable] = None,
                slot_timeout_s: float = 600.0,
                launch_kwargs: Optional[dict] = None,
                autoscale=None,
                autoscale_interval_s: float = 2.0) -> int:
        """Supervise the elastic job: (re)launch on the current assignment
        until it exits 0 or restarts are exhausted.

        ``launcher`` defaults to :func:`horovod_tpu.runner.launch.launch_workers`
        (injectable for tests); ``launch_kwargs`` forwards launcher knobs
        (ssh_port, verbose, connectivity_check, ...) to it.

        ``autoscale`` (an :class:`horovod_tpu.autoscale.PolicyConfig`)
        replaces the plain capacity growth watcher with the full
        closed-loop controller: each round launches an
        :class:`~horovod_tpu.autoscale.AutoscaleController` that polls the
        job's ``/cluster`` signals through the KV store and drives both
        grow and voluntary shrink via the membership-epoch bump.
        """
        last_np: dict = {"np": None}
        if launcher is None:
            from .launch import (
                RESTART_EXIT_CODE,
                VICTIM_EXIT_CODE,
                launch_workers,
            )

            def launcher(cmd, hosts, env):
                spec = ",".join(f"{h.hostname}:{h.slots}" for h in hosts)
                capacity_now = sum(h.slots for h in hosts)
                np_total = min(capacity_now, self.max_np or 10 ** 9,
                               self.target_np or 10 ** 9)
                np_total = max(np_total, min(self.min_np, capacity_now))
                env = dict(env)
                env["HVDTPU_AUTOSCALE_TARGET_NP"] = str(
                    self.target_np or np_total)
                prev_np, last_np["np"] = last_np["np"], np_total
                failure: dict = {}
                stop_watch = threading.Event()
                controller_box: list = []

                def autoscale_hook(services):
                    # Closed loop: sense (/cluster via the job KV) ->
                    # decide (ScalePolicy) -> act (epoch bump).  One
                    # controller per launch round; stopped when the
                    # round's workers exit.
                    from .._native import KvClient
                    from ..autoscale import AutoscaleController, ScalePolicy
                    from ..elastic.runner import WorkerNotificationClient
                    from ..obs.aggregate import ClusterAggregator

                    def kv_factory():
                        return KvClient("127.0.0.1", services.kv.port,
                                        secret=services.secret)

                    agg = ClusterAggregator(include_local=False,
                                            kv_factory=kv_factory)

                    def capacity() -> int:
                        try:
                            self.poll_hosts()
                        except Exception as e:
                            log.warning("autoscale: discovery poll "
                                        "failed: %s", e)
                        with self._lock:
                            return sum(h.slots
                                       for h in self._current_hosts)

                    def bump() -> None:
                        kv = kv_factory()
                        try:
                            WorkerNotificationClient.bump(kv)
                        finally:
                            kv.close()

                    def set_target(target: int) -> None:
                        self.target_np = target

                    controller_box.append(AutoscaleController(
                        ScalePolicy(autoscale),
                        current_np=np_total, prev_np=prev_np,
                        collect=agg.collect, bump=bump,
                        capacity=capacity, set_target=set_target,
                        interval_s=autoscale_interval_s).start())

                def services_hook(services):
                    if autoscale is not None:
                        return autoscale_hook(services)
                    # Growth watcher: while the job runs, poll discovery;
                    # when total capacity exceeds the running np (and
                    # max_np allows more), bump the membership epoch in
                    # the job's KV store — workers exit with the restart
                    # code at their next commit and we relaunch on the
                    # grown assignment († WorkerNotificationService push).
                    from .._native import KvClient
                    from ..elastic.runner import WorkerNotificationClient

                    def watch():
                        grown_polls = 0
                        while not stop_watch.wait(self._poll_interval):
                            try:
                                self.poll_hosts()
                                with self._lock:
                                    capacity = sum(
                                        h.slots
                                        for h in self._current_hosts)
                                growable = (capacity > np_total
                                            and np_total < (self.max_np
                                                            or 10 ** 9))
                                # Debounce (flaky discovery) and keep
                                # re-bumping while grown: a bump landing
                                # before a worker baselines its notifier
                                # epoch would otherwise be absorbed
                                # silently and the capacity never used.
                                grown_polls = (grown_polls + 1
                                               if growable else 0)
                                if grown_polls >= 2:
                                    kv = KvClient("127.0.0.1",
                                                  services.kv.port,
                                                  secret=services.secret)
                                    WorkerNotificationClient.bump(kv)
                                    kv.close()
                                    log.info(
                                        "elastic: capacity grew to %d "
                                        "slots (running np=%d); signaled "
                                        "workers to restart", capacity,
                                        np_total)
                            except Exception as e:
                                log.warning(
                                    "elastic: growth watcher error: %s", e)

                    threading.Thread(target=watch, daemon=True,
                                     name="hvdtpu-growth-watch").start()

                try:
                    code = launch_workers(cmd, np_total=np_total,
                                          hosts_spec=spec, extra_env=env,
                                          failure_info=failure,
                                          services_hook=services_hook,
                                          **(launch_kwargs or {}))
                finally:
                    stop_watch.set()
                    for c in controller_box:
                        c.stop()
                if code in (RESTART_EXIT_CODE, VICTIM_EXIT_CODE):
                    # Voluntary membership restart, or a victim of some
                    # other rank's fault: either way, the first-exiting
                    # worker is not the culprit — no blacklist.
                    return code
                if code != 0 and failure.get("host") and len(hosts) > 1:
                    # † registration.py: exclude the crashed worker's host
                    # from the next assignment.  Sole-host jobs keep their
                    # host (blacklisting it would make relaunch impossible;
                    # transient failures get the retry instead).
                    self.blacklist(failure["host"])
                return code

        from .launch import RESTART_EXIT_CODE

        restarts = 0
        voluntary = 0
        while True:
            hosts = self.wait_for_available_slots(timeout_s=slot_timeout_s)
            epoch = self.membership_epoch
            _m_rendezvous_rounds.inc()
            _m_hosts.set(len(hosts))
            _m_epoch.set(epoch)
            _frec.RECORDER.record("elastic_launch", epoch=epoch,
                                  hosts=len(hosts), restarts=restarts)
            log.info("elastic: launching on %s (epoch %d)", hosts, epoch)
            env = dict(extra_env or {})
            env["HVDTPU_ELASTIC"] = "1"
            code = launcher(list(command), hosts, env)
            if code == 0:
                return 0
            if code == RESTART_EXIT_CODE:
                # Voluntary membership restarts get their own (generous)
                # budget: a flapping discovery script alternating the
                # host list must not relaunch-loop the job forever.
                voluntary += 1
                if voluntary > max(10, max_restarts):
                    log.warning(
                        "elastic: %d voluntary restarts (flapping "
                        "discovery?); counting further ones against the "
                        "failure budget", voluntary)
                    restarts += 1
            else:
                restarts += 1
            if restarts > max_restarts:
                log.error("elastic: giving up after %d restarts",
                          restarts)
                return code
            # Refresh membership and let discovery/blacklist shape the
            # next assignment (a grown host list enlarges it; a crashed
            # host's blacklisting shrinks it).  A discovery hiccup here
            # is not fatal — the next wait_for_available_slots retries
            # it under the backoff policy.
            try:
                self.poll_hosts()
            except Exception as e:
                log.warning("elastic: post-round discovery poll failed "
                            "(%s); retrying at next slot wait", e)
            if on_epoch_change and self.membership_epoch != epoch:
                on_epoch_change(self.membership_epoch)
