"""Elastic driver: host discovery, blacklist, assignment, job supervision.

† ``horovod/runner/elastic/driver.py`` (``ElasticDriver``,
``HostAssignment``), ``discovery.py`` (``HostDiscovery`` script polling),
``registration.py`` (blacklist), ``worker.py`` (notification).

TPU adaptation (SURVEY §5.3): chip/slice failures are coarser than GPU-host
failures and XLA meshes are static, so membership changes restart the *job*
(workers reload from their committed state/checkpoints) rather than patching
a live ring.  The driver supervises that loop: poll discovery → compute
assignment (respecting the blacklist) → launch → on worker death, blacklist
the host and relaunch → on discovery change, bump the membership epoch (the
workers' ``WorkerNotificationClient`` raises ``HostsUpdatedInterrupt`` at
their next commit, letting them exit cleanly for the relaunch).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence

from .hosts import HostSlots, assign_ranks, parse_hosts
from ..utils import logging as hvd_logging

log = hvd_logging.get_logger()


class HostDiscovery:
    """† ``HostDiscovery`` interface."""

    def find_available_hosts(self) -> List[HostSlots]:
        raise NotImplementedError


class ScriptDiscovery(HostDiscovery):
    """† ``HostDiscoveryScript``: an executable printing ``host[:slots]``
    lines (the ``--host-discovery-script`` contract).  ``default_slots``
    applies to bare hostnames († the ``--slots`` flag)."""

    def __init__(self, script: str, timeout: float = 30.0,
                 default_slots: int = 1) -> None:
        self._script = script
        self._timeout = timeout
        self._default_slots = default_slots

    def find_available_hosts(self) -> List[HostSlots]:
        res = subprocess.run([self._script], capture_output=True, text=True,
                             timeout=self._timeout)
        if res.returncode != 0:
            raise RuntimeError(
                f"discovery script failed ({res.returncode}): {res.stderr}")
        lines = [line.strip() for line in res.stdout.splitlines()
                 if line.strip()]
        if not lines:
            return []
        spec = ",".join(
            line if ":" in line else f"{line}:{self._default_slots}"
            for line in lines)
        return parse_hosts(spec)


class FixedDiscovery(HostDiscovery):
    """Deterministic sequence of host lists (the reference's fake-discovery
    unit-test rig † ``test_elastic_driver.py``); repeats the last entry."""

    def __init__(self, *host_specs: str) -> None:
        self._specs = [parse_hosts(s) if s else [] for s in host_specs]
        self._i = 0

    def find_available_hosts(self) -> List[HostSlots]:
        spec = self._specs[min(self._i, len(self._specs) - 1)]
        self._i += 1
        return spec


class ElasticDriver:
    """Membership brain: current hosts − blacklist → rank assignment."""

    def __init__(self, discovery: HostDiscovery, *, min_np: int,
                 max_np: Optional[int] = None,
                 poll_interval_s: float = 1.0) -> None:
        if min_np < 1:
            raise ValueError("min_np must be >= 1")
        self._discovery = discovery
        self.min_np = min_np
        self.max_np = max_np
        self._poll_interval = poll_interval_s
        self._blacklist: set[str] = set()
        self._lock = threading.Lock()
        self._current_hosts: List[HostSlots] = []
        self.membership_epoch = 0

    # -- membership ---------------------------------------------------------
    def blacklist(self, hostname: str) -> None:
        """† ``registration.py``: a host whose worker crashed is excluded
        from future assignments."""
        with self._lock:
            self._blacklist.add(hostname)
        log.warning("elastic: blacklisted host %s", hostname)

    def blacklisted(self) -> set[str]:
        with self._lock:
            return set(self._blacklist)

    def poll_hosts(self) -> bool:
        """Refresh from discovery; returns True if membership changed."""
        hosts = [h for h in self._discovery.find_available_hosts()
                 if h.hostname not in self.blacklisted()]
        with self._lock:
            changed = hosts != self._current_hosts
            if changed:
                self._current_hosts = hosts
                self.membership_epoch += 1
        return changed

    def wait_for_available_slots(self, min_np: Optional[int] = None,
                                 timeout_s: float = 600.0
                                 ) -> List[HostSlots]:
        """† ``ElasticDriver.wait_for_available_slots``: block until at
        least min_np slots exist among non-blacklisted hosts."""
        need = min_np if min_np is not None else self.min_np
        deadline = time.monotonic() + timeout_s
        while True:
            self.poll_hosts()
            with self._lock:
                hosts = list(self._current_hosts)
            if sum(h.slots for h in hosts) >= need:
                return hosts
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"not enough hosts for min_np={need} within "
                    f"{timeout_s}s (have {hosts}, "
                    f"blacklist {sorted(self.blacklisted())})")
            time.sleep(self._poll_interval)

    def assignment(self, hosts: Optional[Sequence[HostSlots]] = None
                   ) -> List[tuple[int, str, int]]:
        """Rank assignment over current (or given) hosts, capped at max_np."""
        if hosts is None:
            with self._lock:
                hosts = list(self._current_hosts)
        total = sum(h.slots for h in hosts)
        np_total = min(total, self.max_np) if self.max_np else total
        return assign_ranks(list(hosts), np_total)

    # -- supervision --------------------------------------------------------
    def run_job(self, command: Sequence[str], *,
                max_restarts: int = 10,
                extra_env: Optional[dict] = None,
                launcher: Optional[Callable] = None,
                on_epoch_change: Optional[Callable] = None,
                slot_timeout_s: float = 600.0,
                launch_kwargs: Optional[dict] = None) -> int:
        """Supervise the elastic job: (re)launch on the current assignment
        until it exits 0 or restarts are exhausted.

        ``launcher`` defaults to :func:`horovod_tpu.runner.launch.launch_workers`
        (injectable for tests); ``launch_kwargs`` forwards launcher knobs
        (ssh_port, verbose, connectivity_check, ...) to it.
        """
        if launcher is None:
            from .launch import launch_workers

            def launcher(cmd, hosts, env):
                spec = ",".join(f"{h.hostname}:{h.slots}" for h in hosts)
                np_total = min(sum(h.slots for h in hosts),
                               self.max_np or 10 ** 9)
                failure: dict = {}
                code = launch_workers(cmd, np_total=np_total,
                                      hosts_spec=spec, extra_env=env,
                                      failure_info=failure,
                                      **(launch_kwargs or {}))
                if code != 0 and failure.get("host") and len(hosts) > 1:
                    # † registration.py: exclude the crashed worker's host
                    # from the next assignment.  Sole-host jobs keep their
                    # host (blacklisting it would make relaunch impossible;
                    # transient failures get the retry instead).
                    self.blacklist(failure["host"])
                return code

        restarts = 0
        while True:
            hosts = self.wait_for_available_slots(timeout_s=slot_timeout_s)
            epoch = self.membership_epoch
            log.info("elastic: launching on %s (epoch %d)", hosts, epoch)
            env = dict(extra_env or {})
            env["HVDTPU_ELASTIC"] = "1"
            code = launcher(list(command), hosts, env)
            if code == 0:
                return 0
            restarts += 1
            if restarts > max_restarts:
                log.error("elastic: giving up after %d restarts", restarts)
                return code
            # A nonzero exit means some worker died; refresh membership and
            # let discovery/blacklist shape the next assignment.
            self.poll_hosts()
            if on_epoch_change and self.membership_epoch != epoch:
                on_epoch_change(self.membership_epoch)
