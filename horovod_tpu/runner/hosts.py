"""Host-spec parsing († ``runner/common/util/hosts.py`` +
``runner/launch.py`` host handling).

Spec grammar: ``host1:slots1,host2:slots2`` (slots default 1), e.g.
``localhost:4`` or ``tpu-vm-0:8,tpu-vm-1:8``.
"""

from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass(frozen=True)
class HostSlots:
    hostname: str
    slots: int


def parse_hosts(spec: str) -> List[HostSlots]:
    out: List[HostSlots] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, slots = part.partition(":")
        if not host:
            raise ValueError(f"bad host entry {part!r} in {spec!r}")
        if sep:
            try:
                n = int(slots)
            except ValueError:
                raise ValueError(
                    f"bad slot count {slots!r} for host {host!r}") from None
            if n < 1:
                raise ValueError(f"slot count must be >= 1 for {host!r}")
        else:
            n = 1
        out.append(HostSlots(host, n))
    if not out:
        raise ValueError(f"no hosts in spec {spec!r}")
    return out


def assign_ranks(hosts: List[HostSlots], np_total: int
                 ) -> List[tuple[int, str, int]]:
    """(global_rank, hostname, local_rank) for each process, filling hosts
    in order († ``ElasticDriver.HostAssignment`` ordering semantics)."""
    total_slots = sum(h.slots for h in hosts)
    if np_total > total_slots:
        raise ValueError(
            f"requested np={np_total} exceeds {total_slots} available slots")
    out = []
    rank = 0
    for h in hosts:
        for local in range(h.slots):
            if rank >= np_total:
                return out
            out.append((rank, h.hostname, local))
            rank += 1
    return out


def host_hash(salt: str = "") -> str:
    """Stable identifier for THIS machine, for grouping ranks that share a
    host († ``runner/common/util/host_hash.py``: upstream hashes the
    hostname so ranks on one box agree on local-rank grouping even when
    launched under different names).

    ``HOROVOD_HOSTNAME`` overrides the detected hostname — the upstream
    escape hatch for containers where every worker reports the same
    hostname (or conversely where one machine answers to many).  ``salt``
    perturbs the hash the way upstream's ``--mpi-args`` salt does, for
    deliberately splitting co-located workers into separate groups.
    """
    import hashlib
    import os
    import socket

    # Native prefix wins over the compat prefix, as everywhere in config.
    name = os.environ.get("HVDTPU_HOSTNAME") or os.environ.get(
        "HOROVOD_HOSTNAME") or socket.gethostname()
    return hashlib.md5(f"{name}-{salt}".encode()).hexdigest()
