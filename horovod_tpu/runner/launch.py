"""hvdrun: spawn, wire, and babysit a multi-process job.

† ``horovod/runner/launch.py`` (CLI), ``gloo_run.py`` (rendezvous + env +
exec + monitor), ``safe_shell_exec.py`` (process-group kill semantics).

Flow (†3.4):
1. parse hosts/flags (every config knob has a CLI flag; ``--config-file``
   YAML mirrors them — the reference's three-surface rule);
2. start the native rendezvous KV store and the coordinator service in the
   launcher process;
3. exec one worker per rank — locally via subprocess, remotely via ssh —
   with the per-rank env (rank ids + service addresses);
4. stream output; on any worker failing, terminate the rest (monitor role).

Workers bootstrap in ``horovod_tpu.init()``: JAX distributed init against
the coordinator address, then the engine connects to the controller.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

from .hosts import assign_ranks, parse_hosts
from .. import chaos
from .. import config as config_mod


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


from .cluster import local_ip as _local_ip  # noqa: E402  (shared probe)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job "
                    "(reference parity: horovodrun)")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of processes")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print build capabilities and exit "
                        "(† horovodrun --check-build)")
    p.add_argument("-H", "--hosts", default=None,
                   help="host1:slots,host2:slots (default: localhost:np)")
    p.add_argument("--tpu-pod", action="store_true", default=False,
                   help="discover the host list from TPU-VM instance "
                        "metadata (worker-network-endpoints) instead of "
                        "-H; one process per host VM († driver_service "
                        "auto-discovery)")
    p.add_argument("--ssh-port", type=int, default=22)
    # Elastic mode († horovodrun --min-np/--max-np/--host-discovery-script):
    # hosts come from a user script polled by the ElasticDriver, which
    # supervises blacklist/relaunch instead of a single static launch.
    p.add_argument("--min-np", type=int, default=None,
                   help="minimum processes an elastic job may shrink to "
                        "(default: -np)")
    p.add_argument("--max-np", type=int, default=None,
                   help="maximum processes an elastic job may grow to "
                        "(default: -np)")
    p.add_argument("--host-discovery-script", default=None,
                   help="executable printing one 'host[:slots]' line per "
                        "available host; enables elastic mode")
    p.add_argument("--slots", type=int, default=None,
                   help="default slots per discovered host (elastic "
                        "discovery scripts printing bare hostnames; with "
                        "--tpu-pod only for setups partitioning chips "
                        "per-process themselves via TPU_VISIBLE_DEVICES)")
    p.add_argument("--autoscale", action="store_true", default=False,
                   help="close the loop between /cluster signals and "
                        "elastic rendezvous: the driver grows the job on "
                        "load pressure (queue depth / SLO burn) and "
                        "shrinks it when idle (elastic mode only; knobs "
                        "via HVDTPU_AUTOSCALE_*)")
    p.add_argument("--autoscale-interval", type=float, default=None,
                   help="seconds between autoscale control ticks "
                        "(default 2.0)")
    p.add_argument("--elastic-timeout", type=float, default=None,
                   help="seconds to wait for min-np slots before giving up "
                        "(default 600)")
    p.add_argument("--start-timeout", type=float, default=120.0,
                   help="seconds to wait for all workers to register")
    p.add_argument("--config-file", default=None,
                   help="YAML file of knobs (mirrors CLI flags)")
    # Tuning knobs († horovodrun flags mirroring HOROVOD_* envs).
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--autotune", action="store_true", default=False)
    p.add_argument("--autotune-log", default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-dir", default=None,
                   help="write one Timeline v2 file per rank "
                        "(<dir>/rank<r>.json) and merge the local ones "
                        "into <dir>/merged.json after the run — one "
                        "Perfetto trace, one pid lane per rank "
                        "(python -m horovod_tpu.utils.timeline merge)")
    p.add_argument("--timeline-mark-cycles", action="store_true",
                   default=False)
    p.add_argument("--log-level", default=None)
    p.add_argument("--stall-warning-time", type=float, default=None)
    p.add_argument("--platform", default=None, choices=("tpu", "cpu"),
                   help="JAX platform workers select at init() "
                        "(cpu = the dev rig; default: auto)")
    p.add_argument("--no-connectivity-check", action="store_true",
                   default=False,
                   help="skip the multi-host NIC discovery / connectivity "
                        "probe stage († driver_service probe round)")
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program to run (e.g. python train.py)")
    return p


def _knob_env(args) -> dict:
    env = {}
    if args.config_file:
        cfg = config_mod.from_yaml(args.config_file)
        defaults = config_mod.Config()
        for field, suffix, _ in config_mod._ENV_TABLE:
            val = getattr(cfg, field, None)
            if val is not None and val != getattr(defaults, field):
                if isinstance(val, bool):
                    val = "1" if val else "0"
                env["HVDTPU_" + suffix] = str(val)
    if args.fusion_threshold_mb is not None:
        env["HVDTPU_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cycle_time_ms is not None:
        env["HVDTPU_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HVDTPU_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.autotune:
        env["HVDTPU_AUTOTUNE"] = "1"
    if args.autotune_log:
        env["HVDTPU_AUTOTUNE_LOG"] = args.autotune_log
    if args.timeline_filename:
        env["HVDTPU_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HVDTPU_TIMELINE_MARK_CYCLES"] = "1"
    if args.log_level:
        env["HVDTPU_LOG_LEVEL"] = args.log_level
    if args.stall_warning_time is not None:
        env["HVDTPU_STALL_CHECK_TIME_SECONDS"] = str(args.stall_warning_time)
    if args.platform:
        env["HVDTPU_PLATFORM"] = args.platform
    return env


class _Worker:
    def __init__(self, rank: int, proc: subprocess.Popen) -> None:
        self.rank = rank
        self.proc = proc


# Reserved worker exit code: "membership changed — restart me on the new
# assignment".  The monitor still tears the job down, but the elastic
# driver relaunches without blacklisting anyone (a voluntary restart is
# not a fault).  EX_TEMPFAIL by analogy.
RESTART_EXIT_CODE = 75
# Reserved worker exit code: "a collective failed UNDER me — I am a
# victim of some other rank's fault, not the fault itself".  The driver
# relaunches but must not blacklist this worker's host: with a hung
# (never-exiting) peer, the victim's exit is the FIRST the monitor sees,
# and blacklisting by first-exit would permanently evict a healthy host.
VICTIM_EXIT_CODE = 76


def launch_workers(command: Sequence[str], *, np_total: int,
                   hosts_spec: Optional[str] = None,
                   extra_env: Optional[dict] = None,
                   ssh_port: int = 22,
                   verbose: bool = False,
                   prefix_output: bool = True,
                   connectivity_check: bool = True,
                   failure_info: Optional[dict] = None,
                   services_hook=None,
                   timeline_dir: Optional[str] = None) -> int:
    """Start services + workers; wait; return exit code.  Local ranks run as
    child processes, remote ranks through ``ssh`` († gloo_run exec path).

    ``services_hook(services)`` runs once the control-plane services are
    up — the elastic driver uses it to reach the job's KV store for
    membership-epoch notifications while the job runs."""
    from .cluster import DriverServices, pick_coordinator_port

    hosts = parse_hosts(hosts_spec) if hosts_spec else \
        parse_hosts(f"localhost:{np_total}")
    assignment = assign_ranks(hosts, np_total)

    my_ip = _local_ip()
    is_local_job = all(h in ("localhost", "127.0.0.1", my_ip)
                       for _, h, _ in assignment)
    service_ip = "127.0.0.1" if is_local_job else my_ip

    # Per-job shared secret authenticating every control-plane frame
    # († secret.py: random HMAC secret per horovodrun invocation).  Reuse an
    # inherited one so elastic re-launches keep the same credential.
    import secrets as _secrets
    job_secret = ((extra_env or {}).get("HVDTPU_SECRET")
                  or os.environ.get("HVDTPU_SECRET")
                  or _secrets.token_hex(16))
    # Publish to this process so driver-side clients (elastic notification,
    # re-launches) authenticate with the same credential; assignment (not
    # setdefault) so an explicitly passed secret wins over a stale one.
    os.environ["HVDTPU_SECRET"] = job_secret

    # The stall knobs decide controller behavior (round-abort timeout;
    # the stall inspector's straggler-attribution horizon); they may
    # arrive via --config-file (worker-env only), so consult the worker
    # env block before the launcher's own env, under every prefix the
    # worker-side config parser accepts (config._PREFIXES).
    def _stall_knob(suffix: str) -> Optional[float]:
        for src in (extra_env or {}, os.environ):
            for prefix in ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_"):
                raw = src.get(prefix + suffix)
                if raw:
                    try:
                        return float(raw)
                    except ValueError:
                        return None  # config rejects it worker-side
        return None

    stall_shutdown_s = _stall_knob("STALL_SHUTDOWN_TIME_SECONDS")
    stall_warn_s = _stall_knob("STALL_CHECK_TIME_SECONDS")
    services = DriverServices(np_total, service_ip=service_ip,
                              secret=job_secret,
                              stall_shutdown_s=stall_shutdown_s,
                              stall_warn_s=stall_warn_s)
    if services_hook is not None:
        try:
            services_hook(services)
        except Exception as e:  # the hook must never kill the launch
            print(f"[launcher] services_hook failed: {e}", file=sys.stderr)
    if is_local_job:
        coord_port = _free_port()
        coord_host = "127.0.0.1"
    else:
        coord_port = pick_coordinator_port()
        coord_host = assignment[0][1]
        if connectivity_check:
            # NIC discovery + connectivity probe round († driver_service
            # probe tasks): pick a driver address every host can actually
            # reach and the coordinator host's peer-visible address,
            # instead of trusting the default-route IP and DNS names.
            try:
                routing = _run_probe_stage(
                    hosts, services, my_ip=my_ip, ssh_port=ssh_port,
                    verbose=verbose)
            except Exception as e:
                # Any probe-stage failure must release the KV/controller
                # servers and surface a named diagnosis, whatever the
                # exception type (KV waits raise TimeoutError etc.).
                services.close()
                print(f"[launcher] connectivity check failed: {e}",
                      file=sys.stderr)
                raise
            if routing["driver_addr"]:
                services.service_ip = routing["driver_addr"]
            coord_host = routing["host_addrs"].get(
                assignment[0][1], coord_host)
            if verbose:
                print(f"[launcher] probe: driver={services.service_ip} "
                      f"coordinator={coord_host} nics={routing['nics']}",
                      file=sys.stderr)

    workers: List[_Worker] = []
    failed = threading.Event()
    exit_codes: dict[int, int] = {}

    if timeline_dir:
        os.makedirs(timeline_dir, exist_ok=True)

    def base_env(rank: int, local_rank: int) -> dict:
        # Full process env (ssh-launched workers inherit the launcher's
        # environment) + the shared control-plane block.
        env = dict(os.environ)
        env.update(services.worker_env(
            rank, local_rank,
            coordinator_addr=f"{coord_host}:{coord_port}",
            extra_env=extra_env))
        if timeline_dir:
            # One Timeline v2 file per rank; merged after the run into
            # a single multi-lane Perfetto trace.
            env["HVDTPU_TIMELINE"] = os.path.join(
                timeline_dir, f"rank{rank}.json")
        return env

    def stream(worker: _Worker) -> None:
        assert worker.proc.stdout is not None
        for line in worker.proc.stdout:
            if prefix_output:
                sys.stdout.write(f"[{worker.rank}]<stdout>: {line}")
            else:
                sys.stdout.write(line)
            sys.stdout.flush()

    try:
        for rank, host, local_rank in assignment:
            # Chaos site: one traversal per worker spawned.  err aborts
            # the launch (the elastic driver counts it as a failed
            # round and relaunches); delay staggers worker starts.
            chaos.fire("spawn")
            env = base_env(rank, local_rank)
            if host in ("localhost", "127.0.0.1", my_ip):
                proc = subprocess.Popen(
                    list(command), env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, start_new_session=True)
            else:
                # ssh fan-out: env goes on the remote command line since ssh
                # doesn't forward arbitrary vars († gloo_run builds the same
                # `ssh host env K=V ... cmd` line) — EXCEPT the job secret,
                # which would be world-readable in /proc/<pid>/cmdline on
                # the remote host; it travels over ssh stdin instead.
                # Forward the control-plane block, interpreter paths, AND
                # every caller-supplied extra_env key — the remote shell
                # starts from a fresh ssh environment, so anything not on
                # this line is silently dropped for remote ranks.
                forwarded = set(extra_env or ())
                env_kv = " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env.items()
                    if k != "HVDTPU_SECRET"
                    and (k in forwarded
                         or k.startswith(("HVDTPU_", "HOROVOD_", "PATH",
                                          "PYTHONPATH"))))
                remote = ("IFS= read -r HVDTPU_SECRET && "
                          "export HVDTPU_SECRET && "
                          f"cd {shlex.quote(os.getcwd())} && env {env_kv} "
                          + " ".join(shlex.quote(c) for c in command))
                proc = subprocess.Popen(
                    ["ssh", "-p", str(ssh_port),
                     "-o", "StrictHostKeyChecking=no", host, remote],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, start_new_session=True)
                try:
                    assert proc.stdin is not None
                    proc.stdin.write(job_secret + "\n")
                    proc.stdin.close()
                except (BrokenPipeError, OSError):
                    pass  # ssh died instantly; the monitor reports it

            worker = _Worker(rank, proc)
            workers.append(worker)
            threading.Thread(target=stream, args=(worker,),
                             daemon=True).start()

        # Monitor († launcher kills everyone when any worker dies nonzero).
        pending = {w.rank: w for w in workers}
        code = 0
        while pending:
            # Chaos site: one traversal per monitor liveness pass (the
            # launcher's heartbeat over its workers) — a driver-side
            # fault here tears the job down like a dying launcher would.
            chaos.fire("heartbeat")
            for rank_id, w in list(pending.items()):
                rc = w.proc.poll()
                if rc is None:
                    continue
                exit_codes[rank_id] = rc
                del pending[rank_id]
                if rc != 0 and not failed.is_set():
                    failed.set()
                    code = rc
                    if failure_info is not None:
                        # First failure only: later nonzero exits are the
                        # launcher's own SIGTERMs, not independent faults
                        # († blacklist the host that actually crashed).
                        host = next(h for r, h, _ in assignment
                                    if r == rank_id)
                        failure_info.update(
                            {"rank": rank_id, "host": host, "code": rc})
                    if verbose:
                        print(f"[launcher] rank {rank_id} exited {rc}; "
                              "terminating remaining workers",
                              file=sys.stderr)
                    for other in pending.values():
                        _terminate(other.proc)
            time.sleep(0.1)
        if timeline_dir:
            _merge_timeline_dir(timeline_dir, np_total, verbose=verbose)
        return code
    finally:
        for w in workers:
            if w.proc.poll() is None:
                _terminate(w.proc)
        services.close()


def _merge_timeline_dir(timeline_dir: str, np_total: int, *,
                        verbose: bool = False) -> None:
    """Best-effort post-run merge of the per-rank timelines written on
    THIS host (ssh-launched ranks write on their own hosts) into
    ``<dir>/merged.json`` — one trace, one pid lane per rank.  Only THIS
    launch's ranks are merged: a reused dir (shrunk -np, elastic epoch)
    may hold rank files from a previous larger run, and rebasing those
    dead-epoch traces onto this run's clock would fabricate lanes."""
    rank_files = [
        path for r in range(np_total)
        if os.path.exists(path := os.path.join(timeline_dir,
                                               f"rank{r}.json"))]
    if not rank_files:
        return
    from ..utils.timeline import merge_timelines
    out = os.path.join(timeline_dir, "merged.json")
    try:
        summary = merge_timelines(out, rank_files)
    except (OSError, ValueError) as e:
        print(f"[launcher] timeline merge failed: {e}", file=sys.stderr)
        return
    print(f"[launcher] merged {len(summary['ranks'])} rank timeline(s) "
          f"-> {out}", file=sys.stderr)


def _run_probe_stage(hosts, services, *, my_ip: str, ssh_port: int,
                     verbose: bool = False) -> dict:
    """Spawn one probe task per job host (ssh for remote, subprocess for
    the driver's own host) and aggregate via :mod:`.probe`."""
    from .probe import local_addresses, run_probe_stage
    from .._native import KvClient

    host_keys = []
    for h in hosts:
        if h.hostname not in host_keys:
            host_keys.append(h.hostname)
    candidates = ",".join(local_addresses())
    kv_port = services.kv.port
    secret = services.secret

    def launch_fn(host: str) -> subprocess.Popen:
        argv = [sys.executable, "-m", "horovod_tpu.runner.probe",
                host, candidates, str(kv_port)]
        if host in ("localhost", "127.0.0.1", my_ip):
            env = dict(os.environ)
            env["HVDTPU_SECRET"] = secret
            return subprocess.Popen(argv, env=env,
                                    stdout=subprocess.DEVNULL
                                    if not verbose else None)
        env_kv = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in os.environ.items()
            if k != "HVDTPU_SECRET"
            and k.startswith(("HVDTPU_", "PATH", "PYTHONPATH")))
        remote = ("IFS= read -r HVDTPU_SECRET && export HVDTPU_SECRET && "
                  f"cd {shlex.quote(os.getcwd())} && env {env_kv} "
                  + " ".join(shlex.quote(c) for c in argv))
        proc = subprocess.Popen(
            ["ssh", "-p", str(ssh_port),
             "-o", "StrictHostKeyChecking=no", host, remote],
            stdin=subprocess.PIPE, text=True,
            stdout=subprocess.DEVNULL if not verbose else None)
        try:
            assert proc.stdin is not None
            proc.stdin.write(secret + "\n")
            proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass
        return proc

    kv = KvClient("127.0.0.1", kv_port, secret=secret)
    try:
        return run_probe_stage(host_keys, kv=kv, launch_fn=launch_fn)
    finally:
        kv.close()


def _terminate(proc: subprocess.Popen) -> None:
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def run(command: Sequence[str], np: int, *, hosts: Optional[str] = None,
        env: Optional[dict] = None, verbose: bool = False) -> int:
    """Python API († ``horovod.run``)."""
    return launch_workers(command, np_total=np, hosts_spec=hosts,
                          extra_env=env, verbose=verbose)


def _check_build() -> int:
    """† ``horovodrun --check-build``: print what this build supports."""
    import horovod_tpu as hvd

    def have(mod: str) -> bool:
        import importlib.util
        return importlib.util.find_spec(mod) is not None

    def mark(flag: bool) -> str:
        return "[X]" if flag else "[ ]"

    print("horovod_tpu:\n")
    print("Available Frameworks:")
    print(f"    {mark(True)} JAX / Flax")
    print(f"    {mark(have('tensorflow'))} TensorFlow / Keras")
    print(f"    {mark(have('torch'))} PyTorch")
    print("\nAvailable Controllers:")
    print(f"    {mark(hvd.native_built())} native (C++ KV + coordinator)")
    print(f"    {mark(True)} JAX coordination service")
    print("\nAvailable Tensor Operations:")
    print(f"    {mark(hvd.xla_built())} XLA collectives (ICI/DCN on TPU)")
    print(f"    {mark(True)} CPU (host-platform devices)")
    print(f"    {mark(hvd.nccl_built() > 0)} NCCL")
    print(f"    {mark(hvd.mpi_built())} MPI")
    print(f"    {mark(hvd.gloo_built())} Gloo-role rendezvous")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.check_build:
        try:
            return _check_build()
        except BrokenPipeError:  # e.g. piped into `head`
            return 0
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    if args.tpu_pod:
        if args.hosts:
            print("hvdrun: --tpu-pod conflicts with -H/--hosts",
                  file=sys.stderr)
            return 2
        from .cloud import MetadataUnavailable, tpu_pod_hosts
        try:
            pod = tpu_pod_hosts(default_slots=args.slots)
        except MetadataUnavailable as e:
            print(f"hvdrun: {e}", file=sys.stderr)
            return 2
        args.hosts = ",".join(f"{h.hostname}:{h.slots}" for h in pod)
        args.slots = None   # consumed; keep the elastic-only guard honest
        if args.num_proc is None:
            args.num_proc = sum(h.slots for h in pod)
        if args.verbose:
            print(f"[launcher] tpu-pod discovery: {args.hosts}",
                  file=sys.stderr)
    if args.num_proc is None or args.num_proc < 1:
        print("hvdrun: -np/--num-proc (>= 1) is required", file=sys.stderr)
        return 2
    extra_env = _knob_env(args)
    if args.host_discovery_script:
        return run_elastic(command, args, extra_env)
    if (args.min_np is not None or args.max_np is not None
            or args.slots is not None or args.elastic_timeout is not None
            or args.autoscale or args.autoscale_interval is not None):
        print("hvdrun: --min-np/--max-np/--slots/--elastic-timeout/"
              "--autoscale require --host-discovery-script (elastic "
              "mode)", file=sys.stderr)
        return 2
    return launch_workers(command, np_total=args.num_proc,
                          hosts_spec=args.hosts, extra_env=extra_env,
                          ssh_port=args.ssh_port, verbose=args.verbose,
                          connectivity_check=not args.no_connectivity_check,
                          timeline_dir=args.timeline_dir)


def run_elastic(command: Sequence[str], args, extra_env: dict) -> int:
    """Elastic CLI path († ``horovodrun -np 2 --min-np 1
    --host-discovery-script ./d.sh python train.py``): hand supervision to
    the ElasticDriver, which polls discovery, blacklists crashed hosts,
    and relaunches on the surviving assignment; workers resume from their
    last ``state.commit()``."""
    from .elastic import ElasticDriver, ScriptDiscovery

    if args.hosts:
        print("hvdrun: -H/--hosts conflicts with --host-discovery-script "
              "(elastic hosts come from the discovery script)",
              file=sys.stderr)
        return 2
    min_np = args.min_np if args.min_np is not None else args.num_proc
    max_np = args.max_np if args.max_np is not None else args.num_proc
    if not (1 <= min_np <= args.num_proc <= max_np):
        print(f"hvdrun: need 1 <= min-np ({min_np}) <= np "
              f"({args.num_proc}) <= max-np ({max_np})", file=sys.stderr)
        return 2
    discovery = ScriptDiscovery(args.host_discovery_script,
                                default_slots=args.slots or 1)
    driver = ElasticDriver(discovery, min_np=min_np, max_np=max_np)
    cfg = config_mod.from_env()
    autoscale = None
    if args.autoscale or cfg.autoscale:
        from ..autoscale import PolicyConfig
        autoscale = PolicyConfig(
            min_np=min_np, max_np=max_np,
            queue_high=cfg.autoscale_queue_high,
            queue_low=cfg.autoscale_queue_low,
            burn_threshold=cfg.autoscale_burn_threshold,
            scale_up_cooldown_s=cfg.autoscale_up_cooldown_s,
            scale_down_cooldown_s=cfg.autoscale_down_cooldown_s,
            stale_after_s=cfg.autoscale_stale_s,
            forecast_horizon_s=cfg.autoscale_forecast_horizon_s)
    return driver.run_job(
        command, extra_env=extra_env,
        autoscale=autoscale,
        autoscale_interval_s=(args.autoscale_interval
                              if args.autoscale_interval is not None
                              else cfg.autoscale_interval_s),
        slot_timeout_s=(args.elastic_timeout
                        if args.elastic_timeout is not None else 600.0),
        launch_kwargs={
            "ssh_port": args.ssh_port,
            "verbose": args.verbose,
            "connectivity_check": not args.no_connectivity_check,
            # Per-epoch rank timelines share the dir; each relaunch
            # overwrites rank files and refreshes merged.json.
            "timeline_dir": args.timeline_dir,
        })


if __name__ == "__main__":
    sys.exit(main())
