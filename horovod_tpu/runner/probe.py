"""Host/NIC discovery + connectivity probe stage.

† ``runner/driver/driver_service.py`` + ``runner/task_fn.py``: before
launching the real job on multiple hosts, the driver runs a probe task on
every host.  Each task

1. discovers its own IPv4 addresses (NIC inventory),
2. finds which of the driver's candidate addresses it can actually reach
   (interface selection — the launcher must not assume its default-route
   IP is routable from every host),
3. registers both in the rendezvous KV store, and
4. after all hosts registered, TCP-connects to every peer's probe
   listener (the reference's dummy connectivity check), reporting which
   peer address worked.

The driver aggregates: a driver address reachable from every host, each
host's usable address as seen by its peers (used for the JAX coordinator
host), and hard errors listing exactly which pairs cannot talk.

The probe task runs as ``python -m horovod_tpu.runner.probe <host_key>
<driver_addr1,addr2,...> <kv_port>`` over ssh with ``HVDTPU_SECRET`` in
the environment.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional


def local_addresses() -> List[str]:
    """This host's IPv4 addresses, most-routable first (NIC inventory).

    `ip -o -4 addr` when available (Linux), else the UDP-connect trick +
    hostname resolution.  Loopback is kept last so single-host dev jobs
    still match.
    """
    addrs: List[str] = []
    try:
        out = subprocess.run(["ip", "-o", "-4", "addr", "show"],
                             capture_output=True, text=True, timeout=5)
        for line in out.stdout.splitlines():
            parts = line.split()
            if "inet" in parts:
                a = parts[parts.index("inet") + 1].split("/")[0]
                addrs.append(a)
    except (OSError, subprocess.TimeoutExpired):
        pass
    if not addrs:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect(("8.8.8.8", 80))
                addrs.append(s.getsockname()[0])
            finally:
                s.close()
        except OSError:
            pass
        try:
            for info in socket.getaddrinfo(socket.gethostname(), None,
                                           socket.AF_INET):
                addrs.append(info[4][0])
        except OSError:
            pass
    seen = set()
    ordered = []
    for a in addrs:
        if a not in seen:
            seen.add(a)
            ordered.append(a)
    # loopback last
    ordered.sort(key=lambda a: a.startswith("127."))
    return ordered or ["127.0.0.1"]


def _try_connect(addr: str, port: int, timeout: float = 3.0) -> bool:
    try:
        with socket.create_connection((addr, port), timeout=timeout):
            return True
    except OSError:
        return False


def probe_task(host_key: str, driver_candidates: List[str], kv_port: int,
               *, peer_timeout: float = 30.0) -> int:
    """The per-host probe body (runs over ssh on each job host)."""
    from .._native import KvClient

    # (2) interface selection: first driver candidate we can reach.
    driver_addr = next(
        (a for a in driver_candidates if _try_connect(a, kv_port)), None)
    if driver_addr is None:
        print(f"probe[{host_key}]: driver unreachable on any of "
              f"{driver_candidates} port {kv_port}", file=sys.stderr)
        return 3
    kv = KvClient(driver_addr, kv_port, timeout_ms=10000)

    # Probe listener other hosts connect to (the dummy data-plane check).
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("", 0))
    srv.listen(64)
    listen_port = srv.getsockname()[1]
    stop = threading.Event()

    def accept_loop() -> None:
        srv.settimeout(0.5)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    t = threading.Thread(target=accept_loop, daemon=True)
    t.start()

    # (1)+(3) register NICs + chosen driver addr + listener port.
    kv.set(f"probe/{host_key}", json.dumps({
        "addrs": local_addresses(),
        "driver_addr": driver_addr,
        "listen_port": listen_port,
    }).encode())

    # (4) wait for the roster, then connect to every peer.  A driver-side
    # abort (another host failed) closes the KV server mid-wait; exit
    # with a clean one-line diagnosis, not a traceback — the driver
    # already printed which host actually broke.
    try:
        roster = json.loads(kv.wait("probe/all",
                                    timeout_ms=int(peer_timeout * 1000)))
        results: Dict[str, Optional[str]] = {}
        for peer in roster:
            if peer == host_key:
                continue
            info = json.loads(kv.wait(f"probe/{peer}", timeout_ms=10000))
            ok = next((a for a in info["addrs"]
                       if _try_connect(a, info["listen_port"])), None)
            results[peer] = ok
        kv.set(f"probe/{host_key}/connectivity",
               json.dumps(results).encode())
    except (TimeoutError, ConnectionError, OSError) as e:
        print(f"probe[{host_key}]: aborted — driver ended the probe round "
              f"({e.__class__.__name__}); see the launcher's diagnostics",
              file=sys.stderr)
        stop.set()
        srv.close()
        return 5
    # Hold the listener open until the driver announces completion, so
    # slower peers can still connect to us.
    try:
        kv.wait("probe/done", timeout_ms=int(peer_timeout * 1000))
    except TimeoutError:
        pass
    stop.set()
    srv.close()
    kv.close()
    return 0 if all(results.values()) or not results else 4


def run_probe_stage(host_keys: List[str], *, kv, launch_fn,
                    timeout: float = 60.0) -> dict:
    """Driver half: launch a probe on every host via ``launch_fn(host)
    -> Popen``, aggregate registrations, and return the routing
    decisions.

    Returns ``{"driver_addr": addr reachable from every host,
    "host_addrs": {host: addr its peers reached it on}}``.
    Raises RuntimeError naming the exact unreachable pairs.
    """
    procs = {h: launch_fn(h) for h in host_keys}
    deadline = time.monotonic() + timeout
    infos: Dict[str, dict] = {}
    for h in host_keys:
        remaining = max(1, int((deadline - time.monotonic()) * 1000))
        try:
            infos[h] = json.loads(kv.wait(f"probe/{h}",
                                          timeout_ms=remaining))
        except TimeoutError:
            rc = procs[h].poll()
            raise RuntimeError(
                f"host {h!r} never registered with the driver "
                f"(probe rc={rc}); it cannot reach the driver's KV "
                "service — check -H spec, ssh, and firewalls") from None
    kv.set("probe/all", json.dumps(host_keys).encode())

    conn: Dict[str, Dict[str, Optional[str]]] = {}
    for h in host_keys:
        remaining = max(1, int((deadline - time.monotonic()) * 1000))
        try:
            conn[h] = json.loads(kv.wait(f"probe/{h}/connectivity",
                                         timeout_ms=remaining))
        except (TimeoutError, ConnectionError) as e:
            raise RuntimeError(
                f"host {h!r} registered but never finished its peer "
                f"connectivity round ({e.__class__.__name__}); its probe "
                "task likely died mid-check — inspect ssh/network on that "
                "host") from None
    kv.set("probe/done", b"1")
    for p in procs.values():
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()

    bad = [(h, peer) for h, r in conn.items()
           for peer, ok in r.items() if not ok]
    if bad:
        raise RuntimeError(
            "connectivity check failed — unreachable host pairs: "
            + ", ".join(f"{a}->{b}" for a, b in bad))

    # Driver address every host agreed on (per-host choices must overlap).
    chosen = {infos[h]["driver_addr"] for h in host_keys}
    driver_addr = chosen.pop() if len(chosen) == 1 else None
    # Per-host address as actually reached by its peers (majority pick).
    host_addrs: Dict[str, str] = {}
    for h in host_keys:
        votes = [r[h] for r in conn.values() if r.get(h)]
        host_addrs[h] = (max(set(votes), key=votes.count) if votes
                         else infos[h]["addrs"][0])
    return {"driver_addr": driver_addr, "host_addrs": host_addrs,
            "nics": {h: infos[h]["addrs"] for h in host_keys}}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 3:
        print("usage: python -m horovod_tpu.runner.probe "
              "<host_key> <driver_addr1,addr2,...> <kv_port>",
              file=sys.stderr)
        return 2
    host_key, cands, port = argv
    return probe_task(host_key, [a for a in cands.split(",") if a],
                      int(port))


if __name__ == "__main__":
    sys.exit(main())
