"""Continuous-batching inference engine with a paged KV cache.

ABSENT in the reference (Horovod is a training collective layer); this is
the serving counterpart the ROADMAP's "heavy traffic from millions of
users" north star needs.  The batch-synchronous
:func:`horovod_tpu.models.llama.generate` decodes one fixed batch at one
shared sequence length — a single long request stalls the whole batch and
every short request pays worst-case KV memory.  This package replaces that
with request-level scheduling:

- :mod:`~horovod_tpu.serving.kv_pager` — block-paged KV cache over the
  grouped ``[B, S, KV, D]`` layout: a free-list allocator, per-request
  block tables, and paged-attention dispatch (gather-by-block-table under
  XLA, scalar-prefetch BlockSpec routing in the Pallas kernel).
- :mod:`~horovod_tpu.serving.scheduler` — continuous batching: admission
  queue, prefill/decode phase split, per-step join/evict, and a prefill
  token budget that bounds decode latency.
- :mod:`~horovod_tpu.serving.engine` — the serving loop owning compiled
  prefill/decode step functions (bucketed shapes bound recompiles) on
  dp/tp meshes.
- :mod:`~horovod_tpu.serving.api` — ``serve()`` front door: ``submit()``
  futures, streaming token callbacks, per-request TTFT / queue-wait /
  tok/s metrics.
- :mod:`~horovod_tpu.serving.frontdoor` — the production front door on
  top of one-replica sessions: a multi-replica router over the obs
  plane's KV-store signals, a radix prefix cache that lets shared prompt
  prefixes skip prefill, and draft-model speculative decoding.
- :mod:`~horovod_tpu.serving.disagg` — disaggregated prefill/decode:
  pool-tagged replicas, cross-replica KV-block migration over the job
  KV store (versioned manifest + chunked payloads, one shared retry
  deadline), and a pool-aware router whose migration handoff is
  first-class state with durable-point failover.

The split follows HiCCL's policy/transport separation (arXiv:2408.05962):
the scheduler decides *what* runs each step, the engine owns *how* it runs
on the mesh.
"""

from .api import RequestResult, ServingSession, serve  # noqa: F401
from .engine import EngineConfig, ServingEngine  # noqa: F401
from .kv_pager import KVPager, PagedKVCache  # noqa: F401
from .scheduler import Request, Scheduler  # noqa: F401
