"""The serving front door: ``serve()`` → submit futures, stream tokens.

Wraps :class:`~horovod_tpu.serving.engine.ServingEngine` with the
request-facing surface a client sees:

- ``submit(prompt, max_tokens) -> concurrent.futures.Future`` resolving
  to a :class:`RequestResult` (tokens + per-request metrics);
- optional per-token streaming callbacks, invoked in emission order;
- per-request metrics — TTFT, queue wait, decode tok/s — routed into the
  process metrics registry (:mod:`horovod_tpu.obs`: TTFT/ITL histograms,
  request/token counters), logged through
  :mod:`horovod_tpu.utils.logging` and traced as QUEUE (submit → first
  token, prefill included) → DECODE spans on
  :class:`horovod_tpu.utils.timeline.Timeline` (one timeline row per
  request, the reference's per-tensor layout).

The loop can be driven synchronously (:meth:`ServingSession.drain` — the
deterministic mode tests and benchmarks use) or by a background thread
(:meth:`ServingSession.start`), with submissions safe from any thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..obs import REGISTRY as _obs
from ..obs import flightrec as _frec
from ..ops.engine import HorovodInternalError
from ..utils import logging as hvd_logging
from ..utils.timeline import Timeline
from .engine import EngineConfig, ServingEngine
from .scheduler import Request

log = hvd_logging.get_logger()

# Request-level latency series (horovod_tpu.obs).  TTFT and ITL are the
# two serving SLO primitives; queue-wait isolates the admission share of
# TTFT so "slow prefill" and "full pool" are distinguishable in one scrape.
_m_ttft = _obs.histogram(
    "hvd_serving_ttft_seconds",
    "submit -> first emitted token (queue wait + prefill)")
_m_itl = _obs.histogram(
    "hvd_serving_itl_seconds",
    "inter-token latency between consecutive emissions of one request")
_m_queue_wait = _obs.histogram(
    "hvd_serving_queue_wait_seconds", "submit -> admission")
_m_decode_rate = _obs.gauge(
    "hvd_serving_decode_tokens_per_s",
    "steady-state decode rate of the most recently finished request")
_m_requests = _obs.counter(
    "hvd_serving_requests_total", "requests by terminal outcome",
    ("outcome",))
_m_tokens = _obs.counter(
    "hvd_serving_tokens_generated_total",
    "tokens delivered by finished requests")


@dataclasses.dataclass
class RequestResult:
    """What a submit() future resolves to."""

    req_id: int
    prompt: np.ndarray
    tokens: list[int]          # the generated continuation
    metrics: dict              # ttft_s, queue_wait_s, decode_tokens_per_s…

    @property
    def full_sequence(self) -> np.ndarray:
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


class ServingSession:
    """One live engine + its request-facing bookkeeping."""

    def __init__(self, engine: ServingEngine, *,
                 timeline: Optional[Timeline] = None,
                 own_timeline: bool = True,
                 recover: bool = True,
                 max_recoveries: int = 3,
                 recovery_pause_s: float = 0.0) -> None:
        self.engine = engine
        # own_timeline=False: the timeline is borrowed (the runtime's
        # global Timeline v2) and must survive this session's close().
        self._own_timeline = own_timeline
        self._timeline = timeline or Timeline(None)
        self._futures: dict[int, Future] = {}
        self._trace_ids: dict[int, str] = {}       # req_id -> trace id
        self._t_last_emit: dict[int, float] = {}   # req_id -> last token ts
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Graceful degradation: an engine-step failure aborts in-flight
        # requests (error finish_reason), holds /healthz at 503 through
        # the drain window, rejoins (elastic re-rendezvous for
        # collective failures), and resumes — instead of dying.
        self._recover = recover
        self._max_recoveries = max_recoveries
        self._recovery_pause_s = recovery_pause_s
        self.recoveries = 0
        from ..context import set_component_health
        set_component_health("serving", True)

    # -- client surface --------------------------------------------------
    def submit(self, prompt: Sequence[int], max_tokens: int, *,
               eos_token: Optional[int] = None,
               stream_cb: Optional[Callable[[int, int], None]] = None,
               migrate_cb: Optional[Callable] = None,
               trace_ctx: Optional[dict] = None
               ) -> Future:
        """Queue a request; the future resolves to a
        :class:`RequestResult`.  ``stream_cb(req_id, token)`` fires once
        per generated token, in order.  ``migrate_cb`` makes this a
        prefill-only request (disaggregated serving): the future
        resolves after the prefill emission with
        ``finish_reason="migrated"`` and the callback receives the
        exported KV — see :mod:`horovod_tpu.serving.disagg`.
        ``trace_ctx`` joins an upstream trace (a router ingress span's
        ``Span.context()`` dict, carried over the request transport)."""
        fut: Future = Future()
        with self._lock:
            req = self.engine.submit(prompt, max_tokens,
                                     eos_token=eos_token,
                                     stream_cb=stream_cb,
                                     migrate_cb=migrate_cb,
                                     trace_ctx=trace_ctx)
            self._futures[req.req_id] = fut
            if req.trace.sampled:
                self._trace_ids[req.req_id] = req.trace.trace_id
                # Bounded like the tracer's finished-trace table: once a
                # trace would be evicted there, its id here is dead
                # weight — don't leak one entry per request forever.
                from ..obs import trace as _trace
                while len(self._trace_ids) > _trace.TRACER.keep:
                    self._trace_ids.pop(next(iter(self._trace_ids)))
        return fut

    def import_migrated(self, manifest: dict, k_bytes: bytes,
                        v_bytes: bytes, *,
                        stream_cb: Optional[Callable[[int, int], None]]
                        = None) -> Future:
        """Resume a migrated request on this (decode-pool) session: the
        exported KV blocks attach to the local pool with zero
        re-prefill and the request joins the running decode batch.  The
        future resolves to the FULL generated continuation (the
        prefill-emitted token plus every decode token).  Raises
        ``OutOfBlocks``/``ValueError`` when this engine cannot take the
        request right now — the router retries another replica."""
        fut: Future = Future()
        with self._lock:
            req = self.engine.import_migrated(manifest, k_bytes, v_bytes,
                                              stream_cb=stream_cb)
            self._futures[req.req_id] = fut
            if req.trace.sampled:
                self._trace_ids[req.req_id] = req.trace.trace_id
        return fut

    def request_trace(self, req_id: int) -> Optional[dict]:
        """The finished request's trace as a JSON-ready dict (span chain
        with shared trace id), or None when the request was unsampled or
        its trace already evicted from the tracer's bounded table."""
        from ..obs import trace as _trace
        with self._lock:
            tid = self._trace_ids.get(req_id)
        return _trace.TRACER.export(tid) if tid else None

    def drain(self, max_steps: Optional[int] = None) -> None:
        """Synchronously step the engine until every request finished."""
        n = 0
        while self.engine.has_work():
            self._step_once()
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def start(self) -> "ServingSession":
        """Background serving thread (the example's interactive mode)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    with self._lock:
                        busy = self.engine.has_work()
                    if busy:
                        self._step_once()
                    else:
                        time.sleep(0.001)
                except Exception as e:  # engine died: fail every future
                    with self._lock:
                        futs = list(self._futures.values())
                        self._futures.clear()
                    for fut in futs:
                        if not fut.done():
                            fut.set_exception(e)
                    log.exception("serving thread stopped on engine error")
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvdtpu-serving")
        self._thread.start()
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
        if self._own_timeline:
            self._timeline.close()
        from ..context import set_component_health
        set_component_health("serving", None)

    def __enter__(self) -> "ServingSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- engine pump -----------------------------------------------------
    def _step_once(self) -> None:
        try:
            with self._lock:
                emissions = self.engine.step()
                failed = self.engine.pop_failed()
        except Exception as e:
            self._handle_engine_failure(e)
            return
        for req, exc in failed:
            self._t_last_emit.pop(req.req_id, None)
            _m_requests.labels(outcome="failed").inc()
            fut = self._futures.pop(req.req_id, None)
            if fut is not None and not fut.done():
                fut.set_exception(exc)
        now = time.monotonic()
        for req, token in emissions:
            if req.t_first_token is None:
                req.t_first_token = now
                _m_ttft.observe(now - req.t_submit)
            else:
                last = self._t_last_emit.get(req.req_id)
                if last is not None:
                    _m_itl.observe(now - last)
            self._t_last_emit[req.req_id] = now
            if req.stream_cb is not None:
                req.stream_cb(req.req_id, token)
            if req.state.value == "finished":
                self._resolve(req)

    def _resolve(self, req: Request) -> None:
        self._t_last_emit.pop(req.req_id, None)
        m = req.metrics()
        # Registry routing of the per-request metrics dict (the log line
        # below stays — grep-ability is a feature, it is just no longer
        # the only consumer).  TTFT/ITL were observed at emission time;
        # the submit->admission share and the decode rate land here.
        _m_requests.labels(outcome="finished").inc()
        _m_tokens.inc(m["new_tokens"])
        _m_queue_wait.observe(m["queue_wait_s"])
        if m["decode_tokens_per_s"]:
            _m_decode_rate.set(m["decode_tokens_per_s"])
        log.info(
            "serving req=%d prompt=%d new=%d queue_wait=%.4fs ttft=%.4fs "
            "decode_tok_s=%s preemptions=%d trace=%s",
            m["req_id"], m["prompt_len"], m["new_tokens"],
            m["queue_wait_s"] or 0.0, m["ttft_s"] or 0.0,
            f"{m['decode_tokens_per_s']:.1f}"
            if m["decode_tokens_per_s"] else "n/a", m["preemptions"],
            m["trace_id"] or "-")
        fut = self._futures.pop(req.req_id, None)
        if fut is not None and not fut.done():
            fut.set_result(RequestResult(
                req_id=req.req_id, prompt=req.prompt,
                tokens=list(req.generated), metrics=m))

    # -- graceful degradation --------------------------------------------
    def _handle_engine_failure(self, exc: BaseException) -> None:
        """One engine-step failure, survived: abort in-flight requests
        with an ``error`` finish_reason (futures resolve to their
        partial results — streamed tokens are already delivered, not
        lied about), hold ``/healthz`` at 503 through the drain window,
        rejoin through elastic re-rendezvous when the failure was a
        collective abort, then resume serving.  Past
        ``max_recoveries`` the failure is re-raised (a permanently sick
        engine should die loudly, not flap)."""
        from ..context import is_initialized, set_component_health
        self.recoveries += 1
        log.error("serving: engine step failed (%s); aborting in-flight "
                  "requests and degrading (recovery %d/%d)",
                  exc, self.recoveries, self._max_recoveries)
        set_component_health("serving", False,
                             reason=f"engine step failed: {exc}")
        _frec.RECORDER.record("serving_abort", error=repr(exc),
                              recovery=self.recoveries)
        with self._lock:
            aborted = self.engine.abort_inflight(exc)
            futs = [(req, self._futures.pop(req.req_id, None))
                    for req in aborted]
        for req, fut in futs:
            self._t_last_emit.pop(req.req_id, None)
            _m_requests.labels(outcome="aborted").inc()
            if fut is not None and not fut.done():
                m = req.metrics()
                m["error"] = str(exc)
                fut.set_result(RequestResult(
                    req_id=req.req_id, prompt=req.prompt,
                    tokens=list(req.generated), metrics=m))
        if self.recoveries > self._max_recoveries or not self._recover:
            _frec.RECORDER.maybe_dump("serving_abort",
                                      extra={"error": repr(exc)})
            raise exc
        if self._recovery_pause_s:
            # The drain window: probes must see 503 long enough for a
            # router to pull this replica before traffic resumes.
            time.sleep(self._recovery_pause_s)
        if isinstance(exc, HorovodInternalError) and is_initialized():
            # Collective failure: the mesh itself is suspect — rejoin
            # through the elastic path (shutdown -> init -> republish)
            # so this replica re-rendezvouses instead of serving on a
            # dead world.
            try:
                from ..elastic.runner import _reinitialize
                _reinitialize()
            except Exception as e2:
                set_component_health(
                    "serving", False,
                    reason=f"re-rendezvous failed: {e2}")
                raise
        set_component_health("serving", True)
        log.warning("serving: recovered after engine failure (%d request"
                    "(s) aborted); accepting traffic again", len(futs))


def serve(params: Any, cfg, *, mesh=None,
          engine_cfg: Optional[EngineConfig] = None,
          timeline: Optional[Timeline] = None,
          recover: bool = True, max_recoveries: int = 3,
          recovery_pause_s: float = 0.0,
          draft_params: Any = None, draft_cfg=None, **engine_kw
          ) -> ServingSession:
    """Build a serving session for a model.

    ``engine_cfg`` carries the pool/scheduler knobs; keyword overrides
    (``block_size=…``, ``num_blocks=…``, …) are applied on top::

        session = serve(params, cfg, num_blocks=256, max_active=16)
        fut = session.submit(prompt_ids, max_tokens=64)
        session.drain()
        print(fut.result().tokens)

    ``recover``/``max_recoveries``/``recovery_pause_s`` configure the
    graceful-degradation loop: on an engine-step failure the session
    aborts in-flight requests with an ``error`` finish_reason, answers
    503 on ``/healthz`` through the drain window (``recovery_pause_s``),
    re-rendezvouses when the failure was a collective abort, and
    resumes — see :meth:`ServingSession._handle_engine_failure`.

    ``prefix_cache=True`` turns on the radix prefix cache (shared prompt
    prefixes skip prefill); ``spec_k=k`` with ``draft_params`` /
    ``draft_cfg`` turns on draft-model speculative decoding — both from
    :mod:`horovod_tpu.serving.frontdoor`, both token-identical to plain
    greedy decoding.
    """
    base = engine_cfg or EngineConfig()
    if engine_kw:
        base = dataclasses.replace(base, **engine_kw)
    own_timeline = True
    if timeline is None:
        # Request traces render into the runtime's Timeline v2 when one
        # is armed (HVDTPU_TIMELINE / hvd.start_timeline): one Perfetto
        # load then shows the request chains next to the engine's
        # collective spans.  Borrowed, so session.close() must not close
        # the runtime's writer.
        from ..context import global_state, is_initialized
        if is_initialized():
            state_tl = global_state().timeline
            if state_tl is not None and state_tl.enabled:
                timeline = state_tl
                own_timeline = False
    engine = ServingEngine(params, cfg, engine_cfg=base, mesh=mesh,
                           timeline=timeline, draft_params=draft_params,
                           draft_cfg=draft_cfg)
    return ServingSession(engine, timeline=timeline,
                          own_timeline=own_timeline, recover=recover,
                          max_recoveries=max_recoveries,
                          recovery_pause_s=recovery_pause_s)
