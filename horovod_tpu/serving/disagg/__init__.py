"""Disaggregated prefill/decode serving.

Prefill (compute-bound, bursty, TTFT-sensitive) and decode
(memory-bound, steady, ITL-sensitive) have opposite resource shapes;
sharing one replica queue lets a prefill burst stall every decode tick
behind it.  This package splits the replica fleet into two pools:

- a **prefill replica** admits a request, fills its pager blocks, emits
  the first token, then exports the blocks as a versioned manifest +
  chunked K/V payloads over the KV-store transport
  (:mod:`.transport`);
- a **decode replica** imports them through the same refcounted-block /
  longest-prefix machinery the radix prefix cache uses
  (:mod:`.migration` — zero re-prefill) and continues decoding
  token-identically (greedy decode is deterministic);
- the :class:`~.router.DisaggRouter` owns pool-aware placement (prefill
  pool scored on TTFT burn + queue depth, decode pool on ITL p99 +
  occupancy) and the migration handoff as first-class state:
  ``prefilling -> migrating -> decoding``, with failover at any stage
  replaying token-identically from the last durable point (the
  published manifest, or the original prompt when none exists yet).

Pool membership is a tag on the replica's published membership record
(``HVDTPU_SERVING_POOL`` = ``prefill`` | ``decode`` | ``mixed``), and
the autoscale controller scales the two pools independently
(pool-filtered ``signals_from_families`` +
``hvd_autoscale_target_np{pool=...}``).
"""

from .migration import MANIFEST_SCHEMA, export_request, import_request
from .router import DisaggRouter, DisaggRouterConfig, LocalDisaggReplica
from .transport import (DictKV, MigrationUnavailable, delete_migration,
                        fetch_migration, migration_published,
                        publish_migration)

__all__ = [
    "MANIFEST_SCHEMA", "export_request", "import_request",
    "DisaggRouter", "DisaggRouterConfig", "LocalDisaggReplica",
    "DictKV", "MigrationUnavailable",
    "publish_migration", "fetch_migration", "migration_published",
    "delete_migration",
]
