"""KV-block export/import for cross-replica request migration.

The migration unit is the pager block, not the request tensor: a
prefill replica exports exactly the blocks its request's table spans
(``blocks_for(context_len)`` of them, per layer), and the decode
replica re-attaches them through the same refcounted
:class:`~horovod_tpu.serving.kv_pager.KVPager` machinery the radix
prefix cache uses — a cached prompt prefix on the importing side
attaches shared (no payload write), only the remainder is scattered
into fresh blocks, and the request joins the running decode batch with
zero re-prefill.  Greedy decode is deterministic, so the resumed
continuation is token-identical to an unmigrated run; the parity test
in ``tests/test_disagg.py`` asserts it against
:func:`~horovod_tpu.models.llama.generate`.

The manifest is a plain JSON-able dict (schema-versioned, geometry +
payload lengths included) so the transport layer can detect torn reads
and geometry mismatches before any pool write happens.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ...obs import REGISTRY as _obs
from ...obs import trace as _trace
from ..kv_pager import OutOfBlocks
from ..scheduler import Request, RequestState

#: manifest wire-format version; importers reject anything else.
MANIFEST_SCHEMA = 1

_m_exports = _obs.counter(
    "hvd_disagg_exports_total", "KV-block exports by outcome", ("outcome",))
_m_imports = _obs.counter(
    "hvd_disagg_imports_total", "KV-block imports by outcome", ("outcome",))
_m_bytes = _obs.counter(
    "hvd_disagg_kv_bytes_total", "KV payload bytes exported for migration")
_m_blocks_attached = _obs.counter(
    "hvd_disagg_blocks_attached_total",
    "migrated blocks attached on import, by source",
    ("source",))          # source=payload | prefix_cache


def export_request(engine, req: Request):
    """Snapshot ``req``'s KV blocks out of ``engine``'s pool.

    Must run while the pager still holds the request's table (i.e.
    before ``scheduler.finish`` releases the blocks).  Returns
    ``(manifest, k_bytes, v_bytes)`` — the payloads are C-contiguous
    ``[L, nb, BS, KV, Dh]`` dumps, one whole block per page, so the
    importer can attach any prefix of them shared and scatter the rest.
    """
    if not req.generated:
        raise ValueError(f"request {req.req_id} has no prefill emission "
                         "yet; export runs after the first token")
    cache = engine.cache
    ctx = req.context_len
    nb = cache.blocks_for(ctx)
    blocks = engine.pager.table(req.req_id)[:nb]
    try:
        idx = np.asarray(blocks, np.int32)
        # Device-side gather of just this request's pages, then one host
        # copy — never the whole pool.
        k = np.ascontiguousarray(np.asarray(engine.k_pool[:, idx]))
        v = np.ascontiguousarray(np.asarray(engine.v_pool[:, idx]))
    except Exception:
        _m_exports.labels(outcome="error").inc()
        raise
    k_bytes, v_bytes = k.tobytes(), v.tobytes()
    manifest = {
        "schema": MANIFEST_SCHEMA,
        # Torn-read sentinel: the transport re-checks this + the payload
        # lengths after fetching, so a half-rewritten manifest can never
        # reach the pool-write path.
        "version": f"{req.req_id}.{len(req.generated)}.{ctx}",
        "prompt": [int(t) for t in req.prompt],
        "prefill_tokens": [int(t) for t in (
            req.prefill_tokens if req.prefill_tokens is not None
            else req.prompt)],
        "generated": list(req.generated),
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token": (None if req.eos_token is None
                      else int(req.eos_token)),
        "context_len": int(ctx),
        "n_blocks": int(nb),
        "block_size": cache.block_size,
        "n_layers": cache.n_layers,
        "kv_heads": cache.kv_heads,
        "head_dim": cache.head_dim,
        "dtype": str(k.dtype),
        "k_len": len(k_bytes),
        "v_len": len(v_bytes),
        # Trace context rides the manifest so the decode-side import
        # joins the exporting request's trace instead of opening a
        # fresh orphan (sampling decided once at ingress).
        "trace": req.trace.context(),
    }
    _m_exports.labels(outcome="ok").inc()
    _m_bytes.inc(len(k_bytes) + len(v_bytes))
    return manifest, k_bytes, v_bytes


def _check_geometry(engine, manifest: dict) -> None:
    cache = engine.cache
    if manifest.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(
            f"migration manifest schema {manifest.get('schema')!r} != "
            f"supported {MANIFEST_SCHEMA}")
    for field, want in (("block_size", cache.block_size),
                        ("n_layers", cache.n_layers),
                        ("kv_heads", cache.kv_heads),
                        ("head_dim", cache.head_dim)):
        if manifest.get(field) != want:
            raise ValueError(
                f"migration geometry mismatch: manifest {field}="
                f"{manifest.get(field)} but this pool has {want}")
    for field in ("k_len", "v_len", "context_len", "n_blocks"):
        if field not in manifest:
            raise ValueError(f"migration manifest missing {field}")


def import_request(engine, manifest: dict, k_bytes: bytes,
                   v_bytes: bytes, *, stream_cb=None) -> Request:
    """Attach a migrated request to ``engine`` and resume decoding.

    The longest cached prefix of the migrated prompt attaches shared
    from this replica's radix cache (those pages are never written);
    the remaining blocks come off the free list and receive the
    exported payload through the engine's compiled scatter step.  The
    returned request is RUNNING in the decode batch.  Raises
    :class:`~horovod_tpu.serving.kv_pager.OutOfBlocks` when this
    engine lacks a slot or blocks right now — callers (the router)
    retry another decode replica.
    """
    _check_geometry(engine, manifest)
    if len(k_bytes) != manifest["k_len"] or \
            len(v_bytes) != manifest["v_len"]:
        _m_imports.labels(outcome="torn").inc()
        raise ValueError(
            f"migration payload torn: got {len(k_bytes)}/{len(v_bytes)} "
            f"bytes, manifest says {manifest['k_len']}/{manifest['v_len']}")
    if not manifest["generated"]:
        raise ValueError("migration manifest has no generated tokens")

    cache = engine.cache
    ctx = int(manifest["context_len"])
    nb = int(manifest["n_blocks"])
    if nb != cache.blocks_for(ctx):
        raise ValueError(f"manifest n_blocks={nb} inconsistent with "
                         f"context_len={ctx}")
    if engine.spec is not None:
        raise NotImplementedError(
            "migrated import into a speculative-decoding engine is not "
            "supported (draft cache has no migrated state)")
    if None not in engine._slots or \
            len(engine.scheduler.running) >= engine.ecfg.max_active:
        _m_imports.labels(outcome="no_slot").inc()
        raise OutOfBlocks("no free decode slot for migrated request")

    prefill = np.asarray(manifest["prefill_tokens"], np.int32)
    # Longest-prefix attach, same machinery as local admission: matched
    # blocks are shared (refcount bump, no write), and the eviction
    # valve protects them while making room for the rest.
    cached, cached_blocks = (
        engine.prefix_cache.match(prefill)
        if engine.prefix_cache is not None else (0, []))
    need = cache.blocks_for(ctx + 1) - len(cached_blocks)
    if need > engine.pager.free_blocks and engine.prefix_cache is not None:
        engine.prefix_cache.evict(need - engine.pager.free_blocks,
                                  protect=cached_blocks)
    req_id = engine._next_id
    engine._next_id += 1
    try:
        engine.pager.allocate(req_id, ctx + 1, prefix_blocks=cached_blocks)
    except OutOfBlocks:
        _m_imports.labels(outcome="no_blocks").inc()
        raise

    jnp = engine._jnp
    table = engine.pager.table(req_id)
    ncb = len(cached_blocks)
    dtype = np.dtype(manifest["dtype"])
    shape = (cache.n_layers, nb, cache.block_size,
             cache.kv_heads, cache.head_dim)
    if ncb < nb:
        k_arr = np.frombuffer(k_bytes, dtype).reshape(shape)
        v_arr = np.frombuffer(v_bytes, dtype).reshape(shape)
        tail_nb = nb - ncb
        # [L, tail_nb, BS, KV, Dh] -> [L, 1, tail_nb*BS, KV, Dh]: the
        # scatter step's pad-and-reshape is then an exact identity, so
        # the prefill-path jit is reused unchanged.
        L = cache.n_layers
        ks = np.ascontiguousarray(k_arr[:, ncb:]).reshape(
            L, 1, tail_nb * cache.block_size, cache.kv_heads,
            cache.head_dim)
        vs = np.ascontiguousarray(v_arr[:, ncb:]).reshape(
            L, 1, tail_nb * cache.block_size, cache.kv_heads,
            cache.head_dim)
        engine.k_pool, engine.v_pool = engine._scatter(
            engine.k_pool, engine.v_pool, jnp.asarray(ks),
            jnp.asarray(vs), jnp.asarray(table[ncb:nb], jnp.int32))
    _m_blocks_attached.labels(source="payload").inc(nb - ncb)
    _m_blocks_attached.labels(source="prefix_cache").inc(ncb)

    now = time.monotonic()
    req = Request(
        req_id=req_id,
        prompt=np.asarray(manifest["prompt"], np.int32),
        max_new_tokens=int(manifest["max_new_tokens"]),
        eos_token=manifest["eos_token"],
        stream_cb=stream_cb,
        state=RequestState.RUNNING,
        generated=list(manifest["generated"]),
        prefill_tokens=prefill,
        context_len=ctx,
        cached_tokens=cached,
        t_submit=now, t_admitted=now, t_enqueued=now)
    # Adopt the trace context the exporter stamped into the manifest:
    # same trace_id across the handoff, parented under the prefill-side
    # span, and its sampling decision honored.  Old manifests without
    # the field fall back to a fresh local trace.
    req.trace = _trace.TRACER.start_trace(
        "serving.migrated", lane=f"req{req_id}",
        timeline=engine.timeline, parent=manifest.get("trace"),
        req_id=req_id, migrated=True, context_len=ctx, cached_blocks=ncb)
    req.open_phase("decode", migrated=True)
    engine.scheduler.running.append(req)
    engine._assign_slot(req)
    if engine.prefix_cache is not None:
        # The migrated prompt's pages are now first-class local pages;
        # share them so future local admissions (or re-imports of the
        # same request after a decode-replica failover) prefix-attach.
        engine.prefix_cache.insert(prefill, table)
    _m_imports.labels(outcome="ok").inc()
    return req
