"""Pool-aware router: prefill placement, migration handoff, decode.

The :class:`DisaggRouter` is the placement authority over a
disaggregated fleet.  Every request moves through first-class router
states::

    queued -> prefilling -> migrating -> decoding -> (resolved)

- **prefill placement** scores the prefill pool on what prefill burns:
  queue depth + outstanding flights + weighted TTFT p99 + weighted SLO
  burn (smallest wins);
- **decode placement** scores the decode pool on what decode burns:
  batch occupancy + outstanding flights + weighted ITL p99;
- **migration handoff**: the prefill replica publishes the KV export
  under a router-assigned ``mig_id`` (one id per prefill attempt, so
  every publish is write-once); the router then places the import on a
  decode replica.  ``fd/mig`` manifests are the durable replay points:
- **failover at any stage replays token-identically** (greedy decode is
  deterministic) from the last durable point — a prefill replica dead
  *before* its manifest landed restarts from the prompt on a pool
  survivor; dead *after*, the flight proceeds straight to the decode
  pool with the published blocks; a decode replica dead mid-stream
  re-imports the same manifest elsewhere and re-decodes from the first
  token.  Streamed tokens are relayed past the high-water mark only, so
  clients see exactly-once delivery under replay.

``mixed``-pool replicas join both pools (the colocated baseline —
also what a fleet looks like mid-rollout).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from ... import chaos
from ...obs import REGISTRY as _obs
from ...obs import trace as _trace
from ...utils import logging as hvd_logging
from ..api import RequestResult
from ..frontdoor.router import NoReplicaAvailable
from ..frontdoor.transport import DEAD_SIGNALS
from ..kv_pager import OutOfBlocks
from . import transport as mig_transport
from .transport import MigrationUnavailable

log = hvd_logging.get_logger()

_m_placed = _obs.counter(
    "hvd_disagg_placed_total", "placements by pool and replica",
    ("pool", "replica"))
_m_requests = _obs.counter(
    "hvd_disagg_requests_total",
    "disaggregated requests by terminal outcome", ("outcome",))
_m_failovers = _obs.counter(
    "hvd_disagg_failovers_total",
    "stage replays after a replica died or errored", ("stage",))
_m_pool_replicas = _obs.gauge(
    "hvd_disagg_pool_replicas",
    "replicas of this pool currently eligible for placement (alive + "
    "ready + fresh)", ("pool",))
_m_flights = _obs.gauge(
    "hvd_disagg_flights", "in-flight requests by router state",
    ("state",))
_m_handoff_s = _obs.histogram(
    "hvd_disagg_handoff_seconds",
    "prefill emission -> decode import placed (the migration gap a "
    "request's ITL stream sees once)")


@dataclasses.dataclass(frozen=True)
class DisaggRouterConfig:
    #: total placement attempts per request across both stages (initial
    #: prefill + every replay) before its future fails
    max_attempts: int = 4
    #: prefill-pool scoring: queue_depth + outstanding
    #: + ttft_weight * ttft_p99 + burn_weight * slo_burn
    ttft_weight: float = 10.0
    burn_weight: float = 5.0
    #: decode-pool scoring: occupancy + outstanding
    #: + itl_weight * itl_p99 - prefix_weight * cached_fraction
    itl_weight: float = 10.0
    #: bonus for the decode replica whose radix cache already holds the
    #: migrated prompt's prefix (the import attaches those blocks shared
    #: — no payload write, no pool pressure).  Scaled by the fraction of
    #: the prompt cached; kept small so occupancy/ITL still dominate.
    prefix_weight: float = 0.5
    #: drain() poll cadence
    poll_interval_s: float = 0.02
    #: continuous-dead window before an existing flight fails over
    failover_grace_s: float = 1.5
    #: overall budget for one migration fetch on the decode side
    fetch_timeout_ms: int = 15000
    #: delete fd/mig blobs once the request resolves (keep False to
    #: post-mortem migrations in tests)
    cleanup: bool = True


@dataclasses.dataclass
class _Flight:
    fid: int
    prompt: np.ndarray
    max_tokens: int
    eos_token: Optional[int]
    stream_cb: Optional[Callable[[int, int], None]]
    future: Future
    trace: object
    state: str = "queued"         # queued|prefilling|migrating|decoding
    replica: object = None        # current-stage replica handle
    handle: object = None
    mig_id: Optional[str] = None
    attempts: int = 0             # placements across both stages
    prefill_attempts: int = 0     # distinct prefill runs (mig_id suffix)
    delivered: int = 0            # streamed tokens relayed so far
    t_prefill_done: Optional[float] = None
    spans: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False)


class LocalDisaggReplica:
    """In-process disagg replica over one
    :class:`~horovod_tpu.serving.api.ServingSession` plus a shared KV
    (usually :class:`~.transport.DictKV`) the migration blobs travel
    through — the bench/test twin of the KV-transport replica, same
    protocol.  ``drive=False`` when the session's own background
    thread steps the engine (the bench's threaded mode)."""

    def __init__(self, replica_id: str, session, kv, *,
                 pool: str = "mixed", drive: bool = True) -> None:
        self.replica_id = str(replica_id)
        self.session = session
        self.pool = pool
        self.kv = kv
        self._drive = drive
        self.killed = False

    def kill(self) -> None:
        self.killed = True

    def drive(self) -> None:
        if self._drive and not self.killed \
                and self.session.engine.has_work():
            self.session._step_once()

    def signals(self) -> dict:
        if self.killed:
            return dict(DEAD_SIGNALS, pool=self.pool)
        eng = self.session.engine
        return {
            "alive": True, "stale": False, "ready": True,
            "pool": self.pool,
            "queue_depth": float(len(eng.scheduler.waiting)),
            "occupancy": (len(eng.scheduler.running)
                          / eng.ecfg.max_active),
            "ttft_p99": None, "itl_p99": None, "slo_burn": 0.0,
        }

    def submit_prefill(self, prompt, max_tokens: int, *,
                       eos_token: Optional[int] = None, mig_id: str,
                       trace_ctx: Optional[dict] = None):
        tokens: list[int] = []

        def publish(manifest, k_bytes, v_bytes):
            mig_transport.publish_migration(
                self.kv, mig_id, manifest, k_bytes, v_bytes)

        fut = self.session.submit(
            prompt, max_tokens, eos_token=eos_token,
            stream_cb=lambda rid, t: tokens.append(int(t)),
            migrate_cb=publish, trace_ctx=trace_ctx)
        return (fut, tokens, mig_id)

    def cached_prefix(self, tokens) -> int:
        """Non-mutating probe: how many leading tokens this replica's
        radix cache already holds (feeds the router's decode-placement
        prefix bonus; see :meth:`PrefixCache.peek`)."""
        pc = self.session.engine.prefix_cache
        return 0 if pc is None else int(pc.peek(tokens))

    def submit_import(self, mig_id: str, *,
                      fetch_timeout_ms: int = 15000):
        manifest, k_bytes, v_bytes = mig_transport.fetch_migration(
            self.kv, mig_id, timeout_ms=fetch_timeout_ms)
        tokens: list[int] = [int(t) for t in manifest["generated"]]
        fut = self.session.import_migrated(
            manifest, k_bytes, v_bytes,
            stream_cb=lambda rid, t: tokens.append(int(t)))
        return (fut, tokens, mig_id)

    def partial_tokens(self, handle) -> list[int]:
        return list(handle[1])

    def result(self, handle) -> Optional[dict]:
        fut = handle[0]
        if self.killed or not fut.done():
            return None
        try:
            res = fut.result()
        except Exception as e:
            return {"ok": False, "error": str(e),
                    "error_kind": type(e).__name__,
                    "mig_id": handle[2]}
        return {"ok": True, "tokens": list(res.tokens),
                "finish_reason": res.metrics.get("finish_reason"),
                "metrics": res.metrics, "mig_id": handle[2]}


class DisaggRouter:
    """Placement + migration lifecycle over a disaggregated fleet.

    ``replicas`` are handles carrying a ``pool`` attribute and the
    disagg protocol (``signals``/``drive``/``submit_prefill``/
    ``submit_import``/``partial_tokens``/``result``) —
    :class:`LocalDisaggReplica` in-process,
    :class:`~horovod_tpu.serving.frontdoor.transport.KVReplicaClient`
    across processes.  ``kv`` is the router's own view of the job KV
    store, used for the durable-point probe and blob cleanup.
    Single-threaded like the colocated Router: :meth:`pump` is one
    non-blocking pass, :meth:`drain` pumps until resolved."""

    def __init__(self, replicas: Sequence, kv,
                 cfg: DisaggRouterConfig = DisaggRouterConfig()) -> None:
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self.kv = kv
        self.cfg = cfg
        self.prefill_pool = [r for r in replicas
                             if r.pool in ("prefill", "mixed")]
        self.decode_pool = [r for r in replicas
                            if r.pool in ("decode", "mixed")]
        if not self.prefill_pool or not self.decode_pool:
            raise ValueError(
                "DisaggRouter needs at least one prefill-capable and one "
                f"decode-capable replica (pools: "
                f"{[r.pool for r in replicas]})")
        self._flights: dict[int, _Flight] = {}
        self._next_fid = 0
        self._unhealthy_since: dict[str, float] = {}
        self.failovers = 0

    # -- client surface --------------------------------------------------
    def submit(self, prompt, max_tokens: int, *,
               eos_token: Optional[int] = None,
               stream_cb: Optional[Callable[[int, int], None]] = None
               ) -> Future:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        fl = _Flight(
            fid=self._next_fid, prompt=prompt, max_tokens=max_tokens,
            eos_token=eos_token, stream_cb=stream_cb, future=Future(),
            trace=_trace.TRACER.start_trace(
                "disagg.request", lane=f"dg{self._next_fid}",
                prompt_len=int(prompt.size), max_tokens=max_tokens))
        self._next_fid += 1
        self._flights[fl.fid] = fl
        sigs = self._signals()
        self._refresh_pools(sigs)
        self._try_place_prefill(fl, sigs)
        return fl.future

    def pump(self) -> None:
        """One non-blocking router pass: drive replicas, advance every
        flight's state machine, refresh pool health."""
        for rep in self.replicas:
            rep.drive()
        sigs = self._signals()
        self._refresh_pools(sigs)
        now = time.monotonic()
        for rid, sig in sigs.items():
            if sig["alive"] and not sig["stale"]:
                self._unhealthy_since.pop(rid, None)
            else:
                self._unhealthy_since.setdefault(rid, now)
        for fl in list(self._flights.values()):
            if fl.state == "queued":
                self._try_place_prefill(fl, sigs)
            elif fl.state == "prefilling":
                self._pump_prefilling(fl, sigs, now)
            elif fl.state == "migrating":
                self._try_place_decode(fl, sigs)
            elif fl.state == "decoding":
                self._pump_decoding(fl, sigs, now)
        self._sample_flight_gauge()

    def drain(self, timeout_s: Optional[float] = None) -> None:
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while self._flights:
            self.pump()
            if not self._flights:
                return
            if deadline is not None and time.monotonic() > deadline:
                states = {}
                for fl in self._flights.values():
                    states[fl.state] = states.get(fl.state, 0) + 1
                raise TimeoutError(
                    f"disagg drain: {len(self._flights)} unresolved at "
                    f"deadline (by state: {states})")
            time.sleep(self.cfg.poll_interval_s)

    # -- state machine ---------------------------------------------------
    def _pump_prefilling(self, fl: _Flight, sigs: dict,
                         now: float) -> None:
        res = fl.replica.result(fl.handle)
        if res is None:
            if self._dead_for_grace(fl.replica.replica_id, now):
                self._replay_prefill(fl, sigs, why="prefill replica dead")
            return
        if not res.get("ok") or res.get("finish_reason") == "error":
            self._replay_prefill(
                fl, sigs, why=res.get("error", "prefill abort"))
            return
        self._relay(fl, [int(t) for t in res["tokens"]])
        if res.get("finish_reason") == "migrated":
            fl.t_prefill_done = now
            self._close_span(fl, "prefill")
            fl.spans["migrate"] = fl.trace.child(
                "MIGRATE", after=fl.spans.get("_prev"),
                mig_id=fl.mig_id)
            fl.state = "migrating"
            self._try_place_decode(fl, sigs)
        else:
            # Finished inside prefill (eos or max_tokens=1): no
            # migration leg at all.
            self._settle(fl, res)

    def _pump_decoding(self, fl: _Flight, sigs: dict,
                       now: float) -> None:
        self._relay(fl, fl.replica.partial_tokens(fl.handle))
        res = fl.replica.result(fl.handle)
        if res is None:
            if self._dead_for_grace(fl.replica.replica_id, now):
                self._replay_decode(fl, sigs, why="decode replica dead")
            return
        if res.get("ok") and res.get("finish_reason") != "error":
            self._settle(fl, res)
            return
        kind = res.get("error_kind", "")
        if kind == "MigrationUnavailable":
            # The durable point itself is gone (torn/expired blob):
            # fall back one stage and re-prefill from the prompt.
            self._replay_prefill(
                fl, sigs, why=res.get("error", "migration unavailable"))
        elif kind in ("OutOfBlocks", "NotImplementedError"):
            # This decode replica cannot take the import right now —
            # the manifest is still durable, try a pool sibling.
            self._replay_decode(fl, sigs, why=res.get("error", kind))
        else:
            self._replay_decode(
                fl, sigs, why=res.get("error", "decode abort"))

    # -- placement -------------------------------------------------------
    def _try_place_prefill(self, fl: _Flight, sigs: dict) -> None:
        chaos.fire("router")
        eligible = [r for r in self.prefill_pool
                    if self._eligible(sigs[r.replica_id])]
        if not eligible:
            fl.state = "queued"
            return
        outstanding = self._outstanding()

        def score(rep):
            s = sigs[rep.replica_id]
            return (s["queue_depth"]
                    + outstanding.get(rep.replica_id, 0)
                    + self.cfg.ttft_weight * (s["ttft_p99"] or 0.0)
                    + self.cfg.burn_weight * s["slo_burn"])

        chosen = min(eligible, key=score)
        fl.attempts += 1
        fl.prefill_attempts += 1
        # One mig_id per prefill run: every publish is write-once, so a
        # replayed prefill can never splice chunks into a half-read
        # blob of its predecessor.
        fl.mig_id = f"{fl.fid}.{fl.prefill_attempts}"
        fl.replica = chosen
        try:
            # The ingress span's context rides the submit: the prefill
            # engine joins this flight's trace, and the migration
            # manifest then carries the same context on to decode.
            fl.handle = chosen.submit_prefill(
                fl.prompt, fl.max_tokens, eos_token=fl.eos_token,
                mig_id=fl.mig_id, trace_ctx=fl.trace.context())
        except Exception as e:
            log.warning("disagg: prefill submit to %s failed: %s",
                        chosen.replica_id, e)
            fl.state = "queued"
            return
        fl.state = "prefilling"
        sigs[chosen.replica_id]["queue_depth"] += 1
        _m_placed.labels(pool="prefill", replica=chosen.replica_id).inc()
        fl.spans["prefill"] = fl.trace.child(
            "PREFILL", after=fl.spans.get("_prev"),
            replica=chosen.replica_id, attempt=fl.attempts)

    def _cached_fraction(self, rep, prompt) -> float:
        """Fraction of ``prompt`` already resident in ``rep``'s radix
        cache, through the handle's optional non-mutating
        ``cached_prefix`` probe.  Handles without one (e.g. the
        cross-process KV client — a synchronous remote probe per scoring
        pass would cost more than it saves) contribute 0.0."""
        probe = getattr(rep, "cached_prefix", None)
        n = 0 if prompt is None else len(prompt)
        if probe is None or n == 0:
            return 0.0
        try:
            return min(1.0, max(0.0, probe(prompt) / float(n)))
        except Exception:
            return 0.0

    def _try_place_decode(self, fl: _Flight, sigs: dict) -> None:
        chaos.fire("router")
        eligible = [r for r in self.decode_pool
                    if self._eligible(sigs[r.replica_id])]
        if not eligible:
            return                       # stay migrating; retry next pump
        outstanding = self._outstanding()

        def score(rep):
            s = sigs[rep.replica_id]
            return (s["occupancy"]
                    + outstanding.get(rep.replica_id, 0)
                    + self.cfg.itl_weight * (s["itl_p99"] or 0.0)
                    - self.cfg.prefix_weight * self._cached_fraction(
                        rep, fl.prompt))

        chosen = min(eligible, key=score)
        fl.attempts += 1
        try:
            handle = chosen.submit_import(
                fl.mig_id, fetch_timeout_ms=self.cfg.fetch_timeout_ms)
        except MigrationUnavailable as e:
            self._replay_prefill(fl, sigs, why=str(e))
            return
        except (OutOfBlocks, NotImplementedError) as e:
            log.warning("disagg: decode import on %s refused: %s",
                        chosen.replica_id, e)
            # The attempt is charged (it was a placement); stay
            # migrating — another pool sibling may have room — unless
            # the budget is already spent.
            self._charge_attempt(fl, str(e))
            return
        except Exception as e:
            log.warning("disagg: decode import on %s failed: %s",
                        chosen.replica_id, e)
            self._charge_attempt(fl, str(e))
            return
        fl.replica = chosen
        fl.handle = handle
        fl.state = "decoding"
        sigs[chosen.replica_id]["occupancy"] = min(
            1.0, sigs[chosen.replica_id]["occupancy"] + 0.01)
        _m_placed.labels(pool="decode", replica=chosen.replica_id).inc()
        if fl.t_prefill_done is not None:
            _m_handoff_s.observe(time.monotonic() - fl.t_prefill_done)
        self._close_span(fl, "migrate")
        fl.spans["decode"] = fl.trace.child(
            "DECODE", after=fl.spans.get("_prev"),
            replica=chosen.replica_id, attempt=fl.attempts)

    # -- replay / settle -------------------------------------------------
    def _replay_prefill(self, fl: _Flight, sigs: dict, *,
                        why: str) -> None:
        """Prefill-stage failover.  Durable-point check first: when the
        dying replica already published the manifest, the export is
        complete and the flight proceeds to the decode pool instead of
        re-prefilling."""
        if fl.mig_id is not None and \
                mig_transport.migration_published(self.kv, fl.mig_id):
            log.warning(
                "disagg: flight %d lost its prefill replica (%s) but "
                "migration %s is durable; proceeding to decode",
                fl.fid, why, fl.mig_id)
            fl.trace.event("failover", stage="prefill", why=why,
                           durable=True)
            _m_failovers.labels(stage="prefill").inc()
            self.failovers += 1
            fl.t_prefill_done = fl.t_prefill_done or time.monotonic()
            self._close_span(fl, "prefill")
            if "migrate" not in fl.spans:
                fl.spans["migrate"] = fl.trace.child(
                    "MIGRATE", after=fl.spans.get("_prev"),
                    mig_id=fl.mig_id, recovered=True)
            fl.state = "migrating"
            self._try_place_decode(fl, sigs)
            return
        if not self._charge_attempt(fl, why):
            return
        _m_failovers.labels(stage="prefill").inc()
        self.failovers += 1
        log.warning(
            "disagg: flight %d replaying prefill from the prompt (%s), "
            "attempt %d", fl.fid, why, fl.attempts + 1)
        fl.trace.event("failover", stage="prefill", why=why,
                       durable=False)
        self._close_span(fl, "prefill")
        self._close_span(fl, "migrate")
        self._close_span(fl, "decode")
        fl.replica = fl.handle = None
        fl.state = "queued"
        self._try_place_prefill(fl, sigs)

    def _replay_decode(self, fl: _Flight, sigs: dict, *,
                       why: str) -> None:
        """Decode-stage failover: the manifest is the durable point —
        re-import it on a pool sibling and re-decode from the first
        token.  Already-relayed tokens are not re-delivered (the replay
        is token-identical, so the relay high-water mark still
        matches)."""
        if not self._charge_attempt(fl, why):
            return
        _m_failovers.labels(stage="decode").inc()
        self.failovers += 1
        log.warning(
            "disagg: flight %d re-importing migration %s (%s), "
            "attempt %d", fl.fid, fl.mig_id, why, fl.attempts + 1)
        fl.trace.event("failover", stage="decode", why=why)
        self._close_span(fl, "decode")
        fl.replica = fl.handle = None
        fl.state = "migrating"
        if "migrate" not in fl.spans:
            fl.spans["migrate"] = fl.trace.child(
                "MIGRATE", after=fl.spans.get("_prev"),
                mig_id=fl.mig_id, replay=True)
        self._try_place_decode(fl, sigs)

    def _charge_attempt(self, fl: _Flight, why: str) -> bool:
        """Attempt budget gate shared by both replay paths; failing the
        flight resolves its future with the terminal error."""
        if fl.attempts < self.cfg.max_attempts:
            return True
        del self._flights[fl.fid]
        _m_requests.labels(outcome="failed").inc()
        for name in ("prefill", "migrate", "decode"):
            self._close_span(fl, name)
        fl.trace.end(outcome="failed", attempts=fl.attempts, error=why)
        fl.future.set_exception(NoReplicaAvailable(
            f"disagg request {fl.fid} failed after {fl.attempts} "
            f"attempts (last: {why})"))
        return False

    def _settle(self, fl: _Flight, res: dict) -> None:
        tokens = [int(t) for t in res["tokens"]]
        self._relay(fl, tokens)
        del self._flights[fl.fid]
        migrated = fl.t_prefill_done is not None
        _m_requests.labels(outcome="finished").inc()
        mig_transport._m_migrations.labels(
            outcome="completed" if migrated else "prefill_only").inc()
        metrics = dict(res.get("metrics") or {})
        metrics["disagg_attempts"] = fl.attempts
        metrics["migrated"] = migrated
        metrics["mig_id"] = fl.mig_id
        for name in ("prefill", "migrate", "decode"):
            self._close_span(fl, name)
        fl.trace.end(outcome="finished",
                     finish_reason=res.get("finish_reason"),
                     attempts=fl.attempts, migrated=migrated)
        if self.cfg.cleanup and fl.mig_id is not None:
            mig_transport.delete_migration(self.kv, fl.mig_id)
        fl.future.set_result(RequestResult(
            req_id=fl.fid, prompt=fl.prompt, tokens=tokens,
            metrics=metrics))

    # -- shared helpers --------------------------------------------------
    def _relay(self, fl: _Flight, tokens: list) -> None:
        if fl.stream_cb is not None:
            for t in tokens[fl.delivered:]:
                fl.stream_cb(fl.fid, int(t))
        fl.delivered = max(fl.delivered, len(tokens))

    def _close_span(self, fl: _Flight, name: str) -> None:
        sp = fl.spans.pop(name, None)
        if sp is not None:
            sp.end()
            fl.spans["_prev"] = sp

    def _signals(self) -> dict:
        return {rep.replica_id: rep.signals() for rep in self.replicas}

    def _outstanding(self) -> dict:
        out: dict[str, int] = {}
        for other in self._flights.values():
            if other.replica is not None:
                rid = other.replica.replica_id
                out[rid] = out.get(rid, 0) + 1
        return out

    def _dead_for_grace(self, rid: str, now: float) -> bool:
        since = self._unhealthy_since.get(rid)
        return (since is not None
                and now - since >= self.cfg.failover_grace_s)

    @staticmethod
    def _eligible(sig: dict) -> bool:
        return sig["alive"] and not sig["stale"] and sig["ready"]

    def _refresh_pools(self, sigs: dict) -> None:
        for pool, members in (("prefill", self.prefill_pool),
                              ("decode", self.decode_pool)):
            n = sum(1 for r in members
                    if self._eligible(sigs[r.replica_id]))
            _m_pool_replicas.labels(pool=pool).set(float(n))

    def _sample_flight_gauge(self) -> None:
        counts = {"queued": 0, "prefilling": 0, "migrating": 0,
                  "decoding": 0}
        for fl in self._flights.values():
            counts[fl.state] = counts.get(fl.state, 0) + 1
        for state, n in counts.items():
            _m_flights.labels(state=state).set(float(n))
