"""Migration blobs over the KV-store control plane.

A migration is three chunked blobs under ``fd/mig/<mig_id>/`` — the K
payload, the V payload, and the JSON manifest, written in that order so
a reader that sees the manifest can fetch complete payloads.  All
chunks of one migration share ONE
:class:`~horovod_tpu.utils.retry.RetryPolicy` deadline (the same
budget-shape fix :func:`~horovod_tpu.runner.api.kv_put_blob` got for
run_func blobs): a flaky store degrades the whole publish, never
stretches it to ``chunks x timeout``.

Torn-read detection is two-layered: each blob's meta record carries its
byte length (:func:`kv_get_blob` checks it), and the manifest's
``version`` field is re-read after the payload fetch — a republish of
the same mig_id mid-fetch (failover replaying the export) flips the
version and the importer retries from the manifest instead of attaching
spliced pages.

Chaos sites: ``mig_export`` fires once per published blob (so
``after=N`` lands a fault genuinely mid-migration, between chunks) and
``mig_import`` once per fetched blob.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ... import chaos
from ...obs import REGISTRY as _obs
from ...runner.api import kv_get_blob, kv_put_blob
from ...utils import retry as _retry

#: migration blobs live here in the job KV namespace.
MIG_PREFIX = "fd/mig/"

_m_migrations = _obs.counter(
    "hvd_disagg_migrations_total",
    "migration transfers by stage outcome", ("outcome",))
_m_publish_s = _obs.histogram(
    "hvd_disagg_publish_seconds",
    "export-side publish latency (all chunks of one migration)")
_m_fetch_s = _obs.histogram(
    "hvd_disagg_fetch_seconds",
    "import-side fetch latency (all chunks of one migration)")


class MigrationUnavailable(Exception):
    """The migration blob is absent, torn, or expired — the caller
    replays from an earlier durable point (usually the prompt)."""


def _keys(mig_id: str) -> tuple[str, str, str]:
    base = f"{MIG_PREFIX}{mig_id}"
    return f"{base}/k", f"{base}/v", f"{base}/manifest"


def publish_migration(kv, mig_id: str, manifest: dict, k_bytes: bytes,
                      v_bytes: bytes, *,
                      deadline_s: Optional[float] = None) -> None:
    """Publish one migration under ``fd/mig/<mig_id>`` — payloads first,
    manifest last, ONE shared deadline across every chunk of all three
    blobs."""
    k_key, v_key, m_key = _keys(mig_id)
    n_chunks = sum(max(1, (len(b) + (4 << 20) - 1) // (4 << 20))
                   for b in (k_bytes, v_bytes)) + 1
    if deadline_s is None:
        deadline_s = max(10.0, 2.0 * n_chunks)
    t0 = time.monotonic()
    deadline = t0 + deadline_s
    try:
        for key, blob in ((k_key, k_bytes), (v_key, v_bytes)):
            chaos.fire("mig_export")
            kv_put_blob(kv, key, blob,
                        deadline_s=max(0.001, deadline - time.monotonic()))
        chaos.fire("mig_export")
        kv_put_blob(kv, m_key,
                    json.dumps(manifest, sort_keys=True).encode(),
                    deadline_s=max(0.001, deadline - time.monotonic()))
    except Exception:
        _m_migrations.labels(outcome="publish_error").inc()
        raise
    _m_migrations.labels(outcome="published").inc()
    _m_publish_s.observe(time.monotonic() - t0)


def fetch_migration(kv, mig_id: str, *, timeout_ms: int = 15000
                    ) -> tuple[dict, bytes, bytes]:
    """Fetch one migration; ONE overall deadline across the manifest
    wait and every payload chunk.  Raises
    :class:`MigrationUnavailable` on absence/timeout and on a torn read
    (payload length or manifest version contradicting the manifest that
    started the fetch)."""
    k_key, v_key, m_key = _keys(mig_id)
    t0 = time.monotonic()
    deadline = t0 + timeout_ms / 1000.0

    def remaining_ms() -> int:
        return max(1, int((deadline - time.monotonic()) * 1000))

    try:
        chaos.fire("mig_import")
        manifest = json.loads(kv_get_blob(kv, m_key,
                                          timeout_ms=remaining_ms()))
        chaos.fire("mig_import")
        k_bytes = kv_get_blob(kv, k_key, timeout_ms=remaining_ms())
        chaos.fire("mig_import")
        v_bytes = kv_get_blob(kv, v_key, timeout_ms=remaining_ms())
        # Version re-check: a concurrent republish of this mig_id
        # (failover re-running the export) may have swapped the payload
        # blobs under us after we read the manifest.
        manifest2 = json.loads(kv_get_blob(kv, m_key,
                                           timeout_ms=remaining_ms()))
    except (TimeoutError, ConnectionError, OSError, ValueError) as e:
        _m_migrations.labels(outcome="fetch_error").inc()
        raise MigrationUnavailable(
            f"migration {mig_id!r} unavailable: {e}") from e
    if manifest2.get("version") != manifest.get("version"):
        _m_migrations.labels(outcome="torn").inc()
        raise MigrationUnavailable(
            f"migration {mig_id!r} torn: manifest version flipped "
            f"{manifest.get('version')!r} -> {manifest2.get('version')!r} "
            "mid-fetch")
    if len(k_bytes) != manifest.get("k_len") or \
            len(v_bytes) != manifest.get("v_len"):
        _m_migrations.labels(outcome="torn").inc()
        raise MigrationUnavailable(
            f"migration {mig_id!r} torn: payload bytes "
            f"{len(k_bytes)}/{len(v_bytes)} != manifest "
            f"{manifest.get('k_len')}/{manifest.get('v_len')}")
    _m_migrations.labels(outcome="fetched").inc()
    _m_fetch_s.observe(time.monotonic() - t0)
    return manifest, k_bytes, v_bytes


def migration_published(kv, mig_id: str) -> bool:
    """Cheap non-blocking durability probe: has this migration's
    manifest landed?  (The manifest is written LAST, so a visible
    manifest means complete payloads.)  The router's failover logic
    branches on this — a published manifest is the durable replay
    point; an unpublished one means replay from the prompt."""
    _, _, m_key = _keys(mig_id)
    try:
        return kv.get(f"{m_key}/meta") is not None
    except (ConnectionError, OSError, TimeoutError):
        return False


def delete_migration(kv, mig_id: str) -> None:
    """Best-effort cleanup once the decode replica owns the request."""
    k_key, v_key, m_key = _keys(mig_id)
    try:
        # Manifest first: a racing fetch then fails fast on the absent
        # manifest instead of reading half-deleted payload chunks.
        for prefix in (m_key, k_key, v_key):
            meta = kv.get(f"{prefix}/meta")
            if meta is None:
                continue
            n = int(meta.decode().partition(":")[0])
            kv.delete(f"{prefix}/meta")
            for i in range(n):
                kv.delete(f"{prefix}/{i}")
    except (ConnectionError, OSError, ValueError):
        pass


class DictKV:
    """In-process KV fake with the client surface the blob helpers use
    (``set``/``get``/``wait``/``delete``) — lets the disagg router,
    bench, and tests run the real transport path without a KV server."""

    def __init__(self) -> None:
        self._data: dict[str, bytes] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: bytes) -> None:
        if isinstance(value, str):
            value = value.encode()
        with self._cond:
            self._data[key] = bytes(value)
            self._cond.notify_all()

    def get(self, key: str) -> Optional[bytes]:
        with self._cond:
            return self._data.get(key)

    def wait(self, key: str, timeout_ms: int = 10000) -> bytes:
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cond:
            while key not in self._data:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"DictKV: timeout waiting for {key!r}")
                self._cond.wait(left)
            return self._data[key]

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)
