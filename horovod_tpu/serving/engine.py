"""The serving loop: compiled prefill/decode steps over the page pool.

Transport half of the policy/transport split (the scheduler decides what
runs; this owns how it runs on devices):

- **Page pool** — ``[L, num_blocks, block_size, KV, Dh]`` K and V arrays,
  allocated once, donated through every jitted step so writes land in
  place.  On a mesh the pool is constrained ``kv_heads`` over tp (the
  round-5 never-replicate-the-cache rule) and activations ``batch`` over
  dp·fsdp, via :mod:`horovod_tpu.parallel.sharding` logical rules.
- **Bucketed shapes** — prompts are right-padded to a bucket length and
  decode block tables to a power-of-two column count, so the number of
  distinct compiled shapes is logarithmic in the workload spread rather
  than linear (each novel shape is a fresh XLA compile).
- **Fixed decode batch** — the decode step always runs ``max_active``
  slots; inactive slots carry token 0 at position 0 against an
  all-scratch block table (block 0 is reserved), so their masked writes
  are harmless and their logits are ignored.
- **Greedy decode** — token-identical to batch
  :func:`~horovod_tpu.models.llama.generate` on the same prompts (the
  model-side steps reuse its math op for op); asserted in
  ``tests/test_serving.py``.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Optional

import numpy as np

from ..models import llama
from .. import chaos
from ..obs import REGISTRY as _obs
from ..obs import trace as _trace
from ..utils import logging as hvd_logging
from .kv_pager import KVPager, OutOfBlocks, PagedKVCache
from .scheduler import Request, RequestState, Scheduler

log = hvd_logging.get_logger()

# Serving-plane health (horovod_tpu.obs), sampled once per step():
_m_queue_depth = _obs.gauge(
    "hvd_serving_queue_depth", "requests waiting for admission")
_m_occupancy = _obs.gauge(
    "hvd_serving_batch_occupancy",
    "active decode slots / max_active (1.0 = the compiled batch is full)")
_m_kv_util = _obs.gauge(
    "hvd_serving_kv_utilization",
    "allocated pool blocks / usable blocks (block 0 is scratch)")
_m_steps = _obs.counter(
    "hvd_serving_steps_total", "serving rounds executed")
_m_prefill_tokens = _obs.counter(
    "hvd_serving_prefill_tokens_total", "prompt tokens prefilled")
_m_decode_tokens = _obs.counter(
    "hvd_serving_decode_tokens_total", "tokens emitted by decode ticks")
_m_prefill_skipped = _obs.counter(
    "hvd_serving_prefill_skipped_tokens_total",
    "prompt tokens NOT prefilled because a cached prefix covered them")


def _bucket_pow2(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-engine knobs (model geometry comes from ``LlamaConfig``)."""

    #: tokens per KV block (pool page size)
    block_size: int = 16
    #: total pool blocks (block 0 is scratch; HBM budget knob)
    num_blocks: int = 128
    #: decode slots — the fixed compiled decode batch
    max_active: int = 8
    #: max prompt tokens admitted to prefill per step (bounds the latency
    #: a decode tick can see; an over-budget prompt still runs, alone)
    prefill_token_budget: int = 512
    #: round prompt lengths up to one of these before compiling; empty =
    #: exact lengths (one compile per distinct prompt length)
    prefill_buckets: tuple = ()
    #: "auto" (Pallas paged kernel on TPU), "never" (XLA gather), or
    #: "interpret" (kernel through the Pallas interpreter — CPU testing)
    use_flash: str = "auto"
    #: radix prefix cache (frontdoor): admissions sharing a cached
    #: prompt prefix attach its blocks and skip prefilling them
    prefix_cache: bool = False
    #: cap on blocks the cache may pin (None = pool-pressure bounded)
    prefix_cache_max_blocks: Optional[int] = None
    #: speculative decoding: draft tokens per round (0 = off; > 0 needs
    #: ``draft_params``/``draft_cfg`` at engine construction)
    spec_k: int = 0


class ServingEngine:
    """Continuous-batching engine over one model + page pool.

    Drive it with :meth:`submit` + :meth:`step` (one scheduler round:
    retire, admit+prefill, decode tick); :meth:`run` loops until idle.
    Emitted tokens reach the caller through ``Request.generated`` and the
    per-token callbacks the API layer wires in.
    """

    def __init__(self, params: Any, cfg: llama.LlamaConfig, *,
                 engine_cfg: EngineConfig = EngineConfig(),
                 mesh=None, timeline=None,
                 draft_params: Any = None,
                 draft_cfg: Optional[llama.LlamaConfig] = None) -> None:
        #: Timeline-v2 sink request traces render on (one lane per
        #: request with QUEUE->PREFILL->DECODE flow arrows); None keeps
        #: traces JSON/flight-recorder-only.
        self.timeline = timeline
        if cfg.use_moe:
            raise NotImplementedError("serving does not support MoE configs")
        self.params = params
        self.cfg = cfg
        self.ecfg = engine_cfg
        self.mesh = mesh
        if mesh is not None:
            dpf = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
            if engine_cfg.max_active % dpf:
                raise ValueError(
                    f"max_active={engine_cfg.max_active} must divide over "
                    f"dp*fsdp={dpf}")
            for a in ("sp", "ep", "pp"):
                if mesh.shape.get(a, 1) > 1:
                    raise NotImplementedError(
                        "serving supports dp/fsdp/tp meshes; "
                        f"{a} is a training-path axis here")
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp

        self.cache = PagedKVCache(
            n_layers=cfg.n_layers, num_blocks=engine_cfg.num_blocks,
            block_size=engine_cfg.block_size, kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim)
        self.pager = KVPager(self.cache)
        self.prefix_cache = None
        if engine_cfg.prefix_cache:
            from .frontdoor.prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(
                self.pager,
                max_blocks=engine_cfg.prefix_cache_max_blocks)
        self.scheduler = Scheduler(
            self.pager, max_active=engine_cfg.max_active,
            prefill_token_budget=engine_cfg.prefill_token_budget,
            prefix_cache=self.prefix_cache)

        def fresh_pool():
            pool = jnp.zeros(self.cache.shape, cfg.dtype)
            if mesh is not None:
                from ..parallel import sharding as shd
                pool = jax.device_put(pool, shd.logical_sharding(
                    mesh, (None, None, None, "kv_heads", None),
                    llama.shard_rules(cfg, mesh)))
            return pool

        self.k_pool = fresh_pool()
        self.v_pool = fresh_pool()

        self._slots: list[Optional[Request]] = \
            [None] * engine_cfg.max_active
        self._next_id = 0
        self._steps = 0

        flash = engine_cfg.use_flash
        from ..ops import flash_attention as FA
        kernel_ok = FA.paged_supported(engine_cfg.block_size, cfg.head_dim)
        self._interpret = flash == "interpret"
        self._use_flash = kernel_ok and (
            flash == "interpret"
            or (flash == "auto" and jax.default_backend() == "tpu"))

        # One jit per step kind; bucketing keeps the traced shape set
        # small and jax's cache does the rest.
        self._prefill = jax.jit(partial(self._prefill_impl))
        self._scatter = jax.jit(partial(self._scatter_impl),
                                donate_argnums=(0, 1))
        self._decode = jax.jit(partial(self._decode_impl),
                               donate_argnums=(1, 2))
        self._extend = jax.jit(partial(self._extend_impl),
                               donate_argnums=(1, 2))

        self.spec = None
        if engine_cfg.spec_k:
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "spec_k > 0 needs draft_params and draft_cfg")
            from .frontdoor.spec_decode import SpecDecoder
            self.spec = SpecDecoder(self, draft_params, draft_cfg,
                                    k=engine_cfg.spec_k)

    # -- jitted step bodies ---------------------------------------------
    def _prefill_impl(self, params, tokens, last_pos):
        jnp = self._jnp
        logits, ks, vs = llama.prefill_step(
            params, tokens, self.cfg, mesh=self.mesh, last_pos=last_pos)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), ks, vs

    def _scatter_impl(self, kp, vp, ks, vs, blocks):
        """Write one request's prefill K/V ([L, 1, P, KV, Dh]) into its
        pool blocks.  P is padded up to a whole number of blocks; the
        tail slots hold pad-token K/V, masked by position until decode
        overwrites them one at a time."""
        jnp = self._jnp
        L = ks.shape[0]
        P = ks.shape[2]
        BS = self.cache.block_size
        nb = blocks.shape[0]
        pad = nb * BS - P
        ks = jnp.pad(ks[:, 0], ((0, 0), (0, pad), (0, 0), (0, 0)))
        vs = jnp.pad(vs[:, 0], ((0, 0), (0, pad), (0, 0), (0, 0)))
        ks = ks.reshape(L, nb, BS, *ks.shape[2:])
        vs = vs.reshape(L, nb, BS, *vs.shape[2:])
        return kp.at[:, blocks].set(ks), vp.at[:, blocks].set(vs)

    def _decode_impl(self, params, kp, vp, tok, pos, tables):
        jnp = self._jnp
        logits, kp, vp = llama.decode_step_paged(
            params, tok, pos, kp, vp, tables, self.cfg, mesh=self.mesh,
            use_flash=self._use_flash, interpret=self._interpret)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

    def _extend_impl(self, params, kp, vp, tok, pos, valid, tables):
        """Multi-token paged forward ([B, S] at arbitrary positions):
        the prefix-hit tail prefill and the speculative verify step."""
        jnp = self._jnp
        logits, kp, vp = llama.extend_step_paged(
            params, tok, pos, valid, kp, vp, tables, self.cfg,
            mesh=self.mesh)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

    # -- public surface --------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, eos_token=None,
               stream_cb=None, migrate_cb=None, trace_ctx=None) -> Request:
        # Chaos site: admission.  err rejects the request before it
        # queues (the caller sees the raise, nothing leaks into the
        # scheduler); delay throttles intake.
        chaos.fire("serving_admit")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        need = self.cache.blocks_for(int(prompt.size) + 1)
        usable = self.cache.num_blocks - 1
        if need > usable:
            # Reject up front: an unfillable prompt at the head of the
            # strictly-FIFO queue would otherwise livelock admission.
            raise ValueError(
                f"prompt of {prompt.size} tokens needs {need} blocks; the "
                f"pool only has {usable} (raise num_blocks/block_size)")
        req = Request(req_id=self._next_id, prompt=prompt,
                      max_new_tokens=max_new_tokens, eos_token=eos_token,
                      stream_cb=stream_cb, migrate_cb=migrate_cb)
        self._next_id += 1
        # Admission is the root of the request's causal chain: one trace
        # id covers every phase span from here to the terminal state
        # (obs/trace decides sampling; unsampled requests ride NULL_SPAN).
        # trace_ctx joins a trace started upstream (the frontdoor router's
        # ingress span, carried through the request transport) instead of
        # opening a fresh one.
        req.trace = _trace.TRACER.start_trace(
            "serving.request", lane=f"req{req.req_id}",
            timeline=self.timeline, parent=trace_ctx, req_id=req.req_id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens)
        self.scheduler.submit(req)
        return req

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def pop_failed(self) -> list:
        """Requests the scheduler declared unrunnable (e.g. a preempted
        request whose folded-in progress no longer fits the pool), as
        ``(request, exception)`` pairs — callers fail their futures."""
        failed = self.scheduler.failed
        self.scheduler.failed = []
        return failed

    def step(self) -> list[tuple[Request, int]]:
        """One serving round; returns the (request, token) emissions.

        A raise out of here (device failure, collective abort, injected
        fault) leaves the scheduler/pager bookkeeping consistent enough
        for :meth:`abort_inflight` — the session layer catches, aborts
        the in-flight set with an ``error`` finish_reason, flips
        ``/healthz``, and drains-and-rejoins instead of dying."""
        # Chaos site: one traversal per serving round (decode step).
        chaos.fire("serving_step")
        emitted: list[tuple[Request, int]] = []
        self._steps += 1
        _m_steps.inc()
        for req in self.scheduler.admit():
            self._assign_slot(req)
            _m_prefill_tokens.inc(
                int(req.prefill_tokens.shape[0]) - req.cached_tokens)
            emitted.append((req, self._prefill_one(req)))
            if req.migrate_cb is not None \
                    and req.state == RequestState.RUNNING:
                # Disaggregated handoff: this replica's job ends at the
                # prefill emission — export the KV blocks while the
                # pager table is still held and let a decode replica
                # continue the request (serving/disagg).
                self._migrate_out(req)
        if self.scheduler.running:
            ticked = (self.spec.tick() if self.spec is not None
                      else self._decode_tick())
            _m_decode_tokens.inc(len(ticked))
            emitted.extend(ticked)
        self._sample_gauges()
        return emitted

    def _sample_gauges(self) -> None:
        """Pool/queue health after a step: queue depth, compiled-batch
        occupancy, KV-pool utilization."""
        _m_queue_depth.set(len(self.scheduler.waiting))
        _m_occupancy.set(
            len(self.scheduler.running) / self.ecfg.max_active)
        usable = self.cache.num_blocks - 1
        _m_kv_util.set((usable - self.pager.free_blocks) / usable)

    def run(self, max_steps: Optional[int] = None
            ) -> list[tuple[Request, int]]:
        """Steps until the queue drains; returns all emissions in order."""
        out: list[tuple[Request, int]] = []
        n = 0
        while self.has_work():
            out.extend(self.step())
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return out

    # -- internals -------------------------------------------------------
    def _assign_slot(self, req: Request) -> None:
        i = self._slots.index(None)
        self._slots[i] = req

    def _drop_slot(self, req: Request) -> None:
        self._slots[self._slots.index(req)] = None

    def _sync_slots(self) -> None:
        """Preemption inside scheduler.grow() removes requests from the
        running set behind the engine's back; drop their slots."""
        running = set(id(r) for r in self.scheduler.running)
        for i, r in enumerate(self._slots):
            if r is not None and id(r) not in running:
                self._slots[i] = None

    def _bucket_len(self, n: int) -> int:
        for b in self.ecfg.prefill_buckets:
            if n <= b:
                return b
        return n

    def _prefill_one(self, req: Request) -> int:
        if req.cached_tokens > 0:
            return self._prefill_cached(req)
        jnp = self._jnp
        toks = req.prefill_tokens
        P = int(toks.shape[0])
        Pb = self._bucket_len(P)
        sp = req.open_phase("prefill", tokens=P, bucket=Pb)
        # The span is the context's current span while the prefill
        # dispatches, so nested layers (collectives the model enqueues)
        # attach their events to this request's chain.
        with sp.use():
            padded = np.zeros((1, Pb), np.int32)
            padded[0, :P] = toks
            tok, ks, vs = self._prefill(
                self.params, jnp.asarray(padded),
                jnp.asarray([P - 1], jnp.int32))
            blocks = self.pager.table(req.req_id)
            nb = self.cache.blocks_for(P)
            # Only the blocks the P real positions span are written; the
            # +1 slot block (for the emitted token) is untouched here.
            lim = min(Pb, nb * self.cache.block_size)
            ks, vs = ks[:, :, :lim], vs[:, :, :lim]
            self.k_pool, self.v_pool = self._scatter(
                self.k_pool, self.v_pool, ks, vs,
                jnp.asarray(blocks[:nb], jnp.int32))
            if self.spec is not None:
                self.spec.mirror_prefill(req, padded, P)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(toks, self.pager.table(req.req_id))
        req.close_phase("prefill")
        token = self._emit(req, int(tok[0]))
        if req.state == RequestState.RUNNING:
            # The decode phase opens once and spans every tick until the
            # terminal state (scheduler.finish/preempt closes it).
            req.open_phase("decode")
        return token

    def _prefill_cached(self, req: Request) -> int:
        """Prefix-hit prefill: the cached head's K/V is already in the
        pool under the shared table head, so only the ``P - C`` tail
        tokens run — through the multi-token extend step, attending over
        the cached blocks via the request's table."""
        jnp = self._jnp
        toks = req.prefill_tokens
        P = int(toks.shape[0])
        C = req.cached_tokens
        S = P - C
        Sb = _bucket_pow2(S)
        sp = req.open_phase("prefill", tokens=P, cached=C, bucket=Sb)
        with sp.use():
            req.trace.event("prefill_skip", cached_tokens=C)
            tok2 = np.zeros((1, Sb), np.int32)
            tok2[0, :S] = toks[C:]
            # Padded slots repeat a valid position but carry valid=False,
            # so their writes land in scratch block 0 and their logits
            # are never read.
            pos2 = np.full((1, Sb), P - 1, np.int32)
            pos2[0, :S] = np.arange(C, P, dtype=np.int32)
            val2 = np.zeros((1, Sb), bool)
            val2[0, :S] = True
            n_cols = min(_bucket_pow2(self.cache.blocks_for(P)),
                         self.cache.num_blocks)
            tables = self.pager.table_matrix([req.req_id], n_cols)
            nxt, self.k_pool, self.v_pool = self._extend(
                self.params, self.k_pool, self.v_pool,
                jnp.asarray(tok2), jnp.asarray(pos2),
                jnp.asarray(val2), jnp.asarray(tables))
            if self.spec is not None:
                self.spec.mirror_extend(tok2, pos2, val2, tables)
        if self.prefix_cache is not None:
            # The tail may complete further full blocks; share them too.
            self.prefix_cache.insert(toks, self.pager.table(req.req_id))
        _m_prefill_skipped.inc(C)
        req.close_phase("prefill")
        token = self._emit(req, int(nxt[0, S - 1]))
        if req.state == RequestState.RUNNING:
            req.open_phase("decode")
        return token

    def _decode_tick(self) -> list[tuple[Request, int]]:
        jnp = self._jnp
        # Reserve the write position for every running request first —
        # growth can preempt, shrinking the running set.
        for req in list(self.scheduler.running):
            if req in self.scheduler.running:
                try:
                    self.scheduler.grow(req)
                except OutOfBlocks as e:
                    # Only reachable when req cannot fit even alone
                    # (grow preempts every other victim first): fail
                    # THIS request and keep the batch serving — a
                    # per-request capacity problem must not abort the
                    # engine.
                    self.scheduler.fail_running(req, e)
        self._sync_slots()
        active = [r for r in self._slots if r is not None]
        if not active:
            return []
        R = self.ecfg.max_active
        need_cols = max(
            self.cache.blocks_for(r.context_len + 1) for r in active)
        n_cols = min(_bucket_pow2(need_cols), self.cache.num_blocks)
        tok = np.zeros((R,), np.int32)
        pos = np.zeros((R,), np.int32)
        ids = [-1] * R
        for i, r in enumerate(self._slots):
            if r is None:
                continue
            tok[i] = r.generated[-1]
            pos[i] = r.context_len
            ids[i] = r.req_id
        tables = self.pager.table_matrix(ids, n_cols)
        nxt, self.k_pool, self.v_pool = self._decode(
            self.params, self.k_pool, self.v_pool,
            jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(tables))
        nxt = np.asarray(nxt)
        emitted = []
        for i, r in enumerate(list(self._slots)):
            if r is None:
                continue
            r.context_len += 1          # this tick wrote pos[i]
            emitted.append((r, self._emit(r, int(nxt[i]))))
        return emitted

    def _emit(self, req: Request, token: int) -> int:
        req.generated.append(token)
        eos = req.eos_token is not None and token == req.eos_token
        done = eos or len(req.generated) >= req.max_new_tokens
        if done:
            req.finish_reason = "stop" if eos else "length"
            self.scheduler.finish(req)
            self._drop_slot(req)
        return token

    def _migrate_out(self, req: Request) -> None:
        """Export ``req``'s KV blocks and retire it locally with
        ``finish_reason="migrated"``.  Runs right after the prefill
        emission, BEFORE ``scheduler.finish`` releases the blocks; the
        export is a host-side copy, so by the time the callback gets the
        payload the pool blocks are free to recycle.  A callback failure
        (KV store down, injected fault) fails THIS request only — the
        batch keeps serving."""
        from .disagg import migration
        sp = req.open_phase("migrate", context_len=req.context_len)
        try:
            with sp.use():
                manifest, k_bytes, v_bytes = migration.export_request(
                    self, req)
            req.close_phase("migrate",
                            bytes=len(k_bytes) + len(v_bytes))
            req.finish_reason = "migrated"
            self.scheduler.finish(req)
            self._drop_slot(req)
            req.migrate_cb(manifest, k_bytes, v_bytes)
        except Exception as e:
            req.close_phase("migrate", error=str(e))
            if req in self.scheduler.running:
                self.scheduler.fail_running(req, e)
                self._drop_slot(req)
            else:
                # Export succeeded but the publish callback failed after
                # finish(): surface through the failed list so the
                # session fails the future instead of hanging it.
                req.state = RequestState.CANCELLED
                req.finish_reason = "error"
                self.scheduler.failed.append((req, e))

    def import_migrated(self, manifest: dict, k_bytes: bytes,
                        v_bytes: bytes, *, stream_cb=None) -> Request:
        """Attach a migrated request's exported KV blocks to this
        engine's pool and resume decoding it — zero re-prefill, token
        identical to a local prefill (greedy decode).  See
        :mod:`horovod_tpu.serving.disagg.migration`."""
        from .disagg import migration
        return migration.import_request(self, manifest, k_bytes, v_bytes,
                                        stream_cb=stream_cb)

    def abort_inflight(self, exc: BaseException) -> list[Request]:
        """Graceful-degradation half of a step failure: finish every
        queued and running request NOW with ``finish_reason="error"``
        (partial tokens preserved — streamed clients already hold
        them), release their pool blocks, and leave the engine empty
        and reusable.  Returns the aborted requests; the session layer
        resolves their futures and owns the /healthz + rejoin story."""
        aborted: list[Request] = []
        for req in list(self.scheduler.running):
            self.scheduler.running.remove(req)
            self.pager.release(req.req_id)
            aborted.append(req)
        while self.scheduler.waiting:
            aborted.append(self.scheduler.waiting.popleft())
        for req in aborted:
            req.state = RequestState.CANCELLED
            req.finish_reason = "error"
            req.t_finished = time.monotonic()
            req.close_trace("aborted", error=str(exc))
        self._slots = [None] * self.ecfg.max_active
        self._sample_gauges()
        return aborted
