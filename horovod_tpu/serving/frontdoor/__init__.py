"""Production serving front door: multi-replica routing, radix prefix KV
reuse, speculative decoding.

Three cooperating pieces behind one :class:`Router` entry point:

- :mod:`.router` — admits requests and places them across dp serving
  replicas by the signals the obs plane already publishes to the job KV
  store (queue depth, TTFT p99, SLO burn rate, readiness), with
  prefix-affinity stickiness and health-aware failover;
- :mod:`.prefix_cache` — a radix-tree prefix cache over the
  :class:`~horovod_tpu.serving.kv_pager.KVPager` so shared prompt
  prefixes skip prefill entirely (block-granular refcounted sharing);
- :mod:`.spec_decode` — draft-model speculative decoding as a scheduler
  mode: draft k tokens with a small model, verify in one target forward
  over the paged cache, accept the agreeing prefix, roll back the rest.

``transport`` carries requests between a router process and replica
processes over the job's existing authenticated KV store — the same "no
new network surface" rule the obs plane follows.
"""

from .prefix_cache import PrefixCache
from .router import (LocalReplica, NoReplicaAvailable, Router,
                     RouterConfig)
from .spec_decode import SpecDecoder

__all__ = [
    "LocalReplica", "NoReplicaAvailable", "PrefixCache", "Router",
    "RouterConfig", "SpecDecoder",
]
