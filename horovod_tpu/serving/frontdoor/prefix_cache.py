"""Radix-tree prefix cache over the paged KV pool (vLLM-style).

Two requests that share a prompt prefix compute byte-identical K/V for
it (greedy serving is deterministic and RoPE positions of a shared
prefix are identical by construction), so the second request can point
its block table at the first one's blocks and skip prefilling them.
This module owns the sharing index; the refcounting that makes it safe
lives in :class:`~horovod_tpu.serving.kv_pager.KVPager`:

- **nodes are whole blocks**: one radix node per ``block_size`` token
  chunk, keyed by the chunk's exact token ids.  Only FULL blocks enter
  the tree — a partially-filled block is still written by decode ticks,
  and a shared block must be immutable (this is what makes
  copy-on-write unnecessary);
- **insert-on-prefill**: after a request's prompt K/V lands in the
  pool, its full prompt blocks are inserted; each newly-shared block is
  ``pin()``-ed so it survives the owning request's release;
- **longest-prefix match at admission**, capped at ``len(prompt) - 1``
  tokens rounded down to a block multiple — at least one prompt token
  must prefill to produce the first-token logits;
- **LRU eviction of refcount-1 leaves** (held only by the cache's own
  pin) under :class:`~horovod_tpu.serving.kv_pager.OutOfBlocks`
  pressure; evicting a leaf can expose its parent as the next
  candidate, so eviction cascades bottom-up.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ...obs import REGISTRY as _obs
from ..kv_pager import KVPager

_m_hits = _obs.counter(
    "hvd_prefix_cache_hits_total",
    "admissions whose prompt matched a cached prefix (>= 1 block)")
_m_misses = _obs.counter(
    "hvd_prefix_cache_misses_total",
    "admissions with no cached prefix block")
_m_evictions = _obs.counter(
    "hvd_prefix_cache_evictions_total",
    "cached blocks evicted (LRU, refcount-1 leaves) under pool pressure")
_m_shared = _obs.counter(
    "hvd_prefix_cache_blocks_shared_total",
    "prefill block-writes skipped by attaching cached blocks instead")
_m_resident = _obs.gauge(
    "hvd_prefix_cache_blocks", "blocks currently pinned by the cache")


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_use")

    def __init__(self, key: tuple, block: int,
                 parent: Optional["_Node"]) -> None:
        self.key = key
        self.block = block
        self.children: dict[tuple, "_Node"] = {}
        self.parent = parent
        self.last_use = 0


class PrefixCache:
    """Radix tree of cached prompt blocks over one :class:`KVPager`.

    ``max_blocks`` bounds the pinned working set (None = bounded only by
    pool pressure via :meth:`evict`).
    """

    def __init__(self, pager: KVPager, *,
                 max_blocks: Optional[int] = None) -> None:
        self.pager = pager
        self.block_size = pager.cache.block_size
        self.max_blocks = max_blocks
        self._root: dict[tuple, _Node] = {}
        self._tick = 0
        self._n_blocks = 0

    # -- queries ---------------------------------------------------------
    @property
    def resident_blocks(self) -> int:
        return self._n_blocks

    def _chunks(self, tokens, n_blocks: int):
        toks = np.asarray(tokens, np.int32)
        BS = self.block_size
        for i in range(n_blocks):
            yield tuple(int(t) for t in toks[i * BS:(i + 1) * BS])

    def match(self, tokens) -> tuple[int, list[int]]:
        """Longest cached prefix of ``tokens``: (matched_token_count,
        blocks).  Capped at ``len(tokens) - 1`` so at least one token
        always prefills (the first-token logits must come from
        somewhere); matched nodes get their LRU stamp refreshed."""
        n = int(np.asarray(tokens).shape[0])
        limit_blocks = max(0, n - 1) // self.block_size
        self._tick += 1
        blocks: list[int] = []
        children = self._root
        for key in self._chunks(tokens, limit_blocks):
            node = children.get(key)
            if node is None:
                break
            node.last_use = self._tick
            blocks.append(node.block)
            children = node.children
        if blocks:
            _m_hits.inc()
            _m_shared.inc(len(blocks))
        else:
            _m_misses.inc()
        return len(blocks) * self.block_size, blocks

    def peek(self, tokens) -> int:
        """Matched-token count of the longest cached prefix, without any
        side effect: no LRU refresh, no hit/miss counters.  The disagg
        router uses this as a placement probe — a probe that mutated LRU
        order would let scoring traffic evict real working sets."""
        n = int(np.asarray(tokens).shape[0])
        limit_blocks = max(0, n - 1) // self.block_size
        matched = 0
        children = self._root
        for key in self._chunks(tokens, limit_blocks):
            node = children.get(key)
            if node is None:
                break
            matched += 1
            children = node.children
        return matched * self.block_size

    def insert(self, tokens, table: Sequence[int]) -> int:
        """Insert the full blocks of a just-prefilled prompt; returns the
        number of NEW nodes.  ``table`` is the request's block table (its
        head is the cached prefix on a hit, so re-inserting a matched
        path just refreshes LRU stamps).  A concurrent-miss collision
        (two requests prefilled the same prompt before either inserted)
        keeps the first request's block; the loser's stays privately
        owned and frees on release."""
        n_full = int(np.asarray(tokens).shape[0]) // self.block_size
        self._tick += 1
        added = 0
        children, parent = self._root, None
        for i, key in enumerate(self._chunks(tokens, n_full)):
            node = children.get(key)
            if node is None:
                if self.max_blocks is not None \
                        and self._n_blocks >= self.max_blocks \
                        and not self.evict(1, protect=table):
                    break                      # cap reached, nothing evictable
                node = _Node(key, int(table[i]), parent)
                self.pager.pin(node.block)
                children[key] = node
                self._n_blocks += 1
                added += 1
            node.last_use = self._tick
            children, parent = node.children, node
        _m_resident.set(self._n_blocks)
        return added

    def evict(self, n_blocks: int, protect: Sequence[int] = ()) -> int:
        """Unpin up to ``n_blocks`` least-recently-used evictable leaves
        (evictable = refcount 1, i.e. held by nobody but the cache, and
        not in ``protect`` — the admission path protects a just-matched
        prefix that has not been attached to a table yet).  Returns how
        many blocks were actually freed."""
        guard = frozenset(int(b) for b in protect)
        freed = 0
        while freed < n_blocks:
            victim = self._lru_leaf(guard)
            if victim is None:
                break
            self.pager.unpin(victim.block)
            siblings = (victim.parent.children if victim.parent is not None
                        else self._root)
            del siblings[victim.key]
            self._n_blocks -= 1
            freed += 1
            _m_evictions.inc()
        _m_resident.set(self._n_blocks)
        return freed

    def _lru_leaf(self, guard: frozenset) -> Optional[_Node]:
        best: Optional[_Node] = None
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
                continue
            if node.block in guard or self.pager.refcount(node.block) != 1:
                continue
            if best is None or node.last_use < best.last_use:
                best = node
        return best
