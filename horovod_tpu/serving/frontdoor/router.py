"""Multi-replica request router: signal-driven placement + failover.

The router is the placement authority in front of N dp serving
replicas.  It holds no model state — placement runs entirely on the
signals the obs plane already publishes to the job KV store per rank
(queue depth, batch occupancy, TTFT p99, SLO burn rate, readiness), so
the router scrapes nothing and opens no new connections:

- **eligibility** — a replica takes new placements only when it is
  alive (membership present), READY (``hvd_replica_ready``, mirroring
  the replica's ``/healthz`` serving component), and its snapshot is
  FRESH by the shared 2x-publish-interval rule
  (:func:`horovod_tpu.obs.aggregate.snapshot_is_stale`) — a frozen
  publisher is a crashed or wedged replica no matter what its last
  snapshot claimed;
- **prefix affinity** — requests whose prompts share a head stick to
  the replica that saw the head first, so its radix prefix cache
  (:mod:`.prefix_cache`) keeps hitting; affinity yields to eligibility
  (a dead favorite is re-hashed, not waited for);
- **least-loaded scoring** otherwise: queue depth + weighted TTFT p99
  + weighted SLO burn, smallest wins;
- **failover** — flights on a replica that goes dead resubmit to a
  survivor with their partial tokens DISCARDED (the survivor replays
  from the prompt; greedy decode makes the replay token-identical, and
  streaming consumers see at-least-once delivery).  ``finish_reason``
  semantics are preserved: the client sees the natural ``stop`` /
  ``length`` from whichever replica finished, never a synthetic one.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from ... import chaos
from ...obs import REGISTRY as _obs
from ...obs import trace as _trace
from ...utils import logging as hvd_logging
from ..api import RequestResult

log = hvd_logging.get_logger()

_m_placed = _obs.counter(
    "hvd_router_placed_total", "placements by replica", ("replica",))
_m_failovers = _obs.counter(
    "hvd_router_failovers_total",
    "flights resubmitted after their replica went dead or errored")
_m_affinity = _obs.counter(
    "hvd_router_affinity_hits_total",
    "placements that followed prefix affinity to a sticky replica")
_m_requests = _obs.counter(
    "hvd_router_requests_total", "router requests by terminal outcome",
    ("outcome",))
_m_healthy = _obs.gauge(
    "hvd_router_replica_healthy",
    "1 = alive+ready+fresh, eligible for new placements", ("replica",))
_m_pending = _obs.gauge(
    "hvd_router_pending",
    "submitted flights waiting for an eligible replica")


class NoReplicaAvailable(RuntimeError):
    """No replica is alive, ready, and fresh."""


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    #: placement attempts per request (1 initial + failovers) before its
    #: future fails
    max_attempts: int = 3
    #: prompt tokens hashed into the prefix-affinity key (0 disables
    #: stickiness)
    affinity_tokens: int = 16
    #: bounded affinity table (LRU) — old prefixes age out
    affinity_capacity: int = 1024
    #: scoring weights: score = queue_depth + ttft_weight * ttft_p99
    #: + burn_weight * slo_burn; the smallest score wins
    ttft_weight: float = 10.0
    burn_weight: float = 5.0
    #: drain() poll cadence
    poll_interval_s: float = 0.02
    #: an EXISTING flight fails over only after its replica has looked
    #: dead (not alive, or snapshot stale) for this long continuously —
    #: one missed publish interval (a replica busy compiling) must not
    #: strand work; dead-at-placement replicas are skipped immediately
    failover_grace_s: float = 1.5


@dataclasses.dataclass
class _Flight:
    fid: int
    prompt: np.ndarray
    max_tokens: int
    eos_token: Optional[int]
    stream_cb: Optional[Callable[[int, int], None]]
    future: Future
    affinity_key: Optional[tuple]
    trace: object
    replica: object = None
    handle: object = None
    attempts: int = 0
    delivered: int = 0            # streamed tokens relayed so far


class LocalReplica:
    """In-process replica over one
    :class:`~horovod_tpu.serving.api.ServingSession` — the bench/test
    twin of :class:`~.transport.KVReplicaClient` (same protocol), plus
    :meth:`kill` to simulate a crash: a killed replica stops stepping
    and goes dead in its signals, leaving its flights to failover."""

    def __init__(self, replica_id: str, session) -> None:
        self.replica_id = str(replica_id)
        self.session = session
        self.killed = False

    def kill(self) -> None:
        self.killed = True

    def drive(self) -> None:
        if not self.killed and self.session.engine.has_work():
            self.session._step_once()

    def signals(self) -> dict:
        if self.killed:
            from .transport import DEAD_SIGNALS
            return dict(DEAD_SIGNALS)
        eng = self.session.engine
        return {
            "alive": True, "stale": False, "ready": True,
            "queue_depth": float(len(eng.scheduler.waiting)),
            "occupancy": (len(eng.scheduler.running)
                          / eng.ecfg.max_active),
            "ttft_p99": None, "slo_burn": 0.0,
        }

    def submit(self, prompt, max_tokens: int, *,
               eos_token: Optional[int] = None,
               trace_ctx: Optional[dict] = None):
        tokens: list[int] = []
        fut = self.session.submit(
            prompt, max_tokens, eos_token=eos_token,
            stream_cb=lambda rid, t: tokens.append(int(t)),
            trace_ctx=trace_ctx)
        return (fut, tokens)

    def partial_tokens(self, handle) -> list[int]:
        return list(handle[1])

    def result(self, handle) -> Optional[dict]:
        fut = handle[0]
        if self.killed or not fut.done():
            return None
        try:
            res = fut.result()
        except Exception as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "tokens": list(res.tokens),
                "finish_reason": res.metrics.get("finish_reason"),
                "metrics": res.metrics}


class Router:
    """Placement + lifecycle over a set of replica handles
    (:class:`LocalReplica` in-process,
    :class:`~.transport.KVReplicaClient` across processes — any object
    with the same five-method protocol).

    Single-threaded by design: :meth:`submit` records the flight and
    tries to place it; :meth:`pump` is one non-blocking pass (drive
    local replicas, relay streams, resolve results, failover dead
    replicas' flights, place the pending queue); :meth:`drain` pumps
    until every flight resolves."""

    def __init__(self, replicas: Sequence,
                 cfg: RouterConfig = RouterConfig()) -> None:
        if not replicas:
            raise ValueError("router needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self.cfg = cfg
        self._flights: dict[int, _Flight] = {}     # placed, in flight
        self._pending: deque[_Flight] = deque()    # awaiting placement
        self._affinity: OrderedDict = OrderedDict()
        self._next_fid = 0
        self._unhealthy_since: dict[str, float] = {}
        self.failovers = 0

    # -- client surface --------------------------------------------------
    def submit(self, prompt, max_tokens: int, *,
               eos_token: Optional[int] = None,
               stream_cb: Optional[Callable[[int, int], None]] = None
               ) -> Future:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        key = (tuple(int(t) for t
                     in prompt[:self.cfg.affinity_tokens])
               if self.cfg.affinity_tokens > 0 else None)
        fl = _Flight(
            fid=self._next_fid, prompt=prompt, max_tokens=max_tokens,
            eos_token=eos_token, stream_cb=stream_cb, future=Future(),
            affinity_key=key,
            trace=_trace.TRACER.start_trace(
                "router.request", lane=f"fd{self._next_fid}",
                prompt_len=int(prompt.size), max_tokens=max_tokens))
        self._next_fid += 1
        sigs = self._signals()
        self._refresh_health(sigs)
        try:
            self._place(fl, sigs)
        except NoReplicaAvailable:
            # Queue rather than reject: a drain window (every replica
            # briefly unready) should delay requests, not drop them.
            self._pending.append(fl)
        _m_pending.set(float(len(self._pending)))
        return fl.future

    def pump(self) -> None:
        """One non-blocking router pass."""
        for rep in self.replicas:
            rep.drive()
        sigs = self._signals()
        self._refresh_health(sigs)
        now = time.monotonic()
        for rid, sig in sigs.items():
            if self._eligible(sig, for_placement=False):
                self._unhealthy_since.pop(rid, None)
            else:
                self._unhealthy_since.setdefault(rid, now)
        for fl in list(self._flights.values()):
            self._relay_stream(fl)
            res = fl.replica.result(fl.handle)
            if res is not None:
                self._settle(fl, res, sigs)
            elif self._dead_for_grace(fl.replica.replica_id, now):
                self._failover(fl, sigs, why="replica dead")
        while self._pending:
            fl = self._pending[0]
            try:
                self._place(fl, sigs)
            except NoReplicaAvailable:
                break
            self._pending.popleft()
        _m_pending.set(float(len(self._pending)))

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Pump until every flight resolved (or the deadline passes)."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while self._flights or self._pending:
            self.pump()
            if not (self._flights or self._pending):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"router drain: {len(self._flights)} in flight, "
                    f"{len(self._pending)} pending at deadline")
            time.sleep(self.cfg.poll_interval_s)

    # -- internals -------------------------------------------------------
    def _signals(self) -> dict:
        return {rep.replica_id: rep.signals() for rep in self.replicas}

    def _dead_for_grace(self, rid: str, now: float) -> bool:
        since = self._unhealthy_since.get(rid)
        return (since is not None
                and now - since >= self.cfg.failover_grace_s)

    @staticmethod
    def _eligible(sig: dict, *, for_placement: bool = True) -> bool:
        """Placement needs alive+fresh+ready; an EXISTING flight only
        needs its replica alive and fresh — an unready replica is
        draining but may still finish what it holds."""
        ok = sig["alive"] and not sig["stale"]
        return ok and sig["ready"] if for_placement else ok

    def _refresh_health(self, sigs: dict) -> None:
        for rid, sig in sigs.items():
            _m_healthy.labels(replica=rid).set(
                1.0 if self._eligible(sig) else 0.0)

    def _place(self, fl: _Flight, sigs: dict) -> None:
        # Chaos site: one traversal per placement decision; err makes
        # this placement fail over (or queue), delay slows the router.
        chaos.fire("router")
        eligible = [rep for rep in self.replicas
                    if self._eligible(sigs[rep.replica_id])]
        if not eligible:
            raise NoReplicaAvailable(
                "no replica is alive, ready, and fresh")
        chosen = None
        sticky = (self._affinity.get(fl.affinity_key)
                  if fl.affinity_key is not None else None)
        if sticky is not None:
            for rep in eligible:
                if rep.replica_id == sticky:
                    chosen = rep
                    _m_affinity.inc()
                    break
        if chosen is None:
            # The router's own outstanding-flight count per replica
            # joins the published queue depth: snapshots lag by a
            # publish interval, so a burst of submits scored on the
            # snapshot alone would dogpile whichever replica last
            # published an idle view.
            outstanding: dict[str, int] = {}
            for other in self._flights.values():
                rid = other.replica.replica_id
                outstanding[rid] = outstanding.get(rid, 0) + 1

            def score(rep):
                s = sigs[rep.replica_id]
                return (s["queue_depth"] + s["occupancy"]
                        + outstanding.get(rep.replica_id, 0)
                        + self.cfg.ttft_weight * (s["ttft_p99"] or 0.0)
                        + self.cfg.burn_weight * s["slo_burn"])
            chosen = min(eligible, key=score)
        if fl.affinity_key is not None:
            self._affinity[fl.affinity_key] = chosen.replica_id
            self._affinity.move_to_end(fl.affinity_key)
            while len(self._affinity) > self.cfg.affinity_capacity:
                self._affinity.popitem(last=False)
        fl.attempts += 1
        fl.replica = chosen
        fl.delivered = 0
        # The ingress span's context rides the submit so the replica's
        # engine trace joins this flight's trace_id (one connected trace
        # across router and replica processes).
        fl.handle = chosen.submit(fl.prompt, fl.max_tokens,
                                  eos_token=fl.eos_token,
                                  trace_ctx=fl.trace.context())
        # Queue depth moves immediately so the next placement in this
        # same pass doesn't dogpile the replica that just looked idle.
        sigs[chosen.replica_id]["queue_depth"] += 1
        self._flights[fl.fid] = fl
        _m_placed.labels(replica=chosen.replica_id).inc()
        sp = fl.trace.child("ROUTE", replica=chosen.replica_id,
                            attempt=fl.attempts)
        sp.end()

    def _relay_stream(self, fl: _Flight) -> None:
        if fl.stream_cb is None:
            return
        toks = fl.replica.partial_tokens(fl.handle)
        for t in toks[fl.delivered:]:
            fl.stream_cb(fl.fid, int(t))
        fl.delivered = max(fl.delivered, len(toks))

    def _settle(self, fl: _Flight, res: dict, sigs: dict) -> None:
        if not res.get("ok") or res.get("finish_reason") == "error":
            # The replica aborted the request (engine failure mid
            # request) — same treatment as a dead replica: discard
            # partials, try a survivor.
            self._failover(fl, sigs,
                           why=res.get("error", "replica abort"))
            return
        tokens = [int(t) for t in res["tokens"]]
        if fl.stream_cb is not None:
            for t in tokens[fl.delivered:]:
                fl.stream_cb(fl.fid, t)
        del self._flights[fl.fid]
        _m_requests.labels(outcome="finished").inc()
        metrics = dict(res.get("metrics") or {})
        metrics["router_attempts"] = fl.attempts
        metrics["replica"] = fl.replica.replica_id
        fl.trace.end(outcome="finished",
                     finish_reason=res.get("finish_reason"),
                     attempts=fl.attempts)
        fl.future.set_result(RequestResult(
            req_id=fl.fid, prompt=fl.prompt, tokens=tokens,
            metrics=metrics))

    def _failover(self, fl: _Flight, sigs: dict, *, why: str) -> None:
        del self._flights[fl.fid]
        if fl.attempts >= self.cfg.max_attempts:
            _m_requests.labels(outcome="failed").inc()
            fl.trace.end(outcome="failed", attempts=fl.attempts,
                         error=why)
            fl.future.set_exception(NoReplicaAvailable(
                f"request {fl.fid} failed after {fl.attempts} "
                f"attempts (last: {why})"))
            return
        self.failovers += 1
        _m_failovers.inc()
        log.warning(
            "router: flight %d leaving replica %s (%s); resubmitting "
            "(attempt %d, partial tokens discarded — replay is "
            "at-least-once)", fl.fid, fl.replica.replica_id, why,
            fl.attempts + 1)
        fl.trace.event("failover", from_replica=fl.replica.replica_id,
                       why=why)
        # Partial tokens are discarded: the survivor re-decodes from
        # the prompt, and greedy determinism makes the replayed stream
        # identical to the lost one.
        fl.delivered = 0
        fl.replica = fl.handle = None
        try:
            self._place(fl, sigs)
        except NoReplicaAvailable:
            self._pending.append(fl)
