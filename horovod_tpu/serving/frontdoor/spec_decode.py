"""Draft-model speculative decoding as a scheduler mode.

Per round, for every running request (the whole fixed decode batch at
once):

1. **draft** — a small llama config decodes ``k`` tokens sequentially
   over its OWN page pools (same ``num_blocks``/``block_size`` geometry
   as the target, so both models index the pool through the SAME block
   tables — one allocator, two pools);
2. **verify** — ONE target forward
   (:func:`horovod_tpu.models.llama.extend_step_paged`) over the
   ``k + 1`` tokens ``[t_last, d_1..d_k]`` at positions ``C..C+k``
   yields the target's greedy token ``g_j`` after every prefix;
3. **accept** — the agreeing prefix ``d_1..d_m`` (``d_i == g_{i-1}``)
   is emitted plus the bonus token ``g_m``, so every round emits at
   least one token and the emitted stream equals target-only greedy
   decoding EXACTLY, independent of draft quality (the draft only
   decides how many target-correct tokens each round yields);
4. **roll back** — the table is truncated to the accepted context via
   :meth:`KVPager.truncate`, so rejected positions' stale K/V can
   never be read: positions inside kept blocks are overwritten by the
   next round's contiguous writes before anything attends that far, and
   whole rejected blocks go back to the free list.

The draft mirrors every context-building step of the target (prompt
prefill, prefix-hit tail prefill) into its own pools; because the
prefix cache pins block ids and a shared prefix always occupies the
same absolute positions, the draft-pool contents under pinned blocks
stay valid for every request that matches the prefix.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from ...models import llama
from ...obs import REGISTRY as _obs
from ..kv_pager import PagedKVCache
from ..scheduler import RequestState

_m_rounds = _obs.counter(
    "hvd_spec_rounds_total", "speculative draft/verify rounds executed")
_m_drafted = _obs.counter(
    "hvd_spec_tokens_drafted_total", "draft tokens proposed")
_m_accepted = _obs.counter(
    "hvd_spec_tokens_accepted_total",
    "draft tokens the target verified and accepted")
_m_accept_rate = _obs.gauge(
    "hvd_spec_accept_rate",
    "cumulative accepted/drafted ratio of this engine")


class SpecDecoder:
    """Speculative-decode engine mode: owns the draft model, its page
    pools, and the per-round draft/verify/accept/rollback loop.  Built
    by :class:`~horovod_tpu.serving.engine.ServingEngine` when
    ``EngineConfig.spec_k > 0``."""

    def __init__(self, engine, draft_params, draft_cfg: llama.LlamaConfig,
                 *, k: int) -> None:
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        if draft_cfg.use_moe:
            raise NotImplementedError("draft model must be dense")
        if draft_cfg.vocab_size != engine.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{engine.cfg.vocab_size}: drafted ids must be target ids")
        self.eng = engine
        self.k = int(k)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        jax, jnp = engine._jax, engine._jnp
        self._jnp = jnp
        # Same block geometry as the target pool -> shared block tables.
        self.cache = PagedKVCache(
            n_layers=draft_cfg.n_layers,
            num_blocks=engine.cache.num_blocks,
            block_size=engine.cache.block_size,
            kv_heads=draft_cfg.n_kv_heads, head_dim=draft_cfg.head_dim)
        # The draft pools stay replicated on a mesh: the draft is small
        # by design and its kv_heads need not divide tp.
        self.dk_pool = jnp.zeros(self.cache.shape, draft_cfg.dtype)
        self.dv_pool = jnp.zeros(self.cache.shape, draft_cfg.dtype)
        self._drafted_total = 0
        self._accepted_total = 0

        self._prefill = jax.jit(partial(self._prefill_impl))
        self._decode = jax.jit(partial(self._decode_impl),
                               donate_argnums=(1, 2))
        self._extend = jax.jit(partial(self._extend_impl),
                               donate_argnums=(1, 2))

    # -- draft-model jitted bodies (target mesh rules do not apply) ------
    def _prefill_impl(self, params, tokens, last_pos):
        _, ks, vs = llama.prefill_step(
            params, tokens, self.draft_cfg, mesh=None, last_pos=last_pos)
        return ks, vs

    def _decode_impl(self, params, kp, vp, tok, pos, tables):
        jnp = self._jnp
        logits, kp, vp = llama.decode_step_paged(
            params, tok, pos, kp, vp, tables, self.draft_cfg, mesh=None,
            use_flash=False)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

    def _extend_impl(self, params, kp, vp, tok, pos, valid, tables):
        _, kp, vp = llama.extend_step_paged(
            params, tok, pos, valid, kp, vp, tables, self.draft_cfg,
            mesh=None)
        return kp, vp

    # -- context mirroring ----------------------------------------------
    def mirror_prefill(self, req, padded: np.ndarray, n_tokens: int
                       ) -> None:
        """Run the draft's prompt prefill and scatter its K/V into the
        draft pools under the request's (shared) block table — the
        draft-side twin of the engine's prefill+scatter."""
        jnp = self._jnp
        eng = self.eng
        ks, vs = self._prefill(
            self.draft_params, jnp.asarray(padded),
            jnp.asarray([n_tokens - 1], jnp.int32))
        blocks = eng.pager.table(req.req_id)
        nb = self.cache.blocks_for(n_tokens)
        lim = min(padded.shape[1], nb * self.cache.block_size)
        self.dk_pool, self.dv_pool = eng._scatter(
            self.dk_pool, self.dv_pool, ks[:, :, :lim], vs[:, :, :lim],
            jnp.asarray(blocks[:nb], jnp.int32))

    def mirror_extend(self, tok2, pos2, val2, tables) -> None:
        """Mirror a prefix-hit tail prefill into the draft pools (the
        cached head's draft K/V is already there from the insert-time
        request — pinned block ids are never reallocated)."""
        jnp = self._jnp
        self.dk_pool, self.dv_pool = self._extend(
            self.draft_params, self.dk_pool, self.dv_pool,
            jnp.asarray(tok2), jnp.asarray(pos2), jnp.asarray(val2),
            jnp.asarray(tables))

    # -- the round -------------------------------------------------------
    def tick(self) -> list:
        """One speculative round for the whole running set; returns the
        (request, token) emissions like ``ServingEngine._decode_tick``."""
        eng = self.eng
        jnp = self._jnp
        sched = eng.scheduler
        k = self.k
        from ..kv_pager import OutOfBlocks
        from ..engine import _bucket_pow2
        # Reserve the whole round's write window (k drafts + bonus) up
        # front; rollback returns whatever goes unused.
        for req in list(sched.running):
            if req in sched.running:
                try:
                    sched.grow(req, k + 1)
                except OutOfBlocks as e:
                    sched.fail_running(req, e)
        eng._sync_slots()
        active = [r for r in eng._slots if r is not None]
        if not active:
            return []
        R = eng.ecfg.max_active
        need_cols = max(self.cache.blocks_for(r.context_len + k + 1)
                        for r in active)
        n_cols = min(_bucket_pow2(need_cols), self.cache.num_blocks)
        tok = np.zeros((R,), np.int32)
        pos = np.zeros((R,), np.int32)
        act = np.zeros((R,), bool)
        ids = [-1] * R
        for i, r in enumerate(eng._slots):
            if r is None:
                continue
            tok[i] = r.generated[-1]
            pos[i] = r.context_len
            act[i] = True
            ids[i] = r.req_id
        tables = jnp.asarray(eng.pager.table_matrix(ids, n_cols))

        # 1. draft k tokens sequentially with the small model.
        drafts = np.zeros((R, k), np.int32)
        cur = jnp.asarray(tok)
        dk, dv = self.dk_pool, self.dv_pool
        for j in range(k):
            cur, dk, dv = self._decode(
                self.draft_params, dk, dv, cur,
                jnp.asarray(pos + j, jnp.int32), tables)
            drafts[:, j] = np.asarray(cur)
        # Write d_k's K/V too (output discarded): a fully-accepted round
        # keeps position C+k in context, and without this write that
        # position would stay a hole the draft attends over forever.
        _, dk, dv = self._decode(
            self.draft_params, dk, dv, cur,
            jnp.asarray(pos + k, jnp.int32), tables)
        self.dk_pool, self.dv_pool = dk, dv

        # 2. verify all k+1 positions in one target forward.
        vtok = np.concatenate([tok[:, None], drafts], axis=1)
        vpos = pos[:, None] + np.arange(k + 1, dtype=np.int32)[None, :]
        valid = np.repeat(act[:, None], k + 1, axis=1)
        g, eng.k_pool, eng.v_pool = eng._extend(
            eng.params, eng.k_pool, eng.v_pool, jnp.asarray(vtok),
            jnp.asarray(vpos), jnp.asarray(valid), tables)
        g = np.asarray(g)                                    # [R, k+1]

        # 3./4. accept the agreeing prefix + bonus token, roll back rest.
        _m_rounds.inc()
        emitted = []
        for i, r in enumerate(list(eng._slots)):
            if r is None:
                continue
            m = 0
            while m < k and int(drafts[i, m]) == int(g[i, m]):
                m += 1
            _m_drafted.inc(k)
            _m_accepted.inc(m)
            self._drafted_total += k
            self._accepted_total += m
            C = r.context_len
            for t in [int(drafts[i, j]) for j in range(m)] + [int(g[i, m])]:
                emitted.append((r, eng._emit(r, t)))
                if r.state is not RequestState.RUNNING:
                    break                  # eos/length: blocks released
            if r.state is RequestState.RUNNING:
                r.context_len = C + m + 1
                eng.pager.truncate(r.req_id, r.context_len)
        if self._drafted_total:
            _m_accept_rate.set(self._accepted_total / self._drafted_total)
        return emitted
