"""Router <-> replica request transport over the job's KV store.

Replicas are dp serving processes; the router is one process placing
requests across them.  Like the obs plane (``obs/aggregate``), the
transport rides the job's existing authenticated KV control plane — no
new network surface.  Key layout (replica rank ``r``, router-assigned
sequence number ``q``):

- ``fd/member/<r>`` — membership record (JSON), written at replica
  start and re-published after an elastic re-init
  (:func:`republish_membership` hooks the elastic rejoin path);
- ``fd/req/<r>/<q>`` — one request, a chunked blob
  (:func:`~horovod_tpu.runner.api.kv_put_blob`: the meta key lands
  last, so a replica that sees it can read the whole payload);
- ``fd/res/<r>/<q>`` — the matching result blob;
- ``fd/prog/<r>/<q>`` — plain JSON progress record (tokens emitted so
  far), re-set on every streamed token for router-side relays.

Sequence numbers are assigned by the router and consumed in order by
the replica — a SINGLE-ROUTER assumption (one placement authority per
job), which buys a poll loop with no key listing.

Replica-side readiness rides the obs plane: :class:`ReplicaServer`
mirrors ``context.component_health("serving")`` into the
``hvd_replica_ready`` gauge, which the rank's
:class:`~horovod_tpu.obs.aggregate.RankPublisher` snapshot carries to
the router along with queue depth, TTFT p99 and SLO burn — the router
never scrapes replicas directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from ...obs import REGISTRY as _obs
from ...obs.aggregate import (SNAP_PREFIX, _kv_from_env,
                              decode_snapshot_blob, snapshot_is_stale)
from ...obs.aggregate import _num as _edge_num

MEMBER_PREFIX = "fd/member/"
REQ_PREFIX = "fd/req/"
RES_PREFIX = "fd/res/"
PROG_PREFIX = "fd/prog/"

_m_ready = _obs.gauge(
    "hvd_replica_ready",
    "this replica accepts new placements (serving component healthy); "
    "published to the router through the rank's obs snapshot")
_m_pool_info = _obs.gauge(
    "hvd_serving_pool_info",
    "pool this replica serves (value 1; the pool is the label) — merged "
    "cluster snapshots add the rank label, giving the autoscaler its "
    "rank->pool map", ("pool",))
_m_served = _obs.counter(
    "hvd_replica_requests_served_total",
    "requests this replica completed for the router")

#: live ReplicaServers in this process, for membership republish after
#: an elastic re-init (the KV store may be a fresh one).
_servers: list = []
_servers_lock = threading.Lock()


def republish_membership() -> None:
    """Re-register every live replica server (elastic rejoin hook —
    called from the runner's re-initialize path; must never raise)."""
    with _servers_lock:
        servers = list(_servers)
    for s in servers:
        try:
            s.register()
        except (ConnectionError, OSError, TimeoutError):
            pass


# ---------------------------------------------------------------------------
# signal extraction (router side)
# ---------------------------------------------------------------------------

def _hist_quantile(fam: Optional[dict], q: float) -> Optional[float]:
    """Upper-edge quantile estimate from a snapshot histogram family
    (cumulative buckets); None when absent or empty.  Multiple labeled
    series merge by bucket — the router wants the replica-wide view."""
    if not fam or not fam.get("samples"):
        return None
    acc: dict[float, int] = {}
    total = 0
    for s in fam["samples"]:
        total += int(s.get("count", 0))
        for le, c in s.get("buckets", ()):
            le = _edge_num(le)
            acc[le] = acc.get(le, 0) + int(c)
    if total == 0:
        return None
    target = q * total
    last_finite = 0.0
    for le in sorted(acc):
        if le != float("inf"):
            last_finite = le
        if acc[le] >= target:
            return le if le != float("inf") else last_finite
    return last_finite


def signals_from_snapshot(snap: dict) -> dict:
    """Placement signals out of one rank's published obs snapshot:
    queue depth, batch occupancy, readiness, TTFT p99, worst SLO burn
    rate, and the shared 2x-interval staleness verdict."""
    fams = {f["name"]: f for f in snap.get("snapshot", ())}

    def gauge(name: str, default: float = 0.0) -> float:
        fam = fams.get(name)
        if not fam or not fam.get("samples"):
            return default
        return float(fam["samples"][0]["value"])

    burn = 0.0
    burn_fam = fams.get("hvd_slo_burn_rate")
    if burn_fam:
        burn = max((float(s["value"]) for s in burn_fam["samples"]),
                   default=0.0)
    pool = None
    pool_fam = fams.get("hvd_serving_pool_info")
    if pool_fam and pool_fam.get("samples"):
        pool = pool_fam["samples"][0].get("labels", {}).get("pool")
    return {
        "rank": int(snap.get("rank", -1)),
        "alive": True,
        "stale": snapshot_is_stale(snap),
        "ready": gauge("hvd_replica_ready") >= 1.0,
        "pool": pool,
        "queue_depth": gauge("hvd_serving_queue_depth"),
        "occupancy": gauge("hvd_serving_batch_occupancy"),
        "ttft_p99": _hist_quantile(
            fams.get("hvd_serving_ttft_seconds"), 0.99),
        "itl_p99": _hist_quantile(
            fams.get("hvd_serving_itl_seconds"), 0.99),
        "slo_burn": burn,
        "time": float(snap.get("time", 0.0)),
    }


#: the signal record for a replica the router cannot see at all
DEAD_SIGNALS = {"alive": False, "stale": True, "ready": False,
                "pool": None, "queue_depth": float("inf"),
                "occupancy": 1.0, "ttft_p99": None, "itl_p99": None,
                "slo_burn": 0.0}


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------

class ReplicaServer:
    """One replica's transport endpoint: polls ``fd/req/<rank>/<seq>``
    in sequence order, submits into the local
    :class:`~horovod_tpu.serving.api.ServingSession`, streams progress,
    and publishes results.  Start the session's background thread (or
    drain it elsewhere) — this class only moves requests, it does not
    step the engine."""

    def __init__(self, session, rank: int, *,
                 kv_factory: Callable = _kv_from_env,
                 poll_interval_s: float = 0.05,
                 pool: Optional[str] = None) -> None:
        kv = kv_factory()
        if kv is None:
            raise RuntimeError(
                "ReplicaServer needs the job KV store "
                "(HVDTPU_RENDEZVOUS_ADDR unset?)")
        self._kv = kv
        self._kv_lock = threading.Lock()
        self.session = session
        self.rank = int(rank)
        #: which pool this replica serves (disaggregated serving):
        #: "prefill", "decode", or "mixed" (the default — eligible for
        #: everything, the pre-disagg behavior).
        self.pool = pool or os.environ.get("HVDTPU_SERVING_POOL", "mixed")
        self._poll = poll_interval_s
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"hvdtpu-fd-replica{rank}",
            daemon=True)

    def register(self) -> None:
        rec = {"rank": self.rank, "pid": os.getpid(),
               "pool": self.pool, "time": time.time()}
        _m_pool_info.labels(pool=self.pool).set(1.0)
        with self._kv_lock:
            self._kv.set(f"{MEMBER_PREFIX}{self.rank}",
                         json.dumps(rec).encode())

    def start(self) -> "ReplicaServer":
        self.register()
        self._sample_ready()
        with _servers_lock:
            _servers.append(self)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        with _servers_lock:
            if self in _servers:
                _servers.remove(self)
        with self._kv_lock:
            try:
                self._kv.delete(f"{MEMBER_PREFIX}{self.rank}")
            except (ConnectionError, OSError):
                pass

    def _sample_ready(self) -> None:
        from ...context import component_health
        _m_ready.set(1.0 if component_health("serving") else 0.0)

    def _loop(self) -> None:
        from ...runner.api import kv_get_blob
        while not self._stop.is_set():
            self._sample_ready()
            key = f"{REQ_PREFIX}{self.rank}/{self._seq}"
            try:
                with self._kv_lock:
                    has = self._kv.get(f"{key}/meta") is not None
                if not has:
                    self._stop.wait(self._poll)
                    continue
                with self._kv_lock:
                    payload = json.loads(
                        kv_get_blob(self._kv, key).decode())
            except (ConnectionError, OSError, TimeoutError, ValueError):
                self._stop.wait(self._poll)
                continue
            seq = self._seq
            self._seq += 1
            self._dispatch(seq, payload)

    def _dispatch(self, seq: int, payload: dict) -> None:
        prog_key = f"{PROG_PREFIX}{self.rank}/{seq}"
        tokens: list[int] = []

        def on_token(req_id: int, token: int) -> None:
            # Runs on the serving thread; the lock serializes against
            # the poll loop's KV use.
            tokens.append(int(token))
            try:
                with self._kv_lock:
                    self._kv.set(prog_key, json.dumps(tokens).encode())
            except (ConnectionError, OSError, TimeoutError):
                pass             # progress is best-effort; results are not

        mode = payload.get("mode", "generate")
        # Trace context carried over the transport: the router's ingress
        # span; the engine joins its trace instead of opening a new one
        # (decode_import gets it from the migration manifest instead).
        trace_ctx = payload.get("trace")
        extra = {}
        try:
            if mode == "generate":
                fut = self.session.submit(
                    payload["prompt"], payload["max_tokens"],
                    eos_token=payload.get("eos_token"),
                    stream_cb=on_token, trace_ctx=trace_ctx)
            elif mode == "prefill_export":
                # Prefill-pool leg of a disaggregated request: run the
                # prefill, export the KV blocks, publish them under the
                # router-assigned migration id, and resolve with
                # finish_reason="migrated".
                from ..disagg import transport as mig_transport
                mig_id = payload["mig_id"]
                extra["mig_id"] = mig_id

                def publish(manifest, k_bytes, v_bytes):
                    with self._kv_lock:
                        mig_transport.publish_migration(
                            self._kv, mig_id, manifest, k_bytes, v_bytes)

                fut = self.session.submit(
                    payload["prompt"], payload["max_tokens"],
                    eos_token=payload.get("eos_token"),
                    stream_cb=on_token, migrate_cb=publish,
                    trace_ctx=trace_ctx)
            elif mode == "decode_import":
                # Decode-pool leg: fetch the migrated blocks, attach
                # them to the local pool, resume decoding.  The
                # progress stream is seeded with the tokens the prefill
                # replica already emitted.
                from ..disagg import transport as mig_transport
                mig_id = payload["mig_id"]
                with self._kv_lock:
                    manifest, k_bytes, v_bytes = \
                        mig_transport.fetch_migration(
                            self._kv, mig_id,
                            timeout_ms=int(payload.get(
                                "fetch_timeout_ms", 15000)))
                tokens.extend(int(t) for t in manifest["generated"])
                with self._kv_lock:
                    self._kv.set(prog_key, json.dumps(tokens).encode())
                fut = self.session.import_migrated(
                    manifest, k_bytes, v_bytes, stream_cb=on_token)
            else:
                raise ValueError(f"unknown request mode {mode!r}")
        except Exception as e:
            self._publish_error(seq, e, extra)
            return
        fut.add_done_callback(
            lambda f: self._publish_result(seq, f, extra))

    def _publish_error(self, seq: int, exc: Exception,
                       extra: Optional[dict] = None) -> None:
        out = {"ok": False, "error": str(exc),
               "error_kind": type(exc).__name__}
        out.update(extra or {})
        from ...runner.api import kv_put_blob
        try:
            with self._kv_lock:
                kv_put_blob(self._kv, f"{RES_PREFIX}{self.rank}/{seq}",
                            json.dumps(out).encode())
        except (ConnectionError, OSError, TimeoutError):
            pass

    def _publish_result(self, seq: int, fut,
                        extra: Optional[dict] = None) -> None:
        from ...runner.api import kv_put_blob
        try:
            res = fut.result()
            out = {"ok": True, "tokens": list(res.tokens),
                   "finish_reason": res.metrics.get("finish_reason"),
                   "metrics": res.metrics}
        except Exception as e:               # replica-side failure
            out = {"ok": False, "error": str(e),
                   "error_kind": type(e).__name__}
        out.update(extra or {})
        _m_served.inc()
        try:
            with self._kv_lock:
                kv_put_blob(self._kv, f"{RES_PREFIX}{self.rank}/{seq}",
                            json.dumps(out).encode())
        except (ConnectionError, OSError, TimeoutError):
            pass   # the router's staleness/failover path covers the loss


# ---------------------------------------------------------------------------
# router side
# ---------------------------------------------------------------------------

class KVReplicaClient:
    """Router-side handle to one replica rank, implementing the replica
    protocol the :class:`~horovod_tpu.serving.frontdoor.router.Router`
    places against (``signals``/``submit``/``result``/``partial_tokens``
    /``drive``).  Submit handles are the transport sequence numbers."""

    def __init__(self, rank: int, kv=None, *,
                 kv_factory: Callable = _kv_from_env) -> None:
        self.rank = int(rank)
        self.replica_id = str(rank)
        self._kv = kv if kv is not None else kv_factory()
        if self._kv is None:
            raise RuntimeError(
                "KVReplicaClient needs the job KV store "
                "(HVDTPU_RENDEZVOUS_ADDR unset?)")
        self._seq = 0          # single-router assumption (module doc)
        self._pool: Optional[str] = None

    @property
    def pool(self) -> str:
        """Pool tag from the replica's published membership record
        ("mixed" until the record is visible); cached after first
        read — a replica's pool does not change within a job."""
        if self._pool is None:
            try:
                raw = self._kv.get(f"{MEMBER_PREFIX}{self.rank}")
                if raw is not None:
                    self._pool = json.loads(raw.decode()).get(
                        "pool", "mixed")
            except (ConnectionError, OSError, TimeoutError, ValueError):
                pass
        return self._pool or "mixed"

    def drive(self) -> None:
        """Remote replicas step themselves."""

    def signals(self) -> dict:
        try:
            if self._kv.get(f"{MEMBER_PREFIX}{self.rank}") is None:
                return dict(DEAD_SIGNALS, rank=self.rank)
            if self._kv.get(f"{SNAP_PREFIX}{self.rank}/meta") is None:
                return dict(DEAD_SIGNALS, rank=self.rank)
            from ...runner.api import kv_get_blob
            snap = decode_snapshot_blob(kv_get_blob(
                self._kv, f"{SNAP_PREFIX}{self.rank}", timeout_ms=2000))
        except (ConnectionError, OSError, TimeoutError, ValueError):
            return dict(DEAD_SIGNALS, rank=self.rank)
        return signals_from_snapshot(snap)

    def submit(self, prompt, max_tokens: int, *,
               eos_token: Optional[int] = None,
               trace_ctx: Optional[dict] = None) -> int:
        payload = {"prompt": [int(t) for t in np.asarray(prompt)],
                   "max_tokens": int(max_tokens),
                   "eos_token": eos_token}
        if trace_ctx is not None:
            payload["trace"] = trace_ctx
        return self._submit_payload(payload)

    def submit_prefill(self, prompt, max_tokens: int, *,
                       eos_token: Optional[int] = None,
                       mig_id: str,
                       trace_ctx: Optional[dict] = None) -> int:
        """Disaggregated prefill leg: the replica prefills, publishes
        the KV export under ``mig_id``, and resolves with
        ``finish_reason="migrated"``."""
        payload = {"prompt": [int(t) for t in np.asarray(prompt)],
                   "max_tokens": int(max_tokens),
                   "eos_token": eos_token,
                   "mode": "prefill_export", "mig_id": str(mig_id)}
        if trace_ctx is not None:
            payload["trace"] = trace_ctx
        return self._submit_payload(payload)

    def submit_import(self, mig_id: str, *,
                      fetch_timeout_ms: int = 15000) -> int:
        """Disaggregated decode leg: the replica fetches the migration
        blob, attaches it, and decodes to completion."""
        return self._submit_payload(
            {"mode": "decode_import", "mig_id": str(mig_id),
             "fetch_timeout_ms": int(fetch_timeout_ms)})

    def _submit_payload(self, payload: dict) -> int:
        from ...runner.api import kv_put_blob
        seq = self._seq
        self._seq += 1
        kv_put_blob(self._kv, f"{REQ_PREFIX}{self.rank}/{seq}",
                    json.dumps(payload).encode())
        return seq

    def partial_tokens(self, handle: int) -> list[int]:
        try:
            raw = self._kv.get(f"{PROG_PREFIX}{self.rank}/{handle}")
        except (ConnectionError, OSError, TimeoutError):
            return []
        return json.loads(raw.decode()) if raw else []

    def result(self, handle: int) -> Optional[dict]:
        try:
            key = f"{RES_PREFIX}{self.rank}/{handle}"
            if self._kv.get(f"{key}/meta") is None:
                return None
            from ...runner.api import kv_get_blob
            return json.loads(
                kv_get_blob(self._kv, key, timeout_ms=2000).decode())
        except (ConnectionError, OSError, TimeoutError, ValueError):
            return None
