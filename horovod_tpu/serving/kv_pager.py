"""Block-paged KV cache: the allocator and the device-side page pool.

The batch `generate()` cache is ``[B, T, KV, D]`` with ``T = prompt +
max_new`` — every request pays worst-case memory up front, and the batch
dimension is welded shut.  Here the same grouped layout is cut into
fixed-size blocks pooled across requests (PagedAttention, Kwon et al.;
vLLM's central idea):

- device pool: ``[L, num_blocks, block_size, KV, D]`` per K and V —
  one allocation for the whole serving session, never resized;
- host allocator (:class:`KVPager`): a free list of block ids with
  per-request block tables mapping logical position ``p`` to physical
  block ``table[p // block_size]``;
- attention reads the pool either by gathering a request's blocks into a
  contiguous ``[B, T_pad, KV, D]`` view (XLA path — a plain take, which
  GSPMD shards like any other gather) or directly via the Pallas decode
  kernel's scalar-prefetch BlockSpec routing
  (:func:`horovod_tpu.ops.flash_attention.paged_attention`), the same
  grouped-KV index-map trick the training flash kernel uses for GQA.

Block 0 is RESERVED as a scratch target: inactive decode slots in the
fixed-shape step function point their table rows at it, so their masked
garbage writes can never land in a live request's block.

Blocks are REFCOUNTED so the prefix cache
(:mod:`horovod_tpu.serving.frontdoor.prefix_cache`) can share one
physical block across many requests: a block's count is the number of
request tables containing it plus one if the cache holds a pin on it.
Shared blocks are only ever *prefix* blocks — fully written at insert
time and never rewritten (writes always land at positions past the
shared prefix, hence in privately-owned blocks), so no copy-on-write is
needed.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Sequence

import numpy as np


class OutOfBlocks(RuntimeError):
    """The pool has no free block; callers preempt a request and retry."""


@dataclasses.dataclass
class PagedKVCache:
    """Shape/bookkeeping descriptor for one device-side page pool.

    The jax pool arrays themselves live in the engine (they are donated
    through the jitted step functions); this object owns the static
    geometry the allocator and the step builders agree on."""

    n_layers: int
    num_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int

    @property
    def shape(self) -> tuple[int, int, int, int, int]:
        return (self.n_layers, self.num_blocks, self.block_size,
                self.kv_heads, self.head_dim)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` positions."""
        return -(-n_tokens // self.block_size)

    def bytes_per_block(self, itemsize: int) -> int:
        # x2: K and V pools.
        return (2 * self.n_layers * self.block_size * self.kv_heads
                * self.head_dim * itemsize)


class KVPager:
    """Free-list block allocator with refcounted per-request block tables.

    Invariants (tested):
    - block 0 is never handed out (scratch target for masked writes);
    - per held block, ``refcount == (#tables containing it)
      + (1 if pinned)``; a block appears at most once per table;
    - the free list and the held set partition the usable pool:
      ``len(held) + len(free) == num_blocks - 1``;
    - double-free, foreign-free, double-pin and pinning/sharing a
      non-live block raise.
    """

    def __init__(self, cache: PagedKVCache) -> None:
        if cache.num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is scratch)")
        self.cache = cache
        # LIFO free list: recently-freed blocks are re-used first, which
        # keeps the working set of pool pages dense.
        self._free: list[int] = list(range(cache.num_blocks - 1, 0, -1))
        self._tables: dict[int, list[int]] = {}
        self._refs: dict[int, int] = {}        # held block -> refcount
        self._pinned: set[int] = set()         # cache-held blocks

    # -- queries ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def table(self, req_id: int) -> list[int]:
        return list(self._tables[req_id])

    def refcount(self, block: int) -> int:
        """Live references to ``block`` (0 = on the free list)."""
        return self._refs.get(block, 0)

    def is_pinned(self, block: int) -> bool:
        return block in self._pinned

    def shared_blocks(self) -> int:
        """Blocks referenced by more than one holder (sharing gauge)."""
        return sum(1 for r in self._refs.values() if r > 1)

    def num_tokens_capacity(self) -> int:
        return self.free_blocks * self.cache.block_size

    def can_allocate(self, n_tokens: int) -> bool:
        return self.cache.blocks_for(n_tokens) <= self.free_blocks

    # -- allocation ------------------------------------------------------
    def _take(self, n: int) -> list[int]:
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def _decref(self, block: int) -> None:
        r = self._refs[block] - 1
        if r:
            self._refs[block] = r
        else:
            del self._refs[block]
            self._free.append(block)

    def allocate(self, req_id: int, n_tokens: int,
                 prefix_blocks: Sequence[int] = ()) -> list[int]:
        """Fresh table covering ``n_tokens`` for a new request.

        ``prefix_blocks`` (from a prefix-cache hit) head the table as
        shared references — their refcounts bump instead of consuming
        free blocks; only the remainder is drawn from the free list."""
        if req_id in self._tables:
            raise ValueError(f"request {req_id} already has a table")
        need = self.cache.blocks_for(n_tokens) - len(prefix_blocks)
        if need < 0:
            raise ValueError(
                f"{len(prefix_blocks)} prefix blocks exceed the "
                f"{self.cache.blocks_for(n_tokens)} needed for "
                f"{n_tokens} tokens")
        for b in prefix_blocks:
            if b not in self._refs:
                raise ValueError(f"prefix block {b} is not live")
        if need > len(self._free):
            raise OutOfBlocks(
                f"need {need} blocks for {n_tokens} tokens, "
                f"{len(self._free)} free")
        for b in prefix_blocks:
            self._refs[b] += 1
        blocks = list(prefix_blocks) + self._take(need)
        self._tables[req_id] = blocks
        return list(blocks)

    def extend(self, req_id: int, n_tokens: int) -> list[int]:
        """Grow ``req_id``'s table to cover ``n_tokens`` total positions;
        returns the full table.  Raises :class:`OutOfBlocks` (allocator
        state unchanged) when the pool is exhausted — the scheduler
        preempts a request and retries."""
        table = self._tables[req_id]
        need = self.cache.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return list(table)
        if need > len(self._free):
            raise OutOfBlocks(
                f"request {req_id} needs {need} more blocks, "
                f"{len(self._free)} free")
        table.extend(self._take(need))
        return list(table)

    def truncate(self, req_id: int, n_tokens: int) -> list[int]:
        """Shrink ``req_id``'s table to the blocks covering ``n_tokens``
        positions, releasing the tail (speculative-decode rollback: the
        blocks past the accepted prefix go back to the pool so their
        stale rejected-token K/V can never be read through this table).
        Returns the remaining table."""
        table = self._tables[req_id]
        keep = self.cache.blocks_for(n_tokens)
        for b in table[keep:]:
            self._decref(b)
        del table[keep:]
        return list(table)

    def release(self, req_id: int) -> None:
        """Drop every reference ``req_id`` holds; unshared blocks return
        to the free list, shared/pinned ones stay with their holders."""
        blocks = self._tables.pop(req_id, None)
        if blocks is None:
            raise KeyError(f"request {req_id} holds no blocks")
        for b in blocks:
            self._decref(b)

    # -- cache pins ------------------------------------------------------
    def pin(self, block: int) -> None:
        """Add the prefix cache's reference to a live block, keeping it
        resident after every owning request releases."""
        if block not in self._refs:
            raise ValueError(f"cannot pin block {block}: not live")
        if block in self._pinned:
            raise ValueError(f"block {block} already pinned")
        self._pinned.add(block)
        self._refs[block] += 1

    def unpin(self, block: int) -> None:
        """Drop the cache's reference (eviction); the block frees once no
        request table holds it."""
        if block not in self._pinned:
            raise ValueError(f"block {block} is not pinned")
        self._pinned.discard(block)
        self._decref(block)

    # -- fixed-shape table matrix for the compiled step ------------------
    def table_matrix(self, req_ids: list[int], n_cols: int) -> np.ndarray:
        """``[len(req_ids), n_cols]`` int32 block tables, rows padded with
        the scratch block 0 (ids of ``-1`` mean an inactive slot — an
        all-scratch row)."""
        out = np.zeros((len(req_ids), n_cols), np.int32)
        for i, rid in enumerate(req_ids):
            if rid < 0:
                continue
            tbl = self._tables[rid][:n_cols]
            out[i, :len(tbl)] = tbl
        return out

    def check_invariants(self) -> None:
        uses = Counter(b for tbl in self._tables.values() for b in tbl)
        for tbl in self._tables.values():
            assert len(set(tbl)) == len(tbl), "block twice in one table"
        for b in self._pinned:
            uses[b] += 1
        assert 0 not in uses, "scratch block 0 leaked into a table/pin"
        assert 0 not in self._free, "scratch block 0 leaked into free list"
        assert dict(uses) == self._refs, \
            f"refcounts drifted: counted {dict(uses)}, stored {self._refs}"
        assert not (set(self._free) & set(self._refs)), \
            "block both free and held"
        assert len(self._refs) + len(self._free) \
            == self.cache.num_blocks - 1, "blocks lost or duplicated"


def gather_blocks(pool, table) -> "jax.Array":  # noqa: F821
    """Contiguous ``[B, n_cols * block_size, KV, D]`` view of each row's
    blocks: the XLA paged-attention dispatch (a take along the block dim,
    shardable by GSPMD like any gather).

    pool: ``[num_blocks, block_size, KV, D]`` (one layer's pages);
    table: ``[B, n_cols]`` int32.
    """
    B, n_cols = table.shape
    g = pool[table]                       # [B, n_cols, BS, KV, D]
    return g.reshape(B, n_cols * pool.shape[1], *pool.shape[2:])
