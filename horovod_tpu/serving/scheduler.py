"""Continuous-batching request scheduler (policy, no device code).

Separation of concerns mirrors HiCCL's policy/transport split
(arXiv:2408.05962): this module decides WHAT runs each step — admission,
phase split, join/evict, preemption — and the engine owns HOW it runs on
the mesh.  Everything here is host-side Python over the
:class:`~horovod_tpu.serving.kv_pager.KVPager` bookkeeping; it never
touches a jax array, so its invariants are testable without a backend.

Policy:
- **FIFO admission** — strict arrival order, no head-of-line bypass, so
  long prompts cannot starve (fairness under mixed lengths is a test).
- **Prefill token budget** — at most ``prefill_token_budget`` prompt
  tokens enter prefill per step (always at least one request, so an
  over-budget prompt still runs — alone).  Bounding prefill work per step
  bounds the latency decode ticks see between tokens.
- **Join/evict per step** — finished requests leave and free their blocks
  before admission, so a drained slot is refilled the same step.
- **LIFO preemption on OOM** — when a growing request cannot get a block,
  the youngest running request is preempted: blocks freed, request
  re-queued at the FRONT with its generated tokens folded into the
  prompt.  Greedy decode is deterministic, so a preempted request resumes
  with an identical continuation.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..obs import REGISTRY as _obs
from ..obs import trace as _trace
from .kv_pager import KVPager, OutOfBlocks

_m_preemptions = _obs.counter(
    "hvd_serving_preemptions_total",
    "running requests evicted back to the queue on pool pressure")


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class Request:
    """One inference request and its lifecycle bookkeeping."""

    req_id: int
    prompt: np.ndarray                  # [P] int32 — original prompt
    max_new_tokens: int
    eos_token: Optional[int] = None
    stream_cb: Optional[Callable[[int, int], None]] = None
    state: RequestState = RequestState.WAITING
    #: why the request stopped: "stop" (eos), "length" (max_new_tokens),
    #: "error" (failed/aborted — the graceful-degradation contract: an
    #: engine failure finishes in-flight requests with this reason and
    #: their partial tokens instead of hanging them), "cancelled";
    #: None while live.
    finish_reason: Optional[str] = None
    #: tokens generated so far (grows per decode tick / prefill emit)
    generated: list[int] = dataclasses.field(default_factory=list)
    #: prompt actually prefilled (original + generated-before-preemption)
    prefill_tokens: Optional[np.ndarray] = None
    #: current context length in the pool (prefilled + generated there)
    context_len: int = 0
    #: prompt tokens satisfied from the prefix cache at admission (their
    #: K/V blocks were attached shared instead of prefilled); reset on
    #: preemption — re-admission re-matches
    cached_tokens: int = 0
    preemptions: int = 0
    # metrics timestamps (time.monotonic)
    t_submit: float = 0.0
    t_admitted: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finished: Optional[float] = None
    #: last time the request (re-)entered the waiting queue — t_submit
    #: at first submit, the preemption time afterwards; the trace's
    #: per-QUEUE-span wait is measured from here (t_submit would charge
    #: a preempted request's whole prior lifetime to queueing).
    t_enqueued: Optional[float] = None
    #: disaggregated-serving hook: when set, the engine stops the request
    #: after its prefill emission, exports its KV blocks, and calls
    #: ``migrate_cb(manifest, k_bytes, v_bytes)`` — the request finishes
    #: locally with ``finish_reason="migrated"`` and a decode-pool
    #: replica continues it (see serving/disagg).  None = normal serving.
    migrate_cb: Optional[Callable] = None
    #: request-scoped trace (obs/trace): the root span of this request's
    #: causal chain (NULL_SPAN when unsampled/untraced) plus the open
    #: phase spans, keyed "queue"/"prefill"/"decode"; "prev" holds the
    #: last ended phase span so the next phase chains a flow arrow to it.
    trace: object = dataclasses.field(
        default=_trace.NULL_SPAN, compare=False, repr=False)
    spans: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False)

    @property
    def remaining_new(self) -> int:
        return self.max_new_tokens - len(self.generated)

    def metrics(self) -> dict:
        done = self.t_finished or time.monotonic()
        ttft = (self.t_first_token - self.t_submit
                if self.t_first_token is not None else None)
        decode_s = (done - self.t_first_token
                    if self.t_first_token is not None else None)
        return {
            "req_id": self.req_id,
            "prompt_len": int(self.prompt.shape[0]),
            "new_tokens": len(self.generated),
            "queue_wait_s": ((self.t_admitted or done) - self.t_submit),
            "ttft_s": ttft,
            "decode_tokens_per_s": (
                (len(self.generated) - 1) / decode_s
                if decode_s and len(self.generated) > 1 else None),
            "preemptions": self.preemptions,
            "cached_tokens": self.cached_tokens,
            "finish_reason": self.finish_reason,
            "trace_id": self.trace.trace_id,
        }

    # -- trace phases (one connected QUEUE->PREFILL->DECODE chain) -------
    def open_phase(self, name: str, **attrs) -> object:
        """Open a phase span chained (flow arrow) to the previously
        ended one; no-ops end-to-end on unsampled requests."""
        sp = self.trace.child(name.upper(), after=self.spans.get("prev"),
                              **attrs)
        self.spans[name] = sp
        return sp

    def close_phase(self, name: str, **attrs) -> None:
        sp = self.spans.pop(name, None)
        if sp is not None:
            sp.end(**attrs)
            self.spans["prev"] = sp

    def close_trace(self, outcome: str, **attrs) -> None:
        """End any open phase and the root span (terminal state)."""
        for phase in ("queue", "prefill", "decode"):
            self.close_phase(phase)
        self.trace.end(outcome=outcome, new_tokens=len(self.generated),
                       preemptions=self.preemptions, **attrs)


class Scheduler:
    """Admission queue + running set over a :class:`KVPager`.

    The engine drives it:  ``finish()``/``cancel()`` retire requests,
    ``admit()`` returns this step's prefill batch, ``grow()`` reserves
    decode blocks (preempting on OOM), ``running`` is the decode batch.
    """

    def __init__(self, pager: KVPager, *, max_active: int,
                 prefill_token_budget: int,
                 prefix_cache=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if max_active < 1:
            raise ValueError("max_active must be >= 1")
        self.pager = pager
        #: optional frontdoor.PrefixCache — admission matches prompts
        #: against it and pool pressure evicts from it before preempting
        self.prefix_cache = prefix_cache
        self.max_active = max_active
        self.prefill_token_budget = max(1, prefill_token_budget)
        self.waiting: deque[Request] = deque()
        self.running: list[Request] = []
        #: requests that can never run (prompt exceeds the whole pool) —
        #: the engine drains these and fails their futures; leaving them
        #: queued would livelock admission behind an unfillable head.
        self.failed: list[tuple[Request, Exception]] = []
        self._clock = clock

    def _fits_pool_at_all(self, n_tokens: int) -> bool:
        return (self.pager.cache.blocks_for(n_tokens + 1)
                <= self.pager.cache.num_blocks - 1)

    def _fail_terminal(self, req: Request, exc: Exception) -> None:
        """The one place a request reaches ``self.failed``: terminal
        bookkeeping shared by the waiting-queue and running-set failure
        paths so the contract cannot drift between them."""
        req.state = RequestState.CANCELLED
        req.finish_reason = "error"
        req.t_finished = self._clock()
        req.close_trace("failed", error=str(exc))
        self.failed.append((req, exc))

    def _fail(self, req: Request, why: str) -> None:
        self._fail_terminal(req, OutOfBlocks(why))

    # -- queue surface ---------------------------------------------------
    def submit(self, req: Request) -> None:
        req.t_submit = req.t_submit or self._clock()
        req.t_enqueued = req.t_submit
        req.state = RequestState.WAITING
        req.open_phase("queue", prompt_len=int(req.prompt.shape[0]))
        self.waiting.append(req)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- per-step phases -------------------------------------------------
    def finish(self, req: Request) -> None:
        req.state = RequestState.FINISHED
        req.t_finished = self._clock()
        self.running.remove(req)
        self.pager.release(req.req_id)
        m = req.metrics()
        req.close_trace("finished",
                        ttft_s=m["ttft_s"],
                        queue_wait_s=round(m["queue_wait_s"], 6),
                        decode_tokens_per_s=m["decode_tokens_per_s"])

    def cancel(self, req: Request) -> None:
        req.state = RequestState.CANCELLED
        req.finish_reason = "cancelled"
        req.t_finished = self._clock()
        req.close_trace("cancelled")
        if req in self.running:
            self.running.remove(req)
            self.pager.release(req.req_id)
        elif req in self.waiting:
            self.waiting.remove(req)

    def admit(self) -> list[Request]:
        """Admit waiting requests in FIFO order until the active-slot cap,
        block supply, or the prefill token budget stops the step.  Each
        admitted request gets its blocks allocated here (prompt + 1 slot
        for the token prefill emits)."""
        admitted: list[Request] = []
        budget = self.prefill_token_budget
        while self.waiting and len(self.running) < self.max_active:
            req = self.waiting[0]
            prefill = req.prefill_tokens if req.prefill_tokens is not None \
                else req.prompt
            n = int(prefill.shape[0])
            if not self._fits_pool_at_all(n):
                # Can never fit even in an empty pool: fail it rather
                # than livelock the strictly-FIFO queue behind it.
                self.waiting.popleft()
                self._fail(req, f"request {req.req_id} needs "
                           f"{self.pager.cache.blocks_for(n + 1)} blocks "
                           f"for its {n}-token prefill; the pool only has "
                           f"{self.pager.cache.num_blocks - 1}")
                continue
            if admitted and n > budget:
                break                    # budget spent; strictly FIFO
            # Longest cached prefix: its blocks attach shared (no
            # prefill, no free-list draw) and only the remainder needs
            # fresh blocks.  match() does not reserve, so the eviction
            # valve below must protect the matched blocks.
            cached, cached_blocks = (
                self.prefix_cache.match(prefill)
                if self.prefix_cache is not None else (0, []))
            need = (self.pager.cache.blocks_for(n + 1)
                    - len(cached_blocks))
            if need > self.pager.free_blocks:
                if self.prefix_cache is not None:
                    self.prefix_cache.evict(
                        need - self.pager.free_blocks,
                        protect=cached_blocks)
                if need > self.pager.free_blocks:
                    break                # no head-of-line bypass
            self.waiting.popleft()
            req.prefill_tokens = np.asarray(prefill, np.int32)
            self.pager.allocate(req.req_id, n + 1,
                                prefix_blocks=cached_blocks)
            req.cached_tokens = cached
            req.context_len = n
            req.state = RequestState.RUNNING
            req.t_admitted = req.t_admitted or self._clock()
            # Batch decision lands on the trace: which slot of this
            # step's prefill batch took the request, and what the
            # admission cost was.
            req.close_phase(
                "queue",
                queue_wait_s=round(
                    self._clock() - (req.t_enqueued
                                     if req.t_enqueued is not None
                                     else req.t_submit), 6),
                prefill_batch_slot=len(admitted),
                budget_left=budget - n)
            self.running.append(req)
            admitted.append(req)
            budget -= n
            if budget <= 0:
                break
        return admitted

    def fail_running(self, req: Request, exc: Exception) -> None:
        """Fail one RUNNING request that cannot continue (it cannot fit
        in the pool even alone) without disturbing the rest of the
        batch — a per-request capacity problem is not an engine
        failure, so it must not trip the session's degradation path."""
        self.running.remove(req)
        self.pager.release(req.req_id)
        self._fail_terminal(req, exc)

    def grow(self, req: Request, n: int = 1) -> None:
        """Reserve pool space for ``req``'s next ``n`` positions (one
        decode tick, or a whole speculative round), evicting cold cached
        prefixes and then preempting the youngest OTHER running request
        until the allocation fits."""
        while True:
            try:
                self.pager.extend(req.req_id, req.context_len + n)
                return
            except OutOfBlocks:
                # Pressure valve order: dropping a refcount-1 cached
                # block loses a possible future prefill skip; preempting
                # loses certain already-done work.  Cache first.
                if self.prefix_cache is not None \
                        and self.prefix_cache.evict(1):
                    continue
                victim = self._youngest_other(req)
                if victim is None:
                    raise OutOfBlocks(
                        f"pool too small for request {req.req_id} alone "
                        f"(context {req.context_len})")
                self.preempt(victim)

    def preempt(self, req: Request) -> None:
        """Evict a RUNNING request back to the queue front.  Its generated
        tokens fold into the prefill prompt, so on re-admission it
        re-prefills once and continues exactly where it stopped (greedy
        decode is deterministic)."""
        self.running.remove(req)
        self.pager.release(req.req_id)
        req.prefill_tokens = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)])
        req.context_len = 0
        req.cached_tokens = 0            # re-admission re-matches
        req.state = RequestState.WAITING
        req.preemptions += 1
        _m_preemptions.inc()
        # The eviction is part of the request's causal chain: close the
        # decode phase as preempted and re-enter the queue as a new span
        # (the chain reads QUEUE->PREFILL->DECODE->QUEUE->...).
        req.close_phase("decode", preempted=True)
        req.trace.event("preempt",
                        generated=len(req.generated),
                        refill_tokens=int(req.prefill_tokens.shape[0]))
        req.t_enqueued = self._clock()
        req.open_phase("queue", preemption=req.preemptions)
        self.waiting.appendleft(req)

    def _youngest_other(self, keep: Request) -> Optional[Request]:
        for req in reversed(self.running):
            if req is not keep:
                return req
        return None
