"""Spark integration: run a horovod_tpu training function on Spark executors.

† ``horovod/spark/__init__.py`` / ``horovod/spark/runner.py``: upstream's
``horovod.spark.run(fn, args, num_proc)`` starts rendezvous services on the
driver, launches a barrier-mode Spark job with one task per rank, each task
wires the env and invokes ``fn``; results come back rank-ordered.  The
MPI/Gloo machinery is replaced here by the native KV/controller services and
the JAX coordination service — Spark is purely the process placer.

Topology (TPU-native): one Spark task per rank; each task's ``fn`` calls
``hvd.init()``, which reads the injected ``HVDTPU_*`` env exactly as
``hvdrun``-launched workers do.  Address/local-rank exchange rides the
barrier stage's ``allGather`` (upstream ran a separate probe service for
this; the barrier primitive subsumes it).

The Estimator API (high-level DataFrame training) lives in
``horovod_tpu/estimator`` — this module is the function-launch surface.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runner.cluster import DriverServices, pick_coordinator_port

__all__ = ["run"]


def _task_body(fn: Callable, args: Sequence, kwargs: Dict[str, Any],
               envs: List[Dict[str, str]], coord_port: int):
    """The per-task closure (pickled to executors).  Returns a 1-element
    iterator with (rank, result)."""

    def body(_it):
        from pyspark import BarrierTaskContext
        from horovod_tpu.runner.cluster import placement_env, placement_info
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        # One allGather round replaces upstream's task-to-driver probe
        # phase: every rank learns each rank's host (for local_rank) and
        # rank 0's IP (for the JAX coordinator).
        infos = ctx.allGather(placement_info())
        env = dict(envs[rank])
        env.update(placement_env(infos, rank, coord_port))
        # Spark reuses worker processes (spark.python.worker.reuse): clear
        # any HVDTPU_* state a previous run left behind before wiring ours.
        for k in [k for k in os.environ if k.startswith("HVDTPU_")]:
            del os.environ[k]
        os.environ.update(env)
        result = fn(*args, **(kwargs or {}))
        yield (rank, result)

    return body


def run(fn: Callable,
        args: Sequence = (),
        kwargs: Optional[Dict[str, Any]] = None,
        num_proc: Optional[int] = None,
        extra_env: Optional[Dict[str, str]] = None,
        platform: Optional[str] = None,
        verbose: bool = False) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks as horovod_tpu ranks and
    return the rank-ordered list of results († ``horovod.spark.run``).

    ``fn`` runs on each executor; call ``hvd.init()`` inside it.
    ``num_proc`` defaults to the cluster's default parallelism.

    Security note: the per-rank env blocks — including the job's HMAC
    secret (``HVDTPU_SECRET``) — travel inside the task closure that Spark
    pickles to executors, so the secret transits Spark task serialization
    and may appear in event logs if closure logging is enabled (upstream's
    Spark path has the same exposure).  The secret is per-job and expires
    with the driver services; for stricter handling, pre-distribute a
    secret via your cluster's credential mechanism and set ``HVDTPU_SECRET``
    in the executor environment instead.
    """
    try:
        from pyspark.sql import SparkSession
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark; on TPU VM slices "
            "without Spark use `hvdrun` (horovod_tpu.runner) instead"
        ) from e

    spark = SparkSession.getActiveSession() or \
        SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is not None and num_proc < 1:
        raise ValueError(f"num_proc must be >= 1, got {num_proc}")
    n = num_proc if num_proc is not None else sc.defaultParallelism

    driver_ip = sc.getConf().get("spark.driver.host", None) or None
    with DriverServices(n, service_ip=driver_ip) as services:
        # local_rank is a placeholder here; tasks overwrite it after the
        # barrier allGather reveals host placement.
        envs = [services.worker_env(r, 0, platform=platform,
                                    extra_env=extra_env) for r in range(n)]
        coord_port = pick_coordinator_port()
        body = _task_body(fn, args, kwargs or {}, envs, coord_port)
        if verbose:
            print(f"horovod_tpu.spark: launching {n} ranks "
                  f"(driver services at {services.service_ip})")
        results = (sc.parallelize(range(n), n)
                   .barrier()
                   .mapPartitions(body)
                   .collect())
    ordered = sorted(results, key=lambda t: t[0])
    got = [r for r, _ in ordered]
    if got != list(range(n)):
        raise RuntimeError(
            f"spark job returned results for ranks {got}, expected 0..{n-1}")
    return [res for _, res in ordered]
