"""JAX estimator under the Spark namespace — the TPU-native analogue of
† ``horovod.spark.torch`` (upstream's second framework estimator; torch
users on this framework train eagerly via ``horovod_tpu.torch``, while the
DataFrame-estimator surface is JAX/Flax-native here).
"""

from ..estimator import JaxEstimator, JaxModel
from ..estimator.store import LocalStore

__all__ = ["JaxEstimator", "JaxModel", "LocalStore"]
