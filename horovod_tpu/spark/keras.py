"""† ``horovod.spark.keras``: upstream users import the Keras estimator as
``from horovod.spark.keras import KerasEstimator``.  The TPU-native
estimator implementation lives in ``horovod_tpu/estimator``; this module is
the upstream-shaped import path for it.
"""

from ..estimator import KerasEstimator, KerasModel
from ..estimator.store import LocalStore

__all__ = ["KerasEstimator", "KerasModel", "LocalStore"]
