"""TensorFlow binding: Horovod's TF API surface on the TPU-native runtime.

† ``horovod/tensorflow/__init__.py`` + ``mpi_ops.cc`` + ``mpi_ops.py``:
``hvd.allreduce/allgather/broadcast/alltoall`` on ``tf.Tensor``,
``DistributedGradientTape`` (TF2/eager gradient allreduce),
``DistributedOptimizer`` (Keras-optimizer wrap; local gradient aggregation
via ``backward_passes_per_step`` ≙ † ``gradient_aggregation_eager.py``),
``broadcast_variables`` (step-0 sync of †3.3).

Architecture: the reference registers TF custom C++ ``AsyncOpKernel``s that
enqueue into its background runtime.  Here the runtime's data plane is XLA
itself, so TF tensors bridge host-side (numpy) into the engine's per-rank
arrays; inside ``tf.function`` graphs the bridge rides ``tf.py_function``
(an eager host-call — the moral equivalent of the reference's async kernel
handing off to the background thread).  ``jit_compile=True`` graphs cannot
host-call; for fully-compiled training use the JAX path, which is this
framework's native mode (the reference's own XLA story,
† ``xla_mpi_ops.cc``, was likewise an escape hatch).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np
import tensorflow as tf

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401  (re-exported basics †basics.py)
    Average,
    Sum,
    Min,
    Max,
    Product,
    Adasum,
    ReduceOp,
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    broadcast_object,
    join,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401


def _to_per_rank(arr: np.ndarray):
    # One host->device copy + on-device replication for local ranks (see
    # replicate_local: never local_size host copies of the payload).
    from horovod_tpu.ops.collectives import replicate_local
    return replicate_local(arr)


def _np(x) -> np.ndarray:
    return np.array(_hvd.to_numpy(x))


# ---------------------------------------------------------------------------
# Eager verbs
# ---------------------------------------------------------------------------

def allreduce(tensor: tf.Tensor, op: ReduceOp = Average,
              name: Optional[str] = None) -> tf.Tensor:
    """† ``hvd.allreduce`` on a TF tensor (eager or inside ``tf.function``
    via host-call)."""
    del name
    if tf.executing_eagerly() and not isinstance(tensor, tf.Variable) \
            and not hasattr(tensor, "graph"):
        out = _np(_hvd.allreduce(_to_per_rank(np.asarray(tensor)), op))
        return tf.constant(out, dtype=tensor.dtype)
    dtype = tensor.dtype

    def _host(t):
        out = _np(_hvd.allreduce(_to_per_rank(t.numpy()), op))
        return tf.constant(out.astype(dtype.as_numpy_dtype))

    result = tf.py_function(_host, inp=[tensor], Tout=dtype)
    result.set_shape(tensor.shape)
    return result


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    del name
    out = _np(_hvd.allgather(_to_per_rank(np.asarray(tensor))))
    return tf.constant(out, dtype=tensor.dtype)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    del name
    out = _np(_hvd.broadcast(_to_per_rank(np.asarray(tensor)), root_rank))
    return tf.constant(out, dtype=tensor.dtype)


def alltoall(tensor: tf.Tensor, splits: Optional[Sequence[int]] = None,
             name: Optional[str] = None) -> tf.Tensor:
    del name
    out = _np(_hvd.alltoall(_to_per_rank(np.asarray(tensor)), splits))
    return tf.constant(out, dtype=tensor.dtype)


def reducescatter(tensor: tf.Tensor, op: ReduceOp = Sum,
                  name: Optional[str] = None) -> tf.Tensor:
    del name
    out = _np(_hvd.reducescatter(_to_per_rank(np.asarray(tensor)), op))
    return tf.constant(out, dtype=tensor.dtype)


# ---------------------------------------------------------------------------
# Async verbs
# ---------------------------------------------------------------------------

def allreduce_async(tensor: tf.Tensor, op: ReduceOp = Average,
                    name: Optional[str] = None):
    return _hvd.allreduce_async(_to_per_rank(np.asarray(tensor)), op,
                                name=name)


def synchronize(handle) -> tf.Tensor:
    return tf.constant(_np(_hvd.synchronize(handle)))


def poll(handle) -> bool:
    return _hvd.poll(handle)


# ---------------------------------------------------------------------------
# Variable sync († broadcast_variables / BroadcastGlobalVariablesCallback)
# ---------------------------------------------------------------------------

def broadcast_variables(variables: Sequence[tf.Variable],
                        root_rank: int = 0) -> None:
    """In-place broadcast of TF variables from ``root_rank``
    († ``hvd.broadcast_variables`` — the step-0 weight sync).

    One pytree broadcast for all variables, not one collective each (a large
    model has thousands of variables; per-tensor multihost round-trips would
    dominate startup — same batching the torch binding does).
    """
    variables = list(variables)
    if not variables:
        return
    if tf.executing_eagerly():
        tensors = {str(i): np.asarray(v) for i, v in enumerate(variables)}
        synced = _hvd.broadcast_parameters(tensors, root_rank=root_rank)
        for i, v in enumerate(variables):
            v.assign(tf.constant(_np(synced[str(i)]),
                                 dtype=v.dtype, shape=v.shape))
        return
    # tf.function graph: read values as graph tensors, broadcast in one
    # host-call, assign back (runs on first-batch sync inside @tf.function,
    # the reference's documented pattern).
    values = [tf.convert_to_tensor(v) for v in variables]

    def _host(*vals):
        tensors = {str(i): val.numpy() for i, val in enumerate(vals)}
        synced = _hvd.broadcast_parameters(tensors, root_rank=root_rank)
        return [tf.constant(_np(synced[str(i)])) for i in range(len(vals))]

    out = tf.py_function(_host, inp=values, Tout=[v.dtype for v in values])
    if not isinstance(out, (list, tuple)):
        out = [out]
    for v, r in zip(variables, out):
        r.set_shape(v.shape)
        v.assign(r)


# ---------------------------------------------------------------------------
# DistributedGradientTape († _DistributedGradientTape, TF2 eager hot path)
# ---------------------------------------------------------------------------

class _DistributedGradientTape:
    """Wraps ``tf.GradientTape``; ``gradient()`` returns allreduced grads.

    All gradients ship through ONE fused engine cycle
    († fusion buffer: the tape's grads are exactly the many-small-tensors
    case the fusion path exists for).
    """

    def __init__(self, tape: tf.GradientTape, op: ReduceOp = Average,
                 compression=Compression.none) -> None:
        self._tape = tape
        self._op = op
        self._compression = compression

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        flat = tf.nest.flatten(grads)
        reduced = _grouped_allreduce_grads(flat, self._op, self._compression)
        return tf.nest.pack_sequence_as(grads, reduced)


def DistributedGradientTape(tape: tf.GradientTape, op: ReduceOp = Average,
                            compression=Compression.none
                            ) -> _DistributedGradientTape:
    """† ``hvd.DistributedGradientTape``."""
    return _DistributedGradientTape(tape, op=op, compression=compression)


def _grouped_allreduce_grads(flat_grads, op: ReduceOp, compression):
    """Allreduce a flat gradient list in one fused cycle; None passes
    through (untrained variables yield None grads, † _allreduce_grads).

    Inside ``tf.function`` graphs the whole list rides ONE host-call
    (a single fused engine cycle ≙ the fusion buffer)."""
    if not tf.executing_eagerly():
        live = [tf.convert_to_tensor(g) for g in flat_grads if g is not None]
        if not live:
            return list(flat_grads)
        dtypes = [g.dtype for g in live]

        def _host(*gs):
            outs = _grouped_allreduce_grads_eager(list(gs), op, compression)
            return [tf.constant(np.asarray(o)) for o in outs]

        reduced_live = tf.py_function(_host, inp=live, Tout=dtypes)
        if not isinstance(reduced_live, (list, tuple)):
            reduced_live = [reduced_live]
        it = iter(reduced_live)
        out = []
        for g in flat_grads:
            if g is None:
                out.append(None)
            else:
                r = next(it)
                if isinstance(g, tf.Tensor):
                    r.set_shape(g.shape)
                out.append(r)
        return out
    return _grouped_allreduce_grads_eager(flat_grads, op, compression)


def _grouped_allreduce_grads_eager(flat_grads, op: ReduceOp, compression):
    import jax.numpy as jnp
    handles: list = []
    ctxs: list = []
    idx: list[int] = []
    # Quantized compressors route as engine wire modes; cast compressors
    # keep the host-side compress (see ops/compression.py).
    from horovod_tpu.ops.compression import routes_engine_side
    kw = ({"compression": compression} if routes_engine_side(compression)
          else {})
    for i, g in enumerate(flat_grads):
        if g is None:
            continue
        arr = np.asarray(g.values if isinstance(g, tf.IndexedSlices) else g)
        if isinstance(g, tf.IndexedSlices):
            # † sparse_as_dense: densify indexed slices before the ring.
            dense = np.zeros(g.dense_shape.numpy(), arr.dtype)
            np.add.at(dense, g.indices.numpy(), arr)
            arr = dense
        if kw:
            wire, ctx = jnp.asarray(arr), None
        else:
            wire, ctx = compression.compress(jnp.asarray(arr))
        handles.append(_hvd.allreduce_async(
            _to_per_rank(np.asarray(wire)), op, name=f"tf.grad.{i}", **kw))
        ctxs.append(ctx)
        idx.append(i)
    out = list(flat_grads)
    results = [_hvd.synchronize(h) for h in handles]
    for i, res, ctx in zip(idx, results, ctxs):
        dec = compression.decompress(res, ctx)
        g = flat_grads[i]
        out[i] = tf.constant(_np(dec), dtype=g.dtype)
    return out


# ---------------------------------------------------------------------------
# DistributedOptimizer († Keras optimizer wrap + gradient aggregation)
# ---------------------------------------------------------------------------

def DistributedOptimizer(optimizer, op: ReduceOp = Average,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         name: Optional[str] = None):
    """† ``hvd.DistributedOptimizer``: returns an optimizer of the same
    class whose gradient application first allreduces across ranks.

    Works in eager custom loops and in ``model.fit`` graphs (host-call);
    ``backward_passes_per_step > 1`` accumulates locally and applies the
    averaged update every Nth call († ``LocalGradientAggregationHelper``).
    """
    del name
    cls = optimizer.__class__
    dist_cls = type("Distributed" + cls.__name__, (cls,), {
        "_hvd_op": op,
        "_hvd_compression": compression,
        "_hvd_bpps": backward_passes_per_step,
        "apply_gradients": _dist_apply_gradients,
    })
    new = dist_cls.from_config(optimizer.get_config())
    new._hvd_agg_buf = None
    new._hvd_agg_count = 0
    return new


def _dist_apply_gradients(self, grads_and_vars, *args, **kwargs):
    grads_and_vars = list(grads_and_vars)
    grads = [g for g, _ in grads_and_vars]
    tvars = [v for _, v in grads_and_vars]
    eager = tf.executing_eagerly() and all(
        not hasattr(g, "graph") for g in grads if g is not None)
    if self._hvd_bpps > 1:
        if not eager:
            raise RuntimeError(
                "backward_passes_per_step > 1 requires eager execution "
                "(run_eagerly=True) in this binding")
        if self._hvd_agg_buf is None:
            self._hvd_agg_buf = [
                None if g is None else np.asarray(g) for g in grads]
        else:
            for i, g in enumerate(grads):
                if g is not None:
                    self._hvd_agg_buf[i] = self._hvd_agg_buf[i] + np.asarray(g)
        self._hvd_agg_count += 1
        if self._hvd_agg_count < self._hvd_bpps:
            return None  # † aggregation step: no variable update yet
        grads = [None if b is None else tf.constant(b / self._hvd_bpps)
                 for b in self._hvd_agg_buf]
        self._hvd_agg_buf = None
        self._hvd_agg_count = 0

    reduced = _grouped_allreduce_grads(grads, self._hvd_op,
                                       self._hvd_compression)
    return super(type(self), self).apply_gradients(
        zip(reduced, tvars), *args, **kwargs)


def __getattr__(name: str):
    if name == "elastic":
        # † ``import horovod.tensorflow as hvd; hvd.elastic.TensorFlowKerasState``
        import importlib
        return importlib.import_module("horovod_tpu.tensorflow.elastic")
    raise AttributeError(
        f"module 'horovod_tpu.tensorflow' has no attribute {name!r}")
