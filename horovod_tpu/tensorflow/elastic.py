"""TF/Keras elastic state († ``horovod/tensorflow/elastic.py``).

``TensorFlowKerasState(model, optimizer=None, epoch=0, ...)``: commit
snapshots weights host-side (numpy), restore rolls back, sync broadcasts
rank-0's weights to all ranks.  Works with Keras 3 models (TF backend) and
bare lists of ``tf.Variable``.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import numpy as np

from horovod_tpu.elastic import (  # noqa: F401  (reference-shaped surface)
    HorovodInternalError,
    HostsUpdatedInterrupt,
    ObjectState,
    State,
    run,
)
from . import broadcast_variables


class TensorFlowKerasState(State):
    """† ``TensorFlowKerasState``: model weights + optimizer variables +
    plain attributes under the commit/restore/sync protocol."""

    def __init__(self, model, optimizer=None, **kwargs: Any) -> None:
        super().__init__()
        self._model = model
        self._optimizer = optimizer
        self._objects: dict[str, Any] = dict(kwargs)
        self._saved: dict[str, Any] = {}
        self.save()

    def __getattr__(self, name: str) -> Any:
        if name == "model":
            return self.__dict__["_model"]
        if name == "optimizer":
            return self.__dict__["_optimizer"]
        objects = self.__dict__.get("_objects", {})
        if name in objects:
            return objects[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            super().__setattr__(name, value)
        elif name in ("model", "optimizer"):
            super().__setattr__("_" + name, value)
        else:
            self._objects[name] = value

    def _opt_vars(self) -> list:
        if self._optimizer is None:
            return []
        return list(getattr(self._optimizer, "variables", lambda: [])()
                    if callable(getattr(self._optimizer, "variables", None))
                    else self._optimizer.variables)

    def save(self) -> None:
        self._saved = {
            "objects": copy.deepcopy(self._objects),
            "weights": [np.array(w) for w in self._model.get_weights()],
            "opt": [np.array(v) for v in self._opt_vars()],
        }

    def restore(self) -> None:
        self._objects = copy.deepcopy(self._saved["objects"])
        self._model.set_weights([w.copy() for w in self._saved["weights"]])
        for var, val in zip(self._opt_vars(), self._saved["opt"]):
            var.assign(val)

    def sync(self) -> None:
        import horovod_tpu as hvd
        broadcast_variables(self._model.variables, root_rank=0)
        opt_vars = self._opt_vars()
        if opt_vars:
            broadcast_variables(opt_vars, root_rank=0)
        self._objects = hvd.broadcast_object(self._objects, root_rank=0)
        self.save()


# † horovod/keras/elastic.py KerasState is the same object in the Keras-3
# world (tf.keras IS keras); alias for reference users.
KerasState = TensorFlowKerasState
