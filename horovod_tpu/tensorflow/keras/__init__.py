"""† ``horovod/tensorflow/keras/`` — the tf.keras-flavored surface.

Re-exports the Keras callbacks (shared with :mod:`horovod_tpu.keras`, same
as the reference's shared ``horovod/_keras/``) plus the TF
``DistributedOptimizer`` and ``broadcast_variables``.
"""

from horovod_tpu.keras import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    MetricAverageCallback,
    LearningRateWarmupCallback,
    LearningRateScheduleCallback,
)
from horovod_tpu.tensorflow import (  # noqa: F401
    Average,
    Sum,
    Min,
    Max,
    Product,
    Adasum,
    ReduceOp,
    Compression,
    DistributedOptimizer,
    allreduce,
    allgather,
    broadcast,
    broadcast_variables,
    broadcast_object,
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    join,
)

# † horovod/keras callbacks module alias (hvd.callbacks.*)
from horovod_tpu import keras as _k

callbacks = _k
