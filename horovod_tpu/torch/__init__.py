"""PyTorch binding: Horovod's torch API surface on the TPU-native runtime.

† ``horovod/torch/__init__.py`` + ``optimizer.py`` + ``mpi_ops_v2.cc``:
``hvd.allreduce(tensor)``, ``*_async_`` + ``synchronize``,
``DistributedOptimizer`` (per-parameter grad hooks → async allreduce,
``step()`` synchronizes), ``broadcast_parameters`` /
``broadcast_optimizer_state``.

Topology: one process per rank, as in the reference (launch with
``hvdrun -np N``).  Each process's torch tensors are that rank's data; the
bridge is zero-ceremony (torch CPU tensor ↔ numpy ↔ per-rank jax array via
``from_local``).  Single-process mode treats the process's tensor as
present on each of its devices (so Sum multiplies by ``local_size`` exactly
as N identical ranks would).

On TPU VM deployments the torch compute itself stays on CPU (or torch-xla
where available); the collectives ride the XLA data plane either way.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np
import torch

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401  (re-exported basics †basics.py)
    Average,
    Sum,
    Min,
    Max,
    Product,
    Adasum,
    ReduceOp,
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401


def _to_per_rank(t: torch.Tensor):
    # One host->device copy per collective; on-device replication covers
    # the process's other local ranks (never local_size host copies of
    # the gradient bytes — on a real multi-chip host that would stage
    # N x the payload through host memory per step).
    from horovod_tpu.ops.collectives import replicate_local
    return replicate_local(t.detach().cpu().numpy())


def _from_result(x, like: torch.Tensor) -> torch.Tensor:
    out = torch.from_numpy(np.array(_hvd.to_numpy(x)))
    return out.to(dtype=like.dtype)


# -- eager verbs --

def allreduce(tensor: torch.Tensor, op: ReduceOp = Average,
              name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.allreduce(_to_per_rank(tensor), op), tensor)


def allgather(tensor: torch.Tensor, name: Optional[str] = None
              ) -> torch.Tensor:
    del name
    return _from_result(_hvd.allgather(_to_per_rank(tensor)), tensor)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.broadcast(_to_per_rank(tensor), root_rank),
                        tensor)


def alltoall(tensor: torch.Tensor, splits=None,
             name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.alltoall(_to_per_rank(tensor), splits), tensor)


# -- in-place variants († ``hvd.allreduce_`` / ``hvd.broadcast_``: the
# torch API's underscore convention writes the result back into the given
# tensor; same collectives underneath) --

def allreduce_(tensor: torch.Tensor, op: ReduceOp = Average,
               name: Optional[str] = None) -> torch.Tensor:
    with torch.no_grad():
        tensor.copy_(allreduce(tensor, op, name))
    return tensor


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    with torch.no_grad():
        tensor.copy_(broadcast(tensor, root_rank, name))
    return tensor


# -- async verbs († *_async / *_async_ + HandleManager) --

class _InplaceHandle:
    """Async handle whose synchronize() writes back into the source
    tensor († the ``*_async_`` in-place convention)."""

    def __init__(self, handle, target: torch.Tensor) -> None:
        self.handle = handle
        self.target = target


def allreduce_async(tensor: torch.Tensor, op: ReduceOp = Average,
                    name: Optional[str] = None):
    return _hvd.allreduce_async(_to_per_rank(tensor), op, name=name)


def allreduce_async_(tensor: torch.Tensor, op: ReduceOp = Average,
                     name: Optional[str] = None) -> _InplaceHandle:
    return _InplaceHandle(allreduce_async(tensor, op, name), tensor)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None):
    return _hvd.broadcast_async(_to_per_rank(tensor), root_rank, name=name)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> _InplaceHandle:
    return _InplaceHandle(broadcast_async(tensor, root_rank, name), tensor)


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None):
    return _hvd.allgather_async(_to_per_rank(tensor), name=name)


def synchronize(handle) -> torch.Tensor:
    if isinstance(handle, _InplaceHandle):
        result = synchronize(handle.handle)
        with torch.no_grad():
            handle.target.copy_(result)
        return handle.target
    result = _hvd.synchronize(handle)
    return torch.from_numpy(np.array(_hvd.to_numpy(result)))


def poll(handle) -> bool:
    if isinstance(handle, _InplaceHandle):
        handle = handle.handle
    return _hvd.poll(handle)


# -- parameter/optimizer sync --

def broadcast_parameters(params: Any, root_rank: int = 0) -> None:
    """In-place broadcast of a ``state_dict`` or named-parameter iterable
    († ``broadcast_parameters``)."""
    if isinstance(params, dict):
        items = list(params.items())
    else:
        items = list(params)
    tensors = {k: v.detach().cpu().numpy() for k, v in items
               if isinstance(v, torch.Tensor)}
    synced = _hvd.broadcast_parameters(tensors, root_rank=root_rank)
    for k, v in items:
        if isinstance(v, torch.Tensor):
            with torch.no_grad():
                v.copy_(torch.from_numpy(np.array(_hvd.to_numpy(synced[k])))
                        .to(dtype=v.dtype))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """† ``broadcast_optimizer_state`` — sync optimizer tensor state.

    All state tensors ship in ONE broadcast (a pytree dict), not one
    collective per tensor — Adam on a large model has thousands of state
    tensors and per-tensor multihost round-trips would dominate startup.
    """
    refs: dict[str, torch.Tensor] = {}
    for gi, group in enumerate(optimizer.param_groups):
        for pi, p in enumerate(group["params"]):
            for key, val in optimizer.state.get(p, {}).items():
                if isinstance(val, torch.Tensor):
                    refs[f"g{gi}.p{pi}.{key}"] = val
    if not refs:
        return
    synced = _hvd.broadcast_parameters(
        {k: v.detach().cpu().numpy() for k, v in refs.items()},
        root_rank=root_rank)
    for k, val in refs.items():
        with torch.no_grad():
            val.copy_(torch.from_numpy(np.array(_hvd.to_numpy(synced[k])))
                      .to(dtype=val.dtype))


class _DistributedOptimizer(torch.optim.Optimizer):
    """† ``horovod/torch/optimizer.py _DistributedOptimizer``: grad hooks
    enqueue async allreduces during backward; ``step()`` synchronizes and
    applies averaged gradients."""

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 op: ReduceOp = Average,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1) -> None:
        self._inner = optimizer
        self.op = op
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._pass_counts: dict = {}
        self._handles: dict = {}
        self._ctxs: dict = {}
        if named_parameters is not None:
            names = {id(p): n for n, p in named_parameters}
        else:
            names = {}
        self._names = names
        self._hook_handles = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook(p)))

    # expose the inner optimizer's surface
    @property
    def param_groups(self):
        return self._inner.param_groups

    @param_groups.setter
    def param_groups(self, value):
        self._inner.param_groups = value

    @property
    def state(self):
        return self._inner.state

    def _name_of(self, p: torch.Tensor) -> str:
        return self._names.get(id(p), f"param.{id(p)}")

    def _make_hook(self, p: torch.Tensor):
        def hook(param: torch.Tensor) -> None:
            # Local gradient aggregation († backward_passes_per_step): torch
            # accumulates into p.grad across backwards; the collective fires
            # only on the Nth pass, carrying the accumulated sum / N.
            count = self._pass_counts.get(p, 0) + 1
            self._pass_counts[p] = count
            if count < self._bpps:
                return
            self._pass_counts[p] = 0
            if p in self._handles:
                raise RuntimeError(
                    f"gradient for {self._name_of(p)} reduced twice before "
                    "step() — call step() once per backward "
                    "(† duplicate in-flight name check)")
            grad = param.grad
            arr = grad.detach().cpu().numpy()
            if self._bpps > 1:
                arr = arr / self._bpps
            import jax.numpy as jnp
            from horovod_tpu.ops.collectives import replicate_local
            wire, ctx = self._compression.compress(jnp.asarray(arr))
            handle = _hvd.allreduce_async(
                replicate_local(np.asarray(wire)),
                self.op, name=f"grad.{self._name_of(p)}")
            self._handles[p] = handle
            self._ctxs[p] = (ctx, grad.dtype)
        return hook

    def synchronize(self) -> None:
        """† ``synchronize()``: block on all outstanding grad reductions and
        write results back into ``p.grad``."""
        for p, handle in self._handles.items():
            result = _hvd.synchronize(handle)
            ctx, dtype = self._ctxs[p]
            result = self._compression.decompress(result, ctx)
            with torch.no_grad():
                p.grad.copy_(torch.from_numpy(
                    np.array(_hvd.to_numpy(result))).to(dtype=dtype))
        self._handles.clear()
        self._ctxs.clear()

    def step(self, closure=None):
        if self._bpps > 1 and any(self._pass_counts.values()):
            raise RuntimeError(
                f"step() called after "
                f"{max(self._pass_counts.values())} backward passes; "
                f"backward_passes_per_step={self._bpps} requires exactly "
                f"{self._bpps} († optimizer.step() assertion)")
        self.synchronize()
        return self._inner.step(closure)

    def zero_grad(self, set_to_none: bool = True):
        return self._inner.zero_grad(set_to_none=set_to_none)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         op: ReduceOp = Average,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1
                         ) -> _DistributedOptimizer:
    """† ``hvd.DistributedOptimizer`` for torch."""
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters, op=op,
        compression=compression,
        backward_passes_per_step=backward_passes_per_step)


def __getattr__(name: str):
    if name == "elastic":
        # † ``import horovod.torch as hvd; hvd.elastic.run`` — lazy so the
        # elastic machinery isn't paid for by collective-only users.
        import importlib
        return importlib.import_module("horovod_tpu.torch.elastic")
    if name == "SyncBatchNorm":
        # † ``hvd.SyncBatchNorm`` — lazy: it imports this module back.
        from .sync_batch_norm import SyncBatchNorm
        return SyncBatchNorm
    raise AttributeError(f"module 'horovod_tpu.torch' has no attribute {name!r}")
