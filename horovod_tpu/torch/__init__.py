"""PyTorch binding: Horovod's torch API surface on the TPU-native runtime.

† ``horovod/torch/__init__.py`` + ``optimizer.py`` + ``mpi_ops_v2.cc``:
``hvd.allreduce(tensor)``, ``*_async_`` + ``synchronize``,
``DistributedOptimizer`` (per-parameter grad hooks → async allreduce,
``step()`` synchronizes), ``broadcast_parameters`` /
``broadcast_optimizer_state``.

Topology: one process per rank, as in the reference (launch with
``hvdrun -np N``).  Each process's torch tensors are that rank's data; the
bridge is zero-ceremony (torch CPU tensor ↔ numpy ↔ per-rank jax array via
``from_local``).  Single-process mode treats the process's tensor as
present on each of its devices (so Sum multiplies by ``local_size`` exactly
as N identical ranks would).

On TPU VM deployments the torch compute itself stays on CPU (or torch-xla
where available); the collectives ride the XLA data plane either way.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np
import torch

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401  (re-exported basics †basics.py)
    Average,
    Sum,
    Min,
    Max,
    Product,
    Adasum,
    ReduceOp,
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401


def _to_per_rank(t: torch.Tensor):
    # One host->device copy per collective; on-device replication covers
    # the process's other local ranks (never local_size host copies of
    # the gradient bytes — on a real multi-chip host that would stage
    # N x the payload through host memory per step).
    from horovod_tpu.ops.collectives import replicate_local
    return replicate_local(t.detach().cpu().numpy())


def _from_result(x, like: torch.Tensor) -> torch.Tensor:
    # device->host: one host copy per verb (jax.device_get hands back a
    # read-only buffer, so torch.from_numpy needs a writable copy —
    # verified on this jax: every device_get result has writeable=False,
    # making a "skip the copy when writable" fast path dead code).  A
    # zero-copy torch path needs torch-xla sharing the device runtime,
    # which this image cannot provide; the bucketed optimizer path
    # amortizes this cost for training (torch_bridge_bench: 44x).
    return torch.from_numpy(np.array(_hvd.to_numpy(x))).to(dtype=like.dtype)


# -- eager verbs --

def allreduce(tensor: torch.Tensor, op: ReduceOp = Average,
              name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.allreduce(_to_per_rank(tensor), op), tensor)


def allgather(tensor: torch.Tensor, name: Optional[str] = None
              ) -> torch.Tensor:
    del name
    return _from_result(_hvd.allgather(_to_per_rank(tensor)), tensor)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.broadcast(_to_per_rank(tensor), root_rank),
                        tensor)


def alltoall(tensor: torch.Tensor, splits=None,
             name: Optional[str] = None) -> torch.Tensor:
    del name
    return _from_result(_hvd.alltoall(_to_per_rank(tensor), splits), tensor)


# -- in-place variants († ``hvd.allreduce_`` / ``hvd.broadcast_``: the
# torch API's underscore convention writes the result back into the given
# tensor; same collectives underneath) --

def allreduce_(tensor: torch.Tensor, op: ReduceOp = Average,
               name: Optional[str] = None) -> torch.Tensor:
    with torch.no_grad():
        tensor.copy_(allreduce(tensor, op, name))
    return tensor


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    with torch.no_grad():
        tensor.copy_(broadcast(tensor, root_rank, name))
    return tensor


# -- async verbs († *_async / *_async_ + HandleManager) --

class _InplaceHandle:
    """Async handle whose synchronize() writes back into the source
    tensor († the ``*_async_`` in-place convention)."""

    def __init__(self, handle, target: torch.Tensor) -> None:
        self.handle = handle
        self.target = target


def allreduce_async(tensor: torch.Tensor, op: ReduceOp = Average,
                    name: Optional[str] = None):
    return _hvd.allreduce_async(_to_per_rank(tensor), op, name=name)


def allreduce_async_(tensor: torch.Tensor, op: ReduceOp = Average,
                     name: Optional[str] = None) -> _InplaceHandle:
    return _InplaceHandle(allreduce_async(tensor, op, name), tensor)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None):
    return _hvd.broadcast_async(_to_per_rank(tensor), root_rank, name=name)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> _InplaceHandle:
    return _InplaceHandle(broadcast_async(tensor, root_rank, name), tensor)


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None):
    return _hvd.allgather_async(_to_per_rank(tensor), name=name)


def synchronize(handle) -> torch.Tensor:
    if isinstance(handle, _InplaceHandle):
        result = synchronize(handle.handle)
        with torch.no_grad():
            handle.target.copy_(result)
        return handle.target
    result = _hvd.synchronize(handle)
    return torch.from_numpy(np.array(_hvd.to_numpy(result)))


def poll(handle) -> bool:
    if isinstance(handle, _InplaceHandle):
        handle = handle.handle
    return _hvd.poll(handle)


# -- parameter/optimizer sync --

def broadcast_parameters(params: Any, root_rank: int = 0) -> None:
    """In-place broadcast of a ``state_dict`` or named-parameter iterable
    († ``broadcast_parameters``)."""
    if isinstance(params, dict):
        items = list(params.items())
    else:
        items = list(params)
    tensors = {k: v.detach().cpu().numpy() for k, v in items
               if isinstance(v, torch.Tensor)}
    synced = _hvd.broadcast_parameters(tensors, root_rank=root_rank)
    for k, v in items:
        if isinstance(v, torch.Tensor):
            with torch.no_grad():
                v.copy_(torch.from_numpy(np.array(_hvd.to_numpy(synced[k])))
                        .to(dtype=v.dtype))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """† ``broadcast_optimizer_state`` — sync optimizer tensor state.

    All state tensors ship in ONE broadcast (a pytree dict), not one
    collective per tensor — Adam on a large model has thousands of state
    tensors and per-tensor multihost round-trips would dominate startup.
    """
    refs: dict[str, torch.Tensor] = {}
    for gi, group in enumerate(optimizer.param_groups):
        for pi, p in enumerate(group["params"]):
            for key, val in optimizer.state.get(p, {}).items():
                if isinstance(val, torch.Tensor):
                    refs[f"g{gi}.p{pi}.{key}"] = val
    if not refs:
        return
    synced = _hvd.broadcast_parameters(
        {k: v.detach().cpu().numpy() for k, v in refs.items()},
        root_rank=root_rank)
    for k, val in refs.items():
        with torch.no_grad():
            val.copy_(torch.from_numpy(np.array(_hvd.to_numpy(synced[k])))
                      .to(dtype=val.dtype))


class _DistributedOptimizer(torch.optim.Optimizer):
    """† ``horovod/torch/optimizer.py _DistributedOptimizer``: grad hooks
    enqueue async allreduces during backward; ``step()`` synchronizes and
    applies averaged gradients.

    Transfer batching (beyond the reference's per-tensor zero-copy
    adapters, which a host-bridge cannot have): gradients are staged into
    per-dtype host buckets as hooks fire; a bucket flushes — ONE
    host→device transfer and ONE fused collective — when it reaches
    ``bucket_cap_bytes`` (default: the engine's fusion threshold), and the
    remainder flushes at ``synchronize()``.  Write-back is one
    device→host fetch per bucket.  So host traffic per step is
    O(total_bytes / bucket_cap), not O(n_params), while flushed buckets
    still overlap the rest of backward.  Bucket composition follows hook
    firing order, which torch keeps deterministic for a fixed graph — the
    same property the reference's response cache relies on for its
    steady-state bit-vector fast path.
    """

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters=None,
                 op: ReduceOp = Average,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 bucket_cap_bytes: Optional[int] = None) -> None:
        self._inner = optimizer
        self.op = op
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._bucket_cap = bucket_cap_bytes
        if self._bucket_cap is None and _hvd.is_initialized():
            # Latch now, before any autotune proposal can move the live
            # threshold (ranks construct the optimizer at the same point,
            # so the latched value agrees everywhere).
            self._bucket_cap = \
                _hvd.global_state().config.fusion_threshold
        self._pass_counts: dict = {}
        # dtype-key -> list of (param, host_grad_array) awaiting flush
        self._staged: dict = {}
        self._staged_bytes: dict = {}
        # list of in-flight bucket records
        self._inflight: list = []
        self._pending_params: set = set()
        self._bucket_seq = 0
        if named_parameters is not None:
            names = {id(p): n for n, p in named_parameters}
        else:
            names = {}
        self._names = names
        self._hook_handles = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook(p)))

    # expose the inner optimizer's surface
    @property
    def param_groups(self):
        return self._inner.param_groups

    @param_groups.setter
    def param_groups(self, value):
        self._inner.param_groups = value

    @property
    def state(self):
        return self._inner.state

    def _name_of(self, p: torch.Tensor) -> str:
        return self._names.get(id(p), f"param.{id(p)}")

    def _cap_bytes(self) -> int:
        # Latched once: bucket boundaries decide bucket names, which must
        # match on every rank.  Reading the live config each hook would
        # diverge under autotune (each rank tunes fusion_threshold from
        # local timings), deadlocking negotiation on mismatched buckets.
        if self._bucket_cap is None:
            self._bucket_cap = \
                _hvd.global_state().config.fusion_threshold
        return self._bucket_cap

    def _make_hook(self, p: torch.Tensor):
        def hook(param: torch.Tensor) -> None:
            # Local gradient aggregation († backward_passes_per_step): torch
            # accumulates into p.grad across backwards; the collective fires
            # only on the Nth pass, carrying the accumulated sum / N.
            count = self._pass_counts.get(p, 0) + 1
            self._pass_counts[p] = count
            if count < self._bpps:
                return
            self._pass_counts[p] = 0
            if p in self._pending_params:
                raise RuntimeError(
                    f"gradient for {self._name_of(p)} reduced twice before "
                    "step() — call step() once per backward "
                    "(† duplicate in-flight name check)")
            self._pending_params.add(p)
            arr = param.grad.detach().cpu().numpy()
            if self._bpps > 1:
                arr = arr / self._bpps
            key = str(arr.dtype)
            self._staged.setdefault(key, []).append((p, arr))
            nbytes = self._staged_bytes.get(key, 0) + arr.nbytes
            self._staged_bytes[key] = nbytes
            # Adasum's projection is per-tensor math, not elementwise —
            # concatenating tensors would change the result, so each grad
            # flushes as its own single-entry bucket.
            if self.op is Adasum or nbytes >= self._cap_bytes():
                self._flush_bucket(key)
        return hook

    def _flush_bucket(self, key: str) -> None:
        """Stage one dtype bucket to the device and enqueue ONE fused
        allreduce for it."""
        entries = self._staged.pop(key, [])
        self._staged_bytes.pop(key, None)
        if not entries:
            return
        import hashlib

        import jax.numpy as jnp
        from horovod_tpu.ops.collectives import replicate_local
        flat = (entries[0][1].ravel() if len(entries) == 1 else
                np.concatenate([a.ravel() for _, a in entries]))
        # Quantized compressors route as engine wire modes (quantization
        # must live inside the collective); cast compressors keep the
        # host-side compress so the staged device buffer is already 16-bit.
        from horovod_tpu.ops.compression import routes_engine_side
        kw = ({"compression": self._compression}
              if routes_engine_side(self._compression) else {})
        wire, ctx = self._compression.compress(jnp.asarray(flat))
        seq = self._bucket_seq
        self._bucket_seq += 1
        # Content fingerprint (member names + sizes): ranks whose hook
        # firing sets diverge (data-dependent unused params) produce
        # different names, so negotiation stalls loudly instead of fusing
        # unrelated gradients into a silently corrupt bucket.
        fp = hashlib.sha1("|".join(
            f"{self._name_of(p)}:{a.size}" for p, a in entries)
            .encode()).hexdigest()[:10]
        handle = _hvd.allreduce_async(
            replicate_local(np.asarray(wire)), self.op,
            name=f"gradbucket.{key}.{seq}.{fp}", **kw)
        self._inflight.append((handle, entries, ctx))

    def synchronize(self) -> None:
        """† ``synchronize()``: flush staged buckets, block on all
        outstanding reductions, and write results back into ``p.grad``
        (one device→host fetch per bucket).

        Staging state is cleared even when a collective errors
        (HorovodInternalError) so the elastic restore/retry path can run
        a fresh backward without a spurious 'reduced twice' error.
        """
        try:
            for key in list(self._staged):
                self._flush_bucket(key)
            for handle, entries, ctx in self._inflight:
                result = _hvd.synchronize(handle)
                result = self._compression.decompress(result, ctx)
                host = np.asarray(_hvd.to_numpy(result))
                offset = 0
                for p, arr in entries:
                    piece = host[offset:offset + arr.size].reshape(arr.shape)
                    offset += arr.size
                    with torch.no_grad():
                        p.grad.copy_(torch.from_numpy(np.array(piece))
                                     .to(dtype=p.grad.dtype))
        finally:
            self._inflight.clear()
            self._staged.clear()
            self._staged_bytes.clear()
            self._pending_params.clear()
            # Names restart each step so the dispatch/response caches see
            # the identical signature sequence every iteration.
            self._bucket_seq = 0

    def step(self, closure=None):
        if self._bpps > 1 and any(self._pass_counts.values()):
            raise RuntimeError(
                f"step() called after "
                f"{max(self._pass_counts.values())} backward passes; "
                f"backward_passes_per_step={self._bpps} requires exactly "
                f"{self._bpps} († optimizer.step() assertion)")
        self.synchronize()
        return self._inner.step(closure)

    def zero_grad(self, set_to_none: bool = True):
        return self._inner.zero_grad(set_to_none=set_to_none)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         op: ReduceOp = Average,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         bucket_cap_bytes: Optional[int] = None
                         ) -> _DistributedOptimizer:
    """† ``hvd.DistributedOptimizer`` for torch."""
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters, op=op,
        compression=compression,
        backward_passes_per_step=backward_passes_per_step,
        bucket_cap_bytes=bucket_cap_bytes)


def __getattr__(name: str):
    if name == "elastic":
        # † ``import horovod.torch as hvd; hvd.elastic.run`` — lazy so the
        # elastic machinery isn't paid for by collective-only users.
        import importlib
        return importlib.import_module("horovod_tpu.torch.elastic")
    if name == "SyncBatchNorm":
        # † ``hvd.SyncBatchNorm`` — lazy: it imports this module back.
        from .sync_batch_norm import SyncBatchNorm
        return SyncBatchNorm
    raise AttributeError(f"module 'horovod_tpu.torch' has no attribute {name!r}")
