"""Torch elastic state († ``horovod/torch/elastic/state.py``).

``TorchState(model=..., optimizer=..., epoch=0, batch=0)``:

- ``commit()`` deep-copies module/optimizer ``state_dict``s host-side (the
  reference's host-RAM snapshot — survives device teardown),
- ``restore()`` rolls back to the last commit,
- ``sync()`` broadcasts current values from rank 0 (joining workers adopt
  the incumbent weights; † ``TorchState.sync``).

Plain picklable attributes (epoch, batch, ...) follow ``ObjectState``
semantics.  Usable with the shared ``@hvd.elastic.run`` decorator and
``ElasticSampler`` (re-exported here so ``import horovod_tpu.torch as hvd;
hvd.elastic.*`` reads like the reference).
"""

from __future__ import annotations

import copy
from typing import Any

import torch

from horovod_tpu.elastic import (  # noqa: F401  (reference-shaped surface)
    ElasticSampler,
    HorovodInternalError,
    HostsUpdatedInterrupt,
    ObjectState,
    State,
    run,
)
from . import broadcast_optimizer_state, broadcast_parameters


class TorchState(State):
    """† ``TorchState``: handlers per value type — ``nn.Module`` and
    ``Optimizer`` snapshot/sync via their ``state_dict``; everything else
    via pickle-able object semantics."""

    def __init__(self, model: torch.nn.Module | None = None,
                 optimizer: torch.optim.Optimizer | None = None,
                 **kwargs: Any) -> None:
        super().__init__()
        self._model = model
        self._optimizer = optimizer
        self._objects: dict[str, Any] = dict(kwargs)
        self._saved: dict[str, Any] = {}
        self.save()

    # -- attribute plumbing: state.epoch etc. read/write the object dict --

    def __getattr__(self, name: str) -> Any:
        if name == "model":
            return self.__dict__["_model"]
        if name == "optimizer":
            return self.__dict__["_optimizer"]
        objects = self.__dict__.get("_objects", {})
        if name in objects:
            return objects[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            super().__setattr__(name, value)
        elif name == "model":
            self._model = value
        elif name == "optimizer":
            self._optimizer = value
        else:
            self._objects[name] = value

    # -- State protocol --

    def save(self) -> None:
        snap: dict[str, Any] = {
            "objects": copy.deepcopy(self._objects)}
        if self._model is not None:
            snap["model"] = {
                k: v.detach().clone() if isinstance(v, torch.Tensor) else
                copy.deepcopy(v)
                for k, v in self._model.state_dict().items()}
        if self._optimizer is not None:
            snap["optimizer"] = copy.deepcopy(self._optimizer.state_dict())
        self._saved = snap

    def restore(self) -> None:
        self._objects = copy.deepcopy(self._saved["objects"])
        if self._model is not None and "model" in self._saved:
            self._model.load_state_dict(self._saved["model"])
        if self._optimizer is not None and "optimizer" in self._saved:
            self._optimizer.load_state_dict(
                copy.deepcopy(self._saved["optimizer"]))

    def sync(self) -> None:
        import horovod_tpu as hvd
        if self._model is not None:
            broadcast_parameters(self._model.state_dict(), root_rank=0)
        if self._optimizer is not None:
            broadcast_optimizer_state(self._optimizer, root_rank=0)
        self._objects = hvd.broadcast_object(self._objects, root_rank=0)
        self.save()
