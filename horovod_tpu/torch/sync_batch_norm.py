"""Cross-rank synchronized batch normalization for the torch binding.

† ``horovod/torch/sync_batch_norm.py``: a drop-in ``_BatchNorm`` replacement
whose batch statistics are computed over the GLOBAL batch (all ranks), for
the small-per-rank-batch regime where per-rank statistics destabilize
training.  Upstream gathers count/mean/var with allgather and reduces
gradient terms with allreduce on NCCL; here both rounds are single fused
``allreduce(Sum)`` calls on the XLA data plane (sum / sum-of-squares /
count forward, sum_dy / sum_dy_xhat backward) — statistically identical,
one collective per direction.  The summed count also makes uneven per-rank
batches exact (the reference's count allgather serves the same purpose).
"""

from __future__ import annotations

import torch
import torch.nn.functional as F
from torch.nn.modules.batchnorm import _BatchNorm

import horovod_tpu.torch as hvd

__all__ = ["SyncBatchNorm"]


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, x, weight, bias, eps):
        # Channel axis is dim 1; reduce over batch + spatial dims.
        c = x.shape[1]
        red = [0] + list(range(2, x.dim()))
        local_count = x.numel() / c

        # One fused allreduce for [sum, sumsq, count] († upstream's
        # count/mean/var allgather round, collapsed).  Summing the counts
        # keeps uneven per-rank batches exact.
        stats = torch.cat([x.sum(red), (x * x).sum(red),
                           x.new_tensor([local_count])])
        stats = hvd.allreduce(stats, op=hvd.Sum,
                              name="sync_batch_norm.fwd")
        total = stats[2 * c]
        mean = stats[:c] / total
        var = stats[c:2 * c] / total - mean * mean

        shape = [1, c] + [1] * (x.dim() - 2)
        invstd = torch.rsqrt(var + eps)
        xhat = (x - mean.view(shape)) * invstd.view(shape)
        y = xhat * weight.view(shape) + bias.view(shape)

        ctx.save_for_backward(xhat, weight, invstd)
        ctx.total = float(total)
        ctx.red = red
        ctx.mark_non_differentiable(mean, var, total)
        return y, mean, var, total

    @staticmethod
    def backward(ctx, dy, _dmean, _dvar, _dtotal):
        xhat, weight, invstd = ctx.saved_tensors
        c = xhat.shape[1]
        shape = [1, c] + [1] * (xhat.dim() - 2)

        sum_dy = dy.sum(ctx.red)
        sum_dy_xhat = (dy * xhat).sum(ctx.red)
        # † backward allreduce round: dx needs the GLOBAL reduction terms
        # (the normalization statistics were global).
        reduced = hvd.allreduce(torch.cat([sum_dy, sum_dy_xhat]),
                                op=hvd.Sum, name="sync_batch_norm.bwd")
        g_sum_dy, g_sum_dy_xhat = reduced[:c], reduced[c:]

        n = ctx.total
        dx = (weight.view(shape) * invstd.view(shape)) * (
            dy - (g_sum_dy.view(shape) + xhat * g_sum_dy_xhat.view(shape)) / n)
        # weight/bias grads stay LOCAL († upstream): DistributedOptimizer
        # averages them afterwards exactly like every other parameter.
        return dx, sum_dy_xhat, sum_dy, None


class SyncBatchNorm(_BatchNorm):
    """† ``hvd.SyncBatchNorm``: BatchNorm1d/2d/3d with global statistics.

    Running statistics follow stock ``nn.BatchNorm`` semantics, including
    ``momentum=None`` (cumulative moving average) and
    ``track_running_stats=False`` (always normalize with batch stats).
    Eval mode and single-rank jobs fall back to the stock kernel.
    """

    def _check_input_dim(self, x):
        if x.dim() < 2:
            raise ValueError(
                f"expected at least 2D input, got {x.dim()}D")

    def forward(self, x):
        self._check_input_dim(x)

        # Stock _BatchNorm bookkeeping: exponential factor, with
        # momentum=None meaning cumulative average 1/num_batches_tracked.
        eaf = 0.0 if self.momentum is None else self.momentum
        if self.training and self.track_running_stats \
                and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            if self.momentum is None:
                eaf = 1.0 / float(self.num_batches_tracked)

        if not self.training or hvd.size() == 1:
            # Stock semantics verbatim: in eval without running stats,
            # normalize with batch statistics (bn_training).
            bn_training = self.training or (self.running_mean is None
                                            and self.running_var is None)
            track = not self.training or self.track_running_stats
            return F.batch_norm(
                x,
                self.running_mean if track else None,
                self.running_var if track else None,
                self.weight, self.bias, bn_training, eaf, self.eps)

        weight = self.weight if self.affine else x.new_ones(x.shape[1])
        bias = self.bias if self.affine else x.new_zeros(x.shape[1])
        y, mean, var, total = _SyncBatchNormFn.apply(x, weight, bias,
                                                     self.eps)

        if self.track_running_stats and self.running_mean is not None:
            with torch.no_grad():
                n = float(total)  # true global count (uneven-batch exact)
                unbiased = var * n / max(n - 1.0, 1.0)
                self.running_mean.mul_(1 - eaf).add_(mean, alpha=eaf)
                self.running_var.mul_(1 - eaf).add_(unbiased, alpha=eaf)
        return y
