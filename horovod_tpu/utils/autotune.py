"""Online autotuning of fusion-threshold, cycle-time, wire precision and
collective schedule.

† ``horovod/common/parameter_manager.cc`` + ``optim/bayesian_optimization.cc``:
the reference tunes (fusion threshold, cycle time) online with Bayesian
optimization (Gaussian process + expected improvement) against observed
throughput, after a warmup, writing decisions to ``HOROVOD_AUTOTUNE_LOG``.

This implementation keeps the same control loop (warmup → propose → score →
commit best) with a Gaussian-process surrogate implemented in numpy (RBF
kernel + expected improvement over a candidate grid).  Eigen/LBFGS hyperparam
refits are replaced by a small fixed-length-scale kernel — adequate for a
low-noise search space.

Knob space, v5: 6-D.  Beyond the reference's (threshold, cycle-time),
the third dimension is the engine's **wire precision**
(``ops/reduction.py``): fp32, bf16, or block-scaled int8; the fourth is
the **collective schedule** (``ops/sched``, arm set derived from
``lower.SCHED_MODES``): monolithic, the dispatched decomposed
reduce-scatter/allgather pipeline at a candidate chunk count, or its
compiled single-program twin (``compiled:rs_ag:<k>``);
the fifth is the **hierarchy split** (``ops/hierarchical`` + the sched
executor's ``hier:<n_local>:<k>`` path): flat, the topology-detected
two-tier split, or the detected split halved — HiCCL's level-split
selection as a search dimension, seeded by the perfmodel's analytic
per-message-size decision table (logged at init); the sixth is the
**bucket cap** (``config.bucket_bytes``): the size target the backward
bucketer and the engine's fusion grouping both honor — 0 (uncapped,
fusion threshold alone governs) or a candidate cap that trades fewer,
larger collectives against earlier dispatch of the first gradients.
The score is *effective* bytes/s — logical fp32 payload bytes per cycle
second — so a mode that moves fewer wire bytes (or overlaps more of its
communication) in less time scores higher, and the GP picks what the
interconnect actually rewards (on TPU, quantized + decomposed + tiered;
on the CPU rig, whose collectives are byte-width-insensitive and
serialized, it correctly learns fp32 + monolithic + flat).

Multi-process jobs pin the precision, schedule AND hierarchy dimensions
to the configured defaults: each rank scores from rank-local timings,
and a per-rank commit of any of them would resolve the same tensor to
different wire modes / chunk programs / tier meshes on different ranks
at enqueue — divergent fused XLA dispatches across processes, i.e. a
hang.  The bucket cap stays searchable even then, for the same reason
the threshold does: it only shapes the local cycle thread's fusion
grouping, and group composition still agrees via negotiation order.
Single-controller mode (one process, all devices) tunes all six
dimensions.

Tensor-size bucketing: the precision knob governs the *quantizable
bucket* — tensors at or above ``quant_min_bytes``.  Tensors below the
floor always ride fp32 (``reduction.resolve_precision``): the per-block
scale traffic and encode pass are not worth it there, so the bucket
boundary is a config knob rather than a fourth GP dimension.  The
committed precision lands in ``config.wire_precision``, which entries
resolve against at enqueue time.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from ..obs import REGISTRY as _obs

# Candidate grid (log2 bytes for threshold, ms for cycle time), spanning the
# same range the reference explores, crossed with the wire modes worth
# searching (fp8's e4m3 error is opt-in only, never auto-committed).
_THRESHOLDS = [1 << p for p in range(20, 28)]         # 1 MB .. 128 MB
_CYCLE_TIMES = [0.5, 1.0, 2.5, 5.0, 10.0, 20.0]        # ms
_WIRE_MODES = ["fp32", "bf16", "int8"]
# Schedule dimension (ops/sched): one arm set, DERIVED from
# lower.SCHED_MODES so adding a sched mode grows the grid automatically
# (tests/test_autotune.py asserts the sync) — monolithic, the dispatched
# decomposition and its compiled twin at the chunk counts worth
# searching (higher counts add dispatch overhead faster than they add
# overlap window; 2 and 4 bracket the useful range).
_SCHED_CHUNK_COUNTS = (2, 4)
# Bucket-cap dimension (config.bucket_bytes): 0 means uncapped — the
# fusion threshold alone governs grouping — plus the caps worth
# searching (a small cap dispatches the first backward buckets sooner;
# a large one amortizes per-collective overhead).
_BUCKET_BYTES = [0, 4 << 20, 32 << 20]


def _sched_arms() -> list:
    from ..ops.sched import autotune_sched_arms
    return autotune_sched_arms(_SCHED_CHUNK_COUNTS)
# GP-space spacing between adjacent modes; comparable to one grid step in
# the log2-threshold dimension so no dimension dominates the RBF distance.
_MODE_SCALE = 2.0
# Cycles discarded right after a knob commit before scoring resumes.  The
# first cycles under a new config pay XLA compiles for the new fused (and,
# on the compiled-schedule arms, whole-program) signatures; scoring that
# stall grades the warm incumbent against cold challengers, so the initial
# config would win every search on compile overhead alone.
_SETTLE_CYCLES = 2

_m_trials = _obs.counter(
    "hvd_autotune_trials_total", "knob configurations scored by the tuner")
_m_score = _obs.gauge(
    "hvd_autotune_score_bytes_per_s",
    "latest trial's effective (logical bytes) throughput score")
_m_threshold = _obs.gauge(
    "hvd_autotune_fusion_threshold_bytes", "fusion threshold in effect")
_m_cycle_ms = _obs.gauge(
    "hvd_autotune_cycle_time_ms", "engine cycle time in effect")


class _GP:
    """Minimal RBF-kernel GP regressor for the 6-D knob space."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-3) -> None:
        self.ls = length_scale
        self.noise = noise
        self.X: Optional[np.ndarray] = None
        self.y: Optional[np.ndarray] = None
        self._K_inv: Optional[np.ndarray] = None

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d / self.ls ** 2)

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self.X, self.y = X, y
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._K_inv = np.linalg.inv(K)

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        assert self.X is not None and self._K_inv is not None
        Ks = self._k(Xs, self.X)
        mu = Ks @ self._K_inv @ self.y
        var = 1.0 - np.einsum("ij,jk,ik->i", Ks, self._K_inv, Ks)
        return mu, np.maximum(var, 1e-12)


def _expected_improvement(mu: np.ndarray, var: np.ndarray, best: float
                          ) -> np.ndarray:
    sigma = np.sqrt(var)
    z = (mu - best) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2.0 * math.pi)
    return (mu - best) * cdf + sigma * pdf


class Autotuner:
    """Propose/score loop attached to the engine's cycle callback."""

    def _norm_point(self, threshold: int, cycle_ms: float, mode: str,
                    sched: str, hier: str, bucket: int
                    ) -> tuple[float, float, float, float, float, float]:
        """Raw knobs -> GP coordinates (mode/sched/hier/bucket indices
        are instance-local)."""
        return (math.log2(threshold), math.log2(cycle_ms),
                self._modes.index(mode) * _MODE_SCALE,
                self._scheds.index(sched) * _MODE_SCALE,
                self._hiers.index(hier) * _MODE_SCALE,
                self._buckets.index(bucket) * _MODE_SCALE)

    def __init__(self, state) -> None:
        self._state = state
        cfg = state.config
        self._warmup_left = cfg.autotune_warmup_samples
        self._steps_per_sample = cfg.autotune_steps_per_sample
        self._log_path = cfg.autotune_log
        # Mode dimension, per instance:
        # - An explicitly configured off-grid mode (fp16/fp8) joins the
        #   search instead of being silently reverted — the user opted
        #   into its error model, so the tuner may keep proposing it.
        # - Multi-process engines PIN the mode to the configured default:
        #   each rank tunes from rank-local scores, and a per-rank
        #   wire_precision commit would make the same tensor resolve to
        #   different modes on different ranks at enqueue — divergent
        #   fused programs across processes, i.e. a hang.  (threshold/
        #   cycle knobs only pace the local cycle thread; group
        #   composition still agrees via negotiation order, and bucket
        #   construction latches its own cap — see torch optimizer.)
        engine = getattr(state, "engine", None)
        distributed = bool(engine is not None and engine.distributed)
        default = cfg.wire_precision or "fp32"
        # Schedule dimension, pinned in multi-process jobs for the same
        # reason as the wire mode (module docstring): a per-rank
        # sched_mode/sched_chunks commit diverges the enqueue-time
        # schedule resolution across ranks.  (The engine's meta
        # reconciliation would converge the fleet anyway, but onto ONE
        # rank's proposal — the other ranks' scores would then grade a
        # config they never ran.)
        cfg_mode = getattr(cfg, "sched_mode", "monolithic")
        if cfg_mode == "decomposed":
            sched_default = f"rs_ag:{max(1, cfg.sched_chunks)}"
        elif cfg_mode == "compiled":
            sched_default = f"compiled:rs_ag:{max(1, cfg.sched_chunks)}"
        else:
            sched_default = "monolithic"
        # Hierarchy dimension (HiCCL level split): "flat" plus the
        # topology-detected two-tier split and the detected split halved
        # ("tier:<n_local>"), when they actually tier this world size.
        n = getattr(state, "size", 1)
        detected = None
        try:
            from ..ops.collectives import _detect_local_size
            nl = _detect_local_size(state)
            if nl and 1 < nl < n and n % nl == 0:
                detected = int(nl)
        except Exception:
            detected = None
        hier_vals = ["flat"]
        if detected:
            hier_vals.append(f"tier:{detected}")
            half = detected // 2
            if 2 <= half < n and n % half == 0:
                hier_vals.append(f"tier:{half}")
        hier_default = "flat"
        if getattr(cfg, "hierarchical_allreduce", False):
            nl0 = cfg.hierarchical_local_size or detected
            if nl0 and 1 < nl0 < n and n % nl0 == 0:
                hier_default = f"tier:{int(nl0)}"
        # Bucket-cap dimension: like the threshold, it only shapes the
        # local cycle thread's fusion grouping, so it stays searchable
        # even in distributed mode (module docstring).  An off-grid
        # configured cap joins the candidates instead of being reverted.
        bucket_default = int(getattr(cfg, "bucket_bytes", 0) or 0)
        self._buckets = list(_BUCKET_BYTES) + (
            [bucket_default] if bucket_default not in _BUCKET_BYTES else [])
        if distributed:
            self._modes = [default]
            self._scheds = [sched_default]
            self._hiers = [hier_default]
        else:
            sched_arms = _sched_arms()
            self._modes = _WIRE_MODES + (
                [default] if default not in _WIRE_MODES else [])
            self._scheds = sched_arms + (
                [sched_default] if sched_default not in sched_arms
                else [])
            self._hiers = hier_vals + (
                [hier_default] if hier_default not in hier_vals else [])
        self._grid_raw = [(t, c, m, s, h, b) for t in _THRESHOLDS
                          for c in _CYCLE_TIMES for m in self._modes
                          for s in self._scheds for h in self._hiers
                          for b in self._buckets]
        self._grid = np.array([self._norm_point(*p) for p in self._grid_raw])
        # Seed the hierarchy dimension with the perfmodel's analytic
        # per-message-size split table (logged, and kept on the instance
        # for the obs plane): which sizes should tier, before a single
        # trial runs.
        self.split_table: list = []
        if detected and len(self._hiers) > 1:
            try:
                from ..obs.perfmodel import hier_split_table
                gbs_cross = cfg.perf_link_gbs or 1.0
                self.split_table = hier_split_table(
                    _THRESHOLDS, n, detected,
                    gbs_local=gbs_cross * 10.0,  # nominal ICI ~10x DCN
                    gbs_cross=gbs_cross,
                    latency_us=cfg.perf_link_latency_us)
                self._log("hier split table (n_local=%d): %s" % (
                    detected, ", ".join(
                        f"{r['payload_bytes']}B->{r['split']}"
                        for r in self.split_table)))
            except Exception:
                self.split_table = []
        # Normalized GP inputs AND the exact raw grid knobs of each
        # sample.  Committing from the raw record (not a ``2 ** log2``
        # round-trip of the normalized floats) keeps the committed
        # cycle-time exactly on the candidate grid — the round-trip
        # drifted (e.g. 2.5 ms -> 2.4999999999999996) so the converged
        # knobs were values no candidate ever proposed.
        self._samples_X: list[
            tuple[float, float, float, float, float, float]] = []
        self._samples_raw: list[tuple[int, float, str, str, str, int]] = []
        self._samples_y: list[float] = []
        self._current = (cfg.fusion_threshold, cfg.cycle_time_ms, default,
                         sched_default, hier_default, bucket_default)
        self._acc_bytes = 0
        self._acc_time = 0.0
        self._acc_cycles = 0
        self._settle_left = 0
        self._done = False

    def record_cycle(self, payload_bytes: int, cycle_seconds: float) -> None:
        """Score one engine cycle.  ``payload_bytes`` is the LOGICAL
        payload (entry bytes, not wire bytes) so the score is effective
        throughput and precision modes compete on delivered gradients."""
        if self._done or payload_bytes == 0:
            return
        if self._settle_left > 0:
            self._settle_left -= 1
            return
        self._acc_bytes += payload_bytes
        self._acc_time += cycle_seconds
        self._acc_cycles += 1
        if self._acc_cycles < self._steps_per_sample:
            return
        score = self._acc_bytes / max(self._acc_time, 1e-9)  # bytes/s
        self._acc_bytes, self._acc_time, self._acc_cycles = 0, 0.0, 0
        if self._warmup_left > 0:
            self._warmup_left -= 1
            self._log(f"warmup score={score:.3e}")
            return
        t, c, m, s, h, b = self._current
        self._samples_X.append(self._norm_point(t, c, m, s, h, b))
        self._samples_raw.append((t, c, m, s, h, b))
        self._samples_y.append(score)
        _m_trials.inc()
        _m_score.set(score)
        self._propose_next()

    def _propose_next(self) -> None:
        X = np.asarray(self._samples_X)
        y = np.asarray(self._samples_y)
        y_norm = (y - y.mean()) / (y.std() + 1e-9)
        gp = _GP(length_scale=2.0)
        gp.fit(X, y_norm)
        mu, var = gp.predict(self._grid)
        ei = _expected_improvement(mu, var, y_norm.max())
        idx = int(np.argmax(ei))
        threshold, cycle, mode, sched, hier, bucket = self._grid_raw[idx]
        self._apply(threshold, cycle, mode, sched, hier, bucket)
        best = int(np.argmax(y))
        self._log(
            f"sample #{len(y)} score={y[-1]:.3e} -> next "
            f"threshold={threshold} cycle_ms={cycle} wire={mode} "
            f"sched={sched} hier={hier} bucket={bucket} "
            f"(best so far {self._raw(best)} @ {y[best]:.3e})")
        # Convergence: stop after exploring enough with no improvement,
        # committing the best-seen knobs († ParameterManager stops tuning).
        if len(y) >= 12 and best < len(y) - 6:
            bt, bc, bm, bs, bh, bb = self._raw(best)
            self._apply(bt, bc, bm, bs, bh, bb)
            self._done = True
            self._log(f"converged: threshold={bt} cycle_ms={bc} "
                      f"wire={bm} sched={bs} hier={bh} bucket={bb}")

    def _raw(self, i: int) -> tuple[int, float, str, str, str, int]:
        """Exact grid knobs of sample *i* — from the raw record, never a
        ``2 ** log2(x)`` round-trip of the normalized GP coordinates."""
        return self._samples_raw[i]

    def _apply(self, threshold: int, cycle_ms: float, mode: str,
               sched: str, hier: str, bucket: int = 0) -> None:
        from ..ops.sched import parse_compiled_descriptor, parse_descriptor
        self._current = (threshold, cycle_ms, mode, sched, hier, bucket)
        self._settle_left = _SETTLE_CYCLES
        self._state.config.fusion_threshold = threshold
        self._state.config.cycle_time_ms = cycle_ms
        self._state.config.wire_precision = mode
        self._state.config.bucket_bytes = bucket
        ck = parse_compiled_descriptor(sched)
        if sched == "monolithic":
            self._state.config.sched_mode = "monolithic"
        elif ck is not None:
            # Compiled-vs-dispatched is an ARM of the search, not a
            # preprocessing choice: the GP scores the single-program
            # backend against the executor walk per signature.
            self._state.config.sched_mode = "compiled"
            self._state.config.sched_chunks = ck
        else:
            self._state.config.sched_mode = "decomposed"
            self._state.config.sched_chunks = parse_descriptor(sched)
        if hier == "flat":
            self._state.config.hierarchical_allreduce = False
        else:
            self._state.config.hierarchical_allreduce = True
            self._state.config.hierarchical_local_size = int(
                hier.split(":", 1)[1])
        _m_threshold.set(threshold)
        _m_cycle_ms.set(cycle_ms)
        from ..ops import reduction as _R
        _R.publish_mode_gauge(mode)

    def _log(self, msg: str) -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as fh:
            fh.write(f"{time.time():.3f} {msg}\n")
