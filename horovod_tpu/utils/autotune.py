"""Online autotuning of fusion-threshold and cycle-time.

† ``horovod/common/parameter_manager.cc`` + ``optim/bayesian_optimization.cc``:
the reference tunes (fusion threshold, cycle time) online with Bayesian
optimization (Gaussian process + expected improvement) against observed
throughput, after a warmup, writing decisions to ``HOROVOD_AUTOTUNE_LOG``.

This implementation keeps the same control loop (warmup → propose → score →
commit best) with a Gaussian-process surrogate implemented in numpy (RBF
kernel + expected improvement over a candidate grid).  Eigen/LBFGS hyperparam
refits are replaced by a small fixed-length-scale kernel — adequate for a
2-D, low-noise search space.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from ..obs import REGISTRY as _obs

# Candidate grid (log2 bytes for threshold, ms for cycle time), spanning the
# same range the reference explores.
_THRESHOLDS = [1 << p for p in range(20, 28)]         # 1 MB .. 128 MB
_CYCLE_TIMES = [0.5, 1.0, 2.5, 5.0, 10.0, 20.0]        # ms

_m_trials = _obs.counter(
    "hvd_autotune_trials_total", "knob configurations scored by the tuner")
_m_score = _obs.gauge(
    "hvd_autotune_score_bytes_per_s", "latest trial's throughput score")
_m_threshold = _obs.gauge(
    "hvd_autotune_fusion_threshold_bytes", "fusion threshold in effect")
_m_cycle_ms = _obs.gauge(
    "hvd_autotune_cycle_time_ms", "engine cycle time in effect")


class _GP:
    """Minimal RBF-kernel GP regressor for the 2-D knob space."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-3) -> None:
        self.ls = length_scale
        self.noise = noise
        self.X: Optional[np.ndarray] = None
        self.y: Optional[np.ndarray] = None
        self._K_inv: Optional[np.ndarray] = None

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d / self.ls ** 2)

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self.X, self.y = X, y
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._K_inv = np.linalg.inv(K)

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        assert self.X is not None and self._K_inv is not None
        Ks = self._k(Xs, self.X)
        mu = Ks @ self._K_inv @ self.y
        var = 1.0 - np.einsum("ij,jk,ik->i", Ks, self._K_inv, Ks)
        return mu, np.maximum(var, 1e-12)


def _expected_improvement(mu: np.ndarray, var: np.ndarray, best: float
                          ) -> np.ndarray:
    sigma = np.sqrt(var)
    z = (mu - best) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2.0 * math.pi)
    return (mu - best) * cdf + sigma * pdf


class Autotuner:
    """Propose/score loop attached to the engine's cycle callback."""

    def __init__(self, state) -> None:
        self._state = state
        cfg = state.config
        self._warmup_left = cfg.autotune_warmup_samples
        self._steps_per_sample = cfg.autotune_steps_per_sample
        self._log_path = cfg.autotune_log
        # Normalized candidate grid.
        self._grid = np.array([
            (math.log2(t), math.log2(c))
            for t in _THRESHOLDS for c in _CYCLE_TIMES])
        self._grid_raw = [(t, c) for t in _THRESHOLDS for c in _CYCLE_TIMES]
        self._samples_X: list[tuple[float, float]] = []
        self._samples_y: list[float] = []
        self._current = (cfg.fusion_threshold, cfg.cycle_time_ms)
        self._acc_bytes = 0
        self._acc_time = 0.0
        self._acc_cycles = 0
        self._done = False

    def record_cycle(self, payload_bytes: int, cycle_seconds: float) -> None:
        if self._done or payload_bytes == 0:
            return
        self._acc_bytes += payload_bytes
        self._acc_time += cycle_seconds
        self._acc_cycles += 1
        if self._acc_cycles < self._steps_per_sample:
            return
        score = self._acc_bytes / max(self._acc_time, 1e-9)  # bytes/s
        self._acc_bytes, self._acc_time, self._acc_cycles = 0, 0.0, 0
        if self._warmup_left > 0:
            self._warmup_left -= 1
            self._log(f"warmup score={score:.3e}")
            return
        t, c = self._current
        self._samples_X.append((math.log2(t), math.log2(c)))
        self._samples_y.append(score)
        _m_trials.inc()
        _m_score.set(score)
        self._propose_next()

    def _propose_next(self) -> None:
        X = np.asarray(self._samples_X)
        y = np.asarray(self._samples_y)
        y_norm = (y - y.mean()) / (y.std() + 1e-9)
        gp = _GP(length_scale=2.0)
        gp.fit(X, y_norm)
        mu, var = gp.predict(self._grid)
        ei = _expected_improvement(mu, var, y_norm.max())
        idx = int(np.argmax(ei))
        threshold, cycle = self._grid_raw[idx]
        self._apply(threshold, cycle)
        best = int(np.argmax(y))
        self._log(
            f"sample #{len(y)} score={y[-1]:.3e} -> next "
            f"threshold={threshold} cycle_ms={cycle} "
            f"(best so far {self._raw(best)} @ {y[best]:.3e})")
        # Convergence: stop after exploring enough with no improvement,
        # committing the best-seen knobs († ParameterManager stops tuning).
        if len(y) >= 12 and best < len(y) - 6:
            bt, bc = self._raw(best)
            self._apply(bt, bc)
            self._done = True
            self._log(f"converged: threshold={bt} cycle_ms={bc}")

    def _raw(self, i: int) -> tuple[int, float]:
        t, c = self._samples_X[i]
        return int(round(2 ** t)), float(2 ** c)

    def _apply(self, threshold: int, cycle_ms: float) -> None:
        self._current = (threshold, cycle_ms)
        self._state.config.fusion_threshold = threshold
        self._state.config.cycle_time_ms = cycle_ms
        _m_threshold.set(threshold)
        _m_cycle_ms.set(cycle_ms)

    def _log(self, msg: str) -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as fh:
            fh.write(f"{time.time():.3f} {msg}\n")
