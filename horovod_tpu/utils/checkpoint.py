"""Sharded checkpoint/resume.

The reference has no checkpoint subsystem (SURVEY §5.4 — examples guard
``ModelCheckpoint`` with ``hvd.rank() == 0`` and elastic keeps in-memory
snapshots only).  On TPU, sharded checkpointing is the idiomatic answer
(and the elastic restart model depends on it), so it is first-class here,
built on orbax: every host writes its own shards in parallel, restore
re-shards onto whatever mesh the new job has.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class Checkpointer:
    """Thin orbax wrapper with rank-0-only-metadata semantics.

    Usage::

        ckpt = Checkpointer("/path/ckpts")
        ckpt.save(step, {"params": params, "opt_state": opt_state})
        restored = ckpt.restore(target={"params": params_like, ...})
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 single_process: bool = False,
                 read_only: bool = False) -> None:
        """``single_process=True`` scopes orbax's cross-process barriers to
        THIS process.  Required when saving from one rank of a
        ``jax.distributed``-initialized multi-process job (the hvdrun
        rig): rank-0-only saves otherwise deadlock in the multihost sync
        that expects every process to participate.  Non-saving ranks of
        such a job should ALSO pass ``read_only=True`` — a writable
        manager's constructor sweeps ``*-tmp`` directories, racing the
        primary's in-flight save."""
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        if single_process:
            import jax as _jax
            me = _jax.process_index()
            mp_options = ocp.options.MultiprocessingOptions(
                primary_host=me, active_processes={me},
                barrier_sync_key_prefix=f"proc{me}")
        else:
            mp_options = ocp.options.MultiprocessingOptions()
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                read_only=read_only,
                # Synchronous saves in single-process mode: the async
                # path's tmp->final rename lands after wait_until_finished
                # under scoped active_processes, so a peer reading "the
                # latest step" right after a cross-process barrier could
                # still see the unfinalized tmp directory.
                enable_async_checkpointing=not single_process,
                # A reader must never sweep the writer's tmp directories.
                cleanup_tmp_directories=not read_only,
                # The directory is created above; orbax refuses
                # create=True alongside active_processes.
                create=not single_process and not read_only,
                multiprocessing_options=mp_options))

    def save(self, step: int, tree: Any, *, wait: bool = True) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                target: Optional[Any] = None) -> Any:
        """Restore ``step`` (default latest).  ``target`` provides structure
        and shardings — pass abstract arrays (jax.eval_shape +
        NamedSharding) to re-shard onto a new mesh."""
        import orbax.checkpoint as ocp
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        if target is not None:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
        # Targetless restore still goes through StandardRestore: a FRESH
        # manager (different instance than the saver's) has no handler
        # registered for the saved item, and older orbax (0.7.x) refuses
        # to infer one from the checkpoint alone.
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()
