"""Force the JAX host-CPU platform for multi-device test/dev rigs.

One canonical copy of the recipe every CPU-rig entry point needs (the dev
image pins an ``axon`` TPU platform via sitecustomize whose initialization
can hang when the tunnel is down, and it ignores the ``JAX_PLATFORMS`` env
var — only ``jax.config`` set before any backend touch wins).

Import this module (or the package) freely before calling: importing jax
does not initialize a backend; only device queries/computation do.
"""

from __future__ import annotations

import os


def force_cpu_platform(n_devices: int = 1) -> None:
    """Pin jax to ``n_devices`` virtual host-CPU devices.

    Must run before anything touches a JAX backend (``jax.devices()``,
    any computation); afterwards ``jax.config.update`` is a silent no-op.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Partitionable threefry (defaults False on 0.4.x): jitted init with
    # sharded out_shardings must draw the same values as replicated init,
    # or every mesh-vs-dp oracle test drifts ~0.5%.
    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # pragma: no cover - removed on future jax
        pass
