"""Leveled logging († ``horovod/common/logging.cc``: ``LOG(INFO)`` macros,
``HOROVOD_LOG_LEVEL``, ``HOROVOD_LOG_HIDE_TIME``).

Python's stdlib logging already provides the mechanism; this module maps the
reference's level names (including ``trace`` and ``fatal``) onto it and
applies the env-driven configuration **at import**: setting
``HVDTPU_LOG_LEVEL`` / ``HOROVOD_TPU_LOG_LEVEL`` / ``HOROVOD_LOG_LEVEL``
(first set wins) and ``..._LOG_HIDE_TIME`` configures the logger before any
code runs — matching the reference, where the env vars take effect at
process start, not at ``hvd.init()``.  ``hvd.init()`` re-applies them
through :mod:`horovod_tpu.config` (same values, so it is a no-op unless a
``Config`` overrides programmatically).
"""

from __future__ import annotations

import logging
import os

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_LOGGER_NAME = "horovod_tpu"

# Same precedence order as horovod_tpu.config._PREFIXES (native name wins
# over the reference-compat one); duplicated here because config imports
# are not allowed at logging-import time (logging is the bottom of the
# dependency stack).
_ENV_PREFIXES = ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_")


def _env(suffix: str):
    for prefix in _ENV_PREFIXES:
        v = os.environ.get(prefix + suffix)
        if v is not None:
            return v
    return None


def get_logger() -> logging.Logger:
    return logging.getLogger(_LOGGER_NAME)


def configure(level: str, *, hide_timestamp: bool = False) -> None:
    logger = get_logger()
    logger.setLevel(_LEVELS.get(level.lower(), logging.WARNING))
    if not logger.handlers:
        handler = logging.StreamHandler()
        logger.addHandler(handler)
        logger.propagate = False
    fmt = "[%(levelname)s] %(name)s: %(message)s" if hide_timestamp else \
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
    for handler in logger.handlers:
        handler.setFormatter(logging.Formatter(fmt))


def _configure_from_env() -> None:
    """Apply the env knobs at import (the docstring's promise)."""
    level = _env("LOG_LEVEL")
    hide = _env("LOG_HIDE_TIME")
    if level is None and hide is None:
        return
    configure(level or "warning",
              hide_timestamp=(hide or "").strip().lower()
              in ("1", "true", "yes", "on"))


_configure_from_env()
