"""Leveled logging († ``horovod/common/logging.cc``: ``LOG(INFO)`` macros,
``HOROVOD_LOG_LEVEL``, ``HOROVOD_LOG_HIDE_TIME``).

Python's stdlib logging already provides the mechanism; this module maps the
reference's level names (including ``trace`` and ``fatal``) onto it and
applies the env-driven configuration.
"""

from __future__ import annotations

import logging

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_LOGGER_NAME = "horovod_tpu"


def get_logger() -> logging.Logger:
    return logging.getLogger(_LOGGER_NAME)


def configure(level: str, *, hide_timestamp: bool = False) -> None:
    logger = get_logger()
    logger.setLevel(_LEVELS.get(level.lower(), logging.WARNING))
    if not logger.handlers:
        handler = logging.StreamHandler()
        logger.addHandler(handler)
        logger.propagate = False
    fmt = "[%(levelname)s] %(name)s: %(message)s" if hide_timestamp else \
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
    for handler in logger.handlers:
        handler.setFormatter(logging.Formatter(fmt))
