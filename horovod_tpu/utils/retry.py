"""Unified retry/backoff policy for every transient-failure path.

Before this module each subsystem invented its own loop: ``kv_get_blob``
restarted its full timeout per chunk, the elastic driver slept a fixed
poll interval through discovery-script crashes, the obs publisher gave
up for a whole interval on the first ``ConnectionError``, and the
metrics server abandoned its port on one ``EADDRINUSE``.  Retrying is a
*policy* decision — how long, how fast, which errors — and policies
multiply badly when each call site hand-rolls one.  This module is the
single place the runtime answers those questions:

- :class:`RetryPolicy` — declarative knobs: an overall **deadline**
  (the caller's budget, shared across every attempt — not per attempt),
  an optional attempt cap, capped exponential backoff, and
  **deterministic jitter** (seeded per ``(op, attempt)``, so two runs
  of the same job schedule identical sleeps — the property the chaos
  harness's reproducibility assertion rides on);
- :func:`retry_call` — run a callable under a policy (call-shaped
  sites: a KV chunk read, a socket bind);
- :class:`Backoff` — the iterator form for hand-written loops that
  interleave retrying with other work (the elastic slot wait, the
  publisher thread);
- :func:`retryable_error` — the shared transient-vs-permanent
  classifier (connection/timeout trouble retries; ``ValueError`` and
  friends never do — retrying a programming error just hides it).

Every retry and give-up increments an obs counter labeled by ``op``,
so a scrape answers "what is flaky right now" before anyone reads logs:
``hvd_retries_total{op}``, ``hvd_retry_giveups_total{op}``,
``hvd_retry_sleep_seconds_total{op}``.

Stdlib-only; safe to import from anywhere (including the launcher,
which never calls ``hvd.init()``).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

from ..obs import REGISTRY as _obs

_m_retries = _obs.counter(
    "hvd_retries_total",
    "retried attempts after a transient failure, by operation", ("op",))
_m_giveups = _obs.counter(
    "hvd_retry_giveups_total",
    "operations that exhausted their retry budget (deadline or attempt "
    "cap) and surfaced the last error", ("op",))
_m_sleep = _obs.counter(
    "hvd_retry_sleep_seconds_total",
    "seconds spent in retry backoff sleeps, by operation", ("op",))

#: default transient classification: connection trouble, timeouts, and
#: OS-level I/O errors retry; everything else (ValueError, KeyError,
#: programming errors) surfaces immediately.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)


class Permanent(Exception):
    """Mix-in that vetoes retrying regardless of the other base classes
    — e.g. an overall-deadline-expired ``TimeoutError`` must surface,
    not burn more of a budget that is already gone."""


def retryable_error(err: BaseException,
                    retryable: Tuple[Type[BaseException], ...]
                    = DEFAULT_RETRYABLE) -> bool:
    """The shared transient-vs-permanent verdict."""
    if isinstance(err, Permanent):
        return False
    return isinstance(err, retryable)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How to retry: budget, schedule, classification.

    ``deadline_s`` is an OVERALL budget measured from the first attempt
    — every retry and every backoff sleep draws from the same clock, so
    a flaky dependency can never stretch the caller's wait to
    ``attempts x deadline`` (the bug this module replaced in
    ``kv_get_blob``).  ``max_attempts=None`` means attempts are bounded
    by the deadline alone; with both ``None`` the first failure
    surfaces (no retry).
    """

    max_attempts: Optional[int] = 3
    deadline_s: Optional[float] = None
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    #: +/- fraction of the delay, drawn from a DETERMINISTIC stream
    #: seeded by (seed, op, attempt) — reproducible schedules.
    jitter: float = 0.1
    seed: int = 0
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE

    def delay_for(self, op: str, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based), jittered
        deterministically."""
        d = self.base_delay_s * (self.multiplier ** (attempt - 1))
        d = min(d, self.max_delay_s)
        if self.jitter:
            rng = random.Random(f"{self.seed}:{op}:{attempt}")
            d *= 1.0 + rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)


#: conservative default for control-plane (KV store) round trips.
KV_POLICY = RetryPolicy(max_attempts=None, base_delay_s=0.02,
                        max_delay_s=0.5)


class Backoff:
    """Stateful backoff schedule for hand-written retry loops.

    ``next_delay()`` advances the exponential schedule — and counts the
    retry/sleep in the same obs series :func:`retry_call` maintains, so
    loop-shaped retriers (elastic discovery) are just as visible on a
    scrape as call-shaped ones.  ``reset()`` snaps back to the base
    delay after a success (a probing loop whose dependency recovered
    should probe fast again).
    """

    def __init__(self, policy: RetryPolicy, op: str) -> None:
        self.policy = policy
        self.op = op
        self._attempt = 0

    def next_delay(self) -> float:
        self._attempt += 1
        delay = self.policy.delay_for(self.op, self._attempt)
        _m_retries.labels(op=self.op).inc()
        _m_sleep.labels(op=self.op).inc(delay)
        return delay

    def reset(self) -> None:
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt


def retry_call(fn: Callable[[], Any], *, op: str,
               policy: RetryPolicy = RetryPolicy(),
               clock: Callable[[], float] = time.monotonic,
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable[[BaseException, int], None]]
               = None) -> Any:
    """Call ``fn()`` under ``policy``; return its value.

    Non-retryable errors surface immediately.  Retryable errors are
    retried on the backoff schedule until the attempt cap or the
    overall deadline runs out, then the LAST error is re-raised — the
    caller's except clauses keep matching the real failure type on
    every exhaustion path.  ``on_retry(err, attempt)`` observes each
    scheduled retry — loggers and tests hook it.
    """
    deadline = (clock() + policy.deadline_s
                if policy.deadline_s is not None else None)
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as err:  # noqa: BLE001 - classified below
            if not retryable_error(err, policy.retryable):
                raise
            attempt += 1
            if policy.max_attempts is not None \
                    and attempt >= policy.max_attempts:
                _m_giveups.labels(op=op).inc()
                raise
            delay = policy.delay_for(op, attempt)
            if deadline is not None:
                remaining = deadline - clock()
                if remaining <= 0:
                    _m_giveups.labels(op=op).inc()
                    raise
                delay = min(delay, remaining)
            _m_retries.labels(op=op).inc()
            _m_sleep.labels(op=op).inc(delay)
            if on_retry is not None:
                on_retry(err, attempt)
            if delay > 0:
                sleep(delay)
