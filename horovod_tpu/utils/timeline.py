"""Per-tensor collective lifecycle tracing to Chrome-trace JSON.

Reference († ``horovod/common/timeline.cc``): every tensor's journey
(NEGOTIATE → QUEUE → MEMCPY_IN_FUSION_BUFFER → <BACKEND>_ALLREDUCE →
MEMCPY_OUT_FUSION_BUFFER) is written as ``chrome://tracing`` events when
``HOROVOD_TIMELINE=/path.json`` is set; ``HOROVOD_TIMELINE_MARK_CYCLES`` adds
an instant event per background-loop cycle.

TPU-native differences: there is no explicit fusion-buffer memcpy (XLA fuses
the flatten/concat into the collective program) and no negotiation phase in
single-controller mode, so the phases here are QUEUE → FUSE → DISPATCH →
EXECUTE (device time, asynchronous) → CALLBACK.  For on-device timing use
``jax.profiler`` traces, where XLA names each collective op; this timeline is
the host-side engine view, same as the reference's.

Timeline v2 (beyond the reference):

- **Counter events** (``"ph": "C"``) — the engine samples the metrics
  registry (:mod:`horovod_tpu.obs`) once per cycle into counter tracks,
  so one Perfetto load shows queue depth / cumulative collective bytes as
  graphs directly under the per-tensor spans.
- **Flow events** (``"ph": "s"``/``"f"``) — an arrow from a tensor's
  QUEUE span to its DISPATCH span, so a span picked in the execute phase
  links back to the enqueue that caused it even when other tensors'
  rows interleave.
- **Crash durability** — the writer flushes periodically (and on
  :meth:`flush`), registers an ``atexit`` close, and works as a context
  manager; the Chrome trace format treats the closing ``]`` as optional,
  so a trace cut off mid-run still loads with at most the
  post-last-flush tail missing.

The emitted file loads in ``chrome://tracing`` / Perfetto, like the
reference's.  Events use one "pid" per engine and one "tid" per tensor name,
matching the reference's layout (tensor rows).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from typing import Optional


def rank_suffixed(path: str, rank: int, np_size: int) -> str:
    """Per-rank timeline path: ``/path.json`` → ``/path.r3.json`` when
    the job has more than one process, unchanged for np=1.

    ``HOROVOD_TIMELINE`` names ONE file; co-hosted multi-process workers
    handed the bare path verbatim would all open it for write and
    clobber each other's traces.  The ``.r<rank>`` infix keeps the
    extension (so Perfetto/chrome://tracing still recognize the file)
    and matches the ``rank[_-]?(\\d+)`` filename convention
    :func:`merge_timelines`' rank inference already understands.
    """
    if np_size <= 1:
        return path
    stem, ext = os.path.splitext(path)
    return f"{stem}.r{int(rank)}{ext}" if ext else f"{path}.r{int(rank)}"


class Timeline:
    """Thread-safe Chrome-trace writer; no-op when ``path`` is None.

    ``rank`` (when known) is stamped into a ``clock_sync`` metadata
    event together with the wall-clock epoch of the trace's t=0 — the
    anchor :func:`merge_timelines` uses to rebase per-rank traces onto
    one shared time axis so cross-rank skew is visually real.
    """

    def __init__(self, path: Optional[str], *, mark_cycles: bool = False,
                 flush_interval_s: float = 1.0,
                 rank: Optional[int] = None) -> None:
        self._path = path
        self._mark_cycles = mark_cycles
        self._flush_interval = flush_interval_s
        self._lock = threading.Lock()
        self._fh = None
        self._tids: dict[str, int] = {}
        self._start = time.monotonic()
        self._last_flush = self._start
        self._flow_ids = itertools.count(1)
        self.rank = rank
        if path:
            # hvdrun --timeline-dir names a directory only the launcher
            # host pre-creates; ssh-launched ranks (and any user path)
            # must not die in init() over a missing parent.
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._fh = open(path, "w")
            self._fh.write("[\n")
            # Merge anchor: wall-clock time of this trace's ts=0, plus
            # the rank when the caller knows it (multi-process workers).
            sync_args: dict = {"epoch_us": time.time() * 1e6}
            if rank is not None:
                sync_args["rank"] = int(rank)
            self._emit({"name": "clock_sync", "ph": "M", "pid": 0,
                        "tid": 0, "args": sync_args})
            # Crash/exit durability: an unclosed timeline still flushes
            # its tail at interpreter exit (close() unregisters this).
            atexit.register(self.close)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def __enter__(self) -> "Timeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _ts_us(self) -> float:
        return (time.monotonic() - self._start) * 1e6

    def _tid(self, tensor_name: str) -> int:
        tid = self._tids.get(tensor_name)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[tensor_name] = tid
            self._emit({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": tensor_name},
            })
        return tid

    def _emit(self, ev: dict) -> None:
        assert self._fh is not None
        self._fh.write(json.dumps(ev) + ",\n")
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._fh.flush()
            self._last_flush = now

    def flush(self) -> None:
        """Push buffered events to disk so a crash right now loses
        nothing written so far (Chrome/Perfetto accept the truncated
        array — the closing ``]`` is optional in the trace format)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._last_flush = time.monotonic()

    def start_activity(self, tensor_name: str, activity: str) -> None:
        """Begin a phase for a tensor († ``Timeline::ActivityStart``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": activity, "ph": "B", "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def end_activity(self, tensor_name: str) -> None:
        """End the current phase († ``Timeline::ActivityEnd``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"ph": "E", "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def mark_cycle(self) -> None:
        """Instant event per engine cycle († HOROVOD_TIMELINE_MARK_CYCLES)."""
        if not self.enabled or not self._mark_cycles:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "CYCLE", "ph": "i", "s": "g", "pid": 0,
                        "tid": 0, "ts": self._ts_us()})

    # -- Timeline v2 ---------------------------------------------------------
    def new_flow(self) -> int:
        """Fresh flow id for a QUEUE→DISPATCH arrow."""
        return next(self._flow_ids)

    def flow_start(self, tensor_name: str, flow_id: int) -> None:
        """Open a flow arrow at the tensor's current span (emit right
        after the QUEUE ``start_activity``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "hvd.link", "cat": "flow", "ph": "s",
                        "id": flow_id, "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def flow_end(self, tensor_name: str, flow_id: int) -> None:
        """Land the arrow on the tensor's current span (emit right after
        the DISPATCH ``start_activity``); ``bp: "e"`` binds it to the
        enclosing slice."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "hvd.link", "cat": "flow", "ph": "f",
                        "bp": "e", "id": flow_id, "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def complete(self, lane: str, name: str, t0_mono: float,
                 t1_mono: float, args: Optional[dict] = None) -> None:
        """Complete event (``"ph": "X"``): one slice with explicit start
        and duration, timestamped from ``time.monotonic()`` values.  The
        request tracer (:mod:`horovod_tpu.obs.trace`) emits each ended
        span this way — the span's interval is only known at end time,
        when a B/E pair could no longer be placed retroactively."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            ev = {"name": name, "ph": "X", "pid": 0,
                  "tid": self._tid(lane),
                  "ts": (t0_mono - self._start) * 1e6,
                  "dur": max(0.0, (t1_mono - t0_mono) * 1e6)}
            if args:
                ev["args"] = dict(args)
            self._emit(ev)

    def flow_at(self, lane: str, flow_id: int, ph: str,
                t_mono: float) -> None:
        """Flow endpoint (``ph`` = ``"s"`` or ``"f"``) at an explicit
        monotonic time — the retroactive form of :meth:`flow_start` /
        :meth:`flow_end`, used to chain already-ended ``X`` slices
        (QUEUE→PREFILL→DECODE arrows)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            ev = {"name": "hvd.link", "cat": "flow", "ph": ph,
                  "id": flow_id, "pid": 0, "tid": self._tid(lane),
                  "ts": (t_mono - self._start) * 1e6}
            if ph == "f":
                ev["bp"] = "e"
            self._emit(ev)

    def counter(self, name: str, values: dict) -> None:
        """Counter track sample (``"ph": "C"``): ``values`` is a flat
        ``{series: number}`` dict, rendered by Perfetto as stacked
        graphs.  The engine feeds these from the metrics registry once
        per cycle."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": name, "ph": "C", "pid": 0, "tid": 0,
                        "ts": self._ts_us(), "args": dict(values)})

    def close(self) -> None:
        with self._lock:
            if self._fh is None:
                return
            # Chrome's trace parser accepts a trailing comma-less close; emit
            # a terminal metadata event so the JSON array is well-formed.
            self._fh.write(json.dumps(
                {"name": "trace_end", "ph": "M", "pid": 0, "tid": 0}) + "\n]\n")
            self._fh.close()
            self._fh = None
        atexit.unregister(self.close)


# ---------------------------------------------------------------------------
# Multi-rank merge: N per-rank Timeline files -> one Perfetto trace with
# one pid lane per rank.  The reference's † timeline.cc writes one file
# per process and leaves the join to the user; ``hvdrun --timeline-dir``
# collects per-rank files and this merge rebases them onto one wall-clock
# axis (via each file's clock_sync anchor), so cross-rank skew — who
# enqueued late, whose DISPATCH lags — is directly visible in one load.
# ---------------------------------------------------------------------------

#: flow/async ids are remapped per input file in strides of this, so
#: arrows never alias across ranks (each rank counts its own ids from 1).
_FLOW_ID_STRIDE = 1 << 24

_RANK_RE = None  # compiled lazily; avoids importing re on the hot path


def load_trace_events(path: str) -> list:
    """Read one Chrome-trace JSON file, tolerating the truncated-array
    form a crashed run leaves behind (the closing ``]`` is optional in
    the trace format, and Timeline relies on that for crash durability).
    Accepts both the bare-array and ``{"traceEvents": [...]}`` shapes."""
    with open(path) as fh:
        raw = fh.read()
    try:
        data = json.loads(raw)
    except ValueError:
        data = json.loads(raw.rstrip().rstrip(",") + "\n]")
    if isinstance(data, dict):
        data = data.get("traceEvents", [])
    return [ev for ev in data if isinstance(ev, dict)]


def _infer_rank(path: str, events: list, fallback: int) -> int:
    """A file's rank: the clock_sync stamp when present, else a
    ``rank<N>`` / ``.r<N>.`` hint in the filename (the latter is what
    :func:`rank_suffixed` emits), else the positional index."""
    for ev in events:
        if ev.get("name") == "clock_sync" and ev.get("ph") == "M":
            r = ev.get("args", {}).get("rank")
            if r is not None:
                return int(r)
            break
    global _RANK_RE
    if _RANK_RE is None:
        import re
        _RANK_RE = re.compile(r"(?:rank[_-]?|\.r)(\d+)")
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else fallback


def _epoch_us(events: list) -> Optional[float]:
    for ev in events:
        if ev.get("name") == "clock_sync" and ev.get("ph") == "M":
            e = ev.get("args", {}).get("epoch_us")
            if e is not None:
                return float(e)
    return None


def merge_timelines(out_path: str, inputs: list) -> dict:
    """Merge per-rank timeline files into ``out_path``.

    - one **pid lane per rank** (pid = rank, with ``process_name`` /
      ``process_sort_index`` metadata so Perfetto orders lanes by rank);
    - timestamps **rebased onto one shared axis** via each file's
      ``clock_sync`` wall-clock anchor (files without one keep their own
      zero), so a rank that started its step late is visibly shifted;
    - **counter tracks and flow arrows survive**: counter events move to
      their rank's lane, and flow ids are remapped per rank so no arrow
      aliases another rank's.

    Returns a summary dict (ranks merged, event count, output path).
    """
    per_file = []
    for i, path in enumerate(inputs):
        events = load_trace_events(path)
        per_file.append((_infer_rank(path, events, i),
                         _epoch_us(events), events))
    per_file.sort(key=lambda t: t[0])
    anchors = [e for _, e, _ in per_file if e is not None]
    base = min(anchors) if anchors else 0.0

    merged: list = []
    ranks = []
    for idx, (rank, epoch, events) in enumerate(per_file):
        ranks.append(rank)
        offset = (epoch - base) if epoch is not None else 0.0
        id_off = (idx + 1) * _FLOW_ID_STRIDE
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        for ev in events:
            if ev.get("name") in ("trace_end", "process_name",
                                  "process_sort_index"):
                continue
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = ev["ts"] + offset
            if ev.get("ph") in ("s", "t", "f") and "id" in ev:
                ev["id"] = int(ev["id"]) + id_off
            merged.append(ev)

    with open(out_path, "w") as fh:
        fh.write("[\n")
        for ev in merged:
            fh.write(json.dumps(ev) + ",\n")
        fh.write(json.dumps(
            {"name": "trace_end", "ph": "M", "pid": 0, "tid": 0}) + "\n]\n")
    return {"out": out_path, "ranks": ranks, "events": len(merged)}


def main(argv: Optional[list] = None) -> int:
    """CLI: ``python -m horovod_tpu.utils.timeline merge out.json
    rank0.json rank1.json ...`` — see :func:`merge_timelines`."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.timeline",
        description="Timeline tools (merge per-rank Chrome traces)")
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser(
        "merge", help="merge per-rank timeline files into one trace "
                      "with one pid lane per rank")
    m.add_argument("out", help="output trace path")
    m.add_argument("inputs", nargs="+",
                   help="per-rank timeline files (rank read from each "
                        "file's clock_sync event, else from a rank<N> "
                        "filename hint, else positional)")
    args = p.parse_args(argv)
    if args.cmd == "merge":
        summary = merge_timelines(args.out, args.inputs)
        print(f"merged {len(summary['ranks'])} rank timeline(s) "
              f"{summary['ranks']} -> {summary['out']} "
              f"({summary['events']} events)", file=sys.stderr)
        return 0
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
