"""Per-tensor collective lifecycle tracing to Chrome-trace JSON.

Reference († ``horovod/common/timeline.cc``): every tensor's journey
(NEGOTIATE → QUEUE → MEMCPY_IN_FUSION_BUFFER → <BACKEND>_ALLREDUCE →
MEMCPY_OUT_FUSION_BUFFER) is written as ``chrome://tracing`` events when
``HOROVOD_TIMELINE=/path.json`` is set; ``HOROVOD_TIMELINE_MARK_CYCLES`` adds
an instant event per background-loop cycle.

TPU-native differences: there is no explicit fusion-buffer memcpy (XLA fuses
the flatten/concat into the collective program) and no negotiation phase in
single-controller mode, so the phases here are QUEUE → FUSE → DISPATCH →
EXECUTE (device time, asynchronous) → CALLBACK.  For on-device timing use
``jax.profiler`` traces, where XLA names each collective op; this timeline is
the host-side engine view, same as the reference's.

Timeline v2 (beyond the reference):

- **Counter events** (``"ph": "C"``) — the engine samples the metrics
  registry (:mod:`horovod_tpu.obs`) once per cycle into counter tracks,
  so one Perfetto load shows queue depth / cumulative collective bytes as
  graphs directly under the per-tensor spans.
- **Flow events** (``"ph": "s"``/``"f"``) — an arrow from a tensor's
  QUEUE span to its DISPATCH span, so a span picked in the execute phase
  links back to the enqueue that caused it even when other tensors'
  rows interleave.
- **Crash durability** — the writer flushes periodically (and on
  :meth:`flush`), registers an ``atexit`` close, and works as a context
  manager; the Chrome trace format treats the closing ``]`` as optional,
  so a trace cut off mid-run still loads with at most the
  post-last-flush tail missing.

The emitted file loads in ``chrome://tracing`` / Perfetto, like the
reference's.  Events use one "pid" per engine and one "tid" per tensor name,
matching the reference's layout (tensor rows).
"""

from __future__ import annotations

import atexit
import itertools
import json
import threading
import time
from typing import Optional


class Timeline:
    """Thread-safe Chrome-trace writer; no-op when ``path`` is None."""

    def __init__(self, path: Optional[str], *, mark_cycles: bool = False,
                 flush_interval_s: float = 1.0) -> None:
        self._path = path
        self._mark_cycles = mark_cycles
        self._flush_interval = flush_interval_s
        self._lock = threading.Lock()
        self._fh = None
        self._tids: dict[str, int] = {}
        self._start = time.monotonic()
        self._last_flush = self._start
        self._flow_ids = itertools.count(1)
        if path:
            self._fh = open(path, "w")
            self._fh.write("[\n")
            # Crash/exit durability: an unclosed timeline still flushes
            # its tail at interpreter exit (close() unregisters this).
            atexit.register(self.close)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def __enter__(self) -> "Timeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _ts_us(self) -> float:
        return (time.monotonic() - self._start) * 1e6

    def _tid(self, tensor_name: str) -> int:
        tid = self._tids.get(tensor_name)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[tensor_name] = tid
            self._emit({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": tensor_name},
            })
        return tid

    def _emit(self, ev: dict) -> None:
        assert self._fh is not None
        self._fh.write(json.dumps(ev) + ",\n")
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._fh.flush()
            self._last_flush = now

    def flush(self) -> None:
        """Push buffered events to disk so a crash right now loses
        nothing written so far (Chrome/Perfetto accept the truncated
        array — the closing ``]`` is optional in the trace format)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._last_flush = time.monotonic()

    def start_activity(self, tensor_name: str, activity: str) -> None:
        """Begin a phase for a tensor († ``Timeline::ActivityStart``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": activity, "ph": "B", "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def end_activity(self, tensor_name: str) -> None:
        """End the current phase († ``Timeline::ActivityEnd``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"ph": "E", "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def mark_cycle(self) -> None:
        """Instant event per engine cycle († HOROVOD_TIMELINE_MARK_CYCLES)."""
        if not self.enabled or not self._mark_cycles:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "CYCLE", "ph": "i", "s": "g", "pid": 0,
                        "tid": 0, "ts": self._ts_us()})

    # -- Timeline v2 ---------------------------------------------------------
    def new_flow(self) -> int:
        """Fresh flow id for a QUEUE→DISPATCH arrow."""
        return next(self._flow_ids)

    def flow_start(self, tensor_name: str, flow_id: int) -> None:
        """Open a flow arrow at the tensor's current span (emit right
        after the QUEUE ``start_activity``)."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "hvd.link", "cat": "flow", "ph": "s",
                        "id": flow_id, "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def flow_end(self, tensor_name: str, flow_id: int) -> None:
        """Land the arrow on the tensor's current span (emit right after
        the DISPATCH ``start_activity``); ``bp: "e"`` binds it to the
        enclosing slice."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": "hvd.link", "cat": "flow", "ph": "f",
                        "bp": "e", "id": flow_id, "pid": 0,
                        "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def counter(self, name: str, values: dict) -> None:
        """Counter track sample (``"ph": "C"``): ``values`` is a flat
        ``{series: number}`` dict, rendered by Perfetto as stacked
        graphs.  The engine feeds these from the metrics registry once
        per cycle."""
        if not self.enabled:
            return
        with self._lock:
            if self._fh is None:
                return
            self._emit({"name": name, "ph": "C", "pid": 0, "tid": 0,
                        "ts": self._ts_us(), "args": dict(values)})

    def close(self) -> None:
        with self._lock:
            if self._fh is None:
                return
            # Chrome's trace parser accepts a trailing comma-less close; emit
            # a terminal metadata event so the JSON array is well-formed.
            self._fh.write(json.dumps(
                {"name": "trace_end", "ph": "M", "pid": 0, "tid": 0}) + "\n]\n")
            self._fh.close()
            self._fh = None
        atexit.unregister(self.close)
