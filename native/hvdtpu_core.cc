// hvdtpu_core: native runtime for horovod_tpu.
//
// TPU-native counterpart of the reference's C++ core (†
// horovod/common/{message.cc,controller.cc,response_cache.cc,
// gloo/http_store.cc,stall_inspector.cc}).  What stays native here is the
// *control plane*: the rendezvous KV store and the rank-0 coordinator that
// makes every process agree on which named tensors are globally ready and in
// what order they fuse — the invariant that keeps SPMD collective dispatch
// identical on all ranks.  The *data plane* (the collectives themselves) is
// compiled XLA riding ICI/DCN, so no NCCL/MPI-style op backends exist here.
//
// Exposed as a C ABI consumed via ctypes (no pybind dependency in the
// image).  All framing is length-prefixed binary over TCP; see WireFormat
// below († message.cc Request/Response hand-rolled serialization).
//
// Build: make -C native  (produces libhvdtpu_core.so)

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <random>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// ---------------------------------------------------------------------------
// SHA-256 + HMAC († runner/common/util/secret.py: the reference signs every
// driver<->task RPC with a per-job random secret; here every control-plane
// frame carries an HMAC-SHA256 tag when a secret is configured).  In-tree
// implementation (FIPS 180-4 / RFC 2104) to avoid an OpenSSL dependency.
// ---------------------------------------------------------------------------

struct Sha256 {
  uint32_t h[8];
  uint8_t block[64];
  uint64_t total = 0;
  size_t fill = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Compress(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void Update(const void* data, size_t len) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    total += len;
    if (fill > 0) {
      size_t take = std::min(len, 64 - fill);
      std::memcpy(block + fill, p, take);
      fill += take;
      p += take;
      len -= take;
      if (fill == 64) {
        Compress(block);
        fill = 0;
      }
    }
    while (len >= 64) {
      Compress(p);
      p += 64;
      len -= 64;
    }
    if (len > 0) {
      std::memcpy(block, p, len);
      fill = len;
    }
  }

  void Final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (fill != 56) Update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    Update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

constexpr size_t kTagLen = 32;

void hmac_sha256(const std::string& key, const std::string& msg,
                 uint8_t out[kTagLen]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 kh;
    kh.Update(key.data(), key.size());
    kh.Final(k);  // first 32 bytes; rest stay zero
  } else {
    std::memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 s1;
  s1.Update(ipad, 64);
  s1.Update(msg.data(), msg.size());
  s1.Final(inner);
  Sha256 s2;
  s2.Update(opad, 64);
  s2.Update(inner, 32);
  s2.Final(out);
}

bool tags_equal(const uint8_t* a, const uint8_t* b) {
  // Constant-time compare: no early exit on mismatch.
  uint8_t diff = 0;
  for (size_t i = 0; i < kTagLen; ++i) diff |= a[i] ^ b[i];
  return diff == 0;
}

// ---------------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------------

bool send_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Frame = u32 length + payload.
bool send_frame(int fd, const std::string& payload) {
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  if (!send_all(fd, &len, 4)) return false;
  return send_all(fd, payload.data(), payload.size());
}

bool recv_frame(int fd, std::string* out) {
  uint32_t len_n;
  if (!recv_all(fd, &len_n, 4)) return false;
  uint32_t len = ntohl(len_n);
  // Pre-auth allocation bound: control-plane payloads are tiny (names,
  // addresses, pickled membership state); reject oversized frames before
  // allocating so unauthenticated peers can't balloon the coordinator.
  if (len > (8u << 20)) return false;
  out->resize(len);
  return len == 0 || recv_all(fd, &(*out)[0], len);
}

// Authenticated framing.  Per connection the server picks a random nonce
// (sent in the clear on accept); every subsequent frame's payload is
// tag(32) || body with tag = HMAC-SHA256(secret, nonce || dir || seq ||
// body).  The nonce kills cross-connection replay, the direction byte
// ('C' client->server, 'S' server->client) kills reflection, and the
// per-direction monotonic sequence kills in-connection replay/reorder.  A
// frame that fails verification is a transport error: the connection is
// dropped, the same containment the reference applies to bad-signature
// RPCs.
constexpr size_t kNonceLen = 16;

std::string random_nonce() {
  std::string n(kNonceLen, '\0');
  std::random_device rd;
  for (auto& c : n) c = static_cast<char>(rd());
  return n;
}

struct AuthChannel {
  std::string secret;
  std::string nonce;
  char send_dir = 'C';
  char recv_dir = 'S';
  uint64_t send_seq = 0;
  uint64_t recv_seq = 0;

  std::string MacInput(char dir, uint64_t seq, const std::string& body) const {
    std::string m = nonce;
    m += dir;
    for (int i = 7; i >= 0; --i) m += static_cast<char>(seq >> (8 * i));
    m += body;
    return m;
  }
};

// Server side of the handshake: send the per-connection nonce.
bool auth_accept(int fd, AuthChannel* ch, const std::string& secret) {
  ch->secret = secret;
  ch->send_dir = 'S';
  ch->recv_dir = 'C';
  if (secret.empty()) return true;
  ch->nonce = random_nonce();
  return send_frame(fd, ch->nonce);
}

// Client side: receive the nonce.  Bounded by a receive timeout so a
// client pointed at an unauthenticated server fails fast instead of
// blocking forever on a nonce that will never come.
bool auth_connect(int fd, AuthChannel* ch, const std::string& secret) {
  ch->secret = secret;
  ch->send_dir = 'C';
  ch->recv_dir = 'S';
  if (secret.empty()) return true;
  timeval tv{10, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  bool ok = recv_frame(fd, &ch->nonce) && ch->nonce.size() == kNonceLen;
  timeval off{0, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  return ok;
}

bool send_auth_frame(int fd, AuthChannel* ch, const std::string& body) {
  if (ch->secret.empty()) return send_frame(fd, body);
  std::string payload;
  payload.resize(kTagLen);
  hmac_sha256(ch->secret, ch->MacInput(ch->send_dir, ch->send_seq, body),
              reinterpret_cast<uint8_t*>(&payload[0]));
  ch->send_seq++;
  payload += body;
  return send_frame(fd, payload);
}

bool recv_auth_frame(int fd, AuthChannel* ch, std::string* body) {
  if (ch->secret.empty()) return recv_frame(fd, body);
  std::string payload;
  if (!recv_frame(fd, &payload) || payload.size() < kTagLen) return false;
  std::string b = payload.substr(kTagLen);
  uint8_t want[kTagLen];
  hmac_sha256(ch->secret, ch->MacInput(ch->recv_dir, ch->recv_seq, b), want);
  if (!tags_equal(want, reinterpret_cast<const uint8_t*>(payload.data())))
    return false;
  ch->recv_seq++;
  *body = std::move(b);
  return true;
}

int listen_on(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

int bound_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0) return -1;
  return ntohs(addr.sin_port);
}

int connect_to(const char* host, int port, int timeout_ms) {
  auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
  while (true) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (Clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

// ---------------------------------------------------------------------------
// WireFormat († message.cc): little helpers for binary pack/unpack
// ---------------------------------------------------------------------------

void put_u32(std::string* s, uint32_t v) {
  uint32_t n = htonl(v);
  s->append(reinterpret_cast<const char*>(&n), 4);
}

uint32_t get_u32(const std::string& s, size_t* off) {
  uint32_t n;
  std::memcpy(&n, s.data() + *off, 4);
  *off += 4;
  return ntohl(n);
}

void put_str(std::string* s, const std::string& v) {
  put_u32(s, static_cast<uint32_t>(v.size()));
  s->append(v);
}

std::string get_str(const std::string& s, size_t* off) {
  uint32_t len = get_u32(s, off);
  std::string out = s.substr(*off, len);
  *off += len;
  return out;
}

// ---------------------------------------------------------------------------
// KV store server († gloo/http_store.cc + runner RendezvousServer): the
// bootstrap rendezvous.  Ops: S<key><val> set, G<key> get (blocking with
// timeout handled client-side via W), W<key><timeout_ms> wait+get.
// ---------------------------------------------------------------------------

class KvServer {
 public:
  KvServer(int port, std::string secret) : secret_(std::move(secret)) {
    listen_fd_ = listen_on(port);
    if (listen_fd_ >= 0) {
      port_ = bound_port(listen_fd_);
      accept_thread_ = std::thread([this] { AcceptLoop(); });
    }
  }

  ~KvServer() { Stop(); }

  int port() const { return port_; }
  bool ok() const { return listen_fd_ >= 0; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : client_threads_) {
      if (t.joinable()) t.join();
    }
  }

 private:
  void AcceptLoop() {
    while (!stopping_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> g(mu_);
      client_fds_.insert(fd);
      client_threads_.emplace_back([this, fd] { ClientLoop(fd); });
    }
  }

  void ClientLoop(int fd) {
    AuthChannel ch;
    if (!auth_accept(fd, &ch, secret_)) {
      ::close(fd);
      return;
    }
    std::string frame;
    while (!stopping_ && recv_auth_frame(fd, &ch, &frame)) {
      if (frame.empty()) continue;
      char op = frame[0];
      size_t off = 1;
      if (op == 'S') {
        std::string key = get_str(frame, &off);
        std::string val = frame.substr(off);
        {
          std::lock_guard<std::mutex> g(mu_);
          table_[key] = val;
        }
        cv_.notify_all();
        send_auth_frame(fd, &ch, "K");
      } else if (op == 'W' || op == 'G') {
        std::string key = get_str(frame, &off);
        uint32_t timeout_ms = (op == 'W') ? get_u32(frame, &off) : 0;
        std::unique_lock<std::mutex> lk(mu_);
        auto pred = [&] { return table_.count(key) > 0 || stopping_.load(); };
        if (op == 'W') {
          cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
        }
        auto it = table_.find(key);
        if (it == table_.end()) {
          lk.unlock();
          send_auth_frame(fd, &ch, "M");  // missing
        } else {
          std::string reply = "V" + it->second;
          lk.unlock();
          send_auth_frame(fd, &ch, reply);
        }
      } else if (op == 'D') {  // delete (elastic re-rendezvous reuse)
        std::string key = get_str(frame, &off);
        std::lock_guard<std::mutex> g(mu_);
        table_.erase(key);
        send_auth_frame(fd, &ch, "K");
      }
    }
    ::close(fd);
  }

  int listen_fd_ = -1;
  int port_ = -1;
  std::string secret_;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::vector<std::thread> client_threads_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> table_;
  std::set<int> client_fds_;
};

class KvClient {
 public:
  KvClient(const char* host, int port, int timeout_ms, std::string secret) {
    fd_ = connect_to(host, port, timeout_ms);
    if (fd_ >= 0 && !auth_connect(fd_, &ch_, secret)) {
      ::close(fd_);
      fd_ = -1;
    }
  }
  ~KvClient() {
    if (fd_ >= 0) ::close(fd_);
  }
  bool ok() const { return fd_ >= 0; }

  bool Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu_);
    std::string msg = "S";
    put_str(&msg, key);
    msg += val;
    std::string reply;
    return send_auth_frame(fd_, &ch_, msg) &&
           recv_auth_frame(fd_, &ch_, &reply) && reply == "K";
  }

  // 1 = got value, 0 = absent within timeout, -1 = transport/auth failure
  // (connection dropped — e.g. the server rejected our MAC).
  int Wait(const std::string& key, int timeout_ms, std::string* val) {
    std::lock_guard<std::mutex> g(mu_);
    std::string msg = "W";
    put_str(&msg, key);
    put_u32(&msg, static_cast<uint32_t>(timeout_ms));
    std::string reply;
    if (!send_auth_frame(fd_, &ch_, msg) ||
        !recv_auth_frame(fd_, &ch_, &reply))
      return -1;
    if (reply.empty() || reply[0] != 'V') return 0;
    *val = reply.substr(1);
    return 1;
  }

  bool Del(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    std::string msg = "D";
    put_str(&msg, key);
    std::string reply;
    return send_auth_frame(fd_, &ch_, msg) &&
           recv_auth_frame(fd_, &ch_, &reply) && reply == "K";
  }

 private:
  int fd_ = -1;
  AuthChannel ch_;
  std::mutex mu_;
};

// ---------------------------------------------------------------------------
// Controller († controller.cc Controller::ComputeResponseList + †
// response_cache.cc): rank-0 coordinator deciding, per negotiation round,
// which named tensors are ready on every rank and in what order they fuse.
//
// Round protocol (client -> server frame):
//   u32 rank, u8 flags (bit0: this rank has JOINed — no more inputs,
//   † message.h RequestType::JOIN), u32 n_entries, then per entry either
//     'N' + str name + str meta + str members
//                                (first sighting — server assigns an id;
//                                 meta is an opaque descriptor the engine
//                                 uses to build zero-payload participation
//                                 on joined ranks; members is a csv of the
//                                 global ranks that participate — "" means
//                                 every rank.  † process_set.cc: a
//                                 process-set collective is ready once its
//                                 MEMBERS have submitted, not the world)
//   or
//     'I' + u32 id     (cache fast path † bit-vector exchange)
// Server reply:
//   u32 n_ready, then per ready tensor: u32 id + str name + str meta
//   (names echoed so new ranks can learn ids; † Response joined names),
//   then u32 n_stalled (informational: tensors some ranks submitted but
//   others haven't for > stall_warn_ms — † stall_inspector.cc),
//   then u8 all_joined (1 once every rank has joined) + u32 last_join_rank.
//
// JOIN semantics: a joined rank counts as having implicitly submitted every
// tensor (it will participate with zeros), so readiness = every rank either
// saw the tensor or joined.  When all ranks have joined, the all_joined
// flag is reported once (with the last rank to join — the † hvd.join()
// return value) and join state resets for the next phase.
//
// Ordering invariant: ready tensors are ordered by the round in which they
// first became globally known, then by rank-0's submission order — giving
// every rank the identical fuse order without a second broadcast.
// ---------------------------------------------------------------------------

struct TensorState {
  uint32_t id;
  std::string name;
  std::string meta;
  // Rank whose submission supplied the stored meta this cycle.  Meta
  // storage is lowest-rank-wins within a submission cycle (RecordName):
  // the echoed meta is then a *deterministic* function of the fleet's
  // submissions, independent of TCP arrival order — required for
  // schedule-backend reconciliation (engine adopts the echoed `sc`), so
  // a mixed compiled/decomposed fleet converges on the same common mode
  // every run, not whichever rank's packet landed last.
  uint32_t meta_rank = 0;
  // Global ranks participating in this tensor's collective; empty = every
  // rank († ProcessSet membership).  Readiness and join coverage are
  // computed against this set.
  std::set<uint32_t> members;
  std::set<uint32_t> ranks_seen;
  uint64_t first_seen_round;
  Clock::time_point first_seen_time;
};

// "0,2,5" -> {0, 2, 5} ("" -> {}).
static std::set<uint32_t> parse_members(const std::string& csv) {
  std::set<uint32_t> out;
  size_t start = 0;
  while (start < csv.size()) {
    size_t comma = csv.find(',', start);
    if (comma == std::string::npos) comma = csv.size();
    if (comma > start) {
      out.insert(static_cast<uint32_t>(
          std::strtoul(csv.substr(start, comma - start).c_str(), nullptr,
                       10)));
    }
    start = comma + 1;
  }
  return out;
}

class Controller {
 public:
  // round_abort_ms > 0: a rank waiting in the per-round barrier longer
  // than this receives an abort reply instead of blocking forever — the
  // escape hatch for "another rank's engine died/diverged mid-job"
  // († the reference delivers an error Response to every rank so all
  // raise; a blocked barrier would otherwise hold ranks in recv where
  // their own stall inspectors cannot run).  0 disables (default): long
  // legitimate rounds (first XLA compile) must not be aborted unless the
  // operator opted into stall shutdown.
  Controller(int port, int size, int stall_warn_ms, std::string secret,
             int round_abort_ms = 0)
      : size_(static_cast<uint32_t>(size)), stall_warn_ms_(stall_warn_ms),
        round_abort_ms_(round_abort_ms),
        secret_(std::move(secret)) {
    listen_fd_ = listen_on(port);
    if (listen_fd_ >= 0) {
      port_ = bound_port(listen_fd_);
      accept_thread_ = std::thread([this] { AcceptLoop(); });
    }
  }

  ~Controller() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int fd : all_fds_) ::shutdown(fd, SHUT_RDWR);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : worker_threads_) {
      if (t.joinable()) t.join();
    }
  }

 private:
  void AcceptLoop() {
    while (!stopping_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(mu_);
      all_fds_.insert(fd);
      worker_threads_.emplace_back([this, fd] { RankLoop(fd); });
    }
  }

  // One thread per rank connection; implements the barrier-per-round
  // semantics of † MPIController (gather at rank 0, bcast response).
  void RankLoop(int fd) {
    AuthChannel ch;
    if (!auth_accept(fd, &ch, secret_)) {
      ::close(fd);
      return;
    }
    uint32_t my_rank = UINT32_MAX;
    std::string frame;
    while (!stopping_ && recv_auth_frame(fd, &ch, &frame)) {
      size_t off = 0;
      uint32_t rank = get_u32(frame, &off);
      uint8_t flags = static_cast<uint8_t>(frame[off++]);
      uint32_t n = get_u32(frame, &off);
      struct NewEntry {
        std::string name, meta, members;
      };
      std::vector<NewEntry> names;
      std::vector<uint32_t> ids;
      for (uint32_t i = 0; i < n; ++i) {
        char tag = frame[off++];
        if (tag == 'N') {
          std::string nm = get_str(frame, &off);
          std::string meta = get_str(frame, &off);
          std::string members = get_str(frame, &off);
          names.push_back({std::move(nm), std::move(meta),
                           std::move(members)});
        } else {
          ids.push_back(get_u32(frame, &off));
        }
      }

      std::unique_lock<std::mutex> lk(mu_);
      if (my_rank == UINT32_MAX) {
        my_rank = rank;
        rank_fds_[rank] = fd;
      }
      // Record submissions.
      for (auto& nm : names)
        RecordName(rank, nm.name, nm.meta, nm.members);
      for (uint32_t id : ids) RecordId(rank, id);
      if (flags & 1) {
        if (joined_.insert(rank).second) last_join_rank_ = rank;
      }
      arrived_.insert(rank);

      uint64_t round = round_;
      bool aborted = false;
      if (arrived_.size() == size_) {
        // Last arrival computes the response for everyone († rank-0
        // coordinator builds the response list once per round).
        BuildResponse();
        arrived_.clear();
        round_++;
        cv_.notify_all();
      } else if (round_abort_ms_ > 0) {
        if (!cv_.wait_for(lk, std::chrono::milliseconds(round_abort_ms_),
                          [&] { return round_ != round ||
                                       stopping_.load(); })) {
          // Some rank never checked in (engine dead / process gone):
          // release THIS rank with an abort reply so its engine errors
          // pending work instead of blocking in recv forever.  Withdraw
          // this rank from the round entirely — a slow-but-alive last
          // peer must not later complete the round counting us as a
          // participant whose dispatch will never come.
          aborted = true;
          arrived_.erase(my_rank);
          for (auto& kv : tensors_) kv.second.ranks_seen.erase(my_rank);
          joined_.erase(my_rank);
        }
      } else {
        cv_.wait(lk, [&] { return round_ != round || stopping_.load(); });
      }
      if (stopping_) break;
      std::string reply;
      if (aborted) {
        put_u32(&reply, 0xFFFFFFFFu);  // round-abort sentinel
      } else {
        reply = last_response_;
      }
      lk.unlock();
      send_auth_frame(fd, &ch, reply);
      if (aborted) break;
    }
    ::close(fd);
  }

  void RecordName(uint32_t rank, const std::string& name,
                  const std::string& meta, const std::string& members) {
    auto it = by_name_.find(name);
    if (it == by_name_.end()) {
      uint32_t id = next_id_++;
      TensorState st;
      st.id = id;
      st.name = name;
      st.meta = meta;
      st.meta_rank = rank;
      st.members = parse_members(members);
      st.first_seen_round = round_;
      st.first_seen_time = Clock::now();
      st.ranks_seen.insert(rank);
      tensors_[id] = std::move(st);
      by_name_[name] = id;
    } else {
      TensorState& st = tensors_[it->second];
      // A name ('N') resubmission carries the entry's current meta and
      // replaces the stored one, including replacing it with "" (clients
      // bypass the id fast path whenever a tensor's descriptor changes,
      // e.g. a tail batch with a new shape, or a name reused for a
      // non-joinable collective).  Keeping the echoed meta identical to
      // what the submitting ranks hold this round is what lets joined and
      // live ranks agree on joinability.  Within one submission cycle
      // the LOWEST submitting rank's meta wins: when peers disagree
      // (schedule-mode skew — one rank resolved compiled, another
      // decomposed), the echoed meta the engines adopt must not depend
      // on packet arrival order, or the reconciled common mode would
      // flap run to run.
      bool fresh = st.ranks_seen.empty();
      if (fresh || rank <= st.meta_rank) {
        st.meta = meta;
        st.meta_rank = rank;
      }
      st.members = parse_members(members);
      Touch(st, rank);
    }
  }

  void RecordId(uint32_t rank, uint32_t id) {
    auto it = tensors_.find(id);
    if (it != tensors_.end()) Touch(it->second, rank);
  }

  // A fresh submission cycle starts when a tensor is re-submitted after
  // completing (steady-state training re-reduces the same names every
  // step — the reference's TensorQueue removes entries on completion and
  // re-adds them next step; here the id/name registration persists for the
  // cache and only the readiness state resets).
  void Touch(TensorState& st, uint32_t rank) {
    if (st.ranks_seen.empty()) {
      st.first_seen_round = round_;
      st.first_seen_time = Clock::now();
    }
    st.ranks_seen.insert(rank);
  }

  // Ranks whose participation a tensor needs: its member set, or the
  // whole world when the member set is empty.
  bool RankRequired(const TensorState& st, uint32_t r) const {
    return st.members.empty() || st.members.count(r) != 0;
  }

  void BuildResponse() {
    // Ready = seen-or-joined on every REQUIRED rank (the member set for
    // process-set tensors, the world otherwise); ordered by
    // (first_seen_round, id).  Joined ranks implicitly submit everything
    // († JoinOp: a joined rank participates as zeros).
    std::vector<const TensorState*> ready;
    std::vector<const TensorState*> stalled;
    auto now = Clock::now();
    for (auto& [id, st] : tensors_) {
      if (st.ranks_seen.empty()) continue;  // idle between cycles
      size_t required = st.members.empty()
                            ? size_
                            : st.members.size();
      size_t covered = 0;
      for (uint32_t r : st.ranks_seen) {
        if (RankRequired(st, r)) ++covered;
      }
      for (uint32_t jr : joined_) {
        if (RankRequired(st, jr) && !st.ranks_seen.count(jr)) ++covered;
      }
      if (covered == required) {
        ready.push_back(&st);
      } else if (stall_warn_ms_ > 0 &&
                 std::chrono::duration_cast<std::chrono::milliseconds>(
                     now - st.first_seen_time)
                         .count() > stall_warn_ms_) {
        stalled.push_back(&st);
      }
    }
    std::sort(ready.begin(), ready.end(),
              [](const TensorState* a, const TensorState* b) {
                if (a->first_seen_round != b->first_seen_round)
                  return a->first_seen_round < b->first_seen_round;
                return a->id < b->id;
              });
    std::string resp;
    put_u32(&resp, static_cast<uint32_t>(ready.size()));
    for (auto* st : ready) {
      put_u32(&resp, st->id);
      put_str(&resp, st->name);
      put_str(&resp, st->meta);
      // Join-coverage flag: 1 when some joined rank never submitted this
      // tensor, i.e. readiness depends on fabricated zero participation.
      // Ranks use it to error non-joinable verbs consistently everywhere
      // († the reference returns an error Response for non-allreduce ops
      // while any rank is joined) instead of dispatching a collective the
      // joined rank cannot take part in.
      uint8_t cov = 0;
      for (uint32_t jr : joined_) {
        if (RankRequired(*st, jr) && !st->ranks_seen.count(jr)) {
          cov = 1;
          break;
        }
      }
      resp += static_cast<char>(cov);
      const_cast<TensorState*>(st)->ranks_seen.clear();
    }
    // Stalled entries carry attribution († stall_inspector.cc logs only
    // the tensor name; here the coordinator also names WHICH required
    // ranks never submitted, and for how long the tensor has waited):
    //   "name \x02 missing_ranks_csv \x02 age_ms"
    // The straggler rank is exactly the required-and-not-joined rank
    // absent from ranks_seen — the bitmap the readiness check already
    // walks, exposed instead of discarded.
    put_u32(&resp, static_cast<uint32_t>(stalled.size()));
    for (auto* st : stalled) {
      std::string item = st->name;
      item += '\x02';
      bool first = true;
      for (uint32_t r = 0; r < size_; ++r) {
        if (!RankRequired(*st, r)) continue;
        if (st->ranks_seen.count(r) || joined_.count(r)) continue;
        if (!first) item += ',';
        first = false;
        item += std::to_string(r);
      }
      item += '\x02';
      item += std::to_string(
          std::chrono::duration_cast<std::chrono::milliseconds>(
              now - st->first_seen_time)
              .count());
      put_str(&resp, item);
    }
    uint8_t all_joined = joined_.size() == size_ ? 1 : 0;
    resp += static_cast<char>(all_joined);
    put_u32(&resp, last_join_rank_);
    if (all_joined) {
      // Reported exactly once to every rank of this round; reset so the
      // job can enter another uneven-input phase.
      joined_.clear();
      last_join_rank_ = 0;
    }
    last_response_ = resp;
  }

  uint32_t size_;
  int stall_warn_ms_;
  int round_abort_ms_ = 0;
  std::string secret_;
  int listen_fd_ = -1;
  int port_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::vector<std::thread> worker_threads_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::map<uint32_t, int> rank_fds_;
  std::set<int> all_fds_;
  std::set<uint32_t> arrived_;
  std::set<uint32_t> joined_;
  uint32_t last_join_rank_ = 0;
  uint64_t round_ = 0;
  uint32_t next_id_ = 0;
  std::unordered_map<std::string, uint32_t> by_name_;
  std::map<uint32_t, TensorState> tensors_;
  std::string last_response_;
};

// Client side of the negotiation, with the name->id cache († response cache
// client half: steady state sends ids, not names).
class CtrlClient {
 public:
  CtrlClient(const char* host, int port, int rank, int timeout_ms,
             std::string secret)
      : rank_(static_cast<uint32_t>(rank)) {
    fd_ = connect_to(host, port, timeout_ms);
    if (fd_ >= 0 && !auth_connect(fd_, &ch_, secret)) {
      ::close(fd_);
      fd_ = -1;
    }
  }
  ~CtrlClient() {
    if (fd_ >= 0) ::close(fd_);
  }
  bool ok() const { return fd_ >= 0; }

  struct ReadyItem {
    std::string name;
    std::string meta;
    bool join_cov;  // readiness depended on a joined rank's zero coverage
  };

  struct Entry {
    std::string name;
    std::string meta;
    std::string members;  // csv of participating ranks; "" = every rank
  };

  // entries: pending tensors on this rank this round (meta/members travel
  // only on first sighting; cached names go as ids).
  // joined: this rank has no more inputs († RequestType::JOIN).
  // Returns the agreed globally-ready ordered list with each tensor's
  // meta + join-coverage flag, plus the all-joined signal.
  bool Negotiate(const std::vector<Entry>& entries,
                 bool joined,
                 std::vector<ReadyItem>* ready,
                 std::vector<std::string>* stalled, bool* all_joined,
                 uint32_t* last_join_rank) {
    std::string msg;
    put_u32(&msg, rank_);
    msg += static_cast<char>(joined ? 1 : 0);
    put_u32(&msg, static_cast<uint32_t>(entries.size()));
    for (auto& e : entries) {
      auto it = cache_.find(e.name);
      // Id fast path only while the descriptor is unchanged; a meta or
      // membership change (e.g. tail batch with a new shape, or a name
      // reused under a different process set) must reach the server.
      std::string desc = e.meta + '\x01' + e.members;
      if (it != cache_.end() && meta_cache_[e.name] == desc) {
        msg += 'I';
        put_u32(&msg, it->second);
      } else {
        msg += 'N';
        put_str(&msg, e.name);
        put_str(&msg, e.meta);
        put_str(&msg, e.members);
        meta_cache_[e.name] = desc;
      }
    }
    std::string reply;
    if (!send_auth_frame(fd_, &ch_, msg) ||
        !recv_auth_frame(fd_, &ch_, &reply))
      return false;
    size_t off = 0;
    uint32_t n_ready = get_u32(reply, &off);
    if (n_ready == 0xFFFFFFFFu) {
      round_aborted_ = true;  // † error Response: peer stopped checking in
      return false;
    }
    ready->clear();
    for (uint32_t i = 0; i < n_ready; ++i) {
      uint32_t id = get_u32(reply, &off);
      std::string nm = get_str(reply, &off);
      std::string meta = get_str(reply, &off);
      bool cov = reply[off++] != 0;
      cache_[nm] = id;
      ready->push_back({std::move(nm), std::move(meta), cov});
    }
    uint32_t n_stalled = get_u32(reply, &off);
    stalled->clear();
    for (uint32_t i = 0; i < n_stalled; ++i) {
      stalled->push_back(get_str(reply, &off));
    }
    *all_joined = reply[off++] != 0;
    *last_join_rank = get_u32(reply, &off);
    return true;
  }

  size_t cache_size() const { return cache_.size(); }
  bool round_aborted() const { return round_aborted_; }

 private:
  int fd_ = -1;
  uint32_t rank_;
  AuthChannel ch_;
  bool round_aborted_ = false;
  std::unordered_map<std::string, uint32_t> cache_;
  std::unordered_map<std::string, std::string> meta_cache_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// -- KV store --
void* hvd_kv_server_start(int port, const char* secret) {
  auto* s = new KvServer(port, secret ? secret : "");
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}
int hvd_kv_server_port(void* s) { return static_cast<KvServer*>(s)->port(); }
void hvd_kv_server_stop(void* s) { delete static_cast<KvServer*>(s); }

void* hvd_kv_connect(const char* host, int port, int timeout_ms,
                     const char* secret) {
  auto* c = new KvClient(host, port, timeout_ms, secret ? secret : "");
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}
int hvd_kv_set(void* c, const char* key, const uint8_t* val, int len) {
  return static_cast<KvClient*>(c)->Set(
             key, std::string(reinterpret_cast<const char*>(val),
                              static_cast<size_t>(len)))
             ? 0
             : -1;
}
// Returns value length (may exceed cap, caller re-calls with bigger buf),
// -1 if absent/timeout, -2 on transport/auth failure (connection dropped,
// e.g. MAC rejected).
int hvd_kv_wait(void* c, const char* key, int timeout_ms, uint8_t* buf,
                int cap) {
  std::string val;
  int rc = static_cast<KvClient*>(c)->Wait(key, timeout_ms, &val);
  if (rc < 0) return -2;
  if (rc == 0) return -1;
  int n = static_cast<int>(val.size());
  if (buf != nullptr && cap >= n) std::memcpy(buf, val.data(), val.size());
  return n;
}
int hvd_kv_del(void* c, const char* key) {
  return static_cast<KvClient*>(c)->Del(key) ? 0 : -1;
}
void hvd_kv_close(void* c) { delete static_cast<KvClient*>(c); }

// -- Controller --
void* hvd_ctrl_server_start(int port, int size, int stall_warn_ms,
                            const char* secret, int round_abort_ms) {
  auto* s = new Controller(port, size, stall_warn_ms, secret ? secret : "",
                           round_abort_ms);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}
int hvd_ctrl_server_port(void* s) {
  return static_cast<Controller*>(s)->port();
}
void hvd_ctrl_server_stop(void* s) { delete static_cast<Controller*>(s); }

void* hvd_ctrl_connect(const char* host, int port, int rank, int timeout_ms,
                       const char* secret) {
  auto* c = new CtrlClient(host, port, rank, timeout_ms,
                           secret ? secret : "");
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

// names_blob: '\n'-joined entries ('' = none), each "name",
// "name\x02meta", or "name\x02meta\x02members" (members: csv of
// participating ranks, '' = every rank).  joined: nonzero when this rank
// has JOINed.  On success writes '\n'-joined ready entries
// ("name\x02meta", with "\x02j" appended when readiness depended on a
// joined rank's zero coverage) then '\x01' then '\n'-joined stalled names
// into out, sets *all_joined / *last_join_rank, and returns total length
// (or required length if > cap; -1 on failure).
int hvd_ctrl_negotiate(void* c, const char* names_blob, int joined_flag,
                       char* out, int cap, int* all_joined,
                       int* last_join_rank) {
  std::vector<CtrlClient::Entry> entries;
  {
    std::string blob(names_blob);
    size_t start = 0;
    while (start < blob.size()) {
      size_t nl = blob.find('\n', start);
      if (nl == std::string::npos) nl = blob.size();
      if (nl > start) {
        std::string item = blob.substr(start, nl - start);
        CtrlClient::Entry e;
        size_t sep = item.find('\x02');
        if (sep == std::string::npos) {
          e.name = std::move(item);
        } else {
          e.name = item.substr(0, sep);
          std::string rest = item.substr(sep + 1);
          size_t sep2 = rest.find('\x02');
          if (sep2 == std::string::npos) {
            e.meta = std::move(rest);
          } else {
            e.meta = rest.substr(0, sep2);
            e.members = rest.substr(sep2 + 1);
          }
        }
        entries.push_back(std::move(e));
      }
      start = nl + 1;
    }
  }
  std::vector<CtrlClient::ReadyItem> ready;
  std::vector<std::string> stalled;
  bool aj = false;
  uint32_t last = 0;
  auto* client = static_cast<CtrlClient*>(c);
  if (!client->Negotiate(entries, joined_flag != 0, &ready, &stalled, &aj,
                         &last))
    return client->round_aborted() ? -3 : -1;
  if (all_joined != nullptr) *all_joined = aj ? 1 : 0;
  if (last_join_rank != nullptr) *last_join_rank = static_cast<int>(last);
  std::string joined;
  for (size_t i = 0; i < ready.size(); ++i) {
    if (i) joined += '\n';
    joined += ready[i].name;
    joined += '\x02';
    joined += ready[i].meta;
    if (ready[i].join_cov) joined += "\x02j";
  }
  joined += '\x01';
  for (size_t i = 0; i < stalled.size(); ++i) {
    if (i) joined += '\n';
    joined += stalled[i];
  }
  int n = static_cast<int>(joined.size());
  if (out != nullptr && cap >= n) std::memcpy(out, joined.data(), joined.size());
  return n;
}
int hvd_ctrl_cache_size(void* c) {
  return static_cast<int>(static_cast<CtrlClient*>(c)->cache_size());
}
void hvd_ctrl_close(void* c) { delete static_cast<CtrlClient*>(c); }

}  // extern "C"
