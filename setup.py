"""Build hook: compile the native core into the wheel.

Metadata lives in pyproject.toml; this exists only so a non-editable
``pip install .`` ships ``libhvdtpu_core.so`` inside the package (the
ctypes bridge prefers the packaged copy and falls back to building from
the source tree — † the reference's custom build_ext compiling the C++
core into each framework extension).
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native(build_py):
    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(root, "native")
        if os.path.exists(os.path.join(native, "Makefile")):
            subprocess.run(["make", "-C", native], check=True)
            shutil.copy2(os.path.join(native, "libhvdtpu_core.so"),
                         os.path.join(root, "horovod_tpu", "_native"))
        super().run()


setup(cmdclass={"build_py": build_py_with_native})
