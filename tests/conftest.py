"""Test rig: 8 virtual CPU devices.

This is the TPU-native analogue of the reference's ``horovodrun -np 2 pytest``
multi-process rig (SURVEY §4): ``--xla_force_host_platform_device_count=8``
gives 8 collective participants in-process.

Platform forcing must happen before any JAX backend initializes; the dev
image pins an ``axon`` TPU platform via sitecustomize, so we override with
``jax.config`` (which wins as long as no backend has been touched yet).
"""

from horovod_tpu.utils.cpurig import force_cpu_platform

force_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def hvd_session():
    import horovod_tpu as hvd
    hvd.init()
    assert hvd.size() == 8, f"expected 8 fake devices, got {hvd.size()}"
    yield
    hvd.shutdown()
