"""Worker for the elastic x checkpoint end-to-end circle (VERDICT r3 #5).

Trains a real jax model (tiny MLP, adam) under ``@hvd.elastic.run``; every
committed step ALSO writes a sharded orbax checkpoint
(:class:`horovod_tpu.utils.checkpoint.Checkpointer`) of params + optimizer
moments + step, and every (re)start restores from the latest checkpoint —
the durable-restore leg the in-memory elastic ``State`` cannot provide
(† SURVEY §5.3-5.4: the reference's elastic state is host-RAM only).

The training is FULL-batch (identical fixed data on every rank), so the
averaged gradient — and therefore the whole loss trajectory — is
world-size-invariant: after any kill/grow world-size change, the restored
run must produce EXACTLY the losses an uninterrupted run would have.  The
test asserts that merged (step -> loss) records from all incarnations
agree, which only holds if params AND adam moments round-trip through
orbax across np=4 -> np=2 -> np=4.

Env knobs: HVDTPU_TEST_STATE/LOG/CKPT, HVDTPU_TEST_KILL (rank 2 crashes at
step 4 in the first np=4 incarnation), HVDTPU_TEST_TOTAL,
HVDTPU_TEST_STEP_DELAY.
"""

import os
import sys
import time

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.elastic as hvd_elastic  # noqa: E402
from horovod_tpu.elastic import FileBackedState  # noqa: E402
from horovod_tpu.utils.checkpoint import Checkpointer  # noqa: E402

KILL_STEP = 4


def log_line(path: str, text: str) -> None:
    with open(path, "a") as f:
        f.write(text + "\n")


def build():
    rng = np.random.RandomState(7)
    X = jnp.asarray(rng.randn(32, 4), jnp.float32)
    y = jnp.asarray(rng.randn(32, 1), jnp.float32)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {"w1": jax.random.normal(k1, (4, 8)) * 0.5,
              "b1": jnp.zeros((8,)),
              "w2": jax.random.normal(k2, (8, 1)) * 0.5,
              "b2": jnp.zeros((1,))}

    def loss_fn(p):
        h = jnp.tanh(X @ p["w1"] + p["b1"])
        pred = h @ p["w2"] + p["b2"]
        return jnp.mean((pred - y) ** 2)

    return params, loss_fn


def main() -> int:
    log_path = os.environ["HVDTPU_TEST_LOG"]
    ckpt_dir = os.environ["HVDTPU_TEST_CKPT"]
    total = int(os.environ.get("HVDTPU_TEST_TOTAL", "12"))
    delay = float(os.environ.get("HVDTPU_TEST_STEP_DELAY", "0"))
    kill = os.environ.get("HVDTPU_TEST_KILL") == "1"
    hvd.init()
    me, n = hvd.rank(), hvd.size()

    params, loss_fn = build()
    tx = optax.adam(5e-2)
    opt_state = tx.init(params)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    ckpt = Checkpointer(ckpt_dir, max_to_keep=2, single_process=True,
                        read_only=me != 0)
    # orbax in a jax.distributed job refuses host-local jax.Arrays, so the
    # tree crosses the checkpoint boundary as numpy (jit re-devices it).
    as_np = lambda tree: jax.tree.map(np.asarray, tree)
    start_step = 0
    latest = ckpt.latest_step()
    if latest is not None:
        restored = ckpt.restore(latest, target=as_np(
            {"params": params, "opt_state": opt_state, "step": 0}))
        params, opt_state = restored["params"], restored["opt_state"]
        start_step = int(restored["step"])
    # Elastic bookkeeping state (epoch checks / restart codes live here).
    state = FileBackedState(os.environ["HVDTPU_TEST_STATE"],
                            step=start_step)
    state.step = max(state.step, start_step)
    log_line(log_path, f"START rank={me} size={n} resume_step={state.step}")

    @hvd_elastic.run
    def train(state):
        nonlocal params, opt_state
        for step in range(state.step, total):
            if (kill and n == 4 and me == 2 and step == KILL_STEP
                    and start_step == 0):
                log_line(log_path, f"CRASH rank={me} step={step}")
                os._exit(7)
            if delay:
                time.sleep(delay)
            loss, grads = grad_fn(params)
            # Engine-negotiated gradient averaging (full-batch data ->
            # averaging is a no-op numerically, any world size).
            flat, tree = jax.tree.flatten(grads)
            outs = hvd.grouped_allreduce(
                [hvd.from_local(np.asarray(g)[None]) for g in flat],
                hvd.Average)
            # to_numpy returns this rank's payload with the leading
            # per-rank dim already stripped.
            grads = jax.tree.unflatten(
                tree, [jnp.asarray(hvd.to_numpy(o)) for o in outs])
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if me == 0:
                ckpt.save(step + 1, as_np({"params": params,
                                           "opt_state": opt_state,
                                           "step": step + 1}))
            state.step = step + 1
            state.commit()
            log_line(log_path,
                     f"STEP rank={me} size={n} step={step} "
                     f"loss={float(loss):.8f}")
        return params

    train(state)
    hvd.shutdown()
    log_line(log_path, f"DONE rank={me} size={n} step={state.step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
