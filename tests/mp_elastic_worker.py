"""Worker for the elastic-CLI end-to-end test.

Trains a toy "model" (a scalar advanced by negotiated allreduce) for
TOTAL_STEPS, committing a :class:`FileBackedState` each step.  When run at
size 2, rank 1 hard-crashes at step 3 *before* that step's collective —
the launcher sees the nonzero exit, the ElasticDriver blacklists the
crashed worker's host and relaunches at np=1, and the surviving worker
resumes from the last committed step.  † ``test/integration/elastic``
kill-a-worker scripts; the TPU adaptation restarts the job rather than
patching the ring (see :mod:`horovod_tpu.runner.elastic`).

Per-step arithmetic (so the test can assert exact continuity):
``w <- allreduce_sum(w + 1)`` = ``size * (w + 1)`` — any lost or repeated
step changes the final value.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.elastic import FileBackedState  # noqa: E402

TOTAL_STEPS = 6
KILL_STEP = 3


def log_line(path: str, text: str) -> None:
    with open(path, "a") as f:
        f.write(text + "\n")


def main() -> int:
    state_path = os.environ["HVDTPU_TEST_STATE"]
    log_path = os.environ["HVDTPU_TEST_LOG"]
    hvd.init()
    me, n = hvd.rank(), hvd.size()
    state = FileBackedState(state_path, step=0, w=0.0)
    log_line(log_path,
             f"START rank={me} size={n} resume_step={state.step} "
             f"w={state.w}")
    for step in range(state.step, TOTAL_STEPS):
        if n == 2 and me == 1 and step == KILL_STEP:
            log_line(log_path, f"CRASH rank={me} step={step}")
            os._exit(7)
        x = hvd.from_local(np.full((1, 1), state.w + 1.0, np.float32))
        out = hvd.to_numpy(hvd.synchronize(
            hvd.allreduce_async(x, hvd.Sum, name=f"w.{step}")))
        state.w = float(out[0])
        state.step = step + 1
        state.commit()
        log_line(log_path, f"STEP rank={me} size={n} step={step} w={state.w}")
    hvd.shutdown()
    log_line(log_path, f"DONE rank={me} size={n} step={state.step} "
                       f"w={state.w}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
