"""Worker for the elastic-CLI end-to-end tests (shrink AND grow).

Trains a toy "model" (a scalar advanced by negotiated allreduce) under
the real elastic API — ``@hvd.elastic.run`` over a
:class:`FileBackedState` committed each step — so the full protocol runs:
commit → epoch check → ``HostsUpdatedInterrupt`` → restart-code exit
(growth), and ``HorovodInternalError`` → nonzero exit → blacklist +
relaunch (failure).  † ``test/integration/elastic`` worker scripts; the
TPU adaptation restarts the job rather than patching a live ring
(:mod:`horovod_tpu.runner.elastic`).

Env knobs:
- ``HVDTPU_TEST_KILL=1``: at size 2, rank 1 hard-crashes at step 3
  *before* that step's collective (the shrink scenario).
- ``HVDTPU_TEST_STEP_DELAY``: seconds to sleep per step (gives the
  driver's growth watcher time to fire in the grow scenario).
- ``HVDTPU_TEST_TOTAL``: total steps (default 6).

Per-step arithmetic (exact continuity checks):
``w <- allreduce_sum(w + 1)`` = ``size * (w + 1)`` — at size 1, w after
k steps is exactly k, so a grown relaunch must show ``resume w ==
resume_step``.
"""

import os
import sys
import time

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.elastic as hvd_elastic  # noqa: E402
from horovod_tpu.elastic import FileBackedState  # noqa: E402

KILL_STEP = 3


def log_line(path: str, text: str) -> None:
    with open(path, "a") as f:
        f.write(text + "\n")


def main() -> int:
    state_path = os.environ["HVDTPU_TEST_STATE"]
    log_path = os.environ["HVDTPU_TEST_LOG"]
    total = int(os.environ.get("HVDTPU_TEST_TOTAL", "6"))
    delay = float(os.environ.get("HVDTPU_TEST_STEP_DELAY", "0"))
    kill = os.environ.get("HVDTPU_TEST_KILL") == "1"
    hvd.init()
    me, n = hvd.rank(), hvd.size()
    state = FileBackedState(state_path, step=0, w=0.0)
    log_line(log_path,
             f"START rank={me} size={n} resume_step={state.step} "
             f"w={state.w}")

    @hvd_elastic.run
    def train(state):
        for step in range(state.step, total):
            if kill and n == 2 and me == 1 and step == KILL_STEP:
                log_line(log_path, f"CRASH rank={me} step={step}")
                os._exit(7)
            if delay:
                time.sleep(delay)
            x = hvd.from_local(np.full((1, 1), state.w + 1.0, np.float32))
            out = hvd.to_numpy(hvd.synchronize(
                hvd.allreduce_async(x, hvd.Sum, name=f"w.{step}")))
            state.w = float(out[0])
            state.step = step + 1
            state.commit()   # durable save, then epoch check (may exit 75)
            log_line(log_path,
                     f"STEP rank={me} size={n} step={step} w={state.w}")
        return state.w

    train(state)
    hvd.shutdown()
    log_line(log_path, f"DONE rank={me} size={n} step={state.step} "
                       f"w={state.w}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
