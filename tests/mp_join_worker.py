"""Worker for the uneven-input join() e2e test († test_horovod_join).

Rank 0 has 3 batches, rank 1 has 5: after step 3 rank 0 calls join() and
participates as zeros while rank 1 finishes; both processes terminate
cleanly and every allreduce result is checked against the uneven-input
semantics († RequestType::JOIN — Average divides by the full world size
including joined ranks).
"""

import sys

from horovod_tpu.utils.cpurig import force_cpu_platform

force_cpu_platform(1)

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def main() -> int:
    hvd.init()
    me = hvd.cross_rank()
    n = hvd.size()
    assert n == 2, f"join worker expects 2 ranks, got {n}"

    my_steps = 3 if me == 0 else 5
    for step in range(my_steps):
        x = hvd.from_local(np.full((1, 4), float(me + 1 + step), np.float32))
        out = hvd.to_numpy(hvd.allreduce(x, hvd.Average, process_set=None))
        if step < 3:
            want = np.mean([r + 1 + step for r in range(n)])
        else:
            # Rank 0 joined: contributes zeros, Average still divides by n.
            want = (1 + 1 + step) / n
        assert np.allclose(out, want), (me, step, out, want)

    last = hvd.join(timeout=60)
    assert last == 1, f"rank {me}: expected last joiner 1, got {last}"
    print(f"rank {me}: JOIN-OK last={last}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
