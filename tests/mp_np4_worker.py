"""np=4 worker exercising the hard negotiated paths (round-2 verdict #5).

Modes (``HVDTPU_TEST_MODE``):

- ``train`` (default): fused/grouped allreduce over the real negotiated
  transport, a process-set collective over ranks {0, 2} (readiness counts
  member coverage only — the controller's per-tensor member list), and a
  closing barrier.
- ``stall``: ranks 0-2 submit a tensor rank 3 never does (the classic
  rank-dependent-conditional divergence † stall_inspector.cc); every
  submitting rank must get the stall warning followed by a
  HorovodInternalError shutdown, while the diverged rank exits cleanly.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def train_mode(me: int, n: int) -> int:
    # 1. Many async allreduces in one burst: the cycle thread fuses them
    # into grouped dispatches negotiated across all 4 processes.
    hs = [hvd.allreduce_async(
        hvd.from_local(np.full((1, 5), float(me + i), np.float32)),
        hvd.Average, name=f"grad.{i}") for i in range(8)]
    for i, h in enumerate(hs):
        got = hvd.to_numpy(hvd.synchronize(h))
        want = np.mean([r + i for r in range(n)])
        assert np.allclose(got, want), (i, got, want)

    # 2. Process-set collective over ranks {0, 2}: only members submit;
    # the controller must mark it ready on member coverage alone.
    ps = hvd.add_process_set([0, 2])
    if me in (0, 2):
        x = hvd.from_local(
            np.full((1, 3), float(me + 1), np.float32), process_set=ps)
        h = hvd.allreduce_async(x, hvd.Sum, name="ps.grad", process_set=ps)
        got = hvd.to_numpy(hvd.synchronize(h))
        assert np.allclose(got, 4.0), got    # (0+1) + (2+1)
    hvd.remove_process_set(ps)

    # 3. Barrier across the full world closes the phase.
    hvd.barrier()
    print(f"rank {me}: NP4-OK")
    return 0


def stall_mode(me: int, n: int) -> int:
    if me < 3:
        h = hvd.allreduce_async(
            hvd.from_local(np.ones((1, 2), np.float32)),
            name="t.diverged")
        try:
            hvd.synchronize(h)
        except hvd.HorovodInternalError as e:
            assert "stall" in str(e).lower(), e
            print(f"rank {me}: STALL-ERR-OK")
            return 0
        print(f"rank {me}: FAIL no stall error")
        return 1
    # Rank 3 diverged (never submits); it must stay healthy and exit.
    import time
    time.sleep(6.0)
    print(f"rank {me}: STALL-BYSTANDER-OK")
    return 0


def main() -> int:
    hvd.init()
    me, n = hvd.rank(), hvd.size()
    assert n == 4, n
    mode = os.environ.get("HVDTPU_TEST_MODE", "train")
    rc = train_mode(me, n) if mode == "train" else stall_mode(me, n)
    hvd.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
