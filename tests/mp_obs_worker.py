"""Multi-process workers for the distributed observability plane.

Modes (``HVDTPU_TEST_MODE``):

- ``cluster`` (default, np=2): each rank records rank-distinct metric
  traffic and publishes its snapshot; rank 0 aggregates via
  ``hvd.cluster_metrics`` AND over HTTP (``/cluster`` on a live
  endpoint), asserting both ranks' counters appear rank-labeled, the
  cluster sum is right, and the exposition validates.
- ``stall`` (np=4): ranks 0-2 submit an allreduce rank 3 withholds; the
  submitting ranks must see straggler attribution naming rank 3 and the
  tensor — in the shutdown error, and in the
  ``horovod_tpu_straggler{rank,tensor}`` gauge — while rank 3 exits
  cleanly.
"""

import os
import sys
import time
import urllib.request

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.obs import REGISTRY, aggregate, export, server  # noqa: E402


def _cluster_family(snap, name):
    for fam in snap:
        if fam["name"] == name:
            return fam
    return None


def cluster_mode(me: int, n: int) -> int:
    REGISTRY.counter("obs_e2e_events_total", "e2e traffic").inc(me + 1)
    REGISTRY.histogram("obs_e2e_lat_seconds", "e2e latency",
                       buckets=(0.01, 0.1)).observe(0.05)
    assert aggregate.publish_now(), "publisher not armed or KV unreachable"

    if me == 0:
        # Wait (bounded) for rank 1's publish to land, then assert the
        # merged view through the in-process API...
        deadline = time.monotonic() + 30.0
        while True:
            snap = hvd.cluster_metrics()
            fam = _cluster_family(snap, "obs_e2e_events_total")
            ranks = {s["labels"].get("rank", "") for s in fam["samples"]} \
                if fam else set()
            if {"0", "1"} <= ranks:
                break
            assert time.monotonic() < deadline, \
                f"rank 1 snapshot never appeared (saw {ranks})"
            time.sleep(0.2)
        by_rank = {s["labels"]["rank"]: s["value"] for s in fam["samples"]
                   if "rank" in s["labels"]}
        assert by_rank["0"] == 1.0 and by_rank["1"] == 2.0, by_rank
        [total] = [s["value"] for s in fam["samples"]
                   if "rank" not in s["labels"]]
        assert total == 3.0, total
        # build_info self-identification from BOTH ranks, world size 2.
        bi = _cluster_family(snap, "horovod_tpu_build_info")
        bi_ranks = {s["labels"]["rank"] for s in bi["samples"]
                    if s["value"] == 1.0}
        assert {"0", "1"} <= bi_ranks, bi["samples"]
        assert all(s["labels"]["size"] == "2" for s in bi["samples"]
                   if s["value"] == 1.0), bi["samples"]
        # ...and through the HTTP endpoint (the acceptance path).
        srv = server.MetricsServer(0, addr="127.0.0.1")
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/cluster",
                timeout=10).read().decode()
        finally:
            srv.close()
        export.validate_prometheus(text)
        assert 'obs_e2e_events_total{rank="0"} 1' in text, text
        assert 'obs_e2e_events_total{rank="1"} 2' in text, text
        assert "obs_e2e_events_total 3" in text, text
        assert "obs_e2e_lat_seconds_count 2" in text, text  # bucket merge
        assert "horovod_tpu_cluster_ranks_reporting 2" in text, text
        # Per-rank engine series prove real-subsystem metrics aggregate
        # too, not just test-local families.
        assert 'hvd_negotiate_wait_seconds_count{rank="1"}' in text, text
    hvd.barrier()
    print(f"rank {me}: CLUSTER-OK")
    return 0


def stall_mode(me: int, n: int) -> int:
    if me < 3:
        h = hvd.allreduce_async(
            hvd.from_local(np.ones((1, 2), np.float32)),
            name="t.straggle")
        try:
            hvd.synchronize(h)
        except hvd.HorovodInternalError as e:
            msg = str(e)
            assert "t.straggle" in msg, msg
            # The shutdown error must name the exact withholding rank.
            assert "awaiting rank(s) 3" in msg, msg
            text = hvd.metrics("prometheus")
            assert 'horovod_tpu_straggler{rank="3",tensor="t.straggle"}' \
                in text, text
            print(f"rank {me}: STRAGGLER-OK")
            return 0
        print(f"rank {me}: FAIL no stall error")
        return 1
    time.sleep(6.0)
    print(f"rank {me}: STRAGGLER-BYSTANDER-OK")
    return 0


def main() -> int:
    hvd.init()
    me, n = hvd.cross_rank(), hvd.cross_size()
    mode = os.environ.get("HVDTPU_TEST_MODE", "cluster")
    rc = cluster_mode(me, n) if mode == "cluster" else stall_mode(me, n)
    hvd.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
