"""Multi-process workers for the distributed observability plane.

Modes (``HVDTPU_TEST_MODE``):

- ``cluster`` (default, np=2): each rank records rank-distinct metric
  traffic, runs an SLO evaluation and a sampled trace, and publishes
  its snapshot; rank 0 aggregates via ``hvd.cluster_metrics`` AND over
  HTTP (``/cluster`` on a live endpoint), asserting both ranks'
  counters appear rank-labeled, the cluster sum is right, SLO gauges
  and trace counters aggregated from both ranks, ``/healthz`` answers
  ready, and the exposition validates.
- ``stall`` (np=4): ranks 0-2 submit an allreduce rank 3 withholds; the
  submitting ranks must see straggler attribution naming rank 3 and the
  tensor — in the shutdown error, and in the
  ``horovod_tpu_straggler{rank,tensor}`` gauge — while rank 3 exits
  cleanly.
- ``flightrec`` (np=2): rank 0 submits an allreduce rank 1 withholds
  until stall shutdown; the engine must auto-dump a flight-recorder
  bundle (dir from ``HVDTPU_FLIGHT_RECORDER_DIR``) whose stall
  attribution names rank 1 — missing-rank list AND bitmap — next to the
  event ring and the registry snapshot.
- ``tsdb`` (np=2): the time-series tier end to end — both ranks breach
  an ``HVDTPU_ALERTS`` rule, rank 0 asserts the firing alert on
  ``/alertz``, rank-labeled ``hvd_alerts_firing`` from BOTH ranks on
  ``/cluster``, ``/query`` answers over the local sampled history AND
  the fleet history fed by the merges, and a flight-recorder bundle
  carries the ``alert_fired`` event + the curated tsdb tail.
- ``chaos`` (np=2): /healthz under injected faults.  Rank 1 arms a
  chaos spec delaying its negotiation check-in 2.5s; rank 0 (with
  ``HVDTPU_HEALTH_MAX_NEGOTIATION_AGE=1``) must observe its own
  ``/healthz`` transition 200 → 503 (the stall) → 200 (recovery).
  Rank 0 then takes an injected serving-step fault and must observe
  503 again through the serving drain window and 200 after the
  session recovers, with the aborted request carrying
  ``finish_reason="error"``; finally rank 1's
  ``hvd_faults_injected_total{site="negotiate",kind="delay"}`` must
  arrive rank-labeled on the aggregated ``/cluster`` view.
"""

import glob
import json
import os
import sys
import time
import urllib.request

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.obs import REGISTRY, aggregate, export, server  # noqa: E402


def _cluster_family(snap, name):
    for fam in snap:
        if fam["name"] == name:
            return fam
    return None


def _serving_trace_e2e() -> None:
    """Rank 0's acceptance half: one tiny serving request under an armed
    Timeline v2 must produce one connected trace — QUEUE/PREFILL/DECODE
    spans sharing a trace id, flow-arrow-chained on the request lane."""
    import tempfile

    import jax

    from horovod_tpu import serving
    from horovod_tpu.models import llama

    tl_path = os.path.join(tempfile.mkdtemp(prefix="hvdtpu_obs_"),
                           "tl_rank0.json")
    hvd.start_timeline(tl_path)
    try:
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        with serving.serve(params, cfg, num_blocks=16, block_size=8,
                           max_active=2) as sess:
            fut = sess.submit(np.arange(5, dtype=np.int32), max_tokens=4)
            sess.drain()
            res = fut.result(timeout=60)
            tr = sess.request_trace(res.metrics["req_id"])
    finally:
        hvd.stop_timeline()
    assert tr is not None, "request was not traced at sample rate 1.0"
    names = {s["name"] for s in tr["spans"]}
    assert {"QUEUE", "PREFILL", "DECODE", "serving.request"} <= names, names
    assert {s["trace_id"] for s in tr["spans"]} == {tr["trace_id"]}
    [root] = [s for s in tr["spans"] if s["parent_id"] is None]
    assert all(s["parent_id"] == root["span_id"] for s in tr["spans"]
               if s["parent_id"] is not None), tr["spans"]
    with open(tl_path) as fh:
        events = json.load(fh)
    xs = [e for e in events if e.get("ph") == "X"
          and e.get("args", {}).get("trace_id") == tr["trace_id"]]
    assert {e["name"] for e in xs} >= {"QUEUE", "PREFILL", "DECODE"}, \
        [e["name"] for e in xs]
    links = [e for e in events if e.get("name") == "hvd.link"]
    assert {e["ph"] for e in links} >= {"s", "f"}, links


def _perf_observatory(me: int, n: int) -> None:
    """Drive the three acceptance verbs — monolithic allreduce,
    decomposed rs_ag allreduce, alltoall — through the real negotiated
    engine, then assert the perf model's expected-vs-achieved
    attribution locally; rank 0 re-asserts it rank-labeled on /cluster
    (the gauges ride the same published snapshot)."""
    from horovod_tpu.obs import perfmodel

    cfg = hvd.global_state().config
    numel = 4096
    payload = numel * 4

    def _ar(tag):
        h = hvd.allreduce_async(
            hvd.from_local(np.ones((1, numel), np.float32)),
            hvd.Sum, name=f"perf.{tag}")
        assert np.ravel(hvd.to_numpy(hvd.synchronize(h)))[0] == float(n)

    _ar("mono")
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    try:
        _ar("dec")
    finally:
        cfg.sched_mode = "monolithic"
    a2a = hvd.alltoall([np.full((n, 3), float(me + 1), np.float32)],
                       splits=np.array([[1] * n], np.int32))
    assert np.asarray(a2a[0]).shape == (n, 3), a2a

    # Local attribution: one summary row per (verb, schedule), with the
    # analytic ring wire bytes (2*(n-1)/n of the payload for allreduce).
    rows = {(r["verb"], r["schedule"]): r for r in perfmodel.MODEL.summary()}
    ar = rows[("allreduce", "monolithic")]
    assert ar["n"] == n and ar["payload_bytes"] == payload, ar
    assert ar["expected_wire_bytes"] == 2 * (n - 1) / n * payload, ar
    assert ar["expected_steps"] == 2 * (n - 1), ar
    dec = rows[("allreduce", "rs_ag:2")]
    assert dec["expected_wire_bytes"] == ar["expected_wire_bytes"], dec
    assert dec["expected_steps"] == 2 * (n - 1) * 2, dec
    a2 = rows[("alltoall", "monolithic")]
    assert a2["expected_wire_bytes"] == (n - 1) / n * a2["payload_bytes"]
    for r in rows.values():
        assert 0.0 < r["efficiency"] <= 1.0 and r["basis"] == "peak", r
    # ...and on the local exposition (label order is alphabetical).
    text = hvd.metrics("prometheus")
    for want in (
            'hvd_perf_efficiency{mode="fp32",schedule="monolithic",'
            'tier="flat",verb="allreduce"}',
            'hvd_perf_efficiency{mode="fp32",schedule="rs_ag:2",'
            'tier="flat",verb="allreduce"}',
            'hvd_perf_efficiency{mode="fp32",schedule="monolithic",'
            'tier="flat",verb="alltoall"}'):
        assert want in text, (want, text)


def cluster_mode(me: int, n: int) -> int:
    from horovod_tpu.obs import slo, trace

    REGISTRY.counter("obs_e2e_events_total", "e2e traffic").inc(me + 1)
    REGISTRY.histogram("obs_e2e_lat_seconds", "e2e latency",
                       buckets=(0.01, 0.1)).observe(0.05)
    # SLO engine armed at init() from HVDTPU_SLO (set in main); force a
    # deterministic tick+evaluate so gauges exist before the publish.
    st = slo.status()
    assert "e2e" in st and st["e2e"]["met"], st
    # One sampled trace per rank (hvd_traces_total sums to 2): rank 0
    # runs the full serving acceptance chain when the launcher asks for
    # it (HVDTPU_OBS_SERVING_E2E=1 — the slow-marked e2e; the tiny-llama
    # compile dominates this worker's runtime), a manual span pair
    # otherwise.
    if me == 0 and os.environ.get("HVDTPU_OBS_SERVING_E2E") == "1":
        _serving_trace_e2e()
    else:
        sp = trace.start_trace("e2e.ping", lane=f"ping{me}")
        sp.child("QUEUE").end()
        sp.end()
        assert trace.export()["trace_id"] == sp.trace_id
    _perf_observatory(me, n)
    assert aggregate.publish_now(), "publisher not armed or KV unreachable"

    if me == 0:
        # Wait (bounded) for rank 1's publish to land, then assert the
        # merged view through the in-process API...
        deadline = time.monotonic() + 30.0
        while True:
            snap = hvd.cluster_metrics()
            fam = _cluster_family(snap, "obs_e2e_events_total")
            ranks = {s["labels"].get("rank", "") for s in fam["samples"]} \
                if fam else set()
            if {"0", "1"} <= ranks:
                break
            assert time.monotonic() < deadline, \
                f"rank 1 snapshot never appeared (saw {ranks})"
            time.sleep(0.2)
        by_rank = {s["labels"]["rank"]: s["value"] for s in fam["samples"]
                   if "rank" in s["labels"]}
        assert by_rank["0"] == 1.0 and by_rank["1"] == 2.0, by_rank
        [total] = [s["value"] for s in fam["samples"]
                   if "rank" not in s["labels"]]
        assert total == 3.0, total
        # build_info self-identification from BOTH ranks, world size 2.
        bi = _cluster_family(snap, "horovod_tpu_build_info")
        bi_ranks = {s["labels"]["rank"] for s in bi["samples"]
                    if s["value"] == 1.0}
        assert {"0", "1"} <= bi_ranks, bi["samples"]
        assert all(s["labels"]["size"] == "2" for s in bi["samples"]
                   if s["value"] == 1.0), bi["samples"]
        # ...and through the HTTP endpoint (the acceptance path).
        srv = server.MetricsServer(0, addr="127.0.0.1")
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/cluster",
                timeout=10).read().decode()
        finally:
            srv.close()
        export.validate_prometheus(text)
        assert 'obs_e2e_events_total{rank="0"} 1' in text, text
        assert 'obs_e2e_events_total{rank="1"} 2' in text, text
        assert "obs_e2e_events_total 3" in text, text
        assert "obs_e2e_lat_seconds_count 2" in text, text  # bucket merge
        assert "horovod_tpu_cluster_ranks_reporting 2" in text, text
        # Per-rank engine series prove real-subsystem metrics aggregate
        # too, not just test-local families.
        assert 'hvd_negotiate_wait_seconds_count{rank="1"}' in text, text
        # SLO gauges from BOTH ranks ride the same snapshot path (the
        # autoscaler/router single-scrape contract), traces counted.
        assert 'hvd_slo_attainment{rank="0",slo="e2e"} 1' in text, text
        assert 'hvd_slo_attainment{rank="1",slo="e2e"} 1' in text, text
        assert 'hvd_slo_burn_rate{rank="0",slo="e2e",window="5m"}' \
            in text, text
        assert 'hvd_slo_burn_rate{rank="1",slo="e2e",window="1h"}' \
            in text, text
        assert 'hvd_traces_total{rank="0",sampled="true"} 1' in text, text
        assert 'hvd_traces_total{rank="1",sampled="true"} 1' in text, text
        assert 'hvd_traces_total{sampled="true"} 2' in text, text
        # Perf-model efficiency gauges from BOTH ranks, per verb and
        # schedule — the acceptance surface for expected-vs-achieved
        # attribution (a straggler = one rank's efficiency under its
        # peers' on the same series).
        for rk in ("0", "1"):
            for verb, sched in (("allreduce", "monolithic"),
                                ("allreduce", "rs_ag:2"),
                                ("alltoall", "monolithic")):
                assert (f'hvd_perf_efficiency{{mode="fp32",rank="{rk}",'
                        f'schedule="{sched}",tier="flat",verb="{verb}"}}'
                        ) in text, (verb, sched, rk, text)
        # /healthz on the same endpoint: ready while the runtime is up.
        srv2 = server.MetricsServer(0, addr="127.0.0.1")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv2.port}/healthz",
                    timeout=10) as resp:
                assert resp.status == 200, resp.status
                hz = json.loads(resp.read().decode())
        finally:
            srv2.close()
        assert hz["ready"] is True and hz["status"] == "ok", hz
        assert hz["rank"] == 0 and hz["size"] == 2, hz
        assert hz["engine_alive"] is True, hz
        assert hz["last_negotiation_age_s"] >= 0.0, hz
    hvd.barrier()
    print(f"rank {me}: CLUSTER-OK")
    return 0


def tsdb_mode(me: int, n: int) -> int:
    """np=2 time-series tier: both ranks breach an HVDTPU_ALERTS rule
    (armed through the real config surface at init), the firing gauges
    ride the snapshot path rank-labeled onto /cluster, /alertz reports
    the firing rule, /query answers over both the local sampled history
    and the fleet history the /cluster merges feed, and a
    flight-recorder bundle carries the alert event + tsdb tail."""
    import tempfile
    import urllib.parse

    from horovod_tpu.obs import alerts, flightrec, tsdb

    def query_json(port, expr, source="local"):
        url = (f"http://127.0.0.1:{port}/query.json?source={source}"
               "&expr=" + urllib.parse.quote(expr))
        return json.loads(urllib.request.urlopen(url, timeout=10)
                          .read().decode())

    # Rank-distinct gauge past the alert threshold (>5) + a counter
    # driven between two sampler ticks so rate() has a real slope.
    REGISTRY.gauge("obs_e2e_queue", "alert driver").set(6.0 + me)
    ticks = REGISTRY.counter("obs_e2e_ticks_total", "rate driver")
    ticks.inc(5)
    assert tsdb.sample_now() > 0, "tsdb sampler not armed at init"
    time.sleep(0.15)
    ticks.inc(5)
    tsdb.sample_now()
    # Alert engine ticks on its own daemon cadence (0.1s here); wait
    # bounded for pending->firing, then make sure the firing gauge is
    # in the published snapshot.
    deadline = time.monotonic() + 30.0
    while True:
        st = alerts.status()
        states = {a["alert"]: a["state"] for a in st["alerts"]} if st \
            else {}
        if states.get("e2e_queue") == "firing":
            break
        assert time.monotonic() < deadline, \
            f"alert never fired on rank {me}: {st}"
        time.sleep(0.05)
    tsdb.sample_now()
    assert aggregate.publish_now(), "publisher not armed or KV unreachable"

    if me == 0:
        # Local surfaces first: /alertz + /query on a live endpoint.
        srv = server.MetricsServer(0, addr="127.0.0.1")
        try:
            az = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/alertz.json",
                timeout=10).read().decode())
            assert az["firing"] == 1, az
            [rule] = [a for a in az["alerts"]
                      if a["alert"] == "e2e_queue"]
            assert rule["state"] == "firing" and \
                rule["severity"] == "crit", rule
            res = query_json(srv.port, "obs_e2e_queue")
            assert res["series"][0]["value"] == 6.0, res
            res = query_json(srv.port, "rate(obs_e2e_ticks_total[1m])")
            assert res["series"] and res["series"][0]["value"] > 0, res
            # Fleet history: wait for rank 1's snapshot, then /cluster
            # must carry BOTH ranks' firing gauges rank-labeled, and
            # every merge fed the cluster store /query reads.
            deadline = time.monotonic() + 30.0
            while True:
                snap = hvd.cluster_metrics()
                fam = _cluster_family(snap, "hvd_alerts_firing")
                firing = {s["labels"].get("rank"): s["value"]
                          for s in (fam["samples"] if fam else [])
                          if s["labels"].get("alert") == "e2e_queue"
                          and "rank" in s["labels"]}
                if firing.get("0") == 1.0 and firing.get("1") == 1.0:
                    break
                assert time.monotonic() < deadline, \
                    f"firing gauges never aggregated: {fam}"
                time.sleep(0.2)
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/cluster",
                timeout=10).read().decode()
            export.validate_prometheus(text)
            for rk in ("0", "1"):
                assert (f'hvd_alerts_firing{{alert="e2e_queue",'
                        f'rank="{rk}",severity="crit"}} 1') in text, text
            res = query_json(srv.port, 'obs_e2e_queue{rank="1"}',
                             source="cluster")
            assert res["series"] and res["series"][0]["value"] == 7.0, res
        finally:
            srv.close()
        # Flight-recorder bundle: the fired alert is on the record —
        # event, firing gauge in the metrics snapshot, AND the curated
        # tsdb tail shows the series leading up to it.
        path = os.path.join(tempfile.mkdtemp(prefix="hvdtpu_tsdb_"),
                            "bundle.json")
        assert flightrec.RECORDER.dump(path, reason="manual") == path
        with open(path) as fh:
            b = json.load(fh)
        assert any(e["kind"] == "alert_fired"
                   and e["name"] == "e2e_queue" for e in b["events"]), \
            [e["kind"] for e in b["events"]]
        firing_fam = _cluster_family(b["metrics"], "hvd_alerts_firing")
        assert firing_fam and any(
            s["labels"].get("alert") == "e2e_queue" and s["value"] == 1
            for s in firing_fam["samples"]), firing_fam
        tails = {s["name"]: s for s in b["tsdb"]["series"]}
        assert "hvd_alerts_firing" in tails, b["tsdb"]
        assert tails["hvd_alerts_firing"]["points"][-1][1] == 1.0, tails
    hvd.barrier()
    print(f"rank {me}: TSDB-OK")
    return 0


def _healthz_code(port: int) -> int:
    import urllib.error
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def _wait_healthz(port: int, want: int, deadline_s: float = 30.0) -> None:
    deadline = time.monotonic() + deadline_s
    seen = []
    while time.monotonic() < deadline:
        code = _healthz_code(port)
        seen.append(code)
        if code == want:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"healthz never answered {want} (saw {sorted(set(seen))})")


def chaos_mode(me: int, n: int) -> int:
    from horovod_tpu import chaos

    hvd.barrier()
    if me == 1:
        # Give rank 0 a beat to start polling, then stall our next
        # negotiation check-in for 2.5s — rank 0 blocks in the round
        # barrier and its negotiation age crosses the 1s health limit.
        time.sleep(0.3)
        chaos.arm("negotiate:delay=2500ms:times=1")
        hvd.barrier()              # reached only after the stall clears
        hvd.barrier()              # rank 0's serving pass
        hvd.barrier()              # rank 0's /cluster check: exiting
        # earlier would retract this rank's snapshot mid-aggregation
        print(f"rank {me}: CHAOS-STALLER-OK")
        return 0

    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        assert _healthz_code(srv.port) == 200
        # -- injected negotiation stall: 200 -> 503 -> 200 ------------
        _wait_healthz(srv.port, 503)
        _wait_healthz(srv.port, 200)
        hvd.barrier()

        # -- injected serving fault: 503 through the drain window -----
        import jax
        from horovod_tpu import serving
        from horovod_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        sess = serving.serve(params, cfg, num_blocks=16, block_size=8,
                             max_active=2, recovery_pause_s=0.75)
        with sess:
            chaos.arm("serving_step:err:after=2:times=1")
            try:
                fut = sess.submit(np.arange(4, dtype=np.int32),
                                  max_tokens=8)
                sess.start()
                _wait_healthz(srv.port, 503)
                _wait_healthz(srv.port, 200)
                res = fut.result(timeout=60)
                assert res.metrics["finish_reason"] == "error", res.metrics
                assert sess.recoveries == 1
            finally:
                chaos.disarm()
        hvd.barrier()

        # -- the injected fault is visible on /cluster, rank-labeled --
        deadline = time.monotonic() + 30.0
        while True:
            snap = hvd.cluster_metrics()
            fam = _cluster_family(snap, "hvd_faults_injected_total")
            hit = [s for s in (fam["samples"] if fam else [])
                   if s["labels"].get("rank") == "1"
                   and s["labels"].get("site") == "negotiate"
                   and s["labels"].get("kind") == "delay"]
            if hit and hit[0]["value"] == 1.0:
                break
            assert time.monotonic() < deadline, \
                f"rank 1's injected fault never aggregated: {fam}"
            time.sleep(0.2)
        hvd.barrier()              # release rank 1 to exit
    finally:
        srv.close()
    print(f"rank {me}: CHAOS-OK")
    return 0


def stall_mode(me: int, n: int) -> int:
    if me < 3:
        h = hvd.allreduce_async(
            hvd.from_local(np.ones((1, 2), np.float32)),
            name="t.straggle")
        try:
            hvd.synchronize(h)
        except hvd.HorovodInternalError as e:
            msg = str(e)
            assert "t.straggle" in msg, msg
            # The shutdown error must name the exact withholding rank.
            assert "awaiting rank(s) 3" in msg, msg
            text = hvd.metrics("prometheus")
            assert 'horovod_tpu_straggler{rank="3",tensor="t.straggle"}' \
                in text, text
            print(f"rank {me}: STRAGGLER-OK")
            return 0
        print(f"rank {me}: FAIL no stall error")
        return 1
    time.sleep(6.0)
    print(f"rank {me}: STRAGGLER-BYSTANDER-OK")
    return 0


def flightrec_mode(me: int, n: int) -> int:
    frdir = os.environ["HVDTPU_FLIGHT_RECORDER_DIR"]
    if me == 0:
        h = hvd.allreduce_async(
            hvd.from_local(np.ones((1, 2), np.float32)),
            name="t.blackbox")
        try:
            hvd.synchronize(h)
        except hvd.HorovodInternalError:
            pass
        else:
            print("rank 0: FAIL no stall error")
            return 1
        # The auto-dump runs on the engine's cycle thread; the error
        # reaches this thread first.  Wait (bounded) for the atomic
        # os.replace to land.
        deadline = time.monotonic() + 15.0
        while True:
            bundles = sorted(glob.glob(os.path.join(
                frdir, "flightrec-rank0-*-stall_shutdown-*.json")))
            if bundles:
                break
            assert time.monotonic() < deadline, \
                f"no auto-dumped bundle in {os.listdir(frdir)}"
            time.sleep(0.2)
        with open(bundles[-1]) as fh:
            b = json.load(fh)
        assert b["rank"] == 0 and b["size"] == 2, b
        # Stall attribution names the withholding rank — list AND bitmap.
        st = b["stall"]
        assert "t.blackbox" in st, st
        assert st["t.blackbox"]["missing_ranks"] == [1], st
        assert st["t.blackbox"]["missing_rank_bitmap"] == 0b10, st
        assert st["t.blackbox"]["age_ms"] > 0, st
        # The ring carries the causally-preceding events and the bundle
        # carries a full registry snapshot next to them.
        kinds = {e["kind"] for e in b["events"]}
        assert {"dispatch", "stall_warning", "stall_shutdown"} & kinds, \
            kinds
        fams = {f["name"] for f in b["metrics"]}
        assert "hvd_collectives_total" in fams, fams
        assert "hvd_flightrec_events_total" in fams, fams
        print("rank 0: FLIGHTREC-OK")
        return 0
    time.sleep(6.0)
    print(f"rank {me}: FLIGHTREC-BYSTANDER-OK")
    return 0


def main() -> int:
    mode = os.environ.get("HVDTPU_TEST_MODE", "cluster")
    if mode == "cluster":
        # Armed through the real config surface at init(); the threshold
        # sits past the histogram's last finite edge so the 0.05 sample
        # counts good and attainment is exactly 1.0 on both ranks.
        os.environ.setdefault(
            "HVDTPU_SLO", "e2e=p99(obs_e2e_lat_seconds) < 200ms over 5m")
    elif mode == "tsdb":
        # Fast sampler cadence + one alert rule, both through the real
        # config surface — init() arms the tier exactly like production.
        os.environ.setdefault("HVDTPU_TSDB_INTERVAL", "0.1")
        os.environ.setdefault(
            "HVDTPU_ALERTS", "e2e_queue: obs_e2e_queue > 5 : crit")
    hvd.init()
    me, n = hvd.cross_rank(), hvd.cross_size()
    if mode == "cluster":
        rc = cluster_mode(me, n)
    elif mode == "stall":
        rc = stall_mode(me, n)
    elif mode == "flightrec":
        rc = flightrec_mode(me, n)
    elif mode == "chaos":
        rc = chaos_mode(me, n)
    elif mode == "tsdb":
        rc = tsdb_mode(me, n)
    else:
        raise SystemExit(f"unknown HVDTPU_TEST_MODE={mode!r}")
    hvd.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
