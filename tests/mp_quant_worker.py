"""Quantized-allreduce parity over the real negotiated transport.

Run under ``hvdrun -np 2`` (the ci.yaml quantized-parity job) or ``-np 4``:
every rank allreduces the same random gradients at fp32 and at each wire
mode through the async engine (fusion + coordinator-ordered dispatch), and
asserts the quantized results agree with exact numpy within the documented
shared-scale error bound (tests/test_reduction.py derives it).  Also
exercises the negotiation meta's precision field: all ranks must build the
same quantized program or the fused dispatch diverges and the job hangs —
completion IS the assertion for that.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def main() -> int:
    hvd.init()
    me, n = hvd.rank(), hvd.size()
    hvd.global_state().config.quant_min_bytes = 0
    numel = 4096
    # Every rank derives every rank's gradient (seeded) so exact numpy
    # references need no extra collective.
    grads = [np.random.RandomState(100 + r).randn(numel).astype(np.float32)
             for r in range(n)]
    exact_avg = np.stack(grads).mean(0)
    gmax = np.abs(np.stack(grads)).max()

    for mode, tol_div in (("bf16", None), ("int8", 254.0), ("fp8", 16.0)):
        hs = [hvd.allreduce_async(
            hvd.from_local(grads[me][None, i * 1024:(i + 1) * 1024]),
            hvd.Average, name=f"q.{mode}.{i}", compression=mode)
            for i in range(4)]
        got = np.concatenate(
            [hvd.to_numpy(hvd.synchronize(h)) for h in hs])
        if tol_div is None:
            atol = (n + 1) * gmax * 2.0 ** -7
        else:
            atol = 1.5 * (n + 1) * gmax / tol_div
        err = np.abs(got - exact_avg).max()
        assert err <= atol, (mode, err, atol)
        print(f"rank {me}: {mode} parity err={err:.2e} <= {atol:.2e}",
              flush=True)

    # Mixed modes in one cycle: int8 and fp32 entries must split into
    # separate fused groups consistently on every rank (completion proves
    # the cross-rank group composition matched).
    ha = hvd.allreduce_async(hvd.from_local(grads[me][None, :1024]),
                             hvd.Average, name="q.mix.a", compression="int8")
    hb = hvd.allreduce_async(hvd.from_local(grads[me][None, 1024:2048]),
                             hvd.Average, name="q.mix.b")
    hvd.synchronize(ha)
    hvd.synchronize(hb)
    hvd.barrier()
    print(f"rank {me}: QUANT-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
