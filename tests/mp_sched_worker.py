"""Decomposed-allreduce parity over the real negotiated transport.

Run under ``hvdrun -np 2`` and ``-np 4`` (both sizes are the ci.yaml
decomposed-parity job): every rank allreduces the same seeded gradients
through the async engine twice — once monolithic, once with the decomposed
reduce-scatter/allgather schedule (``HOROVOD_TPU_SCHED_MODE``-style
config flip) — and asserts parity:

- **int8/fp8: BIT-exact at any world size.**  By construction — chunk
  boundaries land on the monolithic kernel's block boundaries and the
  narrow accumulator sums exactly, so association order cannot matter.
- **fp32: BIT-exact at np=2** (two-operand float addition is
  commutative), **<= 2 ulp at np>=4**: psum and psum_scatter associate
  the n-way per-element sum in different ring orders, which no schedule
  controls (measured at np=4 on this rig: exactly 1 ulp relative,
  6.8e-8).  Anything beyond the ulp bound is a real bug.

Also exercises the negotiation meta's ``sc`` field two ways:

- mixed schedules in one cycle must split into consistent fusion groups
  on every rank (divergent groups hang, so completion IS the assertion);
- a join phase where rank 0 leaves early and the remaining ranks keep
  issuing decomposed allreduces — the joined rank must rebuild the
  identical chunked program from the echoed meta (schedule + precision)
  or the per-chunk dispatches deadlock.

``HVDTPU_TEST_MODE=hier`` (np=4, ``HVDTPU_HIERARCHICAL_LOCAL_SIZE=2``)
runs the chunked+tiered battery instead: the ``hier:2:2`` descriptor
negotiates over the same transport (a dispatch-counter guard proves the
tiered executor really ran — a silent flat fallback would make parity
vacuous) with the per-family contract:

- **int8: BIT-exact vs flat** (exact int16 block sums are
  order-independent, and tier boundaries land on the same block grid);
- **fp8: bounded, NOT bit-exact** — fp8 accumulates in fp16
  (ops/reduction.py), so flat/tiered agreement only ever came from a
  shared ring order, which tiering changes; the contract is error vs
  the true mean within 2x flat fp8's own quantization error;
- **fp32: normwise <= 2 ulp** (re-associated sum);
- fp32 fast tier + ``HVDTPU_HIERARCHICAL_CROSS_PRECISION=int8`` slow
  hop: bounded vs truth;

plus mixed flat+tiered fusion groups in one cycle, the join/rebuild
path with a tiered ``sc`` descriptor, and rank-labeled
``hvd_perf_tier_*`` gauges on the aggregated ``/cluster`` view.

``HVDTPU_TEST_MODE=compiled`` (np=2 and np=4, the ci.yaml
compiled-parity job) runs the compiled single-program battery instead
— same parity contract as the decomposed one, plus the zero
per-chunk-dispatch guard and a mixed-mode phase where the coordinator's
echoed meta reconciles compiled- and decomposed-pinned ranks onto one
backend (see :func:`main_compiled`).
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def main() -> int:
    from horovod_tpu.ops.sched.executor import _m_sched

    hvd.init()
    me, n = hvd.rank(), hvd.size()
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 0
    # Per-entry size must clear resolve_schedule's quant gate
    # (numel >= 2 * n * quant_block_size) at every tested world size,
    # or the "decomposed" pass silently runs monolithic and the parity
    # assertion compares monolithic to itself.
    entry = max(2048, 2 * n * cfg.quant_block_size)
    numel = 4 * entry
    grads = [np.random.RandomState(200 + r).randn(numel).astype(np.float32)
             for r in range(n)]

    def run(mode, tag):
        hs = [hvd.allreduce_async(
            hvd.from_local(grads[me][None, i * entry:(i + 1) * entry]),
            hvd.Average, name=f"s.{tag}.{i}", compression=mode or None)
            for i in range(4)]
        return np.concatenate(
            [hvd.to_numpy(hvd.synchronize(h)) for h in hs])

    for mode in ("", "int8", "fp8"):
        cfg.sched_mode = "monolithic"
        ref = run(mode, f"mono.{mode or 'fp32'}")
        cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
        before = _m_sched.total()
        got = run(mode, f"dec.{mode or 'fp32'}")
        assert _m_sched.total() > before, (
            f"{mode or 'fp32'}: decomposed pass never hit the schedule "
            "executor (size gate fallback?) — parity would be vacuous")
        if mode or n == 2:
            # Quantized modes: exact narrow sums -> order-free -> bit-
            # exact at ANY n.  fp32 at n=2: two-operand adds commute.
            assert np.array_equal(ref, got), (
                mode or "fp32", np.abs(ref - got).max())
            tag = "bit-exact"
        else:
            # fp32 at n >= 4: ring association order differs between
            # psum and psum_scatter; <= 2 ulp relative is the contract.
            rel = np.abs(ref - got).max() / max(1e-30, np.abs(ref).max())
            assert rel <= 2 * np.finfo(np.float32).eps, rel
            tag = f"ulp-bounded rel={rel:.1e}"
        print(f"rank {me}: {mode or 'fp32'} decomposed {tag}", flush=True)

    # Mixed schedules in one cycle: decomposed and monolithic entries
    # must split into separate fused groups identically on every rank.
    cfg.sched_mode = "decomposed"
    ha = hvd.allreduce_async(hvd.from_local(grads[me][None, :4096]),
                             hvd.Average, name="s.mix.dec")
    cfg.sched_mode = "monolithic"
    hb = hvd.allreduce_async(hvd.from_local(grads[me][None, :64]),
                             hvd.Average, name="s.mix.mono")
    hvd.synchronize(ha)
    hvd.synchronize(hb)

    # Join/rebuild path: rank 0 joins first; survivors keep issuing
    # DECOMPOSED allreduces that become ready through rank 0's fabricated
    # zero participation — rank 0 must rebuild the same rs_ag program
    # from the meta's sc field (completion + value check assert it).
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    steps = 1 if me == 0 else 3
    for step in range(steps):
        x = hvd.from_local(grads[me][None, :4096] + float(step))
        out = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
        if step == 0:
            want = (np.stack([g[:4096] for g in grads]).sum(0)) / n
        else:
            # Rank 0 joined: zeros, Average still divides by n.
            want = sum(g[:4096] + step for g in grads[1:]) / n
        assert np.allclose(out, want, atol=1e-5), (me, step)
    # join() is itself the final synchronization point: every rank
    # returns only once all ranks joined (no barrier after — uneven step
    # counts desynchronize the auto-name counter, same as mp_join_worker).
    last = hvd.join(timeout=120)
    assert last >= 0
    print(f"rank {me}: SCHED-OK", flush=True)
    hvd.shutdown()
    return 0


def main_hier() -> int:
    import time

    from horovod_tpu.obs import aggregate
    from horovod_tpu.ops.sched.executor import _m_sched_child

    hvd.init()
    me, n = hvd.rank(), hvd.size()
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 0
    assert cfg.hierarchical_local_size == 2, \
        "launcher must set HVDTPU_HIERARCHICAL_LOCAL_SIZE=2"
    desc = f"hier:{cfg.hierarchical_local_size}:2"
    entry = max(2048, 2 * n * cfg.quant_block_size)
    numel = 4 * entry
    grads = [np.random.RandomState(300 + r).randn(numel).astype(np.float32)
             for r in range(n)]
    truth = np.stack(grads).mean(0)
    eps = np.finfo(np.float32).eps

    def run(mode, tag):
        hs = [hvd.allreduce_async(
            hvd.from_local(grads[me][None, i * entry:(i + 1) * entry]),
            hvd.Average, name=f"h.{tag}.{i}", compression=mode or None)
            for i in range(4)]
        return np.concatenate(
            [hvd.to_numpy(hvd.synchronize(h)) for h in hs])

    for mode in ("", "int8", "fp8"):
        cfg.hierarchical_allreduce = False
        cfg.sched_mode = "monolithic"
        ref = run(mode, f"mono.{mode or 'fp32'}")
        cfg.hierarchical_allreduce = True
        cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
        before = _m_sched_child(desc).value
        got = run(mode, f"tier.{mode or 'fp32'}")
        assert _m_sched_child(desc).value > before, (
            f"{mode or 'fp32'}: tiered pass never dispatched {desc} "
            "(flat fallback?) — parity would be vacuous")
        if mode == "int8":
            assert np.array_equal(ref, got), (
                "int8", np.abs(ref - got).max())
            tag = "bit-exact"
        elif mode == "fp8":
            flat_err = np.abs(ref - truth).max()
            hier_err = np.abs(got - truth).max()
            assert flat_err > 0 and hier_err <= 2 * flat_err, (
                hier_err, flat_err)
            tag = f"bounded err={hier_err:.1e} (flat {flat_err:.1e})"
        else:
            rel = np.abs(ref - got).max() / max(1e-30, np.abs(ref).max())
            assert rel <= 2 * eps, rel
            tag = f"ulp-bounded rel={rel:.1e}"
        print(f"rank {me}: {mode or 'fp32'} tiered {tag}", flush=True)

    # fp32 fast tier + quantized DCN hop: the cross precision rides
    # synchronized config (not the descriptor), so every rank resolves
    # the same mixed-mode program.
    cfg.hierarchical_cross_precision = "int8"
    before = _m_sched_child(desc).value
    got = run("", "xprec")
    assert _m_sched_child(desc).value > before
    err = np.abs(got - truth).max()
    assert 0 < err < 0.1, err
    cfg.hierarchical_cross_precision = ""
    print(f"rank {me}: cross-precision bounded err={err:.1e}", flush=True)

    # Mixed tiered + flat-decomposed + monolithic entries in one cycle:
    # the schedule joins the fusion key, so the three families must
    # split into consistent groups on every rank (divergence hangs).
    cfg.sched_mode = "decomposed"
    ha = hvd.allreduce_async(hvd.from_local(grads[me][None, :4096]),
                             hvd.Average, name="h.mix.tier")
    cfg.hierarchical_allreduce = False
    hb = hvd.allreduce_async(hvd.from_local(grads[me][None, :4096]),
                             hvd.Average, name="h.mix.flat")
    cfg.sched_mode = "monolithic"
    hc = hvd.allreduce_async(hvd.from_local(grads[me][None, :64]),
                             hvd.Average, name="h.mix.mono")
    for h in (ha, hb, hc):
        hvd.synchronize(h)

    # Every rank's tiered observations must reach the aggregated cluster
    # view rank-labeled (the CI hierarchical-parity job's obs half).
    # This phase runs BEFORE the join phase: rank 0 must scrape while
    # its peers are still alive (shutdown retracts their KV snapshots),
    # and the post-poll barrier is only safe while every rank's
    # auto-name counter still agrees (join leaves them uneven).
    assert aggregate.publish_now(), "publisher not armed or KV unreachable"
    if me == 0:
        deadline = time.monotonic() + 30.0
        while True:
            snap = hvd.cluster_metrics()
            fam = next((f for f in snap
                        if f["name"] == "hvd_perf_tier_excess_seconds"),
                       None)
            ranks = {s["labels"].get("rank", "") for s in fam["samples"]} \
                if fam else set()
            if {str(r) for r in range(n)} <= ranks:
                break
            assert time.monotonic() < deadline, \
                f"tier gauges never aggregated (saw {ranks})"
            time.sleep(0.2)
        tiers = {s["labels"].get("tier") for s in fam["samples"]}
        assert {"local", "cross"} <= tiers, tiers
        eff = next(f for f in snap if f["name"] == "hvd_perf_efficiency")
        scheds = {s["labels"].get("schedule") for s in eff["samples"]}
        assert desc in scheds, scheds
    hvd.barrier()

    # Join/rebuild with a tiered descriptor riding the sc field: rank 0
    # joins first and must reconstruct the same hier:<n_local>:<k>
    # program from the echoed meta for the survivors' allreduces.
    cfg.hierarchical_allreduce = True
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    steps = 1 if me == 0 else 3
    for step in range(steps):
        x = hvd.from_local(grads[me][None, :4096] + float(step))
        out = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
        if step == 0:
            want = (np.stack([g[:4096] for g in grads]).sum(0)) / n
        else:
            want = sum(g[:4096] + step for g in grads[1:]) / n
        assert np.allclose(out, want, atol=1e-5), (me, step)
    last = hvd.join(timeout=120)
    assert last >= 0
    print(f"rank {me}: HIER-OK", flush=True)
    hvd.shutdown()
    return 0


def main_compiled() -> int:
    """Compiled single-program backend over the negotiated transport.

    ``HVDTPU_TEST_MODE=compiled`` (np=2 and np=4 in the ci.yaml
    compiled-parity job).  Same parity contract as the decomposed
    battery — quantized modes bit-exact at any n, fp32 bit-exact at
    np=2 / <= 2 ulp at np>=4 — plus the two compiled-specific
    invariants:

    - the engine's per-chunk dispatch counter NEVER moves: every
      compiled collective is one cached jitted program (the counter is
      checked after each phase and must read 0 at exit);
    - mixed-mode peers converge: one rank pins ``compiled``, another
      ``decomposed``, and the coordinator's lowest-rank-wins echoed
      meta reconciles every process onto ONE descriptor before fusion
      (divergent backends deadlock on per-executable channel IDs, so
      completion + the counter split IS the assertion).
    """
    from horovod_tpu.ops.sched.compiled import _m_compiled
    from horovod_tpu.ops.sched.executor import _m_sched

    hvd.init()
    me, n = hvd.rank(), hvd.size()
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 0
    entry = max(2048, 2 * n * cfg.quant_block_size)
    numel = 4 * entry
    grads = [np.random.RandomState(400 + r).randn(numel).astype(np.float32)
             for r in range(n)]

    def run(mode, tag):
        hs = [hvd.allreduce_async(
            hvd.from_local(grads[me][None, i * entry:(i + 1) * entry]),
            hvd.Average, name=f"c.{tag}.{i}", compression=mode or None)
            for i in range(4)]
        return np.concatenate(
            [hvd.to_numpy(hvd.synchronize(h)) for h in hs])

    for mode in ("", "int8", "fp8"):
        cfg.sched_mode = "monolithic"
        ref = run(mode, f"mono.{mode or 'fp32'}")
        cfg.sched_mode, cfg.sched_chunks = "compiled", 2
        before = _m_compiled.total()
        got = run(mode, f"cmp.{mode or 'fp32'}")
        assert _m_compiled.total() > before, (
            f"{mode or 'fp32'}: compiled pass never hit the compiled "
            "backend (size gate fallback?) — parity would be vacuous")
        if mode or n == 2:
            assert np.array_equal(ref, got), (
                mode or "fp32", np.abs(ref - got).max())
            tag = "bit-exact"
        else:
            rel = np.abs(ref - got).max() / max(1e-30, np.abs(ref).max())
            assert rel <= 2 * np.finfo(np.float32).eps, rel
            tag = f"ulp-bounded rel={rel:.1e}"
        assert _m_sched.total() == 0, (
            "compiled battery leaked per-chunk engine dispatches")
        print(f"rank {me}: {mode or 'fp32'} compiled {tag}", flush=True)

    # Mixed-mode fusion group: rank 0 pins compiled, the last rank pins
    # decomposed, everyone else monolithic-defaults to compiled.  The
    # coordinator echoes rank 0's meta (lowest-rank-wins), every process
    # adopts it before fusion, and the group dispatches through the
    # compiled backend on ALL ranks — including the one that asked for
    # the per-chunk walk.
    cfg.sched_mode = "decomposed" if me == n - 1 else "compiled"
    cfg.sched_chunks = 2
    before = _m_compiled.total()
    x = hvd.from_local(grads[me][None, :4096])
    h = hvd.allreduce_async(x, hvd.Average, name="c.mixmode")
    out = hvd.to_numpy(hvd.synchronize(h))
    want = np.stack([g[:4096] for g in grads]).mean(0)
    if n == 2:
        assert np.array_equal(out, want)
    else:
        assert np.allclose(out, want, atol=1e-5)
    assert _m_compiled.total() > before, (
        "mixed-mode group did not reconcile onto rank 0's compiled "
        "descriptor")
    assert _m_sched.total() == 0, (
        "decomposed-pinned rank dispatched per-chunk instead of adopting "
        "the echoed compiled descriptor")
    print(f"rank {me}: mixed-mode reconciled to compiled", flush=True)

    # Compiled + monolithic entries in one cycle still split into
    # consistent fusion groups on every rank.
    cfg.sched_mode = "compiled"
    ha = hvd.allreduce_async(hvd.from_local(grads[me][None, :4096]),
                             hvd.Average, name="c.mix.cmp")
    cfg.sched_mode = "monolithic"
    hb = hvd.allreduce_async(hvd.from_local(grads[me][None, :64]),
                             hvd.Average, name="c.mix.mono")
    hvd.synchronize(ha)
    hvd.synchronize(hb)

    # Join/rebuild: rank 0 joins first and must rebuild the SAME compiled
    # program from the echoed meta's sc="compiled:rs_ag:2" field for the
    # survivors' allreduces.
    cfg.sched_mode, cfg.sched_chunks = "compiled", 2
    steps = 1 if me == 0 else 3
    for step in range(steps):
        x = hvd.from_local(grads[me][None, :4096] + float(step))
        out = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
        if step == 0:
            want = (np.stack([g[:4096] for g in grads]).sum(0)) / n
        else:
            want = sum(g[:4096] + step for g in grads[1:]) / n
        assert np.allclose(out, want, atol=1e-5), (me, step)
    last = hvd.join(timeout=120)
    assert last >= 0
    assert _m_sched.total() == 0, (
        "per-chunk dispatch counter moved during the compiled battery")
    print(f"rank {me}: COMPILED-OK", flush=True)
    hvd.shutdown()
    return 0


def main_zero() -> int:
    """ZeRO-1 + bucketed overlap over the negotiated transport.

    ``HVDTPU_TEST_MODE=zero`` (np=2 and np=4, the ci.yaml zero1-parity
    job).  Four phases:

    1. the ZeRO-1 wire pattern as REAL collectives — reduce-scatter the
       gradient to this rank's shard, update 1/n of the parameters
       locally, one parameter allgather — vs the dense step (full
       allreduce + full local update).  Same association contract as
       the decomposed battery: bit-exact at np=2 (two-operand adds
       commute), <= 2 ulp relative at np>=4 (rs+ag re-associates the
       ring sum);
    2. :func:`bucketed_distributed_gradients` parity vs the unbucketed
       engine path, fp32 AND int8, under the decomposed schedule with a
       cap that forces several buckets (per-bucket nudges must not
       change values: entries are block-aligned, so fusion regrouping
       cannot move quant block boundaries — bit-exact both modes);
    3. the compiled zero-dispatch guard: the same bucketed reduction
       under ``sched_mode=compiled`` must ride the single-program
       backend (compiled counter moves) with ZERO new per-chunk engine
       dispatches;
    4. join/rebuild: rank 0 joins first; survivors keep issuing
       bucketed decomposed reductions the joined rank must rebuild from
       the echoed ``sc`` meta (completion + value check assert it).
    """
    from horovod_tpu.ops.sched.compiled import _m_compiled
    from horovod_tpu.ops.sched.executor import _m_sched

    hvd.init()
    me, n = hvd.rank(), hvd.size()
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 0
    # entry is a multiple of quant_block_size, so bucket regrouping in
    # phase 2 never moves a block boundary.
    entry = max(2048, 2 * n * cfg.quant_block_size)
    numel = n * entry
    lr = np.float32(0.1)
    eps = np.finfo(np.float32).eps
    params = np.random.RandomState(7).randn(numel).astype(np.float32)
    grads = [np.random.RandomState(500 + r).randn(numel).astype(np.float32)
             for r in range(n)]

    # -- phase 1: sharded step vs dense step ---------------------------
    g_sum = hvd.to_numpy(hvd.allreduce(
        hvd.from_local(grads[me][None]), hvd.Sum)).reshape(-1)
    p_dense = params - lr * (g_sum / np.float32(n))
    shard_red = hvd.to_local(hvd.reducescatter(
        hvd.from_local(grads[me][None]), hvd.Sum)).reshape(-1)
    my_params = params.reshape(n, entry)[me]
    shard_new = my_params - lr * (shard_red / np.float32(n))
    p_zero = hvd.to_numpy(hvd.allgather(
        hvd.from_local(shard_new[None]))).reshape(-1)
    if n == 2:
        assert np.array_equal(p_dense, p_zero), \
            np.abs(p_dense - p_zero).max()
        tag = "bit-exact"
    else:
        rel = np.abs(p_dense - p_zero).max() / max(
            1e-30, np.abs(p_dense).max())
        assert rel <= 2 * eps, rel
        tag = f"ulp-bounded rel={rel:.1e}"
    print(f"rank {me}: zero1 step {tag}", flush=True)

    # -- phase 2: bucketed eager parity, fp32 + int8 -------------------
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    cap = 2 * entry * 4   # two fp32 entries per bucket -> two buckets
    for mode in (None, "int8"):
        kw = {"compression": hvd.Compression.int8} if mode else {}
        tree = {f"g{i}": hvd.from_local(
            grads[me][None, i * entry:(i + 1) * entry])
            for i in range(4)}
        base = hvd.distributed_gradients(tree, **kw)
        tree = {f"g{i}": hvd.from_local(
            grads[me][None, i * entry:(i + 1) * entry])
            for i in range(4)}
        got = hvd.bucketed_distributed_gradients(tree, bucket_bytes=cap,
                                                 **kw)
        for k in sorted(base):
            b, g = hvd.to_numpy(base[k]), hvd.to_numpy(got[k])
            assert np.array_equal(b, g), (
                mode or "fp32", k, np.abs(b - g).max())
        print(f"rank {me}: {mode or 'fp32'} bucketed bit-exact",
              flush=True)

    # -- phase 3: compiled zero-dispatch guard -------------------------
    cfg.sched_mode, cfg.sched_chunks = "compiled", 2
    sched_before = _m_sched.total()
    before = _m_compiled.total()
    tree = {f"c{i}": hvd.from_local(
        grads[me][None, i * entry:(i + 1) * entry]) for i in range(4)}
    out = hvd.bucketed_distributed_gradients(tree, bucket_bytes=cap)
    want = np.stack(grads).mean(0)
    for i in range(4):
        g = hvd.to_numpy(out[f"c{i}"]).reshape(-1)
        w = want[i * entry:(i + 1) * entry]
        if n == 2:
            assert np.array_equal(g, w)
        else:
            assert np.allclose(g, w, atol=1e-5)
    assert _m_compiled.total() > before, (
        "compiled bucketed pass never hit the compiled backend")
    assert _m_sched.total() == sched_before, (
        "compiled bucketed pass leaked per-chunk engine dispatches")
    print(f"rank {me}: compiled bucketed zero-dispatch", flush=True)

    # -- phase 4: join/rebuild through the bucketed path ---------------
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    steps = 1 if me == 0 else 3
    for step in range(steps):
        tree = {"j": hvd.from_local(grads[me][None, :4096] + float(step))}
        out = hvd.bucketed_distributed_gradients(tree, bucket_bytes=4096)
        got = hvd.to_numpy(out["j"]).reshape(-1)
        if step == 0:
            want = np.stack([g[:4096] for g in grads]).sum(0) / n
        else:
            want = sum(g[:4096] + step for g in grads[1:]) / n
        assert np.allclose(got, want, atol=1e-5), (me, step)
    last = hvd.join(timeout=120)
    assert last >= 0
    print(f"rank {me}: ZERO-OK", flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    if os.environ.get("HVDTPU_TEST_MODE") == "hier":
        sys.exit(main_hier())
    if os.environ.get("HVDTPU_TEST_MODE") == "compiled":
        sys.exit(main_compiled())
    if os.environ.get("HVDTPU_TEST_MODE") == "zero":
        sys.exit(main_zero())
    sys.exit(main())
