"""Decomposed-allreduce parity over the real negotiated transport.

Run under ``hvdrun -np 2`` and ``-np 4`` (both sizes are the ci.yaml
decomposed-parity job): every rank allreduces the same seeded gradients
through the async engine twice — once monolithic, once with the decomposed
reduce-scatter/allgather schedule (``HOROVOD_TPU_SCHED_MODE``-style
config flip) — and asserts parity:

- **int8/fp8: BIT-exact at any world size.**  By construction — chunk
  boundaries land on the monolithic kernel's block boundaries and the
  narrow accumulator sums exactly, so association order cannot matter.
- **fp32: BIT-exact at np=2** (two-operand float addition is
  commutative), **<= 2 ulp at np>=4**: psum and psum_scatter associate
  the n-way per-element sum in different ring orders, which no schedule
  controls (measured at np=4 on this rig: exactly 1 ulp relative,
  6.8e-8).  Anything beyond the ulp bound is a real bug.

Also exercises the negotiation meta's ``sc`` field two ways:

- mixed schedules in one cycle must split into consistent fusion groups
  on every rank (divergent groups hang, so completion IS the assertion);
- a join phase where rank 0 leaves early and the remaining ranks keep
  issuing decomposed allreduces — the joined rank must rebuild the
  identical chunked program from the echoed meta (schedule + precision)
  or the per-chunk dispatches deadlock.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def main() -> int:
    from horovod_tpu.ops.sched.executor import _m_sched

    hvd.init()
    me, n = hvd.rank(), hvd.size()
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 0
    # Per-entry size must clear resolve_schedule's quant gate
    # (numel >= 2 * n * quant_block_size) at every tested world size,
    # or the "decomposed" pass silently runs monolithic and the parity
    # assertion compares monolithic to itself.
    entry = max(2048, 2 * n * cfg.quant_block_size)
    numel = 4 * entry
    grads = [np.random.RandomState(200 + r).randn(numel).astype(np.float32)
             for r in range(n)]

    def run(mode, tag):
        hs = [hvd.allreduce_async(
            hvd.from_local(grads[me][None, i * entry:(i + 1) * entry]),
            hvd.Average, name=f"s.{tag}.{i}", compression=mode or None)
            for i in range(4)]
        return np.concatenate(
            [hvd.to_numpy(hvd.synchronize(h)) for h in hs])

    for mode in ("", "int8", "fp8"):
        cfg.sched_mode = "monolithic"
        ref = run(mode, f"mono.{mode or 'fp32'}")
        cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
        before = _m_sched.total()
        got = run(mode, f"dec.{mode or 'fp32'}")
        assert _m_sched.total() > before, (
            f"{mode or 'fp32'}: decomposed pass never hit the schedule "
            "executor (size gate fallback?) — parity would be vacuous")
        if mode or n == 2:
            # Quantized modes: exact narrow sums -> order-free -> bit-
            # exact at ANY n.  fp32 at n=2: two-operand adds commute.
            assert np.array_equal(ref, got), (
                mode or "fp32", np.abs(ref - got).max())
            tag = "bit-exact"
        else:
            # fp32 at n >= 4: ring association order differs between
            # psum and psum_scatter; <= 2 ulp relative is the contract.
            rel = np.abs(ref - got).max() / max(1e-30, np.abs(ref).max())
            assert rel <= 2 * np.finfo(np.float32).eps, rel
            tag = f"ulp-bounded rel={rel:.1e}"
        print(f"rank {me}: {mode or 'fp32'} decomposed {tag}", flush=True)

    # Mixed schedules in one cycle: decomposed and monolithic entries
    # must split into separate fused groups identically on every rank.
    cfg.sched_mode = "decomposed"
    ha = hvd.allreduce_async(hvd.from_local(grads[me][None, :4096]),
                             hvd.Average, name="s.mix.dec")
    cfg.sched_mode = "monolithic"
    hb = hvd.allreduce_async(hvd.from_local(grads[me][None, :64]),
                             hvd.Average, name="s.mix.mono")
    hvd.synchronize(ha)
    hvd.synchronize(hb)

    # Join/rebuild path: rank 0 joins first; survivors keep issuing
    # DECOMPOSED allreduces that become ready through rank 0's fabricated
    # zero participation — rank 0 must rebuild the same rs_ag program
    # from the meta's sc field (completion + value check assert it).
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    steps = 1 if me == 0 else 3
    for step in range(steps):
        x = hvd.from_local(grads[me][None, :4096] + float(step))
        out = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
        if step == 0:
            want = (np.stack([g[:4096] for g in grads]).sum(0)) / n
        else:
            # Rank 0 joined: zeros, Average still divides by n.
            want = sum(g[:4096] + step for g in grads[1:]) / n
        assert np.allclose(out, want, atol=1e-5), (me, step)
    # join() is itself the final synchronization point: every rank
    # returns only once all ranks joined (no barrier after — uneven step
    # counts desynchronize the auto-name counter, same as mp_join_worker).
    last = hvd.join(timeout=120)
    assert last >= 0
    print(f"rank {me}: SCHED-OK", flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
