"""Worker for the multi-process SyncBatchNorm test.

Two ranks, DIFFERENT data shards.  SyncBatchNorm's output and input
gradient on each shard must equal stock BatchNorm run over the
CONCATENATED global batch († sync_batch_norm.py semantics: global batch
statistics), which each rank reconstructs locally as the oracle.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main() -> int:
    hvd.init()
    me, n = hvd.cross_rank(), hvd.size()
    assert n == 2, n
    torch.manual_seed(7)  # same on both ranks
    # UNEVEN per-rank batches (3 vs 5): the summed-count design must keep
    # statistics and the unbiased running_var correction exact.
    sizes = [3, 5]
    shards = [torch.randn(s, 2, 4, 4) for s in sizes]

    # --- distributed: my shard through SyncBatchNorm ---
    sbn = hvd.SyncBatchNorm(2)
    with torch.no_grad():
        sbn.weight.copy_(torch.tensor([1.5, 0.5]))
        sbn.bias.copy_(torch.tensor([0.1, -0.2]))
    x = shards[me].clone().requires_grad_(True)
    y = sbn(x)
    y.square().sum().backward()

    # --- oracle: stock BN over the concatenated batch ---
    bn = torch.nn.BatchNorm2d(2)
    bn.load_state_dict({k: v.clone() if v.dtype.is_floating_point else v
                        for k, v in sbn.state_dict().items()},
                       strict=False)
    with torch.no_grad():
        bn.weight.copy_(torch.tensor([1.5, 0.5]))
        bn.bias.copy_(torch.tensor([0.1, -0.2]))
        bn.running_mean.zero_()
        bn.running_var.fill_(1.0)
    xg = torch.cat(shards).clone().requires_grad_(True)
    yg = bn(xg)
    yg.square().sum().backward()

    off = sum(sizes[:me])
    my = slice(off, off + sizes[me])
    assert torch.allclose(y, yg[my], atol=1e-5), \
        (y - yg[my]).abs().max().item()
    assert torch.allclose(x.grad, xg.grad[my], atol=1e-4), \
        (x.grad - xg.grad[my]).abs().max().item()
    # weight/bias grads are LOCAL sums; averaged across ranks they must
    # equal the oracle's grad / n (the DistributedOptimizer convention).
    wg = hvd.allreduce(sbn.weight.grad.clone(), op=hvd.Average,
                       name="wg_check")
    assert torch.allclose(wg, bn.weight.grad / n, atol=1e-4)
    # running stats synced to global statistics on every rank (same global
    # count -> same unbiased correction as the oracle)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-5)
    print(f"rank {me}: SYNC-BN-OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
