"""Worker for the multi-process torch DistributedOptimizer e2e test.

Two processes, one rank each, DIFFERENT data per rank — the real Horovod
topology (†3.2 hot path): grad hooks → async allreduce via the negotiated
engine → synchronize in step().  Both ranks must end with identical
parameters equal to training on the averaged gradient.
"""

import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main() -> int:
    hvd.init()
    me, n = hvd.cross_rank(), hvd.size()
    torch.manual_seed(42)                       # same init on all ranks
    model = torch.nn.Linear(4, 1)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())

    # Per-rank data shard (different per rank!).
    rng = np.random.RandomState(100 + me)
    x = torch.from_numpy(rng.randn(16, 4).astype(np.float32))
    w_true = torch.tensor([[1.0, -2.0, 0.5, 3.0]]).T
    y = x @ w_true + 0.1 * torch.from_numpy(
        rng.randn(16, 1).astype(np.float32))

    losses = []
    for _ in range(30):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))

    assert losses[-1] < losses[0] * 0.5, losses

    # Params must be bit-identical across ranks (same averaged grads).
    flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(flat[None])
    for r in range(n):
        assert torch.allclose(gathered[r], flat, atol=1e-6), \
            f"rank {me}: params diverged from rank {r}"

    print(f"rank {me}: TORCH-OK loss {losses[0]:.4f}->{losses[-1]:.4f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
