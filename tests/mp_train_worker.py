"""Worker script for the multi-process e2e launcher test.

Each of the N processes (1 fake CPU device each) initializes horovod_tpu
from the launcher-injected env, then exercises the negotiated collective
path — the whole reference flow of †3.4 (launch) + †3.2 (hot path): async
enqueue → coordinator negotiation → identical fused dispatch on every
process → synchronize.
"""

import os
import sys

# One CPU device per process = one rank per process (the reference's model).
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import horovod_tpu as hvd  # noqa: E402


def main() -> int:
    # Per-rank Chrome-trace timeline; phases self-checked below
    # († timeline.cc QUEUE/NEGOTIATE/DISPATCH breakdown over a real
    # multi-process negotiation).
    import tempfile
    tl_fd, tl_path = tempfile.mkstemp(
        prefix=f"hvdtpu_tl_r{os.environ.get('HVDTPU_CROSS_RANK', '0')}_",
        suffix=".json")
    os.close(tl_fd)
    os.environ["HOROVOD_TIMELINE"] = tl_path
    hvd.init()
    me = hvd.cross_rank()
    n = hvd.size()
    assert hvd.cross_size() == n, (hvd.cross_size(), n)

    # 1. negotiated sync allreduce
    x = hvd.from_local(np.full((1, 4), float(me + 1), np.float32))
    out = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    expected = sum(range(1, n + 1))
    assert np.allclose(out, expected), (out, expected)

    # 2. async + fusion across the negotiated path
    hs = [hvd.allreduce_async(
        hvd.from_local(np.full((1, 3), float(me + i), np.float32)),
        hvd.Average, name=f"grad.{i}") for i in range(5)]
    for i, h in enumerate(hs):
        got = hvd.to_numpy(hvd.synchronize(h))
        want = np.mean([r + i for r in range(n)])
        assert np.allclose(got, want), (i, got, want)

    # 3. broadcast from rank 1
    b = hvd.to_numpy(hvd.broadcast(
        hvd.from_local(np.full((1, 2), float(me), np.float32)), 1))
    assert np.allclose(b, 1.0), b

    # 4. barrier
    hvd.barrier()

    # 5. ragged allgather († MPI_Allgatherv): unequal row counts per rank,
    # composed from negotiated uniform collectives (pad-to-max + slice).
    rows = 2 + 3 * me
    piece = (np.arange(rows * 2, dtype=np.float32).reshape(rows, 2)
             + 100.0 * me)
    got = hvd.to_numpy(hvd.allgather([piece]))
    expected = np.concatenate([
        np.arange((2 + 3 * r) * 2, dtype=np.float32).reshape(-1, 2) + 100.0 * r
        for r in range(n)])
    assert got.shape == expected.shape, (got.shape, expected.shape)
    assert np.allclose(got, expected), (me, got, expected)

    # 6. non-uniform alltoall († MPI_Alltoallv): per-rank splits differ.
    # Works at any np: source i sends 1 + ((i + j) % 2) rows to rank j.
    def splits_of(i):
        return [1 + ((i + j) % 2) for j in range(n)]

    my_splits = splits_of(me)
    send = np.arange(sum(my_splits), dtype=np.float32) + 10.0 * me
    recv = hvd.alltoall([send], splits=np.array([my_splits], np.int32))
    # rank r receives splits_i[r] rows from each source i, source-ordered,
    # each source's rows starting at sum(splits_i[:r]) of its send buffer.
    want_parts = []
    for i in range(n):
        sp = splits_of(i)
        start = sum(sp[:me])
        want_parts.append(
            np.arange(start, start + sp[me], dtype=np.float32) + 10.0 * i)
    want = np.concatenate(want_parts)
    got_a2a = hvd.to_numpy(recv[0])
    assert np.allclose(got_a2a, want), (me, got_a2a, want)

    hvd.shutdown()

    import json
    from horovod_tpu.utils.timeline import rank_suffixed
    events = json.load(open(rank_suffixed(tl_path, me, n)))
    spans = [e["name"] for e in events if e.get("ph") == "B"]
    for phase in ("QUEUE", "NEGOTIATE", "DISPATCH"):
        assert phase in spans, f"timeline missing {phase}: {spans[:20]}"

    print(f"rank {me}: OK sum={float(out[0])}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
