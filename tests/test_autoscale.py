"""Autoscale policy + controller: the decision surface, synchronously.

Every time-dependent behavior (both cooldowns, staleness) runs off the
injected clock — no sleeps anywhere in this file.  The policy is pure,
so each rule gets a direct probe: hysteresis band, scale-up and
scale-down cooldowns, the fast+slow burn AND-gate, the blacklist-aware
capacity clamp, the straggler shrink veto, and the frozen-signal no-op.
"""

import pytest

from horovod_tpu.autoscale import (
    AutoscaleController,
    PolicyConfig,
    ScalePolicy,
    Signals,
    signals_from_families,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


#: construction stamps both cooldowns (warmup grace); tests that probe
#: steady-state behavior advance the clock past them first.
WARM = 1000.0


def _policy(**kw):
    clock = kw.pop("clock", FakeClock())
    warm = kw.pop("warm", True)
    cfg = PolicyConfig(**{**dict(min_np=1, max_np=8,
                                 scale_up_cooldown_s=30.0,
                                 scale_down_cooldown_s=120.0), **kw})
    p = ScalePolicy(cfg, clock=clock)
    if warm:
        clock.t += WARM
    return p, clock


def _sig(**kw):
    return Signals(**{**dict(current_np=4, available_slots=8), **kw})


# ---------------------------------------------------------------------------
# hysteresis band
# ---------------------------------------------------------------------------

def test_hysteresis_band_holds():
    p, _ = _policy(queue_low=1.0, queue_high=8.0)
    for q in (1.5, 4.0, 7.9):
        d = p.decide(_sig(queue_depth=q))
        assert d.action == "hold", (q, d)
        assert d.target_np == 4


def test_queue_high_grows_to_capacity():
    p, _ = _policy()
    d = p.decide(_sig(queue_depth=8.0))
    assert d.action == "grow" and d.target_np == 8, d


def test_queue_low_shrinks_by_divisor():
    p, _ = _policy(shrink_divisor=2)
    d = p.decide(_sig(queue_depth=0.5))
    assert d.action == "shrink" and d.target_np == 2, d


def test_shrink_respects_min_np():
    p, _ = _policy(min_np=3, shrink_divisor=2)
    d = p.decide(_sig(queue_depth=0.0))
    assert d.action == "shrink" and d.target_np == 3, d
    p2, _ = _policy(min_np=4)
    d2 = p2.decide(_sig(queue_depth=0.0))
    assert d2.action == "hold", d2


# ---------------------------------------------------------------------------
# cooldowns (both directions, fake clock)
# ---------------------------------------------------------------------------

def test_scale_up_cooldown_blocks_then_lapses():
    p, clock = _policy(scale_up_cooldown_s=30.0)
    assert p.decide(_sig(current_np=2, queue_depth=9.0)).action == "grow"
    d = p.decide(_sig(current_np=2, queue_depth=9.0))
    assert d.action == "hold" and "cooldown" in d.reason, d
    clock.t = WARM + 29.9
    assert p.decide(_sig(current_np=2, queue_depth=9.0)).action == "hold"
    clock.t = WARM + 30.1
    assert p.decide(_sig(current_np=2, queue_depth=9.0)).action == "grow"


def test_scale_down_cooldown_blocks_then_lapses():
    p, clock = _policy(scale_down_cooldown_s=120.0)
    assert p.decide(_sig(current_np=8, queue_depth=0.0)).action == "shrink"
    d = p.decide(_sig(current_np=4, queue_depth=0.0))
    assert d.action == "hold" and "cooldown" in d.reason, d
    clock.t = WARM + 121.0
    assert p.decide(_sig(current_np=4, queue_depth=0.0)).action == "shrink"


def test_cooldowns_are_independent():
    # A recent grow must not block a shrink, and vice versa.
    p, clock = _policy(scale_up_cooldown_s=30.0, scale_down_cooldown_s=30.0)
    assert p.decide(_sig(current_np=2, queue_depth=9.0)).action == "grow"
    clock.t = WARM + 1.0
    assert p.decide(_sig(current_np=8, queue_depth=0.0)).action == "shrink"


def test_warmup_grace_blocks_first_shrink():
    # A freshly constructed policy (job launch) must not shrink a job
    # that merely looks idle while it warms up — construction stamps
    # both cooldowns.  Found live: the first controller poll shrank an
    # hvdrun --autoscale job 2 seconds in, while workers were compiling.
    p, clock = _policy(warm=False, scale_down_cooldown_s=120.0)
    d = p.decide(_sig(current_np=4, queue_depth=0.0))
    assert d.action == "hold" and "cooldown" in d.reason, d
    clock.t = 121.0
    assert p.decide(_sig(current_np=4, queue_depth=0.0)).action == "shrink"


# ---------------------------------------------------------------------------
# SLO burn AND-gate
# ---------------------------------------------------------------------------

def test_burn_requires_both_windows():
    p, _ = _policy(burn_threshold=1.0)
    # fast alone: a blip, not pressure.
    d = p.decide(_sig(current_np=2, burn_fast=50.0, burn_slow=0.2))
    assert d.action == "hold", d
    # slow alone: stale history, not pressure.
    d = p.decide(_sig(current_np=2, burn_fast=0.2, burn_slow=50.0))
    assert d.action == "hold", d
    # both: grow.
    d = p.decide(_sig(current_np=2, burn_fast=1.5, burn_slow=1.5))
    assert d.action == "grow" and d.target_np == 8, d


def test_single_burn_window_also_blocks_shrink():
    # One window over threshold is not "idle" even with an empty queue.
    p, _ = _policy()
    d = p.decide(_sig(current_np=8, queue_depth=0.0, burn_fast=5.0))
    assert d.action == "hold", d


# ---------------------------------------------------------------------------
# capacity clamp (blacklist-aware) + straggler veto
# ---------------------------------------------------------------------------

def test_grow_clamped_to_available_slots():
    # Blacklisted hosts shrink available_slots below max_np.
    p, _ = _policy(max_np=16)
    d = p.decide(_sig(current_np=2, available_slots=6, queue_depth=9.0))
    assert d.action == "grow" and d.target_np == 6, d


def test_pressure_at_capacity_holds():
    p, _ = _policy()
    d = p.decide(_sig(current_np=8, available_slots=8, queue_depth=9.0))
    assert d.action == "hold" and "capacity" in d.reason, d


def test_max_np_clamps_even_with_slots():
    p, _ = _policy(max_np=6)
    d = p.decide(_sig(current_np=2, available_slots=32, queue_depth=9.0))
    assert d.target_np == 6, d


def test_straggler_vetoes_shrink():
    p, _ = _policy()
    d = p.decide(_sig(queue_depth=0.0, stragglers=1))
    assert d.action == "hold" and "straggler" in d.reason, d


# ---------------------------------------------------------------------------
# frozen signals
# ---------------------------------------------------------------------------

def test_stale_signals_hold_despite_pressure():
    p, _ = _policy(stale_after_s=10.0)
    d = p.decide(_sig(current_np=2, queue_depth=50.0, signal_age_s=11.0))
    assert d.action == "hold" and "stale" in d.reason, d


def test_nobody_reporting_is_infinitely_stale():
    p, _ = _policy()
    d = p.decide(_sig(queue_depth=0.0, signal_age_s=float("inf")))
    assert d.action == "hold" and "stale" in d.reason, d


# ---------------------------------------------------------------------------
# signals_from_families: snapshot -> Signals distillation
# ---------------------------------------------------------------------------

def _fam(name, *samples):
    return {"name": name,
            "samples": [{"labels": lb, "value": v} for lb, v in samples]}


def test_signals_extracts_and_filters_stale_ranks():
    fams = [
        _fam("horovod_tpu_rank_snapshot_age_seconds",
             ({"rank": "0"}, 1.0), ({"rank": "1"}, 99.0)),
        _fam("hvd_engine_queue_depth",
             ({"rank": "0"}, 3.0), ({"rank": "1"}, 50.0)),
        _fam("horovod_tpu_straggler",
             ({"rank": "0", "tensor": "t"}, 0.0),
             ({"rank": "1", "tensor": "t"}, 2.0)),
        _fam("hvd_slo_burn_rate",
             ({"rank": "0", "slo": "s", "window": "5m"}, 2.5),
             ({"rank": "0", "slo": "s", "window": "1h"}, 1.5),
             ({"rank": "1", "slo": "s", "window": "5m"}, 90.0)),
    ]
    s = signals_from_families(fams, current_np=2, available_slots=4,
                              stale_after_s=10.0)
    # Rank 1 is stale: its queue (50), straggler, and burn (90) are all
    # excluded from the vote.
    assert s.queue_depth == 3.0
    assert s.stragglers == 0
    assert s.burn_fast == 2.5 and s.burn_slow == 1.5
    assert s.signal_age_s == 1.0


def test_signals_empty_snapshot_is_stale():
    s = signals_from_families([], current_np=2, available_slots=4)
    assert s.signal_age_s == float("inf")


def _two_pool_fams():
    """Synthetic merged snapshot of a disaggregated fleet: the prefill
    rank is drowning (deep queue, hot SLO burn) while the decode rank
    idles."""
    return [
        _fam("horovod_tpu_rank_snapshot_age_seconds",
             ({"rank": "0"}, 1.0), ({"rank": "1"}, 1.0)),
        _fam("hvd_serving_pool_info",
             ({"rank": "0", "pool": "prefill"}, 1.0),
             ({"rank": "1", "pool": "decode"}, 1.0)),
        _fam("hvd_serving_queue_depth",
             ({"rank": "0"}, 40.0), ({"rank": "1"}, 0.0)),
        _fam("hvd_slo_burn_rate",
             ({"rank": "0", "slo": "ttft_p99", "window": "5m"}, 25.0),
             ({"rank": "0", "slo": "ttft_p99", "window": "1h"}, 12.0),
             ({"rank": "1", "slo": "itl_p99", "window": "5m"}, 0.2),
             ({"rank": "1", "slo": "itl_p99", "window": "1h"}, 0.1)),
    ]


def test_signals_pool_filter_splits_the_fleet():
    fams = _two_pool_fams()
    pre = signals_from_families(fams, current_np=1, available_slots=4,
                                pool="prefill")
    dec = signals_from_families(fams, current_np=1, available_slots=4,
                                pool="decode")
    assert pre.queue_depth == 40.0 and pre.burn_fast == 25.0
    assert dec.queue_depth == 0.0 and dec.burn_fast == 0.2
    # An unknown pool sees nobody -> infinitely stale, policy holds.
    ghost = signals_from_families(fams, current_np=1, available_slots=4,
                                  pool="mixed")
    assert ghost.signal_age_s == float("inf")


def test_prefill_burn_cannot_grow_decode_pool():
    """The isolation regression: with pool filtering, the prefill rank's
    queue/burn storm grows only a prefill-pool controller's target —
    a decode-pool policy fed the same snapshot holds."""
    fams = _two_pool_fams()
    pre_sig = signals_from_families(fams, current_np=1, available_slots=4,
                                    pool="prefill")
    dec_sig = signals_from_families(fams, current_np=1, available_slots=4,
                                    pool="decode")
    p_pre, _ = _policy(min_np=1, queue_low=1.0, queue_high=8.0)
    p_dec, _ = _policy(min_np=1, queue_low=0.0, queue_high=8.0)
    d_pre = p_pre.decide(pre_sig)
    d_dec = p_dec.decide(dec_sig)
    assert d_pre.action == "grow" and d_pre.target_np > 1, d_pre
    assert d_dec.action == "hold", d_dec
    # Without the filter the decode view inherits the prefill queue —
    # the bug this guards against.
    mixed = signals_from_families(fams, current_np=1, available_slots=4)
    assert mixed.queue_depth == 40.0


def test_controller_target_gauge_is_pool_labeled():
    from horovod_tpu.autoscale import controller as ctl
    p, _ = _policy()
    c = AutoscaleController(
        p, current_np=2, collect=lambda: [], bump=lambda: None,
        capacity=lambda: 4, pool="decode")
    c._m_target.set(2.0)
    labels = [s["labels"] for s in ctl._m_target._samples()]
    assert {"pool": "decode"} in labels, labels


# ---------------------------------------------------------------------------
# controller: record + act (no thread, no sleeps)
# ---------------------------------------------------------------------------

def _controller(policy, fams, *, current_np, capacity, prev_np=None):
    bumps, targets = [], []
    c = AutoscaleController(
        policy, current_np=current_np, prev_np=prev_np,
        collect=lambda: fams, bump=lambda: bumps.append(1),
        capacity=lambda: capacity, set_target=targets.append)
    return c, bumps, targets


def test_controller_grow_bumps_and_sets_target():
    p, _ = _policy(scale_up_cooldown_s=30.0)
    fams = [
        _fam("horovod_tpu_rank_snapshot_age_seconds", ({"rank": "0"}, 0.5)),
        _fam("hvd_engine_queue_depth", ({"rank": "0"}, 20.0)),
    ]
    c, bumps, targets = _controller(p, fams, current_np=2, capacity=4)
    d = c.poll_once()
    assert d.action == "grow" and bumps == [1] and targets == [4]
    # Cooldown makes the next tick a hold: no duplicate bump.
    assert c.poll_once().action == "hold" and bumps == [1]


def test_controller_records_observed_shrink():
    from horovod_tpu.obs import REGISTRY
    p, _ = _policy()
    c, bumps, _ = _controller(p, [], current_np=2, capacity=2, prev_np=4)
    before = REGISTRY.get(
        "hvd_autoscale_decisions_total").labels(action="shrink").value
    c.start()
    c.stop()
    after = REGISTRY.get(
        "hvd_autoscale_decisions_total").labels(action="shrink").value
    assert after == before + 1
    assert not bumps  # observed, not initiated: nothing to signal
    assert c.decisions and c.decisions[0].action == "shrink"


def test_controller_survives_collect_failure():
    p, _ = _policy()

    def boom():
        raise ConnectionError("kv down")

    c = AutoscaleController(p, current_np=2, collect=boom,
                            bump=lambda: None, capacity=lambda: 2)
    d = c.poll_once()
    assert d.action == "hold" and "stale" in d.reason, d
