"""Auxiliary subsystems: checkpoint/resume, elastic sampler, join, grouped
async, autotuner unit behavior.
"""

import numpy as np
import pytest

import horovod_tpu as hvd


# ---------------------------------------------------------------------------
# checkpoint (orbax)
# ---------------------------------------------------------------------------

def test_checkpoint_save_restore_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp
    from horovod_tpu.utils.checkpoint import Checkpointer
    ckpt = Checkpointer(str(tmp_path / "ck"))
    tree = {"params": {"w": jnp.arange(8.0), "b": jnp.ones((3,))},
            "step": jnp.int32(7)}
    ckpt.save(7, tree)
    assert ckpt.latest_step() == 7
    restored = ckpt.restore()
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               np.arange(8.0))
    assert int(restored["step"]) == 7
    ckpt.close()


def test_checkpoint_resharded_restore(tmp_path):
    """Restore onto an explicit sharding target — the elastic-restart path
    (new mesh after membership change)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.utils.checkpoint import Checkpointer
    mesh = hvd.mesh()
    ckpt = Checkpointer(str(tmp_path / "ck2"))
    tree = {"w": jnp.arange(16.0)}
    ckpt.save(0, tree)
    target = {"w": jax.ShapeDtypeStruct(
        (16,), jnp.float32, sharding=NamedSharding(mesh, P("hvd")))}
    restored = ckpt.restore(target=target)
    assert restored["w"].sharding == NamedSharding(mesh, P("hvd"))
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(16.0))
    ckpt.close()


def test_checkpoint_max_to_keep(tmp_path):
    import jax.numpy as jnp
    from horovod_tpu.utils.checkpoint import Checkpointer
    ckpt = Checkpointer(str(tmp_path / "ck3"), max_to_keep=2)
    for s in range(4):
        ckpt.save(s, {"x": jnp.float32(s)})
    assert ckpt.all_steps() == [2, 3]
    ckpt.close()


def test_checkpoint_restore_missing(tmp_path):
    from horovod_tpu.utils.checkpoint import Checkpointer
    ckpt = Checkpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore()
    ckpt.close()


# ---------------------------------------------------------------------------
# elastic sampler († test_torch_elastic.py sampler cases)
# ---------------------------------------------------------------------------

def test_sampler_shards_evenly():
    from horovod_tpu.elastic import ElasticSampler
    samplers = []
    for r in range(4):
        s = ElasticSampler(100, shuffle=False)
        s.set_rank_size(r, 4)
        samplers.append(list(s))
    all_idx = sorted(i for s in samplers for i in s)
    assert all_idx == list(range(100))
    assert all(len(s) == 25 for s in samplers)


def test_sampler_reshards_remaining_after_membership_change():
    from horovod_tpu.elastic import ElasticSampler
    s = ElasticSampler(20, shuffle=False)
    s.set_rank_size(0, 2)
    first_half = list(s)[:5]
    s.record_batch(first_half)
    # World shrinks to 1: remaining indices = all except processed.
    s.set_rank_size(0, 1)
    remaining = list(s)
    assert set(remaining) == set(range(20)) - set(first_half)


def test_sampler_epoch_resets_progress():
    from horovod_tpu.elastic import ElasticSampler
    s = ElasticSampler(10, shuffle=True, seed=1)
    s.record_batch([0, 1, 2])
    s.set_epoch(1)
    assert len(s) == 10
    # Shuffle differs across epochs.
    e1 = list(s)
    s.set_epoch(2)
    assert list(s) != e1


def test_sampler_state_dict_roundtrip():
    from horovod_tpu.elastic import ElasticSampler
    s = ElasticSampler(10, shuffle=False)
    s.record_batch([1, 3])
    sd = s.state_dict()
    s2 = ElasticSampler(10, shuffle=False)
    s2.load_state_dict(sd)
    assert set(s2) == set(range(10)) - {1, 3}


# ---------------------------------------------------------------------------
# join + grouped async
# ---------------------------------------------------------------------------

def test_join_returns_last_rank():
    assert hvd.join() == hvd.size() - 1


def test_grouped_allreduce_async():
    xs = [hvd.per_rank_from_fn(
        lambda r, i=i: np.full((4,), float(r + i), np.float32))
        for i in range(3)]
    handles = hvd.grouped_allreduce_async(xs, hvd.Average, name="grp")
    for i, h in enumerate(handles):
        got = hvd.to_numpy(hvd.synchronize(h))
        np.testing.assert_allclose(got, np.full((4,), 3.5 + i), rtol=1e-6)


def test_grouped_allreduce_sync():
    xs = [hvd.per_rank_from_fn(
        lambda r, i=i: np.full((2,), float(r * i), np.float32))
        for i in range(2)]
    outs = hvd.grouped_allreduce_sync(xs, hvd.Sum)
    np.testing.assert_allclose(hvd.to_numpy(outs[0]), 0.0)
    np.testing.assert_allclose(hvd.to_numpy(outs[1]), np.full((2,), 28.0))


# ---------------------------------------------------------------------------
# autotuner unit behavior († parameter_manager tests)
# ---------------------------------------------------------------------------

def test_autotuner_proposes_and_converges(tmp_path):
    from horovod_tpu.utils.autotune import Autotuner

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.config = config_mod.Config(
        autotune=True, autotune_log=str(tmp_path / "at.log"),
        autotune_warmup_samples=1, autotune_steps_per_sample=2)
    at = Autotuner(st)
    # Feed cycles: throughput peaks at larger thresholds.
    for i in range(200):
        if at._done:
            break
        t, c, m, s, h, b = at._current
        score_bias = 1.0 + (np.log2(t) - 20) * 0.1
        at.record_cycle(int(1e6 * score_bias), 0.001)
    log = (tmp_path / "at.log").read_text()
    assert "sample #" in log
    # Knobs were mutated by the proposals.
    assert (st.config.fusion_threshold, st.config.cycle_time_ms) != (
        64 * 1024 * 1024, 5.0) or at._done


def test_autotuner_commits_exact_grid_values(tmp_path):
    """Regression: the converged knobs must be EXACT candidate-grid
    values.  The old ``_raw`` reconstructed them as ``2 ** log2(x)`` from
    the normalized GP samples, which drifted the committed cycle time off
    the grid (2.5 -> 2.4999999999999996).  The 4th (schedule) dimension
    joins the same assertion so the knob-space growth cannot reintroduce
    the drift through a new code path."""
    from horovod_tpu.utils.autotune import (
        Autotuner, _CYCLE_TIMES, _sched_arms, _THRESHOLDS, _WIRE_MODES)

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.config = config_mod.Config(
        autotune=True, autotune_warmup_samples=0,
        autotune_steps_per_sample=1, cycle_time_ms=2.5)
    at = Autotuner(st)
    rng = np.random.RandomState(0)
    for i in range(400):
        if at._done:
            break
        # Flat-ish noisy scores: convergence picks SOME sampled config.
        at.record_cycle(int(1e6 + rng.randint(0, 1000)), 0.001)
    assert at._done, "tuner never converged"
    t, c, m, s, h, b = at._current
    assert t in _THRESHOLDS or t == st.config.fusion_threshold
    assert st.config.fusion_threshold == t
    # The drift bug showed up in the float knob: exact membership now.
    assert c in _CYCLE_TIMES or c == 2.5
    assert st.config.cycle_time_ms == c
    assert m in _WIRE_MODES
    assert st.config.wire_precision == m
    arms = _sched_arms()
    assert s in arms
    if s == "monolithic":
        assert st.config.sched_mode == "monolithic"
    elif s.startswith("compiled:"):
        assert st.config.sched_mode == "compiled"
        assert f"compiled:rs_ag:{st.config.sched_chunks}" == s
    else:
        assert st.config.sched_mode == "decomposed"
        assert f"rs_ag:{st.config.sched_chunks}" == s
    assert b in at._buckets
    assert st.config.bucket_bytes == b
    # Every recorded sample keeps exact raw knobs alongside the GP coords
    # — all six of them, so neither the hierarchy nor the bucket-cap
    # dimension can reintroduce the round-trip drift.
    for (rt, rc, rm, rs, rh, rb), (xt, xc, xm, xs, xh, xb) in zip(
            at._samples_raw, at._samples_X):
        assert rt in _THRESHOLDS or rt == 64 * 1024 * 1024
        assert rc in _CYCLE_TIMES or rc == 2.5
        assert rs in arms
        assert rh in at._hiers
        assert rb in at._buckets
        assert 2.0 ** xt == pytest.approx(rt)


def test_autotune_sched_arms_track_lowering_modes():
    """Regression for the arm-set drift bug: the tuner's schedule arms
    used to be a hand-maintained list disjoint from ``lower.SCHED_MODES``
    (it searched ``rs_ag:*`` strings while the config validator accepted
    a different vocabulary).  The arms are now DERIVED from SCHED_MODES;
    this test pins the sync so a new sched mode cannot ship without an
    autotune arm, and every generated arm round-trips through the
    resolver's descriptor parsers and ``_apply``."""
    from horovod_tpu.ops.sched import known_descriptor
    from horovod_tpu.ops.sched.lower import (SCHED_MODES,
                                             autotune_sched_arms)
    from horovod_tpu.utils.autotune import _SCHED_CHUNK_COUNTS, _sched_arms

    arms = _sched_arms()
    assert arms == autotune_sched_arms(_SCHED_CHUNK_COUNTS)
    # Every declared sched mode contributes at least one arm...
    assert "monolithic" in SCHED_MODES and "monolithic" in arms
    for k in _SCHED_CHUNK_COUNTS:
        assert ("decomposed" not in SCHED_MODES) or f"rs_ag:{k}" in arms
        assert ("compiled" not in SCHED_MODES) \
            or f"compiled:rs_ag:{k}" in arms
    # ...and no arm exists the engine's resolver cannot parse.
    for a in arms:
        assert a == "monolithic" or known_descriptor(a), a
    # _apply commits every arm to a config the validator accepts.
    from horovod_tpu import config as config_mod

    class FakeState:
        pass

    from horovod_tpu.utils.autotune import Autotuner
    st = FakeState()
    st.config = config_mod.Config(autotune=True, autotune_warmup_samples=0,
                                  autotune_steps_per_sample=1)
    at = Autotuner(st)
    for a in arms:
        at._apply(1 << 20, 1.0, "fp32", a, "flat")
        assert st.config.sched_mode in SCHED_MODES
        if a.startswith("compiled:"):
            assert st.config.sched_mode == "compiled"
        elif a == "monolithic":
            assert st.config.sched_mode == "monolithic"
        else:
            assert st.config.sched_mode == "decomposed"


def test_autotuner_discards_settle_cycles_after_commit(tmp_path):
    """A knob commit pays XLA compiles on its first cycles — new fused
    signatures, and on the compiled-schedule arms a whole new program.
    Those cycles must be discarded, not scored: counting them grades the
    warm incumbent against cold challengers, and the tuner converges
    right back onto the (deliberately bad) starting knobs because every
    challenger's window is poisoned by its own compile stall."""
    from horovod_tpu.utils.autotune import _SETTLE_CYCLES, Autotuner

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.config = config_mod.Config(autotune=True, autotune_warmup_samples=0,
                                  autotune_steps_per_sample=1)
    at = Autotuner(st)
    at.record_cycle(1000, 0.001)  # sample #1 -> propose -> _apply
    assert at._settle_left == _SETTLE_CYCLES
    n = len(at._samples_y)
    # The settle window: a compile-stalled outlier cycle must vanish
    # without being accumulated or recorded as a sample.
    for _ in range(_SETTLE_CYCLES):
        at.record_cycle(10 ** 12, 5.0)
    assert len(at._samples_y) == n
    assert at._settle_left == 0
    assert at._acc_cycles == 0 and at._acc_bytes == 0
    # Scoring resumes on the next cycle, clean of the stall.
    at.record_cycle(1000, 0.001)
    assert len(at._samples_y) == n + 1
    assert max(at._samples_y) == pytest.approx(1000 / 0.001)
    # Zero-payload cycles never consume the settle window (an idle cycle
    # compiles nothing, so it proves nothing about warmth).
    at._settle_left = _SETTLE_CYCLES
    at.record_cycle(0, 0.001)
    assert at._settle_left == _SETTLE_CYCLES


def test_autotuner_pins_compiled_sched_when_distributed():
    """Compiled default + multi-process engine: the schedule dimension
    pins to the compiled descriptor (same rank-divergence rule as the
    decomposed pin below)."""
    from horovod_tpu.utils.autotune import Autotuner

    class FakeEngine:
        distributed = True

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.engine = FakeEngine()
    st.config = config_mod.Config(
        autotune=True, autotune_warmup_samples=0,
        autotune_steps_per_sample=1, sched_mode="compiled", sched_chunks=2)
    at = Autotuner(st)
    assert at._scheds == ["compiled:rs_ag:2"]
    assert {g[3] for g in at._grid_raw} == {"compiled:rs_ag:2"}


def test_autotuner_pins_sched_and_mode_when_distributed():
    """Multi-process engines must pin BOTH the wire-precision and the
    schedule dimensions to the configured defaults: a per-rank commit of
    either diverges the enqueue-time resolution across processes (hang).
    """
    from horovod_tpu.utils.autotune import Autotuner

    class FakeEngine:
        distributed = True

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.engine = FakeEngine()
    st.config = config_mod.Config(
        autotune=True, autotune_warmup_samples=0,
        autotune_steps_per_sample=1, wire_precision="int8",
        sched_mode="decomposed", sched_chunks=2)
    at = Autotuner(st)
    assert at._modes == ["int8"]
    assert at._scheds == ["rs_ag:2"]
    assert at._hiers == ["flat"]
    # And every grid candidate keeps them fixed.
    assert {g[2] for g in at._grid_raw} == {"int8"}
    assert {g[3] for g in at._grid_raw} == {"rs_ag:2"}
    assert {g[4] for g in at._grid_raw} == {"flat"}
    # The bucket cap stays SEARCHABLE even when distributed: like the
    # fusion threshold it only shapes the local cycle thread's grouping.
    assert {g[5] for g in at._grid_raw} == set(at._buckets)
    assert len(at._buckets) > 1


def test_autotuner_hierarchy_dimension():
    """The 5th knob: a detected topology split enters the search as
    tier:<n_local> (plus its half), _apply commits the hierarchical
    config knobs, and distributed engines pin to the configured default.
    """
    from horovod_tpu.utils.autotune import Autotuner

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.size = 8
    st.local_size = 8
    st.config = config_mod.Config(
        autotune=True, autotune_warmup_samples=0,
        autotune_steps_per_sample=1, local_size_env=4)
    at = Autotuner(st)
    assert at._hiers == ["flat", "tier:4", "tier:2"]
    # The analytic decision table seeds the search (perfmodel).
    assert at.split_table and {r["split"] for r in at.split_table} <= {
        "flat", "hier"}
    at._apply(1 << 20, 1.0, "fp32", "monolithic", "tier:2")
    assert st.config.hierarchical_allreduce
    assert st.config.hierarchical_local_size == 2
    at._apply(1 << 20, 1.0, "fp32", "monolithic", "flat")
    assert not st.config.hierarchical_allreduce
    # Distributed + flag on: pinned to the configured tier, never "flat".
    class FakeEngine:
        distributed = True
    st2 = FakeState()
    st2.size = 8
    st2.engine = FakeEngine()
    st2.config = config_mod.Config(
        autotune=True, hierarchical_allreduce=True,
        hierarchical_local_size=4)
    at2 = Autotuner(st2)
    assert at2._hiers == ["tier:4"]
    assert {g[4] for g in at2._grid_raw} == {"tier:4"}


def test_autotuner_bucket_bytes_dimension():
    """The 6th knob: bucket cap candidates include 0 (uncapped) plus the
    grid caps, an off-grid configured cap joins the search, and _apply
    commits ``config.bucket_bytes`` (which the engine folds into its
    fusion grouping and the backward bucketer reads as its size target).
    """
    from horovod_tpu.utils.autotune import _BUCKET_BYTES, Autotuner

    class FakeState:
        pass

    from horovod_tpu import config as config_mod
    st = FakeState()
    st.config = config_mod.Config(
        autotune=True, autotune_warmup_samples=0,
        autotune_steps_per_sample=1, bucket_bytes=7 << 20)
    at = Autotuner(st)
    assert at._buckets == list(_BUCKET_BYTES) + [7 << 20]
    assert 0 in at._buckets
    assert at._current[5] == 7 << 20
    at._apply(1 << 20, 1.0, "fp32", "monolithic", "flat", 4 << 20)
    assert st.config.bucket_bytes == 4 << 20
    at._apply(1 << 20, 1.0, "fp32", "monolithic", "flat", 0)
    assert st.config.bucket_bytes == 0
    # Default-arg form (legacy 5-knob callers) commits the uncapped arm.
    at._apply(1 << 20, 1.0, "fp32", "monolithic", "flat")
    assert st.config.bucket_bytes == 0


@pytest.mark.integration
def test_autotune_improves_dispatch_bound_throughput(tmp_path):
    """Round-2 verdict #7: the GP+EI loop must beat a deliberately bad
    (threshold, cycle-time) start on a dispatch-bound gradient stream —
    committed evidence lives in benchmarks/autotune_log.txt and
    benchmarks/measured.jsonl; this asserts it stays true."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Wall-clock perf assertion: one retry absorbs transient host load
    # (the measurement itself is the committed benchmarks/ artifact; this
    # guards against regressions, not against a busy CI box).
    for attempt in range(2):
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "benchmarks",
                                          "autotune_bench.py"),
             "--log", str(tmp_path / "autotune_log.txt"), "--no-persist"],
            capture_output=True, text=True, timeout=800, cwd=repo)
        assert res.returncode == 0, res.stdout + res.stderr
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        if (rec["speedup"] >= 1.0
                and rec["tuned"]["knobs"]["fusion_threshold"] > 4096):
            break
    assert rec["speedup"] >= 1.0, rec
    # The tuner must have moved off the bad 4 KB threshold.
    assert rec["tuned"]["knobs"]["fusion_threshold"] > 4096, rec
