"""Basics API: init/rank/size + config surfaces.

Mirrors † ``test/parallel/test_torch.py`` rank/size assertions and
† ``test/single/test_run.py`` config parsing style.
"""

import os

import pytest

import horovod_tpu as hvd
from horovod_tpu import config as config_mod


def test_initialized_and_sizes():
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.rank() == 0          # single process drives device 0
    assert hvd.local_size() == 8
    assert hvd.cross_rank() == 0
    assert hvd.cross_size() == 1


def test_double_init_is_noop():
    hvd.init()
    assert hvd.size() == 8


def test_mesh_shape():
    m = hvd.mesh()
    assert m.shape["hvd"] == 8


def test_not_initialized_error():
    # A fresh error type check without tearing down the session engine:
    with pytest.raises(hvd.NotInitializedError):
        raise hvd.NotInitializedError()


def test_config_env_parsing(monkeypatch):
    monkeypatch.setenv("HVDTPU_FUSION_THRESHOLD", "1048576")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HVDTPU_LOG_LEVEL", "debug")
    cfg = config_mod.from_env()
    assert cfg.fusion_threshold == 1048576
    assert cfg.cycle_time_ms == 2.5
    assert cfg.autotune is True
    assert cfg.log_level == "debug"


def test_config_env_precedence(monkeypatch):
    # HVDTPU_ wins over HOROVOD_ when both are set.
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "111")
    monkeypatch.setenv("HVDTPU_FUSION_THRESHOLD", "222")
    assert config_mod.from_env().fusion_threshold == 222


def test_config_bad_env(monkeypatch):
    monkeypatch.setenv("HVDTPU_FUSION_THRESHOLD", "not-a-number")
    with pytest.raises(ValueError):
        config_mod.from_env()


def test_config_yaml(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(
        "# comment\n"
        "fusion_threshold: 2097152\n"
        "cycle-time-ms: 7.5\n"
        "autotune: true\n"
        "log_level: info\n")
    cfg = config_mod.from_yaml(str(p))
    assert cfg.fusion_threshold == 2097152
    assert cfg.cycle_time_ms == 7.5
    assert cfg.autotune is True
    assert cfg.log_level == "info"


def test_config_yaml_unknown_key(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("no_such_knob: 1\n")
    with pytest.raises(ValueError):
        config_mod.from_yaml(str(p))
