"""Framework bindings: torch eager verbs + DistributedOptimizer, Keras
callbacks.

Mirrors † ``test/parallel/test_torch.py`` (allreduce/broadcast semantics,
DistributedOptimizer grad averaging, backward_passes_per_step) and
† ``test/parallel/test_keras.py`` (callback behavior).
"""

import numpy as np
import pytest
import torch

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch

N = 8  # fake devices; single process drives all → tensors tile across ranks


# ---------------------------------------------------------------------------
# torch eager verbs
# ---------------------------------------------------------------------------

def test_torch_allreduce_sum_tiles_local_ranks():
    t = torch.arange(4, dtype=torch.float32)
    out = hvd_torch.allreduce(t, hvd.Sum)
    # Single process drives all 8 ranks with the same tensor.
    assert torch.allclose(out, t * N)


def test_torch_bridge_single_host_copy(monkeypatch):
    """The bridge must stage each tensor to the device plane with ONE
    host->device transfer regardless of local_size — on-device
    replication covers the other local ranks (round-2 fix: np.repeat
    staged local_size x the payload through host memory)."""
    import jax
    host_puts = []
    real_put = jax.device_put

    def counting_put(x, *a, **kw):
        if isinstance(x, np.ndarray):
            host_puts.append(x.nbytes)
        return real_put(x, *a, **kw)

    from horovod_tpu.ops import collectives as C
    monkeypatch.setattr(C.jax, "device_put", counting_put)
    t = torch.arange(64, dtype=torch.float32)
    out = hvd_torch.allreduce(t, hvd.Sum)
    assert torch.allclose(out, t * N)
    assert len(host_puts) == 1, (
        f"{len(host_puts)} host->device copies for local_size={N}")


def test_torch_allreduce_average_identity():
    t = torch.randn(3, 3)
    out = hvd_torch.allreduce(t, hvd.Average)
    assert torch.allclose(out, t, atol=1e-6)


def test_torch_broadcast():
    t = torch.full((2, 2), 7.0)
    out = hvd_torch.broadcast(t, root_rank=3)
    assert torch.allclose(out, t)


def test_torch_async_roundtrip():
    t = torch.ones(5)
    h = hvd_torch.allreduce_async(t, hvd.Sum, name="torch.async")
    assert hvd_torch.synchronize(h).shape == (5,)


def test_torch_broadcast_parameters_inplace():
    model = torch.nn.Linear(4, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.allclose(v, before[k], atol=1e-6)


# ---------------------------------------------------------------------------
# torch DistributedOptimizer
# ---------------------------------------------------------------------------

def _train_once(bpps=1, micro_batches=1):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=bpps)
    x = torch.randn(8, 4)
    y = torch.randn(8, 1)
    opt.zero_grad()
    for _ in range(micro_batches):
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
    opt.step()
    return model, opt


def test_torch_optimizer_step_applies_averaged_grads():
    torch.manual_seed(0)
    ref_model = torch.nn.Linear(4, 1)
    ref_opt = torch.optim.SGD(ref_model.parameters(), lr=0.1)
    x = torch.randn(8, 4)
    y = torch.randn(8, 1)
    ref_opt.zero_grad()
    torch.nn.functional.mse_loss(ref_model(x), y).backward()
    ref_opt.step()

    model, _ = _train_once()
    # Identical data on every rank → average == local grad → same result
    # as plain SGD († test_horovod_allreduce_average consistency).
    for p_ref, p in zip(ref_model.parameters(), model.parameters()):
        assert torch.allclose(p_ref, p, atol=1e-5)


def test_torch_optimizer_backward_passes_per_step():
    model, opt = _train_once(bpps=3, micro_batches=3)
    for p in model.parameters():
        assert p.grad is not None


def test_torch_optimizer_step_too_early_raises():
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        backward_passes_per_step=2)
    loss = model(torch.randn(3, 2)).sum()
    loss.backward()
    with pytest.raises(RuntimeError, match="backward_passes_per_step"):
        opt.step()


# ---------------------------------------------------------------------------
# Keras callbacks
# ---------------------------------------------------------------------------

keras = pytest.importorskip("keras")


def _tiny_keras_model():
    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(2),
    ])
    model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
    return model


def test_keras_broadcast_callback_preserves_weights():
    import horovod_tpu.keras as hvd_keras
    model = _tiny_keras_model()
    before = [w.copy() for w in model.get_weights()]
    cb = hvd_keras.BroadcastGlobalVariablesCallback(0)
    cb.set_model(model)
    cb.on_train_begin()
    for b, a in zip(before, model.get_weights()):
        np.testing.assert_allclose(b, a, atol=1e-6)


def test_keras_metric_average_callback():
    import horovod_tpu.keras as hvd_keras
    cb = hvd_keras.MetricAverageCallback()
    logs = {"loss": 2.0, "acc": 0.5}
    cb.on_epoch_end(0, logs)
    # Identical on every rank → average is identity.
    assert logs["loss"] == pytest.approx(2.0)
    assert logs["acc"] == pytest.approx(0.5)


def test_keras_warmup_callback_ramps_lr():
    import horovod_tpu.keras as hvd_keras
    model = _tiny_keras_model()
    cb = hvd_keras.LearningRateWarmupCallback(
        initial_lr=0.1, warmup_epochs=1, multiplier=8.0, steps_per_epoch=10)
    cb.set_model(model)
    cb.on_train_begin()
    lrs = []
    for step in range(10):
        cb.on_train_batch_begin(step)
        lrs.append(float(np.asarray(model.optimizer.learning_rate)))
    assert lrs[0] == pytest.approx(0.1)
    assert lrs[-1] > lrs[0]
    cb.on_train_batch_begin(10)
    assert float(np.asarray(model.optimizer.learning_rate)) == \
        pytest.approx(0.8)


def test_keras_schedule_callback():
    import horovod_tpu.keras as hvd_keras
    model = _tiny_keras_model()
    cb = hvd_keras.LearningRateScheduleCallback(
        initial_lr=0.1, multiplier=lambda e: 0.1 ** e, start_epoch=1)
    cb.set_model(model)
    cb.on_epoch_begin(0)   # before start: untouched
    lr0 = float(np.asarray(model.optimizer.learning_rate))
    cb.on_epoch_begin(2)
    lr2 = float(np.asarray(model.optimizer.learning_rate))
    assert lr0 == pytest.approx(0.1)
    assert lr2 == pytest.approx(0.001)


# ---------------------------------------------------------------------------
# SyncBatchNorm († horovod/torch/sync_batch_norm.py)
# ---------------------------------------------------------------------------

def test_torch_sync_batch_norm_matches_local_bn():
    """In-process rig: every 'rank' sees identical data, so global batch
    statistics equal local ones — SyncBatchNorm must reproduce stock
    BatchNorm exactly, forward and backward."""
    torch.manual_seed(0)
    x = torch.randn(4, 3, 5, 5)

    sbn = hvd_torch.SyncBatchNorm(3)
    bn = torch.nn.BatchNorm2d(3)
    bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})

    xs = x.clone().requires_grad_(True)
    xb = x.clone().requires_grad_(True)
    ys, yb = sbn(xs), bn(xb)
    assert torch.allclose(ys, yb, atol=1e-5), (ys - yb).abs().max()
    ys.square().sum().backward()
    yb.square().sum().backward()
    assert torch.allclose(xs.grad, xb.grad, atol=1e-4)
    assert torch.allclose(sbn.weight.grad, bn.weight.grad, atol=1e-4)
    assert torch.allclose(sbn.bias.grad, bn.bias.grad, atol=1e-4)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)
    # running_var's unbiased correction uses the GLOBAL count (8 fake ranks
    # x 100 samples here), not the local 100 — distributed semantics.
    n = 4 * 5 * 5 * hvd.size()
    biased = x.var([0, 2, 3], unbiased=False)
    expect = 0.9 * torch.ones(3) + 0.1 * biased * n / (n - 1)
    assert torch.allclose(sbn.running_var, expect, atol=1e-5)


def test_torch_sync_batch_norm_eval_fallback():
    sbn = hvd_torch.SyncBatchNorm(4)
    sbn.eval()
    x = torch.randn(2, 4)
    # eval path = stock kernel on running stats (zeros mean/ones var)
    assert torch.allclose(sbn(x), x, atol=1e-5)


def test_torch_sync_batch_norm_bad_dim():
    sbn = hvd_torch.SyncBatchNorm(4)
    with pytest.raises(ValueError):
        sbn(torch.randn(4))


def test_torch_sync_batch_norm_momentum_none():
    """momentum=None = cumulative moving average, like stock BatchNorm
    (regression: the fallback crashed and the sync path used 0.1)."""
    torch.manual_seed(1)
    sbn = hvd_torch.SyncBatchNorm(3, momentum=None)
    bn = torch.nn.BatchNorm2d(3, momentum=None)
    for _ in range(3):
        x = torch.randn(4, 3, 5, 5)
        sbn(x), bn(x)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)
    assert sbn.num_batches_tracked == bn.num_batches_tracked == 3
    sbn.eval()
    sbn(torch.randn(2, 3, 5, 5))  # eval fallback must not crash


def test_torch_sync_batch_norm_no_running_stats():
    """track_running_stats=False: always batch statistics, eval included
    (regression: eval crashed on running_mean=None)."""
    sbn = hvd_torch.SyncBatchNorm(3, track_running_stats=False)
    bn = torch.nn.BatchNorm2d(3, track_running_stats=False)
    x = torch.randn(4, 3, 5, 5)
    assert torch.allclose(sbn(x), bn(x), atol=1e-5)
    sbn.eval(), bn.eval()
    assert torch.allclose(sbn(x), bn(x), atol=1e-5)


def test_torch_inplace_variants():
    """† hvd.allreduce_ / broadcast_ / *_async_ write back into the given
    tensor (torch underscore convention)."""
    t = torch.full((4,), 2.0)
    out = hvd_torch.allreduce_(t, op=hvd_torch.Average, name="inp_ar")
    assert out is t and torch.allclose(t, torch.full((4,), 2.0))

    t = torch.full((3,), float(hvd.rank() + 5))
    out = hvd_torch.broadcast_(t, root_rank=0, name="inp_bc")
    assert out is t and torch.allclose(t, torch.full((3,), 5.0))

    t = torch.full((2,), 3.0)
    h = hvd_torch.allreduce_async_(t, name="inp_ar_async")
    res = hvd_torch.synchronize(h)
    assert res is t and torch.allclose(t, torch.full((2,), 3.0))
    assert hvd_torch.poll(h) in (True, False)

    t = torch.full((2,), float(hvd.rank() + 7))
    h = hvd_torch.broadcast_async_(t, root_rank=0, name="inp_bc_async")
    assert hvd_torch.synchronize(h) is t
    assert torch.allclose(t, torch.full((2,), 7.0))

    g = hvd_torch.synchronize(
        hvd_torch.allgather_async(torch.ones(2), name="inp_ag"))
    assert g.shape[0] == 2 * hvd.size()
