"""Chaos + robustness: fault-spec grammar, deterministic injection, the
unified retry policy, kv blob deadlines, blacklist decay, and serving
graceful degradation.

The multi-process halves live in ``horovod_tpu/chaos/run.py`` (the CI
``chaos-recovery`` scenario harness, wrapped slow-marked in
``test_runner.py``) and ``tests/mp_obs_worker.py`` mode ``chaos``
(/healthz 200→503→200 under an injected negotiation stall).
"""

import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import chaos
from horovod_tpu.chaos.spec import FaultRule, parse_duration_s, parse_spec
from horovod_tpu.obs import REGISTRY
from horovod_tpu.utils import retry


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------

def test_parse_spec_issue_example():
    rules = parse_spec("kv_get:err:p=0.02:seed=7; rank=1:die:after=50steps;"
                       " negotiate:delay=300ms:p=0.05")
    assert rules[0] == FaultRule(site="kv_get", kind="err", index=0,
                                 p=0.02, seed=7)
    assert rules[1].site == "*" and rules[1].kind == "die"
    assert rules[1].rank == 1 and rules[1].after == 50
    assert rules[1].times == 1          # die defaults to once
    assert rules[2].kind == "delay" and rules[2].delay_s == pytest.approx(0.3)
    assert rules[2].p == 0.05


def test_parse_spec_field_order_is_free():
    a, = parse_spec("dispatch:rank=1:die:after=3")
    b, = parse_spec("die:dispatch:after=3:rank=1")
    assert a == b


def test_parse_duration_units():
    assert parse_duration_s("300ms") == pytest.approx(0.3)
    assert parse_duration_s("0.3s") == pytest.approx(0.3)
    assert parse_duration_s("2") == pytest.approx(2.0)
    assert parse_duration_s("1m") == pytest.approx(60.0)
    with pytest.raises(ValueError):
        parse_duration_s("fast")


@pytest.mark.parametrize("bad", [
    "kv_get",                     # no kind
    "kv_get:err:p=1.5",           # p out of range
    "kv_get:err:p=0",             # p out of range
    "kv_get:err:after=0",         # after < 1
    "kv_get:err:times=0",         # times < 1
    "kv_get:err:bogus=1",         # unknown param
    "kv_get:kv_put:err",          # two sites
    "kv_get:err:die",             # two kinds
    "negotiate:delay",            # delay without duration
    "dispatch:err:delay=5ms",     # kind conflict
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_parse_spec_empty_rules_skipped():
    assert parse_spec(" ; kv_get:err ; ") == parse_spec("kv_get:err")


# ---------------------------------------------------------------------------
# injector semantics
# ---------------------------------------------------------------------------

def _drive(inj, site, n):
    fired = 0
    for _ in range(n):
        try:
            inj.fire(site)
        except chaos.InjectedFault:
            fired += 1
    return fired


def test_injector_after_and_times():
    inj = chaos.FaultInjector(parse_spec("s:err:after=3:times=2"))
    outcomes = []
    for _ in range(6):
        try:
            inj.fire("s")
            outcomes.append(False)
        except chaos.InjectedFault:
            outcomes.append(True)
    # eligible from traversal 3, capped at 2 fires
    assert outcomes == [False, False, True, True, False, False]


def test_injector_rank_filter():
    rules = parse_spec("s:err:rank=3")
    hit = chaos.FaultInjector(rules, rank=3)
    miss = chaos.FaultInjector(rules, rank=1)
    assert _drive(hit, "s", 5) == 5
    assert _drive(miss, "s", 5) == 0


def test_injector_site_glob_and_counter():
    before = REGISTRY.get("hvd_faults_injected_total").total()
    inj = chaos.FaultInjector(parse_spec("kv_*:err"))
    assert _drive(inj, "kv_get", 2) == 2
    assert _drive(inj, "kv_put", 1) == 1
    assert _drive(inj, "negotiate", 4) == 0
    assert REGISTRY.get("hvd_faults_injected_total").total() - before == 3


def test_injector_probability_is_deterministic_per_seed():
    spec = "s:err:p=0.3:seed=11"
    a = chaos.FaultInjector(parse_spec(spec))
    b = chaos.FaultInjector(parse_spec(spec))
    fired_a = _drive(a, "s", 300)
    fired_b = _drive(b, "s", 300)
    assert fired_a == fired_b and 0 < fired_a < 300
    assert a.fired_events() == b.fired_events()
    # a different seed draws a different stream
    c = chaos.FaultInjector(parse_spec("s:err:p=0.3:seed=12"))
    _drive(c, "s", 300)
    assert c.fired_events() != a.fired_events()


def test_injector_streams_independent_across_ranks():
    spec = parse_spec("s:err:p=0.5:seed=9")
    r0 = chaos.FaultInjector(spec, rank=0)
    r1 = chaos.FaultInjector(spec, rank=1)
    _drive(r0, "s", 200)
    _drive(r1, "s", 200)
    assert r0.fired_events() != r1.fired_events()
    # ...but each rank's own stream reproduces exactly
    r1b = chaos.FaultInjector(spec, rank=1)
    _drive(r1b, "s", 200)
    assert r1.fired_events() == r1b.fired_events()


def test_injector_delay_sleeps():
    inj = chaos.FaultInjector(parse_spec("s:delay=30ms:times=1"))
    t0 = time.monotonic()
    inj.fire("s")
    assert time.monotonic() - t0 >= 0.025
    t0 = time.monotonic()
    inj.fire("s")                       # times exhausted: no sleep
    assert time.monotonic() - t0 < 0.02


def test_injector_once_latch(tmp_path):
    latch = tmp_path / "latch"
    spec = parse_spec(f"s:err:once={latch}")
    a = chaos.FaultInjector(spec)
    assert _drive(a, "s", 3) == 1       # claimed on first fire
    assert latch.exists()
    b = chaos.FaultInjector(spec)       # "relaunched" process
    assert _drive(b, "s", 3) == 0


def test_arm_is_idempotent_for_same_spec():
    a = chaos.arm("s:err:after=5")
    chaos.fire("s")                     # traversal 1 recorded
    b = chaos.arm("s:err:after=5")      # same spec: injector kept
    assert b is a
    c = chaos.arm("s:err:after=9")      # different spec: replaced
    assert c is not a


def test_arm_rejects_bad_spec():
    with pytest.raises(ValueError):
        chaos.arm("kv_get:bogus=1")
    assert chaos.injector() is None


def test_fire_disarmed_is_noop():
    chaos.disarm()
    chaos.fire("anything")              # must not raise


def test_injected_fault_is_retryable():
    assert retry.retryable_error(chaos.InjectedFault("x"))
    assert issubclass(chaos.InjectedFault, ConnectionError)


def test_fault_records_land_in_flight_ring():
    from horovod_tpu.obs import flightrec
    rec = flightrec.RECORDER
    n0 = len(rec)
    chaos.arm("s:err:times=1")
    with pytest.raises(chaos.InjectedFault):
        chaos.fire("s")
    events = rec.snapshot()[-(len(rec) - n0):] if len(rec) > n0 else []
    assert any(e["kind"] == "fault_injected"
               and e["data"]["fault_kind"] == "err"
               and e["name"] == "s" for e in events), events[-3:]


# ---------------------------------------------------------------------------
# unified retry policy
# ---------------------------------------------------------------------------

def test_retry_call_retries_then_succeeds():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    out = retry.retry_call(flaky, op="t1",
                           policy=retry.RetryPolicy(max_attempts=5,
                                                    base_delay_s=0.01),
                           sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3 and len(sleeps) == 2
    assert sleeps[1] > sleeps[0]        # exponential


def test_retry_call_gives_up_after_max_attempts():
    before = REGISTRY.get("hvd_retry_giveups_total").total()
    with pytest.raises(ConnectionError):
        retry.retry_call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                         op="t2",
                         policy=retry.RetryPolicy(max_attempts=3,
                                                  base_delay_s=0.0),
                         sleep=lambda s: None)
    assert REGISTRY.get("hvd_retry_giveups_total").total() - before == 1


def test_retry_call_honors_overall_deadline():
    clock = {"t": 0.0}

    def sleep(s):
        clock["t"] += s

    with pytest.raises(TimeoutError):
        retry.retry_call(
            lambda: (_ for _ in ()).throw(TimeoutError("slow")),
            op="t3",
            policy=retry.RetryPolicy(max_attempts=None, deadline_s=1.0,
                                     base_delay_s=0.3, max_delay_s=0.3,
                                     jitter=0.0),
            clock=lambda: clock["t"], sleep=sleep)
    assert clock["t"] <= 1.0 + 1e-9     # never slept past the budget


def test_retry_call_permanent_and_unclassified_surface_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        retry.retry_call(bad, op="t4")
    assert calls["n"] == 1

    class Expired(retry.Permanent, TimeoutError):
        pass

    calls["n"] = 0

    def expired():
        calls["n"] += 1
        raise Expired("budget gone")

    with pytest.raises(Expired):
        retry.retry_call(expired, op="t4")
    assert calls["n"] == 1


def test_retry_jitter_is_deterministic():
    p = retry.RetryPolicy(base_delay_s=0.1, jitter=0.2, seed=3)
    a = [p.delay_for("op", i) for i in range(1, 6)]
    b = [p.delay_for("op", i) for i in range(1, 6)]
    assert a == b
    assert a != [p.delay_for("other", i) for i in range(1, 6)]
    flat = retry.RetryPolicy(base_delay_s=0.1, jitter=0.0)
    assert flat.delay_for("op", 1) == pytest.approx(0.1)
    assert flat.delay_for("op", 2) == pytest.approx(0.2)


def test_backoff_loop_helper_resets():
    b = retry.Backoff(retry.RetryPolicy(base_delay_s=0.1, max_delay_s=0.4,
                                        jitter=0.0), op="loop")
    assert [round(b.next_delay(), 3) for _ in range(4)] == \
        [0.1, 0.2, 0.4, 0.4]
    b.reset()
    assert b.next_delay() == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# kv blob deadline + retry (satellite: one budget across chunk fetches)
# ---------------------------------------------------------------------------

class _FakeKV:
    """KV double: programmable per-key behavior."""

    def __init__(self, store=None, fail_every=0):
        self.store = dict(store or {})
        self.calls = 0
        self.fail_every = fail_every

    def _maybe_fail(self):
        self.calls += 1
        if self.fail_every and self.calls % self.fail_every == 0:
            raise ConnectionError("flaky store")

    def set(self, key, value):
        self._maybe_fail()
        self.store[key] = value

    def wait(self, key, timeout_ms=1000):
        self._maybe_fail()
        if key in self.store:
            return self.store[key]
        # emulate the native client's blocking wait running out
        time.sleep(min(timeout_ms / 1000.0, 0.02))
        raise TimeoutError(f"no {key} within {timeout_ms}ms")


def _blob_store(prefix, data, chunk):
    store = {}
    n = max(1, (len(data) + chunk - 1) // chunk)
    for i in range(n):
        store[f"{prefix}/{i}"] = data[i * chunk:(i + 1) * chunk]
    store[f"{prefix}/meta"] = f"{n}:{len(data)}".encode()
    return store


def test_kv_get_blob_roundtrip_and_flaky_retry(monkeypatch):
    from horovod_tpu.runner import api
    data = bytes(range(256)) * 64
    monkeypatch.setattr(api, "_CHUNK", 1024)
    kv = _FakeKV(_blob_store("b", data, 1024), fail_every=3)
    assert api.kv_get_blob(kv, "b", timeout_ms=5000) == data


def test_kv_get_blob_one_overall_deadline(monkeypatch):
    """A missing chunk must exhaust ONE shared budget — pre-fix, each of
    the n chunks restarted the full timeout (n-fold overrun)."""
    from horovod_tpu.runner import api
    monkeypatch.setattr(api, "_CHUNK", 8)
    data = b"x" * 64                      # 8 chunks
    store = _blob_store("b", data, 8)
    for i in range(2, 8):                 # chunks 2..7 never arrive
        del store[f"b/{i}"]
    kv = _FakeKV(store)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        api.kv_get_blob(kv, "b", timeout_ms=300)
    took = time.monotonic() - t0
    assert took < 1.5, f"deadline not shared across chunks: {took:.2f}s"


def test_kv_put_blob_retries_transient_errors(monkeypatch):
    from horovod_tpu.runner import api
    monkeypatch.setattr(api, "_CHUNK", 16)
    data = b"y" * 100
    kv = _FakeKV(fail_every=4)
    api.kv_put_blob(kv, "p", data, deadline_s=5.0)
    got = b"".join(kv.store[f"p/{i}"] for i in range(7))
    assert got == data
    assert kv.store["p/meta"] == b"7:100"


def test_kv_blob_sites_injectable(monkeypatch):
    """Injected kv faults ride the retry path: p<1 errors are absorbed,
    the blob still round-trips, and the fault counter moved."""
    from horovod_tpu.runner import api
    monkeypatch.setattr(api, "_CHUNK", 64)
    before = REGISTRY.get("hvd_faults_injected_total").total()
    chaos.arm("kv_put:err:p=0.2:seed=1; kv_get:err:p=0.2:seed=2")
    try:
        kv = _FakeKV()
        data = b"z" * 1000
        api.kv_put_blob(kv, "c", data, deadline_s=10.0)
        assert api.kv_get_blob(kv, "c", timeout_ms=10000) == data
    finally:
        chaos.disarm()
    assert REGISTRY.get("hvd_faults_injected_total").total() > before


# ---------------------------------------------------------------------------
# blacklist decay (satellite: probation instead of a life sentence)
# ---------------------------------------------------------------------------

def _driver(clock, cooldown=10.0, max_cooldown=40.0, spec="a:2,b:2"):
    from horovod_tpu.runner.elastic import ElasticDriver, FixedDiscovery
    return ElasticDriver(FixedDiscovery(spec), min_np=1,
                         blacklist_cooldown_s=cooldown,
                         blacklist_max_cooldown_s=max_cooldown,
                         clock=lambda: clock["t"])


def test_blacklist_decays_and_readmits_on_probation():
    clock = {"t": 0.0}
    d = _driver(clock)
    d.blacklist("a")
    assert d.blacklisted() == {"a"}
    clock["t"] = 9.9
    assert d.blacklisted() == {"a"}
    clock["t"] = 10.1                    # cooldown lapsed
    assert d.blacklisted() == set()
    assert d.blacklist_failures("a") == 1   # probation, not amnesia
    d.poll_hosts()
    assert [host for _, host, _ in d.assignment()] == \
        ["a", "a", "b", "b"]


def test_blacklist_cooldown_doubles_per_failure_and_caps():
    clock = {"t": 0.0}
    d = _driver(clock, cooldown=10.0, max_cooldown=25.0)
    d.blacklist("a")                     # cooldown 10
    clock["t"] = 11.0
    assert d.blacklisted() == set()
    d.blacklist("a")                     # failure #2: cooldown 20
    clock["t"] = 11.0 + 19.0
    assert d.blacklisted() == {"a"}
    clock["t"] = 11.0 + 21.0
    assert d.blacklisted() == set()
    d.blacklist("a")                     # failure #3: 40 -> capped 25
    clock["t"] = 32.0 + 24.0
    assert d.blacklisted() == {"a"}
    clock["t"] = 32.0 + 26.0
    assert d.blacklisted() == set()


def test_blacklist_zero_cooldown_is_permanent():
    clock = {"t": 0.0}
    d = _driver(clock, cooldown=0.0)
    d.blacklist("a")
    clock["t"] = 1e9
    assert d.blacklisted() == {"a"}


def test_wait_for_slots_survives_discovery_failures():
    from horovod_tpu.runner.elastic import ElasticDriver, HostDiscovery
    from horovod_tpu.runner.hosts import parse_hosts

    class Flaky(HostDiscovery):
        def __init__(self):
            self.calls = 0

        def find_available_hosts(self):
            self.calls += 1
            if self.calls <= 2:
                raise RuntimeError("discovery script crashed")
            return parse_hosts("a:2")

    d = ElasticDriver(Flaky(), min_np=2, poll_interval_s=0.01)
    hosts = d.wait_for_available_slots(timeout_s=10.0)
    assert [h.hostname for h in hosts] == ["a"]
    assert d._discovery.calls == 3


def test_wait_for_slots_still_times_out():
    from horovod_tpu.runner.elastic import ElasticDriver, HostDiscovery

    class Dead(HostDiscovery):
        def find_available_hosts(self):
            raise RuntimeError("never")

    d = ElasticDriver(Dead(), min_np=1, poll_interval_s=0.01)
    with pytest.raises(TimeoutError, match="last discovery error"):
        d.wait_for_available_slots(timeout_s=0.2)


# ---------------------------------------------------------------------------
# /healthz: components + negotiation-age limit
# ---------------------------------------------------------------------------

def test_healthz_component_degrades_and_recovers():
    from horovod_tpu.context import _health_snapshot, set_component_health
    assert _health_snapshot()["ready"] is True
    set_component_health("serving", False, reason="drain window")
    try:
        h = _health_snapshot()
        assert h["ready"] is False
        assert h["status"] == "degraded:serving"
        assert h["components"]["serving"]["reason"] == "drain window"
        set_component_health("serving", True)
        assert _health_snapshot()["ready"] is True
    finally:
        set_component_health("serving", None)
    assert "components" not in _health_snapshot()


def test_healthz_negotiation_age_limit():
    from horovod_tpu.context import _health_snapshot, global_state
    cfg = global_state().config
    old = cfg.health_max_negotiation_age_s
    try:
        cfg.health_max_negotiation_age_s = 1e-9
        h = _health_snapshot()
        assert h["ready"] is False and h["status"] == "stalled"
        cfg.health_max_negotiation_age_s = 1e9
        assert _health_snapshot()["ready"] is True
    finally:
        cfg.health_max_negotiation_age_s = old


# ---------------------------------------------------------------------------
# serving graceful degradation (in-process; the np=1 harness scenario
# additionally asserts the live 200->503->200 HTTP transition)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_serving():
    import jax
    from horovod_tpu.models import llama
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return params, cfg


def test_serving_abort_carries_error_finish_reason(tiny_serving):
    from horovod_tpu import serving
    from horovod_tpu.context import _health_snapshot
    params, cfg = tiny_serving
    with serving.serve(params, cfg, num_blocks=16, block_size=8,
                       max_active=2) as sess:
        chaos.arm("serving_step:err:after=2:times=1")
        try:
            f0 = sess.submit(np.arange(4, dtype=np.int32), max_tokens=8)
            f1 = sess.submit(np.arange(3, dtype=np.int32), max_tokens=8)
            sess.drain()
            r0, r1 = f0.result(timeout=60), f1.result(timeout=60)
        finally:
            chaos.disarm()
        # both in-flight requests finished NOW with the error reason and
        # their partial tokens (step 1 = prefill emit + one decode tick)
        for r in (r0, r1):
            assert r.metrics["finish_reason"] == "error"
            assert "injected fault" in r.metrics["error"]
            assert 1 <= len(r.tokens) < 8
        assert sess.recoveries == 1
        # recovered: healthz is green again and new traffic flows
        assert _health_snapshot()["ready"] is True
        f2 = sess.submit(np.arange(5, dtype=np.int32), max_tokens=3)
        sess.drain()
        r2 = f2.result(timeout=60)
        assert r2.metrics["finish_reason"] == "length"
        assert len(r2.tokens) == 3


def test_serving_finish_reasons_normal_paths(tiny_serving):
    from horovod_tpu import serving
    params, cfg = tiny_serving
    with serving.serve(params, cfg, num_blocks=16, block_size=8,
                       max_active=2) as sess:
        f = sess.submit(np.arange(4, dtype=np.int32), max_tokens=2)
        sess.drain()
        assert f.result(timeout=60).metrics["finish_reason"] == "length"


def test_serving_gives_up_after_max_recoveries(tiny_serving):
    from horovod_tpu import serving
    params, cfg = tiny_serving
    from horovod_tpu.context import set_component_health
    try:
        with serving.serve(params, cfg, num_blocks=16, block_size=8,
                           max_active=2, max_recoveries=0) as sess:
            chaos.arm("serving_step:err")
            try:
                sess.submit(np.arange(4, dtype=np.int32), max_tokens=4)
                with pytest.raises(chaos.InjectedFault):
                    sess.drain()
            finally:
                chaos.disarm()
    finally:
        set_component_health("serving", None)


def test_serving_admission_fault_rejects_before_queue(tiny_serving):
    from horovod_tpu import serving
    params, cfg = tiny_serving
    with serving.serve(params, cfg, num_blocks=16, block_size=8,
                       max_active=2) as sess:
        chaos.arm("serving_admit:err")
        try:
            with pytest.raises(chaos.InjectedFault):
                sess.submit(np.arange(4, dtype=np.int32), max_tokens=4)
        finally:
            chaos.disarm()
        assert not sess.engine.has_work()
