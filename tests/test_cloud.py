"""TPU-VM metadata slice discovery († driver_service auto host inventory,
re-sourced from the GCE metadata server) against a mocked endpoint."""

import http.server
import os
import subprocess
import sys
import threading

import pytest

from horovod_tpu.runner.cloud import (
    MetadataUnavailable,
    parse_worker_endpoints,
    tpu_pod_hosts,
    worker_number,
)
from horovod_tpu.runner.hosts import HostSlots

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TPU_ENV = (
    "ACCELERATOR_TYPE: 'v5p-16'\n"
    "CHIPS_PER_HOST_BOUNDS: '2,2,1'\n"
    "HOST_BOUNDS: '2,1,1'\n"
)


class _Meta(http.server.BaseHTTPRequestHandler):
    attrs = {
        "worker-network-endpoints":
            "uid-0:8470:10.130.0.2,uid-1:8470:10.130.0.3",
        "tpu-env": TPU_ENV,
        "agent-worker-number": "1",
    }

    def do_GET(self):  # noqa: N802 (stdlib naming)
        if self.headers.get("Metadata-Flavor") != "Google":
            self.send_error(403)
            return
        name = self.path.rsplit("/", 1)[-1]
        if name not in self.attrs:
            self.send_error(404)
            return
        body = self.attrs[name].encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture()
def meta_server(monkeypatch):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Meta)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    monkeypatch.setenv("HVDTPU_METADATA_ROOT",
                       f"http://127.0.0.1:{srv.server_address[1]}"
                       "/computeMetadata/v1")
    yield srv
    srv.shutdown()


def test_parse_worker_endpoints_formats():
    assert parse_worker_endpoints(
        "uid:8470:10.0.0.2,uid:8470:10.0.0.3") == ["10.0.0.2", "10.0.0.3"]
    # semicolon-separated + different field orders also appear in the wild
    assert parse_worker_endpoints(
        "10.0.0.4:uid;10.0.0.5:uid") == ["10.0.0.4", "10.0.0.5"]
    assert parse_worker_endpoints("") == []


def test_tpu_pod_hosts_from_mock(meta_server):
    # One process per host VM is the TPU-native model (each drives all
    # its local chips); --slots overrides for self-partitioned setups.
    hosts = tpu_pod_hosts()
    assert hosts == [HostSlots("10.130.0.2", 1), HostSlots("10.130.0.3", 1)]
    assert tpu_pod_hosts(default_slots=4)[0].slots == 4
    assert worker_number() == 1


def test_tpu_pod_hosts_unreachable(monkeypatch):
    monkeypatch.setenv("HVDTPU_METADATA_ROOT", "http://127.0.0.1:1/none")
    with pytest.raises(MetadataUnavailable, match="-H host:slots"):
        tpu_pod_hosts()


@pytest.mark.integration
def test_hvdrun_tpu_pod_flag_without_metadata():
    env = dict(os.environ)
    env["HVDTPU_METADATA_ROOT"] = "http://127.0.0.1:1/none"
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--tpu-pod", "--",
         "python", "x.py"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
    assert res.returncode == 2
    assert "metadata" in res.stderr.lower()


@pytest.mark.integration
def test_hvdrun_tpu_pod_conflicts_with_hosts():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--tpu-pod", "-H", "a:1", "--", "python", "x.py"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert res.returncode == 2
    assert "conflicts" in res.stderr