"""Spark / Ray launcher integrations, tested against fake cluster managers.

† ``test/single/test_spark.py`` / ``test_ray.py``: upstream tests these by
mocking the cluster manager's placement primitives and asserting the
orchestration (env wiring, rank assignment, result collection).  Same here:
a fake ``pyspark`` whose barrier stage forks one process per partition, and
a fake ``ray`` whose actors are forked processes — so the env blocks are
truly per-worker, as on a real cluster.
"""

import multiprocessing
import os
import socket
import sys
import types

import pytest

from horovod_tpu.runner.cluster import DriverServices, local_ranks

_mp = multiprocessing.get_context("fork")


# ---------------------------------------------------------------------------
# cluster.py primitives
# ---------------------------------------------------------------------------

def test_local_ranks():
    assert local_ranks(["a", "a", "b", "a", "b"]) == [0, 1, 0, 2, 1]
    assert local_ranks([]) == []


def test_driver_services_env():
    with DriverServices(4, service_ip="127.0.0.1") as s:
        env = s.worker_env(2, 1, platform="cpu", extra_env={"FOO": "bar"})
        assert env["HVDTPU_CROSS_RANK"] == "2"
        assert env["HVDTPU_CROSS_SIZE"] == "4"
        assert env["HVDTPU_LOCAL_RANK"] == "1"
        assert env["HVDTPU_PLATFORM"] == "cpu"
        assert env["FOO"] == "bar"
        assert env["HVDTPU_SECRET"] == s.secret
        host, _, port = env["HVDTPU_CONTROLLER_ADDR"].rpartition(":")
        assert host == "127.0.0.1" and int(port) == s.controller.port
        assert int(env["HVDTPU_RENDEZVOUS_ADDR"].rpartition(":")[2]) \
            == s.kv.port
        kv_port = s.kv.port
    # close() must actually stop the native servers (regression: a close/
    # stop naming mismatch silently leaked them); the port must refuse.
    with pytest.raises(OSError):
        c = socket.create_connection(("127.0.0.1", kv_port), timeout=2)
        c.close()


def test_driver_services_num_proc_validation():
    with pytest.raises(ValueError):
        DriverServices(0)


# ---------------------------------------------------------------------------
# fake pyspark (barrier stage -> forked process per partition)
# ---------------------------------------------------------------------------

class _FakeBarrierCtx:
    current = None

    def __init__(self, pid, n, barrier, store):
        self._pid, self._n, self._barrier, self._store = pid, n, barrier, store

    @classmethod
    def get(cls):
        return cls.current

    def partitionId(self):
        return self._pid

    def allGather(self, s):
        self._store[self._pid] = s
        self._barrier.wait()
        return [self._store[i] for i in range(self._n)]


def _install_fake_pyspark(monkeypatch, n_parallel=4):
    pyspark = types.ModuleType("pyspark")
    pyspark_sql = types.ModuleType("pyspark.sql")
    pyspark.BarrierTaskContext = _FakeBarrierCtx

    class _FakeBarrierRDD:
        def __init__(self, n):
            self._n = n

        def mapPartitions(self, body):
            self._body = body
            return self

        def collect(self):
            n = self._n
            mgr = _mp.Manager()
            store, results = mgr.dict(), mgr.list()
            barrier = _mp.Barrier(n)

            def child(pid):
                _FakeBarrierCtx.current = _FakeBarrierCtx(
                    pid, n, barrier, store)
                for item in self._body(iter(())):
                    results.append(item)

            procs = [_mp.Process(target=child, args=(p,)) for p in range(n)]
            for p in procs:
                p.start()
            for p in procs:
                p.join(60)
                assert p.exitcode == 0, f"partition failed: {p.exitcode}"
            return list(results)

    class _FakeRDD:
        def __init__(self, n):
            self._n = n

        def barrier(self):
            return _FakeBarrierRDD(self._n)

    class _FakeConf:
        def get(self, key, default=None):
            return default

    class _FakeSparkContext:
        defaultParallelism = n_parallel

        def getConf(self):
            return _FakeConf()

        def parallelize(self, data, n):
            assert len(list(data)) == n
            return _FakeRDD(n)

    class _FakeSession:
        sparkContext = _FakeSparkContext()

    class SparkSession:
        builder = None  # getActiveSession path is the one exercised

        @staticmethod
        def getActiveSession():
            return _FakeSession()

    pyspark_sql.SparkSession = SparkSession
    pyspark.sql = pyspark_sql
    monkeypatch.setitem(sys.modules, "pyspark", pyspark)
    monkeypatch.setitem(sys.modules, "pyspark.sql", pyspark_sql)


def _env_probe():
    """The 'training fn': report this rank's wired environment."""
    return {k: os.environ.get(k, "")
            for k in ("HVDTPU_CROSS_RANK", "HVDTPU_CROSS_SIZE",
                      "HVDTPU_LOCAL_RANK", "HVDTPU_SECRET",
                      "HVDTPU_CONTROLLER_ADDR", "HVDTPU_RENDEZVOUS_ADDR",
                      "HVDTPU_COORDINATOR_ADDR", "HVDTPU_PLATFORM")}


def test_spark_run_wires_ranks(monkeypatch):
    _install_fake_pyspark(monkeypatch)
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(_env_probe, num_proc=3, platform="cpu")
    assert len(results) == 3
    secrets = {r["HVDTPU_SECRET"] for r in results}
    assert len(secrets) == 1
    for rank, r in enumerate(results):
        assert r["HVDTPU_CROSS_RANK"] == str(rank)
        assert r["HVDTPU_CROSS_SIZE"] == "3"
        # all fake partitions run on this host -> local ranks 0,1,2
        assert r["HVDTPU_LOCAL_RANK"] == str(rank)
        assert r["HVDTPU_PLATFORM"] == "cpu"
        assert r["HVDTPU_COORDINATOR_ADDR"].count(":") == 1
    # every rank got the same controller/rendezvous endpoints
    assert len({r["HVDTPU_CONTROLLER_ADDR"] for r in results}) == 1


def test_spark_run_default_num_proc(monkeypatch):
    _install_fake_pyspark(monkeypatch, n_parallel=2)
    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(_env_probe)
    assert [r["HVDTPU_CROSS_RANK"] for r in results] == ["0", "1"]


def test_spark_run_num_proc_validation(monkeypatch):
    _install_fake_pyspark(monkeypatch)
    import horovod_tpu.spark as hvd_spark
    with pytest.raises(ValueError, match="num_proc"):
        hvd_spark.run(_env_probe, num_proc=0)


def test_spark_run_without_pyspark(monkeypatch):
    monkeypatch.setitem(sys.modules, "pyspark", None)
    monkeypatch.setitem(sys.modules, "pyspark.sql", None)
    import horovod_tpu.spark as hvd_spark
    with pytest.raises(ImportError, match="pyspark"):
        hvd_spark.run(_env_probe, num_proc=2)


# ---------------------------------------------------------------------------
# fake ray (actor = forked process with a command pipe)
# ---------------------------------------------------------------------------

class _ActorProc:
    """Forked process executing (method, args) requests sequentially."""

    def __init__(self, cls, init_args):
        parent, child = _mp.Pipe()
        self._pipe = parent

        def loop(conn):
            obj = cls(*init_args)
            while True:
                msg = conn.recv()
                if msg is None:
                    return
                method, args = msg
                try:
                    conn.send(("ok", getattr(obj, method)(*args)))
                except Exception as e:  # pragma: no cover
                    conn.send(("err", repr(e)))

        self._proc = _mp.Process(target=loop, args=(child,))
        self._proc.start()

    def call(self, method, args):
        self._pipe.send((method, args))
        status, val = self._pipe.recv()
        assert status == "ok", val
        return val

    def kill(self):
        try:
            self._pipe.send(None)
        except OSError:
            pass
        self._proc.join(10)


def _install_fake_ray(monkeypatch):
    ray = types.ModuleType("ray")
    ray._initialized = True

    class _Method:
        def __init__(self, actor, name):
            self._actor, self._name = actor, name

        def remote(self, *args):
            return ("ref", self._actor.call(self._name, args))

    class _ActorHandle:
        def __init__(self, proc):
            self._proc = proc

        def __getattr__(self, name):
            return _Method(self._proc, name)

    class _RemoteClass:
        def __init__(self, cls):
            self._cls = cls
            self.opts = {}

        def options(self, **opts):
            self.opts = opts
            return self

        def remote(self, *args):
            return _ActorHandle(_ActorProc(self._cls, args))

    ray.remote = lambda cls: _RemoteClass(cls)
    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: None
    ray.get = lambda refs: ([r[1] for r in refs]
                            if isinstance(refs, list) else refs[1])
    ray.kill = lambda h: h._proc.kill()
    monkeypatch.setitem(sys.modules, "ray", ray)


def test_ray_executor(monkeypatch):
    _install_fake_ray(monkeypatch)
    from horovod_tpu.ray import RayExecutor

    ex = RayExecutor(num_workers=3, platform="cpu")
    ex.start()
    try:
        results = ex.run(_env_probe)
        assert len(results) == 3
        for rank, r in enumerate(results):
            assert r["HVDTPU_CROSS_RANK"] == str(rank)
            assert r["HVDTPU_CROSS_SIZE"] == "3"
            assert r["HVDTPU_LOCAL_RANK"] == str(rank)  # one fake host
            assert r["HVDTPU_PLATFORM"] == "cpu"
        assert len({r["HVDTPU_SECRET"] for r in results}) == 1
        single = ex.execute_single(_env_probe)
        assert single["HVDTPU_CROSS_RANK"] == "0"
    finally:
        ex.shutdown()
    assert ex._workers == []


def test_ray_executor_errors(monkeypatch):
    _install_fake_ray(monkeypatch)
    from horovod_tpu.ray import RayExecutor
    with pytest.raises(ValueError):
        RayExecutor(num_workers=0)
    ex = RayExecutor(num_workers=1)
    with pytest.raises(RuntimeError, match="start"):
        ex.run(_env_probe)


def test_spark_estimator_namespaces():
    """† horovod.spark.keras import path shape."""
    from horovod_tpu.spark.keras import KerasEstimator, LocalStore  # noqa
    from horovod_tpu.spark.jax import JaxEstimator  # noqa
    from horovod_tpu.estimator import KerasEstimator as KE
    assert KerasEstimator is KE


def test_ray_executor_without_ray(monkeypatch):
    monkeypatch.setitem(sys.modules, "ray", None)
    from horovod_tpu.ray import RayExecutor
    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()
