"""Collective verb correctness on the 8-fake-device rig.

Mirrors † ``test/parallel/test_torch.py``: ``test_horovod_allreduce`` (random
tensors × dtypes × dims, assert exact average), ``test_horovod_allgather``
(incl. variable first dims), ``test_horovod_broadcast`` (every root),
``test_horovod_alltoall`` (uniform + explicit splits), error cases raising on
mismatched shapes.
"""

import numpy as np
import pytest

import horovod_tpu as hvd

N = 8


def _rand(shape, dtype, seed):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(-100, 100, size=shape).astype(dtype)
    return rng.randn(*shape).astype(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.int32, np.float16])
@pytest.mark.parametrize("shape", [(4,), (3, 5), (2, 3, 4)])
def test_allreduce_average_sum(dtype, shape):
    parts = [_rand(shape, dtype, seed=r) for r in range(N)]
    x = hvd.per_rank(parts)
    stacked = np.stack(parts)

    got_sum = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    np.testing.assert_allclose(got_sum, stacked.sum(0), rtol=2e-3, atol=1e-2)

    got_avg = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    if np.issubdtype(np.dtype(dtype), np.integer):
        np.testing.assert_array_equal(got_avg, stacked.sum(0) // N)
    else:
        np.testing.assert_allclose(got_avg, stacked.sum(0) / N,
                                   rtol=2e-3, atol=1e-2)


def test_allreduce_min_max_product():
    parts = [_rand((6,), np.float32, seed=10 + r) for r in range(N)]
    x = hvd.per_rank(parts)
    stacked = np.stack(parts)
    np.testing.assert_allclose(
        hvd.to_numpy(hvd.allreduce(x, hvd.Min)), stacked.min(0), rtol=1e-6)
    np.testing.assert_allclose(
        hvd.to_numpy(hvd.allreduce(x, hvd.Max)), stacked.max(0), rtol=1e-6)
    np.testing.assert_allclose(
        hvd.to_numpy(hvd.allreduce(x, hvd.Product)), stacked.prod(0),
        rtol=1e-4)


def test_allreduce_prescale_postscale():
    parts = [np.full((3,), float(r + 1), np.float32) for r in range(N)]
    x = hvd.per_rank(parts)
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Sum, prescale_factor=2.0,
                                     postscale_factor=0.5))
    expected = np.stack(parts).sum(0) * 2.0 * 0.5
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_allreduce_scalar_per_rank():
    x = hvd.per_rank([np.float32(r) for r in range(N)])
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    assert got == sum(range(N))


def test_grouped_allreduce():
    groups = [[_rand((s,), np.float32, seed=100 * s + r) for r in range(N)]
              for s in (3, 7, 1)]
    xs = [hvd.per_rank(g) for g in groups]
    outs = hvd.grouped_allreduce(xs, hvd.Average)
    assert len(outs) == 3
    for g, o in zip(groups, outs):
        np.testing.assert_allclose(
            hvd.to_numpy(o), np.stack(g).mean(0), rtol=1e-5)


def test_grouped_allreduce_mixed_dtype():
    a = hvd.per_rank([np.full((2,), r, np.float32) for r in range(N)])
    b = hvd.per_rank([np.full((3,), r, np.int32) for r in range(N)])
    oa, ob = hvd.grouped_allreduce([a, b], hvd.Sum)
    np.testing.assert_allclose(hvd.to_numpy(oa), np.full((2,), 28.0))
    np.testing.assert_array_equal(hvd.to_numpy(ob), np.full((3,), 28))


def test_per_rank_shape_mismatch_raises():
    vals = [np.zeros((3,), np.float32)] * (N - 1) + [np.zeros((4,), np.float32)]
    with pytest.raises(ValueError, match="mismatched"):
        hvd.per_rank(vals)


def test_allgather_equal_shapes():
    parts = [_rand((2, 3), np.float32, seed=r) for r in range(N)]
    got = hvd.to_numpy(hvd.allgather(hvd.per_rank(parts)))
    np.testing.assert_allclose(got, np.concatenate(parts, 0), rtol=1e-6)


def test_allgather_ragged():
    parts = [_rand((r + 1, 2), np.float32, seed=r) for r in range(N)]
    got = hvd.to_numpy(hvd.allgather(parts))
    np.testing.assert_allclose(got, np.concatenate(parts, 0), rtol=1e-6)


def test_allgather_scalars():
    got = hvd.to_numpy(hvd.allgather(hvd.per_rank(
        [np.float32(r * 10) for r in range(N)])))
    np.testing.assert_allclose(got, np.arange(N) * 10.0)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    parts = [_rand((4, 2), np.float32, seed=r) for r in range(N)]
    got = hvd.to_numpy(hvd.broadcast(hvd.per_rank(parts), root))
    np.testing.assert_allclose(got, parts[root], rtol=1e-6)


def test_broadcast_int_and_bool():
    parts_i = [np.full((3,), r, np.int32) for r in range(N)]
    got = hvd.to_numpy(hvd.broadcast(hvd.per_rank(parts_i), 5))
    np.testing.assert_array_equal(got, parts_i[5])
    parts_b = [np.array([r % 2 == 0, True]) for r in range(N)]
    got_b = hvd.to_numpy(hvd.broadcast(hvd.per_rank(parts_b), 1))
    np.testing.assert_array_equal(got_b, parts_b[1])


def test_broadcast_bad_root():
    x = hvd.per_rank([np.zeros((2,), np.float32)] * N)
    with pytest.raises(ValueError):
        hvd.broadcast(x, N + 1)


def test_alltoall_uniform():
    k = 3
    parts = [np.arange(N * k * 2, dtype=np.float32).reshape(N * k, 2) + 1000 * r
             for r in range(N)]
    got = hvd.to_numpy(hvd.alltoall(hvd.per_rank(parts)))
    for i in range(N):
        for j in range(N):
            np.testing.assert_allclose(
                got[i, j * k:(j + 1) * k], parts[j][i * k:(i + 1) * k])


def test_alltoall_nonuniform_splits():
    splits = [1, 2, 0, 3, 1, 4, 2, 1]  # sums to 14
    rows = sum(splits)
    parts = [np.arange(rows, dtype=np.float32) + 100 * r for r in range(N)]
    pieces = hvd.alltoall(hvd.per_rank(parts), splits=splits)
    offs = np.concatenate([[0], np.cumsum(splits)])
    for dst in range(N):
        expected = np.concatenate(
            [parts[src][offs[dst]:offs[dst + 1]] for src in range(N)])
        np.testing.assert_allclose(hvd.to_numpy(pieces[dst]), expected)


def test_alltoall_bad_splits():
    x = hvd.per_rank([np.zeros((5,), np.float32)] * N)
    with pytest.raises(ValueError):
        hvd.alltoall(x)  # 5 not divisible by 8
    with pytest.raises(ValueError):
        hvd.alltoall(x, splits=[1] * N)  # sums to 8 != 5


def test_reducescatter():
    k = 2
    parts = [_rand((N * k,), np.float32, seed=r) for r in range(N)]
    got = hvd.to_numpy(hvd.reducescatter(hvd.per_rank(parts), hvd.Sum))
    expected = np.stack(parts).sum(0).reshape(N, k)
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_barrier():
    hvd.barrier()


def test_adasum_identical_inputs():
    # adasum(a, a) = a; tree of identical vectors returns the vector.
    a = _rand((16,), np.float32, seed=1)
    out = hvd.to_numpy(hvd.allreduce(hvd.per_rank([a] * N), hvd.Adasum))
    np.testing.assert_allclose(out, a, rtol=1e-5)


def test_adasum_orthogonal_pair_sums():
    # Orthogonal gradients: dot = 0 so adasum degenerates to plain sum.
    ps = hvd.add_process_set([0, 1])
    a = np.array([1.0, 0.0], np.float32)
    b = np.array([0.0, 1.0], np.float32)
    out = hvd.to_numpy(hvd.allreduce(hvd.per_rank([a, b], process_set=ps),
                                     hvd.Adasum, process_set=ps))
    np.testing.assert_allclose(out, a + b, rtol=1e-6)
    hvd.remove_process_set(ps)


def test_dispatch_cache_hits():
    from horovod_tpu.ops.collectives import dispatch_cache_stats
    x = hvd.per_rank([_rand((9,), np.float32, seed=r) for r in range(N)])
    hvd.allreduce(x, hvd.Sum)
    before = dispatch_cache_stats()
    hvd.allreduce(x, hvd.Sum)   # identical signature → cache hit
    after = dispatch_cache_stats()
    assert after["hits"] == before["hits"] + 1
    assert after["misses"] == before["misses"]


def test_fused_grouped_allreduce_single_collective_hlo():
    """The fusion promise, asserted in HLO: one fused group compiles to
    exactly ONE all-reduce collective, however many tensors went in
    († ``fusion_buffer_manager.cc``'s one-collective-per-fused-buffer
    contract; round-3 verdict asked for this assertion)."""
    import re
    from horovod_tpu.ops import collectives as C

    mesh, axis = C._mesh_axis(None)
    shapes = ((8,), (4, 4), (2, 2), (16,))
    numels = tuple(int(np.prod(s)) for s in shapes)
    fn = C._build_grouped_allreduce(mesh, axis, hvd.Sum, numels, shapes,
                                    1.0, 1.0)
    xs = [np.stack([_rand(s, np.float32, seed=i * 10 + r)
                    for r in range(N)]) for i, s in enumerate(shapes)]
    txt = fn.lower(xs).compile().as_text()
    n_collectives = len(re.findall(r"all-reduce(?:-start)?\(", txt))
    assert n_collectives == 1, (
        f"fused group compiled to {n_collectives} collectives:\n"
        + "\n".join(ln[:160] for ln in txt.splitlines()
                    if "all-reduce" in ln))
