"""serving/disagg/: cross-replica KV migration + pool-aware routing.

Deterministic CPU tests.  The load-bearing assertion is the same one
the colocated engine carries: greedy-token parity against batch
``generate()`` — here through a full export → publish → fetch → import
→ resume cycle across two engines, including the radix-partial-prefix
attach on either side, double imports, torn transports, and router
failover at every migration stage.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import llama
from horovod_tpu.obs import REGISTRY
from horovod_tpu.serving.disagg import (DictKV, DisaggRouter,
                                        DisaggRouterConfig,
                                        LocalDisaggReplica,
                                        MigrationUnavailable,
                                        delete_migration, fetch_migration,
                                        migration_published,
                                        publish_migration)
from horovod_tpu.serving.disagg import transport as mig_transport
from horovod_tpu.serving.kv_pager import KVPager, OutOfBlocks, PagedKVCache


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _oracle(params, cfg, prompt, max_new):
    full = np.asarray(llama.generate(
        params, jnp.asarray(np.asarray(prompt)[None]), cfg,
        max_new_tokens=max_new))[0]
    return [int(t) for t in full[len(prompt):]]


def _sess(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_active", 4)
    kw.setdefault("prefix_cache", True)
    return serving.serve(params, cfg, **kw)


def _export_one(sess, prompt, max_new, **submit_kw):
    """Run one prefill-export request to completion on ``sess`` and
    return (manifest, k_bytes, v_bytes, first_token)."""
    box = {}

    def grab(manifest, k_bytes, v_bytes):
        box["mig"] = (manifest, k_bytes, v_bytes)

    toks: list[int] = []
    fut = sess.submit(prompt, max_new, migrate_cb=grab,
                      stream_cb=lambda rid, t: toks.append(int(t)),
                      **submit_kw)
    sess.drain()
    res = fut.result(timeout=5)
    assert res.metrics["finish_reason"] == "migrated", res.metrics
    assert "mig" in box, "migrate_cb never ran"
    assert toks == list(res.tokens)
    return (*box["mig"], list(res.tokens))


def _counter_value(name, **labels):
    fam = REGISTRY.get(name)
    return fam.labels(**labels).value if labels else fam.value


# ---------------------------------------------------------------------------
# pager: export/import refcount interleavings (host-only, no jax)
# ---------------------------------------------------------------------------

def _pager(num_blocks=16, block_size=4):
    return KVPager(PagedKVCache(n_layers=2, num_blocks=num_blocks,
                                block_size=block_size, kv_heads=2,
                                head_dim=8))


def test_pager_import_attach_bumps_refcounts():
    """An import that prefix-attaches an exporter's blocks must bump
    their refcounts — releasing either side alone keeps the pages."""
    p = _pager()
    t1 = p.allocate(1, 16)                    # 4 blocks (the "export")
    t2 = p.allocate(2, 17, prefix_blocks=t1[:2])   # import, 2 shared
    assert t2[:2] == t1[:2]
    assert p.refcount(t1[0]) == 2 and p.refcount(t1[1]) == 2
    assert p.refcount(t1[2]) == 1
    p.check_invariants()
    free_before = p.free_blocks
    p.release(1)                              # exporter finishes first
    # Only the two unshared blocks of t1 actually freed.
    assert p.free_blocks == free_before + 2
    assert p.refcount(t2[0]) == 1, "shared pages must survive the export"
    p.check_invariants()
    p.release(2)
    p.check_invariants()


def test_pager_truncate_keeps_shared_across_export():
    """Truncating the importer back to the shared boundary drops its
    references without freeing pages the exporter still holds."""
    p = _pager()
    t1 = p.allocate(1, 12)                    # 3 blocks
    t2 = p.allocate(2, 20, prefix_blocks=t1)  # 3 shared + 2 own
    assert all(p.refcount(b) == 2 for b in t1)
    kept = p.truncate(2, 8)                   # back to 2 blocks
    assert kept == t1[:2]
    assert p.refcount(t1[2]) == 1, \
        "truncate must decref, not free, a block the exporter holds"
    assert p.table(1) == t1, "exporter's table untouched"
    p.check_invariants()
    p.release(1)
    assert p.refcount(t1[0]) == 1, "importer still holds the prefix"
    p.check_invariants()


def test_pager_double_attach_is_refcounted_not_copied():
    """Two imports of the same exported prefix share the same physical
    pages at refcount 3 — idempotent attach, no duplication."""
    p = _pager()
    t1 = p.allocate(1, 16)
    free_after_first = None
    for rid in (2, 3):
        p.allocate(rid, 17, prefix_blocks=t1[:3])
        if free_after_first is None:
            free_after_first = p.free_blocks
    assert all(p.refcount(b) == 3 for b in t1[:3])
    # The second import consumed only its non-shared tail.
    assert free_after_first - p.free_blocks == 2
    p.check_invariants()
    for rid in (1, 2, 3):
        p.release(rid)
    assert p.free_blocks == p.cache.num_blocks - 1
    p.check_invariants()


# ---------------------------------------------------------------------------
# transport: publish/fetch, shared deadline, torn reads
# ---------------------------------------------------------------------------

def _fake_migration(n=512):
    manifest = {"schema": 1, "version": "7.1.8", "k_len": n, "v_len": n,
                "generated": [3], "context_len": 8, "n_blocks": 2}
    return manifest, bytes(range(256)) * (n // 256), b"\x01" * n


def test_transport_roundtrip_and_cleanup():
    kv = DictKV()
    manifest, k, v = _fake_migration()
    assert not migration_published(kv, "7.1")
    publish_migration(kv, "7.1", manifest, k, v)
    assert migration_published(kv, "7.1")
    m2, k2, v2 = fetch_migration(kv, "7.1", timeout_ms=2000)
    assert (m2, k2, v2) == (manifest, k, v)
    delete_migration(kv, "7.1")
    assert not migration_published(kv, "7.1")
    with pytest.raises(MigrationUnavailable):
        fetch_migration(kv, "7.1", timeout_ms=100)


def test_transport_publish_shares_one_deadline():
    """Every chunk of all three blobs draws on ONE deadline: the
    per-call budgets handed to kv_put_blob must be non-increasing and
    bounded by the overall budget — never chunks x timeout."""
    seen = []
    real = mig_transport.kv_put_blob

    def spy(kv, key, blob, **kw):
        seen.append(kw["deadline_s"])
        return real(kv, key, blob, **kw)

    manifest, k, v = _fake_migration()
    old = mig_transport.kv_put_blob
    mig_transport.kv_put_blob = spy
    try:
        publish_migration(DictKV(), "9.1", manifest, k, v,
                          deadline_s=5.0)
    finally:
        mig_transport.kv_put_blob = old
    assert len(seen) == 3
    assert all(d <= 5.0 for d in seen), seen
    assert seen == sorted(seen, reverse=True), \
        f"later blobs must see a smaller remaining budget: {seen}"


def test_transport_fetch_shares_one_deadline():
    seen = []
    real = mig_transport.kv_get_blob

    def spy(kv, key, timeout_ms=10000):
        seen.append(timeout_ms)
        return real(kv, key, timeout_ms=timeout_ms)

    kv = DictKV()
    manifest, k, v = _fake_migration()
    publish_migration(kv, "9.2", manifest, k, v)
    old = mig_transport.kv_get_blob
    mig_transport.kv_get_blob = spy
    try:
        fetch_migration(kv, "9.2", timeout_ms=4000)
    finally:
        mig_transport.kv_get_blob = old
    assert len(seen) == 4        # manifest, k, v, manifest re-read
    assert all(t <= 4000 for t in seen), seen
    assert seen == sorted(seen, reverse=True), seen


def test_transport_torn_payload_length_detected():
    kv = DictKV()
    manifest, k, v = _fake_migration()
    publish_migration(kv, "9.3", manifest, k, v)
    # Corrupt the K payload under an honest meta record: fewer bytes
    # arrive than the manifest promised.
    kv.set("fd/mig/9.3/k/0", k[: len(k) // 2])
    kv.set("fd/mig/9.3/k/meta", f"1:{len(k) // 2}".encode())
    with pytest.raises(MigrationUnavailable, match="torn"):
        fetch_migration(kv, "9.3", timeout_ms=2000)


def test_transport_version_flip_mid_fetch_detected():
    """A republish that lands between the payload fetch and the
    manifest re-read flips the version; the importer must refuse the
    spliced payloads."""
    import json

    class FlippingKV(DictKV):
        def __init__(self):
            super().__init__()
            self.manifest_reads = 0
            self.armed = False

        def wait(self, key, timeout_ms=10000):
            if self.armed and key == "fd/mig/9.4/manifest/0":
                self.manifest_reads += 1
                if self.manifest_reads >= 2:
                    m = dict(_fake_migration()[0], version="7.2.9")
                    blob = json.dumps(m, sort_keys=True).encode()
                    self.set("fd/mig/9.4/manifest/meta",
                             f"1:{len(blob)}".encode())
                    self.set(key, blob)
            return super().wait(key, timeout_ms)

    kv = FlippingKV()
    manifest, k, v = _fake_migration()
    publish_migration(kv, "9.4", manifest, k, v)
    kv.armed = True
    with pytest.raises(MigrationUnavailable, match="version flipped"):
        fetch_migration(kv, "9.4", timeout_ms=2000)


# ---------------------------------------------------------------------------
# engine: export -> import parity
# ---------------------------------------------------------------------------

def test_migrated_decode_matches_generate(tiny):
    """The headline contract: a request prefilled on engine A and
    decoded on engine B emits exactly the tokens an unmigrated run
    emits (greedy decode is deterministic)."""
    cfg, params = tiny
    rng = np.random.RandomState(21)
    prompt = rng.randint(0, cfg.vocab_size, size=(9,)).astype(np.int32)
    sess_a, sess_b = _sess(tiny), _sess(tiny)

    manifest, k_bytes, v_bytes, head = _export_one(sess_a, prompt, 12)
    assert len(head) == 1, "export runs right after the prefill emission"

    streamed: list[int] = []
    fut = sess_b.import_migrated(
        manifest, k_bytes, v_bytes,
        stream_cb=lambda rid, t: streamed.append(int(t)))
    sess_b.drain()
    res = fut.result(timeout=5)
    want = _oracle(params, cfg, prompt, 12)
    assert head + list(res.tokens)[1:] == want  # head == res.tokens[0]
    assert list(res.tokens) == want, (res.tokens, want)
    assert res.metrics["finish_reason"] == "length"
    # The importer streams only the continuation; the prefill token was
    # already streamed by the exporting replica.
    assert head + streamed == want, (head, streamed)


def test_migrated_decode_honors_eos(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(22)
    prompt = rng.randint(0, cfg.vocab_size, size=(7,)).astype(np.int32)
    want = _oracle(params, cfg, prompt, 10)
    eos = want[4]                 # force an early stop mid-continuation
    sess_a, sess_b = _sess(tiny), _sess(tiny)
    manifest, k_bytes, v_bytes, _ = _export_one(sess_a, prompt, 10,
                                                eos_token=eos)
    fut = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    sess_b.drain()
    res = fut.result(timeout=5)
    assert res.metrics["finish_reason"] == "stop"
    assert list(res.tokens) == want[:5], (res.tokens, want)


def test_migrated_parity_with_radix_partial_prefix(tiny):
    """Both radix corners at once: the EXPORT side prefills through a
    warm prefix-cache hit (its table starts with shared pages), and the
    IMPORT side attaches the longest cached prefix locally instead of
    scattering those payload blocks."""
    cfg, params = tiny
    rng = np.random.RandomState(23)
    stem = rng.randint(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    prompt = np.concatenate(
        [stem, rng.randint(0, cfg.vocab_size, size=(5,))]).astype(np.int32)
    sess_a, sess_b = _sess(tiny), _sess(tiny)

    # Warm BOTH sides' radix caches with a request sharing the stem.
    for warm_sess in (sess_a, sess_b):
        warm_sess.submit(stem, 2)
        warm_sess.drain()

    manifest, k_bytes, v_bytes, head = _export_one(sess_a, prompt, 11)
    before = _counter_value("hvd_disagg_blocks_attached_total",
                            source="prefix_cache")
    fut = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    attached = _counter_value("hvd_disagg_blocks_attached_total",
                              source="prefix_cache") - before
    assert attached >= 1, \
        "import must attach the warmed prefix shared, not re-scatter it"
    sess_b.drain()
    res = fut.result(timeout=5)
    want = _oracle(params, cfg, prompt, 11)
    assert list(res.tokens) == want, (res.tokens, want)
    sess_b.engine.pager.check_invariants()


def test_double_import_is_idempotent(tiny):
    """Importing the same manifest twice (a decode-replica failover
    races its own retry) yields two independent requests with identical
    tokens; the second attach prefix-shares the first's pages."""
    cfg, params = tiny
    rng = np.random.RandomState(24)
    prompt = rng.randint(0, cfg.vocab_size, size=(10,)).astype(np.int32)
    sess_a, sess_b = _sess(tiny), _sess(tiny)
    manifest, k_bytes, v_bytes, _ = _export_one(sess_a, prompt, 9)

    before = _counter_value("hvd_disagg_blocks_attached_total",
                            source="prefix_cache")
    fut1 = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    fut2 = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    attached = _counter_value("hvd_disagg_blocks_attached_total",
                              source="prefix_cache") - before
    assert attached >= 1, \
        "second import must attach the first import's pages shared"
    sess_b.drain()
    want = _oracle(params, cfg, prompt, 9)
    r1, r2 = fut1.result(timeout=5), fut2.result(timeout=5)
    assert list(r1.tokens) == want
    assert list(r2.tokens) == want, "double import must stay token-identical"
    sess_b.engine.pager.check_invariants()


def test_import_rejects_geometry_and_torn_payloads(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(25)
    prompt = rng.randint(0, cfg.vocab_size, size=(6,)).astype(np.int32)
    sess_a = _sess(tiny)
    manifest, k_bytes, v_bytes, _ = _export_one(sess_a, prompt, 6)

    other = _sess(tiny, block_size=8)
    with pytest.raises(ValueError, match="geometry"):
        other.engine.import_migrated(manifest, k_bytes, v_bytes)
    sess_b = _sess(tiny)
    with pytest.raises(ValueError, match="torn"):
        sess_b.engine.import_migrated(manifest, k_bytes[:-8], v_bytes)
    bad = dict(manifest, schema=99)
    with pytest.raises(ValueError, match="schema"):
        sess_b.engine.import_migrated(bad, k_bytes, v_bytes)
    # A healthy import still works after the rejects (no leaked state).
    fut = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    sess_b.drain()
    assert list(fut.result(timeout=5).tokens) == \
        _oracle(params, cfg, prompt, 6)
    sess_b.engine.pager.check_invariants()


def test_migration_manifest_carries_one_connected_trace(tiny):
    """Regression: the decode-side import must ADOPT the manifest's
    trace context — same trace_id across export and import, the
    imported root parented under the exporting request's span — instead
    of opening a fresh orphan trace."""
    cfg, params = tiny
    rng = np.random.RandomState(27)
    prompt = rng.randint(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    sess_a, sess_b = _sess(tiny), _sess(tiny)
    manifest, k_bytes, v_bytes, _ = _export_one(sess_a, prompt, 8)
    assert manifest.get("trace", {}).get("sampled") is True, manifest
    tid = manifest["trace"]["trace_id"]
    fut = sess_b.import_migrated(manifest, k_bytes, v_bytes)
    sess_b.drain()
    fut.result(timeout=5)
    from horovod_tpu.obs import trace as obs_trace
    exp = obs_trace.TRACER.export(tid)
    assert exp is not None, "the adopted trace must finish under the " \
        "exporter's trace_id"
    root = next(s for s in exp["spans"]
                if s["name"] == "serving.migrated")
    assert root["parent_id"] == manifest["trace"]["span_id"], \
        "import root must be parented under the prefill-side span"


def test_import_out_of_slots_raises_out_of_blocks(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(26)
    prompt = rng.randint(0, cfg.vocab_size, size=(6,)).astype(np.int32)
    sess_a = _sess(tiny)
    manifest, k_bytes, v_bytes, _ = _export_one(sess_a, prompt, 8)
    sess_b = _sess(tiny, max_active=1)
    # Occupy the only slot with a long-running local request.
    sess_b.submit(prompt, 32)
    while not sess_b.engine.scheduler.running:
        sess_b._step_once()
    with pytest.raises(OutOfBlocks):
        sess_b.engine.import_migrated(manifest, k_bytes, v_bytes)
    sess_b.drain()


# ---------------------------------------------------------------------------
# router: pool placement + failover at every migration stage
# ---------------------------------------------------------------------------

def _fleet(tiny, pools, **cfg_kw):
    kv = DictKV()
    reps = [LocalDisaggReplica(f"r{i}", _sess(tiny), kv, pool=p)
            for i, p in enumerate(pools)]
    cfg_kw.setdefault("failover_grace_s", 0.05)
    cfg_kw.setdefault("max_attempts", 6)
    router = DisaggRouter(reps, kv, DisaggRouterConfig(**cfg_kw))
    return router, reps, kv


def test_router_migrates_and_matches_generate(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(31)
    prompts = [rng.randint(0, cfg.vocab_size, size=(6 + 3 * i,))
               .astype(np.int32) for i in range(3)]
    router, reps, _ = _fleet(tiny, ["prefill", "decode"])
    streamed: dict[int, list] = {}
    futs = [router.submit(p, 10, stream_cb=lambda fid, t:
                          streamed.setdefault(fid, []).append(t))
            for p in prompts]
    router.drain(timeout_s=120)
    for i, (p, f) in enumerate(zip(prompts, futs)):
        res = f.result(timeout=5)
        want = _oracle(params, cfg, p, 10)
        assert list(res.tokens) == want, (i, res.tokens, want)
        assert res.metrics["migrated"] is True, res.metrics
        assert streamed[i] == want, "streaming must be exactly-once"
    for rep in reps:
        rep.session.engine.pager.check_invariants()


def test_router_prefill_death_before_publish_replays_from_prompt(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(32)
    prompt = rng.randint(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    router, reps, kv = _fleet(
        tiny, ["prefill", "prefill", "decode"])
    fut = router.submit(prompt, 8)
    fl = next(iter(router._flights.values()))
    assert fl.state == "prefilling"
    # Kill the chosen prefill replica before it ever steps: nothing
    # durable exists, so the only correct replay point is the prompt.
    victim = fl.replica
    victim.kill()
    assert not migration_published(kv, fl.mig_id)
    router.drain(timeout_s=120)
    res = fut.result(timeout=5)
    assert list(res.tokens) == _oracle(params, cfg, prompt, 8)
    assert res.metrics["migrated"] is True
    assert router.failovers >= 1
    assert res.metrics["mig_id"].endswith(".2"), \
        "a fresh prefill attempt must use a fresh write-once mig_id"


def test_router_prefill_death_after_publish_uses_durable_point(tiny):
    """The durable-point branch: the victim published its manifest
    before dying, so the flight skips re-prefill entirely and proceeds
    straight to the decode pool with the dead replica's blocks."""
    cfg, params = tiny
    rng = np.random.RandomState(33)
    prompt = rng.randint(0, cfg.vocab_size, size=(9,)).astype(np.int32)
    router, reps, kv = _fleet(
        tiny, ["prefill", "prefill", "decode"])
    fut = router.submit(prompt, 8)
    fl = next(iter(router._flights.values()))
    victim = fl.replica
    # Drive ONLY the victim until its export is durable, then kill it
    # before the router ever reads the result.
    deadline = 120
    while not migration_published(kv, fl.mig_id):
        victim.session._step_once()
        deadline -= 1
        assert deadline > 0, "export never published"
    victim.kill()
    router.drain(timeout_s=120)
    res = fut.result(timeout=5)
    assert list(res.tokens) == _oracle(params, cfg, prompt, 8)
    assert res.metrics["migrated"] is True
    assert router.failovers >= 1
    assert res.metrics["mig_id"] == fl.mig_id and \
        res.metrics["mig_id"].endswith(".1"), \
        "the durable manifest must be reused, not re-prefilled"


def test_router_decode_death_reimports_token_identically(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(34)
    prompt = rng.randint(0, cfg.vocab_size, size=(7,)).astype(np.int32)
    router, reps, kv = _fleet(
        tiny, ["prefill", "decode", "decode"], cleanup=False)
    streamed: list[int] = []
    fut = router.submit(
        prompt, 12, stream_cb=lambda fid, t: streamed.append(t))
    fl = next(iter(router._flights.values()))
    # Pump until the decode leg has streamed a few tokens, then kill
    # the decoding replica mid-stream.
    for _ in range(10_000):
        router.pump()
        if fl.state == "decoding" and fl.delivered >= 3:
            break
    else:
        raise AssertionError(f"never reached mid-decode ({fl.state})")
    fl.replica.kill()
    router.drain(timeout_s=120)
    res = fut.result(timeout=5)
    want = _oracle(params, cfg, prompt, 12)
    assert list(res.tokens) == want, (res.tokens, want)
    assert router.failovers >= 1
    assert streamed == want, \
        f"replay must not re-deliver past the high-water mark: {streamed}"


def test_router_decode_placement_prefers_warm_prefix_cache(tiny):
    """All else equal, decode placement must pick the replica whose
    radix cache already holds the migrated prompt's prefix (the import
    attaches those blocks shared), via the side-effect-free peek()."""
    cfg, params = tiny
    rng = np.random.RandomState(36)
    prompt = rng.randint(0, cfg.vocab_size, size=(9,)).astype(np.int32)
    router, reps, _ = _fleet(tiny, ["prefill", "decode", "decode"])
    # Warm ONLY the SECOND decode replica (r2) — min() would otherwise
    # settle the tie on r1, so the prefix bonus must flip the choice.
    reps[2].session.submit(prompt, 2)
    reps[2].session.drain()
    hits = _counter_value("hvd_prefix_cache_hits_total")
    misses = _counter_value("hvd_prefix_cache_misses_total")
    assert reps[2].cached_prefix(prompt) >= 4
    assert reps[1].cached_prefix(prompt) == 0
    assert _counter_value("hvd_prefix_cache_hits_total") == hits and \
        _counter_value("hvd_prefix_cache_misses_total") == misses, \
        "the placement probe must not mutate cache counters/LRU"
    before = _counter_value("hvd_disagg_placed_total",
                            pool="decode", replica="r2")
    fut = router.submit(prompt, 8)
    router.drain(timeout_s=120)
    res = fut.result(timeout=5)
    assert list(res.tokens) == _oracle(params, cfg, prompt, 8)
    assert _counter_value("hvd_disagg_placed_total", pool="decode",
                          replica="r2") == before + 1, \
        "decode must land on the replica holding the cached prefix"


def test_router_mixed_pool_serves_both_stages(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(35)
    prompt = rng.randint(0, cfg.vocab_size, size=(6,)).astype(np.int32)
    router, reps, _ = _fleet(tiny, ["mixed"])
    fut = router.submit(prompt, 6)
    router.drain(timeout_s=120)
    res = fut.result(timeout=5)
    assert list(res.tokens) == _oracle(params, cfg, prompt, 6)
    assert res.metrics["migrated"] is True


def test_router_requires_both_pools(tiny):
    kv = DictKV()
    rep = LocalDisaggReplica("r0", _sess(tiny), kv, pool="prefill")
    with pytest.raises(ValueError, match="decode-capable"):
        DisaggRouter([rep], kv)
