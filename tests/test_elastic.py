"""Elastic: state commit/restore/sync, driver assignment/blacklist, and the
retry loop.

Mirrors † ``test/single/test_elastic_driver.py`` (fake discovery, assert
rank assignments and blacklisting without real hosts) and
† ``test_torch_elastic.py`` (state commit/restore in-process).
"""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    JaxState,
    ObjectState,
    run,
)
from horovod_tpu.runner.elastic import ElasticDriver, FixedDiscovery
from horovod_tpu.runner.hosts import HostSlots


# ---------------------------------------------------------------------------
# State objects
# ---------------------------------------------------------------------------

def test_object_state_commit_restore():
    s = ObjectState(epoch=0, best=1.5)
    s.epoch = 7
    s.best = 0.2
    s.restore()                       # nothing committed since init
    assert s.epoch == 0 and s.best == 1.5
    s.epoch = 3
    s.commit()
    s.epoch = 9
    s.restore()
    assert s.epoch == 3


def test_jax_state_commit_restore():
    params = {"w": np.arange(4.0, dtype=np.float32)}
    s = JaxState(params=params, step=np.int32(0))
    s.params = {"w": np.asarray(s.params["w"]) * 2}
    s.commit()
    s.params = {"w": np.zeros(4, np.float32)}
    s.restore()
    np.testing.assert_allclose(np.asarray(s.params["w"]),
                               np.arange(4.0) * 2)
    # restored values are live replicated device arrays
    assert s.params["w"].sharding.is_fully_replicated


def test_jax_state_sync_broadcasts():
    s = JaxState(params={"w": np.full((2,), 5.0, np.float32)})
    s.sync()
    np.testing.assert_allclose(np.asarray(s.params["w"]), 5.0)


# ---------------------------------------------------------------------------
# run decorator protocol
# ---------------------------------------------------------------------------

def test_run_retries_on_internal_error(monkeypatch):
    calls = {"n": 0, "restored": 0, "reset": 0}

    class S(ObjectState):
        def restore(self):
            calls["restored"] += 1
            super().restore()

    state = S(step=0)
    state.register_reset_callbacks([lambda: calls.__setitem__(
        "reset", calls["reset"] + 1)])

    monkeypatch.setattr("horovod_tpu.elastic.runner._reinitialize",
                        lambda: None)

    @run
    def train(st):
        calls["n"] += 1
        if calls["n"] < 3:
            raise HorovodInternalError("peer died")
        return "done"

    assert train(state) == "done"
    assert calls["n"] == 3
    assert calls["restored"] == 2
    assert calls["reset"] == 2


def test_run_syncs_on_hosts_updated():
    calls = {"n": 0, "synced": 0}

    class S(ObjectState):
        def sync(self):
            calls["synced"] += 1
            super().sync()

    state = S(step=0)

    @run
    def train(st):
        calls["n"] += 1
        if calls["n"] == 1:
            raise HostsUpdatedInterrupt("new host")
        return st.step

    assert train(state) == 0
    assert calls["synced"] == 1


# ---------------------------------------------------------------------------
# driver († test_elastic_driver.py)
# ---------------------------------------------------------------------------

def test_driver_assignment_and_epoch():
    d = ElasticDriver(FixedDiscovery("a:2,b:2"), min_np=2)
    hosts = d.wait_for_available_slots()
    assert [h.hostname for h in hosts] == ["a", "b"]
    assert d.assignment(hosts) == [(0, "a", 0), (1, "a", 1),
                                   (2, "b", 0), (3, "b", 1)]
    assert d.membership_epoch == 1


def test_driver_blacklist_excludes_host():
    d = ElasticDriver(FixedDiscovery("a:2,b:2"), min_np=1)
    d.wait_for_available_slots()
    d.blacklist("a")
    d.poll_hosts()
    assert [host for _, host, _ in d.assignment()] == ["b", "b"]


def test_driver_membership_change_bumps_epoch():
    d = ElasticDriver(FixedDiscovery("a:2", "a:2,b:2"), min_np=1,
                      poll_interval_s=0.01)
    d.poll_hosts()
    e1 = d.membership_epoch
    assert d.poll_hosts()            # b joined
    assert d.membership_epoch == e1 + 1


def test_driver_max_np_caps_assignment():
    d = ElasticDriver(FixedDiscovery("a:4,b:4"), min_np=1, max_np=3)
    d.poll_hosts()
    assert len(d.assignment()) == 3


def test_driver_min_np_timeout():
    d = ElasticDriver(FixedDiscovery("a:1"), min_np=4, poll_interval_s=0.01)
    with pytest.raises(TimeoutError):
        d.wait_for_available_slots(timeout_s=0.1)


def test_driver_run_job_relaunches_and_blacklists():
    # Fake launcher: first attempt "fails" (worker on host b died), second
    # succeeds after b is blacklisted.
    d = ElasticDriver(FixedDiscovery("a:2,b:2"), min_np=1,
                      poll_interval_s=0.01)
    attempts = []

    def fake_launcher(cmd, hosts, env):
        attempts.append([h.hostname for h in hosts])
        assert env["HVDTPU_ELASTIC"] == "1"
        if len(attempts) == 1:
            d.blacklist("b")     # monitor observed b's worker die
            return 1
        return 0

    code = d.run_job(["python", "train.py"], launcher=fake_launcher)
    assert code == 0
    assert attempts[0] == ["a", "b"]
    assert attempts[1] == ["a"]


def test_script_discovery(tmp_path):
    from horovod_tpu.runner.elastic import ScriptDiscovery
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho host1:2\necho host2:4\n")
    script.chmod(0o755)
    hosts = ScriptDiscovery(str(script)).find_available_hosts()
    assert hosts == [HostSlots("host1", 2), HostSlots("host2", 4)]
