"""Async engine: handles, fusion, error propagation, process sets.

Mirrors † ``test/parallel/test_torch.py`` async tests
(``test_horovod_allreduce_async_fused``, duplicate-name errors) and the
fusion-of-many-small-tensors cases.
"""

import time

import numpy as np
import pytest

import horovod_tpu as hvd

N = 8


def test_async_allreduce_roundtrip():
    x = hvd.per_rank([np.full((4,), float(r), np.float32) for r in range(N)])
    h = hvd.allreduce_async(x, hvd.Average, name="t.async1")
    out = hvd.synchronize(h)
    np.testing.assert_allclose(hvd.to_numpy(out), np.full((4,), 3.5))
    assert hvd.poll(h)


def test_async_many_fused():
    handles = []
    expected = []
    for i in range(20):
        parts = [np.full((5,), float(r + i), np.float32) for r in range(N)]
        expected.append(np.stack(parts).mean(0))
        handles.append(hvd.allreduce_async(hvd.per_rank(parts),
                                           name=f"t.fused.{i}"))
    for h, exp in zip(handles, expected):
        np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h)), exp,
                                   rtol=1e-6)


def test_async_mixed_verbs():
    x = hvd.per_rank([np.full((2,), float(r), np.float32) for r in range(N)])
    h1 = hvd.allreduce_async(x, hvd.Sum, name="t.mix.ar")
    h2 = hvd.broadcast_async(x, 2, name="t.mix.bc")
    h3 = hvd.allgather_async(x, name="t.mix.ag")
    np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h1)),
                               np.full((2,), 28.0))
    np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h2)),
                               np.full((2,), 2.0))
    assert hvd.synchronize(h3).shape == (N * 2,)


def test_duplicate_name_rejected():
    # Pause the engine so both enqueues are observably in-flight together
    # (otherwise the 5 ms cycle could drain h1 before h2 arrives).
    eng = hvd.global_state().engine
    x = hvd.per_rank([np.zeros((10,), np.float32)] * N)
    eng.pause()
    try:
        h1 = hvd.allreduce_async(x, name="t.dup")
        h2 = hvd.allreduce_async(x, name="t.dup")
    finally:
        eng.resume()
    with pytest.raises(hvd.HorovodInternalError):
        hvd.synchronize(h2)
    hvd.synchronize(h1)


def test_error_propagates_to_handle():
    x = hvd.per_rank([np.zeros((5,), np.float32)] * N)
    h = hvd.alltoall_async(x, name="t.err")   # 5 rows not divisible by 8
    with pytest.raises(hvd.HorovodInternalError):
        hvd.synchronize(h)


def test_engine_cycles_advance():
    eng = hvd.global_state().engine
    c0 = eng.cycle_count
    x = hvd.per_rank([np.ones((2,), np.float32)] * N)
    hvd.synchronize(hvd.allreduce_async(x, name="t.cycle"))
    time.sleep(0.05)
    assert eng.cycle_count > c0


def test_fusion_respects_threshold():
    # Two tensors whose combined size exceeds a tiny threshold must split
    # into separate dispatch groups but still both complete correctly.
    state = hvd.global_state()
    old = state.config.fusion_threshold
    state.config.fusion_threshold = 4 * 10  # 10 floats
    try:
        xs = [hvd.per_rank([np.full((8,), float(r + i), np.float32)
                            for r in range(N)]) for i in range(4)]
        hs = [hvd.allreduce_async(x, hvd.Sum, name=f"t.thresh.{i}")
              for i, x in enumerate(xs)]
        for i, h in enumerate(hs):
            exp = np.full((8,), sum(range(N)) + N * i, np.float32)
            np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h)), exp)
    finally:
        state.config.fusion_threshold = old


def test_fusion_splits_mixed_wire_precision():
    """Same-precision entries fuse; mixed modes land in separate groups
    (one compiled program per wire mode), and the negotiation meta
    carries the precision field so joined ranks rebuild entries at the
    same mode."""
    import json
    from horovod_tpu.ops.engine import TensorTableEntry
    eng = hvd.global_state().engine
    old_floor = hvd.global_state().config.quant_min_bytes
    hvd.global_state().config.quant_min_bytes = 0
    try:
        x = hvd.per_rank([np.ones((64,), np.float32)] * N)
        entries = [
            TensorTableEntry(name=f"t.mixp.{i}", verb="allreduce",
                             payload=x, op=hvd.Sum, precision=p)
            for i, p in enumerate(["int8", "int8", "fp32", "bf16"])]
        groups = eng._fuse(entries)
        keyed = sorted(tuple(e.precision for e in g) for g in groups)
        assert keyed == [("bf16",), ("fp32",), ("int8", "int8")]
        meta = json.loads(entries[0].meta())
        assert meta["wp"] == "int8"
        assert "wp" not in json.loads(entries[2].meta())  # "" omitted...
    finally:
        hvd.global_state().config.quant_min_bytes = old_floor


def test_engine_quantized_vs_fp32_parity():
    """Quantized allreduce through the full async engine path must agree
    with the fp32 result within the documented tolerance (1.5x the
    shared-scale error bound; see tests/test_reduction.py)."""
    old_floor = hvd.global_state().config.quant_min_bytes
    hvd.global_state().config.quant_min_bytes = 0
    try:
        rng = np.random.RandomState(42)
        parts = [rng.randn(1000).astype(np.float32) for _ in range(N)]
        x = hvd.per_rank(parts)
        h32 = hvd.allreduce_async(x, hvd.Average, name="t.par.f32")
        h8 = hvd.allreduce_async(x, hvd.Average, name="t.par.i8",
                                 compression="int8")
        ref = hvd.to_numpy(hvd.synchronize(h32))
        got = hvd.to_numpy(hvd.synchronize(h8))
        gmax = np.abs(np.stack(parts)).max()
        np.testing.assert_allclose(got, ref,
                                   atol=1.5 * (N + 1) * gmax / 254.0)
        assert np.abs(got - ref).max() > 0  # int8 wire is lossy: it ran
    finally:
        hvd.global_state().config.quant_min_bytes = old_floor


def test_process_set_allreduce():
    ps = hvd.add_process_set([0, 2, 4, 6])
    parts = [np.full((3,), float(r), np.float32) for r in (0, 2, 4, 6)]
    x = hvd.per_rank(parts, process_set=ps)
    out = hvd.to_numpy(hvd.allreduce(x, hvd.Sum, process_set=ps))
    np.testing.assert_allclose(out, np.full((3,), 12.0))
    assert ps.size() == 4
    assert ps.rank_of(4) == 2
    assert not ps.included(1)
    hvd.remove_process_set(ps)


def test_process_set_async():
    ps = hvd.add_process_set([1, 3])
    x = hvd.per_rank([np.full((2,), 1.0, np.float32),
                      np.full((2,), 3.0, np.float32)], process_set=ps)
    h = hvd.allreduce_async(x, hvd.Average, name="t.ps", process_set=ps)
    np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h)),
                               np.full((2,), 2.0))
    hvd.remove_process_set(ps)


def test_timeline_writes_events(tmp_path):
    from horovod_tpu.utils.timeline import Timeline
    p = tmp_path / "tl.json"
    tl = Timeline(str(p), mark_cycles=True)
    tl.start_activity("tensor.a", "DISPATCH")
    tl.end_activity("tensor.a")
    tl.mark_cycle()
    tl.close()
    import json
    events = json.load(open(p))
    names = [e.get("name") for e in events]
    assert "DISPATCH" in names and "CYCLE" in names


def test_timeline_engine_phases(tmp_path):
    """The engine must emit the full per-tensor lifecycle QUEUE ->
    NEGOTIATE -> DISPATCH († timeline.cc phase breakdown), not just the
    dispatch span."""
    import json
    from horovod_tpu.utils.timeline import Timeline
    p = tmp_path / "phases.json"
    state = hvd.global_state()
    old_tl = state.timeline
    state.timeline = Timeline(str(p))
    try:
        x = hvd.per_rank([np.ones((2,), np.float32)] * N)
        h = hvd.allreduce_async(x, name="t.phases")
        hvd.synchronize(h)
    finally:
        state.timeline.close()
        state.timeline = old_tl
    events = json.load(open(p))
    spans = [e["name"] for e in events
             if e.get("ph") == "B" and e.get("tid", 0) > 0]
    for phase in ("QUEUE", "NEGOTIATE", "DISPATCH"):
        assert phase in spans, f"missing {phase} span: {spans}"
    assert spans.index("QUEUE") < spans.index("NEGOTIATE") \
        < spans.index("DISPATCH")


def test_timeline_decomposed_overlap_spans(tmp_path):
    """Acceptance gate for the schedule IR (ops/sched): with
    HOROVOD_TPU_SCHED_MODE=decomposed, the dryrun trace must show at
    least one communication step (SCHED_RS / SCHED_AG) overlapping a
    compute span (SCHED_COMBINE), with the RS -> COMBINE -> AG flow
    arrows linking each chunk's pipeline."""
    import json
    from horovod_tpu.utils.timeline import Timeline
    state = hvd.global_state()
    cfg = state.config
    old_tl, old_mode, old_chunks = (state.timeline, cfg.sched_mode,
                                    cfg.sched_chunks)
    p = tmp_path / "sched_overlap.json"
    state.timeline = Timeline(str(p))
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 3
    try:
        x = hvd.per_rank(
            [np.random.RandomState(r).randn(6000).astype(np.float32)
             for r in range(N)])
        hvd.synchronize(hvd.allreduce_async(x, hvd.Average, name="t.ovl"))
    finally:
        state.timeline.close()
        state.timeline, cfg.sched_mode, cfg.sched_chunks = (
            old_tl, old_mode, old_chunks)
    events = json.load(open(p))
    lanes = {e["args"]["name"]: e["tid"] for e in events
             if e.get("name") == "thread_name"}
    sched_tids = {v for k, v in lanes.items()
                  if any(t in k for t in ("/rs.", "/combine.", "/ag."))}
    assert len(sched_tids) == 9, lanes            # 3 units x 3 chunks
    # Reconstruct per-step in-flight intervals from B/E pairs.
    open_ts, ivals = {}, {}
    for e in events:
        tid = e.get("tid")
        if e.get("ph") == "B" and tid in sched_tids:
            open_ts[tid] = (e["name"], e["ts"])
        elif e.get("ph") == "E" and tid in open_ts:
            nm, t0 = open_ts.pop(tid)
            ivals.setdefault(nm, []).append((t0, e["ts"]))
    assert {len(v) for v in ivals.values()} == {3}
    comm = ivals["SCHED_RS"] + ivals["SCHED_AG"]
    comp = ivals["SCHED_COMBINE"]
    assert any(max(c0, k0) < min(c1, k1)
               for c0, c1 in comm for k0, k1 in comp), (comm, comp)
    # Flow arrows: one s/f pair per pipeline hop (RS->COMBINE,
    # COMBINE->AG), on the schedule lanes, well-formed ids.
    flows = [e for e in events
             if e.get("cat") == "flow" and e.get("tid") in sched_tids]
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    ends = {e["id"] for e in flows if e["ph"] == "f"}
    assert starts and starts == ends, flows


def test_decomposed_entries_through_engine_match_monolithic():
    """Engine-path parity: the same payload allreduced with the
    decomposed schedule resolved at enqueue must be bit-exact with the
    monolithic dispatch (the CI np=2/4 job asserts the same over real
    negotiated transport)."""
    cfg = hvd.global_state().config
    old_mode, old_chunks = cfg.sched_mode, cfg.sched_chunks
    x = hvd.per_rank(
        [np.random.RandomState(r).randn(4096).astype(np.float32)
         for r in range(N)])
    try:
        ref = hvd.to_numpy(hvd.synchronize(
            hvd.allreduce_async(x, hvd.Average, name="t.dm.mono")))
        cfg.sched_mode, cfg.sched_chunks = "decomposed", 4
        got = hvd.to_numpy(hvd.synchronize(
            hvd.allreduce_async(x, hvd.Average, name="t.dm.dec")))
    finally:
        cfg.sched_mode, cfg.sched_chunks = old_mode, old_chunks
    np.testing.assert_array_equal(ref, got)


def test_join_covered_non_allreduce_errors():
    """A non-allreduce collective whose readiness depended on a joined
    rank's fabricated zeros must error on the ranks that own it — zeros in
    an allgather/broadcast would silently corrupt the result (advisor
    finding; † the reference errors non-allreduce ops during join)."""
    from horovod_tpu.ops.engine import NegotiationOutcome, Negotiator

    class CoveredNegotiator(Negotiator):
        always_check_in = False

        def negotiate(self, entries, *, joined=False):
            names = [e.name for e in entries]
            return NegotiationOutcome(ready=names, join_covered=set(names))

    eng = hvd.global_state().engine
    old = eng._negotiator
    eng._negotiator = CoveredNegotiator()
    try:
        x = hvd.per_rank([np.ones((2,), np.float32)] * N)
        h = hvd.allgather_async(x, name="t.cov.ag")
        with pytest.raises(hvd.HorovodInternalError, match="allreduce"):
            hvd.synchronize(h)
        # The errored entry must be consumed, not re-queued: a deferred
        # dead tensor would renegotiate every cycle forever (livelock —
        # code-review finding).
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            with eng._lock:
                if not eng._queue and "t.cov.ag" not in eng._names_pending:
                    break
            time.sleep(0.01)
        with eng._lock:
            assert not eng._queue
            assert "t.cov.ag" not in eng._names_pending
        hb = hvd.broadcast_async(x, 0, name="t.cov.bc")
        with pytest.raises(hvd.HorovodInternalError, match="allreduce"):
            hvd.synchronize(hb)
        # allreduce itself is joinable and must still complete.
        h2 = hvd.allreduce_async(x, hvd.Sum, name="t.cov.ar")
        np.testing.assert_allclose(hvd.to_numpy(hvd.synchronize(h2)),
                                   np.full((2,), float(N)))
    finally:
        eng._negotiator = old


def test_join_timeout_then_latched_result():
    """join() timing out must leave the rank joined; once the join
    completes with no waiter, the next join() call consumes the latched
    result instead of enrolling in a new join phase (advisor finding)."""
    from horovod_tpu.ops.engine import NegotiationOutcome, Negotiator

    class SlowJoinNegotiator(Negotiator):
        always_check_in = True   # cycles run even with an empty queue

        def __init__(self):
            self.joined_rounds = 0

        def negotiate(self, entries, *, joined=False):
            names = [e.name for e in entries]
            if joined:
                self.joined_rounds += 1
                if self.joined_rounds >= 3:
                    return NegotiationOutcome(
                        ready=names, all_joined=True, last_join_rank=5)
                # A ghost tensor owned by another (live) rank that is NOT
                # joinable: the joined engine must skip it (the owner
                # errors it via join_covered) rather than crash or abort.
                return NegotiationOutcome(
                    ready=names + ["t.ghost.ag"],
                    metas={"t.ghost.ag": '{"v":"allgather",'
                           '"d":"float32","s":[8,2],"o":"sum"}'},
                    join_covered={"t.ghost.ag"})
            return NegotiationOutcome(ready=names)

    eng = hvd.global_state().engine
    old = eng._negotiator
    eng._negotiator = SlowJoinNegotiator()
    try:
        with pytest.raises(TimeoutError):
            eng.join(timeout=1e-4)
        deadline = time.monotonic() + 10
        while not eng._join_pending_consume and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.join(timeout=5) == 5
        # State fully consumed: no stale result for a future phase.
        assert not eng._join_pending_consume
        assert not eng._join_requested
    finally:
        eng._negotiator = old


def test_negotiator_failure_fails_handles():
    """A negotiation transport failure must error every pending handle
    rather than hanging waiters (code-review finding)."""
    from horovod_tpu.ops.engine import Negotiator

    class ExplodingNegotiator(Negotiator):
        always_check_in = False

        def negotiate(self, entries, *, joined=False):
            raise ConnectionError("controller gone")

    eng = hvd.global_state().engine
    old = eng._negotiator
    eng._negotiator = ExplodingNegotiator()
    try:
        x = hvd.per_rank([np.ones((2,), np.float32)] * N)
        h = hvd.allreduce_async(x, name="t.negfail")
        with pytest.raises(hvd.HorovodInternalError, match="controller gone"):
            hvd.synchronize(h)
        # Name must be released so the same tensor can be re-enqueued.
        eng._negotiator = old
        h2 = hvd.allreduce_async(x, name="t.negfail")
        hvd.synchronize(h2)
    finally:
        eng._negotiator = old
