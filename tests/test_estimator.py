"""Estimator API († horovod.spark KerasEstimator/TorchEstimator role):
fit/predict/transform from DataFrames, dicts, and parquet, with the mesh
as the data plane.
"""

import os

import numpy as np
import pytest

from horovod_tpu.estimator import (
    JaxEstimator,
    KerasEstimator,
    LocalStore,
    to_columns,
)
from horovod_tpu.estimator.store import train_val_split

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _regression_frame(n=256, seed=0):
    import pandas as pd
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    w = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    y = x @ w + 0.01 * rng.randn(n).astype(np.float32)
    return pd.DataFrame({"features": list(x), "label": y})


# ---------------------------------------------------------------------------
# data ingestion
# ---------------------------------------------------------------------------

def test_to_columns_from_dataframe_and_dict():
    df = _regression_frame(32)
    cols = to_columns(df)
    assert cols["features"].shape == (32, 4)
    assert cols["label"].shape == (32,)
    cols2 = to_columns({"a": [1, 2], "b": [3.0, 4.0]})
    assert cols2["a"].tolist() == [1, 2]


def test_to_columns_parquet_roundtrip(tmp_path):
    import pandas as pd
    df = pd.DataFrame({"x": np.arange(10.0), "y": np.arange(10) % 2})
    path = str(tmp_path / "part-0.parquet")
    df.to_parquet(path)
    cols = to_columns(str(tmp_path))
    assert cols["x"].shape == (10,)
    np.testing.assert_allclose(cols["x"], np.arange(10.0))


def test_to_columns_validation_errors():
    with pytest.raises(ValueError):
        to_columns({"a": [1, 2], "b": [1, 2, 3]})
    with pytest.raises(KeyError):
        to_columns({"a": [1]}, columns=["missing"])
    with pytest.raises(TypeError):
        to_columns(42)


def test_train_val_split_partitions_rows():
    cols = {"x": np.arange(100), "y": np.arange(100) * 2}
    tr, va = train_val_split(cols, 0.25, seed=0)
    assert len(va["x"]) == 25 and len(tr["x"]) == 75
    assert sorted(np.concatenate([tr["x"], va["x"]]).tolist()) == \
        list(range(100))


# ---------------------------------------------------------------------------
# JaxEstimator
# ---------------------------------------------------------------------------

class _Linear:
    """Minimal flax-API model (init/apply) to keep the test light."""

    def init(self, rng, x):
        import jax
        return {"w": jax.random.normal(rng, (x.shape[-1],)) * 0.1,
                "b": jax.numpy.zeros(())}

    def apply(self, params, x):
        return x @ params["w"] + params["b"]


class _FakeSparkDataFrame:
    """Spark DataFrame stand-in (pyspark is not in the image): the real
    detection is structural — module path + toPandas — so this exercises
    the exact code path a genuine pyspark DataFrame takes."""

    def __init__(self, pdf):
        self._pdf = pdf
        self.select_calls = []

    def select(self, cols):
        self.select_calls.append(list(cols))
        return _FakeSparkDataFrame(self._pdf[list(cols)])

    def toPandas(self):
        return self._pdf.copy()


_FakeSparkDataFrame.__module__ = "pyspark.sql.dataframe"


def test_spark_dataframe_ingestion_end_to_end():
    """† horovod.spark estimators: fit/transform accept a Spark DataFrame
    (column-pruned select -> toPandas collect -> the column path)."""
    import optax
    pdf = _regression_frame(128)
    pdf["unrelated"] = [object()] * len(pdf)  # must be pruned, not crash
    sdf = _FakeSparkDataFrame(pdf)
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], loss="mse", batch_size=64,
                       epochs=20, seed=0, optimizer=optax.adam(0.1))
    fitted = est.fit(sdf)
    assert fitted.history[-1]["loss"] < fitted.history[0]["loss"]
    assert sdf.select_calls == [["features", "label"]]
    out = fitted.transform(_FakeSparkDataFrame(pdf[["features"]]))
    assert "prediction" in out.columns  # pandas result frame


def test_jax_estimator_learns_regression():
    df = _regression_frame()
    import optax
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], loss="mse", batch_size=64,
                       epochs=30, seed=0, optimizer=optax.adam(0.1))
    fitted = est.fit(df)
    assert fitted.history[-1]["loss"] < fitted.history[0]["loss"]
    preds = fitted.predict(df)
    target = to_columns(df)["label"]
    mse = float(np.mean((preds - target) ** 2))
    assert mse < 0.5, mse
    out = fitted.transform(df)
    assert "prediction" in out.columns


def test_jax_estimator_flax_module_classification():
    import flax.linen as nn
    import pandas as pd

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(3)(x)

    rng = np.random.RandomState(1)
    x = rng.randn(240, 5).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64) + (x[:, 1] > 0)
    df = pd.DataFrame({"features": list(x), "label": y})
    import optax
    est = JaxEstimator(model=MLP(), feature_cols=["features"],
                       label_cols=["label"], loss="xent", batch_size=48,
                       epochs=25, validation=0.2, seed=1,
                       optimizer=optax.adam(0.01))
    fitted = est.fit(df)
    assert "val_loss" in fitted.history[-1]
    acc = float(np.mean(
        fitted.predict(df).argmax(-1) == y))
    assert acc > 0.7, acc


def test_jax_estimator_checkpoints_to_store(tmp_path):
    store = LocalStore(str(tmp_path))
    df = _regression_frame(64)
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], batch_size=32, epochs=2,
                       store=store, run_id="run1")
    est.fit(df)
    from horovod_tpu.utils.checkpoint import Checkpointer
    ckpt = Checkpointer(store.checkpoint_path("run1"))
    assert ckpt.latest_step() == 1
    restored = ckpt.restore()
    assert "params" in restored


def test_jax_estimator_rejects_tiny_data():
    df = _regression_frame(4)
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], batch_size=64)
    with pytest.raises(ValueError, match="rows"):
        est.fit(df)


# ---------------------------------------------------------------------------
# KerasEstimator (single-process path; the callback rig is exercised by
# test_bindings.py's multi-rank keras tests)
# ---------------------------------------------------------------------------

def test_keras_estimator_fit_predict(tmp_path):
    keras = pytest.importorskip("keras")
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(1),
    ])
    model.compile(optimizer=keras.optimizers.Adam(0.05), loss="mse")
    df = _regression_frame(128)
    est = KerasEstimator(model=model, feature_cols=["features"],
                         label_cols=["label"], batch_size=32, epochs=8,
                         validation=0.25,
                         store=LocalStore(str(tmp_path)), run_id="k1")
    fitted = est.fit(df)
    assert fitted.history and "val_loss" in fitted.history
    preds = fitted.predict(df)
    assert preds.shape[0] == 128
    out = fitted.transform(df)
    assert "prediction" in out.columns
    import os
    assert os.path.exists(
        os.path.join(str(tmp_path), "runs", "k1", "checkpoints",
                     "model.keras"))


def _write_multi_rowgroup_parquet(path, n_rows, n_feat, rows_per_group,
                                  seed=0):
    """Write a regression parquet in small row groups INCREMENTALLY (the
    writer itself never holds the dataset)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.RandomState(seed)
    w_true = rng.randn(n_feat, 1).astype(np.float32)
    writer = None
    for start in range(0, n_rows, rows_per_group):
        n = min(rows_per_group, n_rows - start)
        X = rng.randn(n, n_feat).astype(np.float32)
        yv = (X @ w_true + 0.01 * rng.randn(n, 1)).astype(np.float32)[:, 0]
        table = pa.table({
            "features": pa.FixedSizeListArray.from_arrays(
                pa.array(X.reshape(-1)), n_feat),
            "label": pa.array(yv),
        })
        if writer is None:
            writer = pq.ParquetWriter(path, table.schema)
        writer.write_table(table, row_group_size=n)
    writer.close()


def test_parquet_batches_streams_row_groups(tmp_path):
    from horovod_tpu.estimator import ParquetBatches
    path = str(tmp_path / "data.parquet")
    _write_multi_rowgroup_parquet(path, n_rows=1000, n_feat=8,
                                  rows_per_group=128)
    batches = ParquetBatches(path, columns=["features", "label"],
                             batch_rows=128)
    assert len(batches) == 1000
    total, chunks = 0, 0
    for chunk in batches:
        assert set(chunk) == {"features", "label"}
        assert chunk["features"].shape[1] == 8
        assert len(chunk["features"]) <= 128
        total += len(chunk["features"])
        chunks += 1
    assert total == 1000 and chunks >= 8
    # Second iteration works (re-opens the files).
    assert sum(len(c["label"]) for c in batches) == 1000


def test_jax_estimator_streaming_fit_learns(tmp_path):
    import optax
    from horovod_tpu.estimator import ParquetBatches
    path = str(tmp_path / "data.parquet")
    _write_multi_rowgroup_parquet(path, n_rows=2048, n_feat=8,
                                  rows_per_group=256)
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], loss="mse", batch_size=64,
                       epochs=8, seed=0, optimizer=optax.adam(0.1))
    fitted = est.fit(ParquetBatches(path, batch_rows=256))
    assert fitted.history[-1]["loss"] < fitted.history[0]["loss"]
    assert fitted.history[0]["steps"] == 2048 // 64
    # Predict from the same parquet path (non-streaming read).
    preds = fitted.predict(path)
    assert preds.shape[0] == 2048


def test_store_create_flavors(tmp_path):
    from horovod_tpu.estimator import FilesystemStore, Store
    st = Store.create(str(tmp_path / "artifacts"))
    assert isinstance(st, FilesystemStore)
    ck = st.checkpoint_path("run1")
    assert os.path.isdir(ck) and "runs/run1" in ck.replace(os.sep, "/")
    with pytest.raises(ValueError, match="mount.*register|register"):
        Store.create("gs://bucket/prefix")


def test_store_register_resolves_scheme():
    from horovod_tpu.estimator import InMemoryObjectStore, Store
    # Plug a client for a scheme (the † HDFSStore/S3Store seam); create()
    # then resolves URIs of that scheme through it instead of erroring.
    Store.register("fakegs")(InMemoryObjectStore)
    try:
        st = Store.create("fakegs://bucket-a/some/prefix")
        assert isinstance(st, InMemoryObjectStore)
        st.obj_write("runs/r1/x.bin", b"payload")
        assert st.obj_exists("runs/r1/x.bin")
        # A second instance of the same bucket URI sees the same objects
        # (two hosts, one bucket).
        st2 = Store.create("fakegs://bucket-a/some/prefix")
        assert st2.obj_read("runs/r1/x.bin") == b"payload"
        assert st2.obj_list("runs/r1/") == ["runs/r1/x.bin"]
    finally:
        Store._registry.pop("fakegs", None)


def test_remote_store_stage_sync_fetch_roundtrip():
    from horovod_tpu.estimator import InMemoryObjectStore
    st = InMemoryObjectStore("fake://bkt-rt/pfx")
    ck = st.checkpoint_path("r7")          # local staging dir
    assert os.path.isdir(ck) and "runs/r7" in ck.replace(os.sep, "/")
    with open(os.path.join(ck, "weights.bin"), "wb") as f:
        f.write(b"\x01\x02")
    with open(os.path.join(st.logs_path("r7"), "log.txt"), "w") as f:
        f.write("hello")
    st.sync("r7")
    assert st.obj_exists("runs/r7/checkpoints/weights.bin")
    # fetch() pulls the run tree back down preserving relative paths —
    # the transform-on-another-host path.
    other = InMemoryObjectStore("fake://bkt-rt/pfx")
    root = other.fetch("r7")
    with open(os.path.join(root, "checkpoints", "weights.bin"), "rb") as f:
        assert f.read() == b"\x01\x02"
    with open(os.path.join(root, "logs", "log.txt")) as f:
        assert f.read() == "hello"


def test_remote_store_sync_catches_same_size_rewrite():
    """An in-place same-size rewrite within the filesystem's mtime
    granularity must still re-upload (content dedup, not size+mtime)."""
    from horovod_tpu.estimator import InMemoryObjectStore
    st = InMemoryObjectStore("fake://bkt-rw/pfx")
    ck = st.checkpoint_path("r8")
    path = os.path.join(ck, "weights.bin")
    with open(path, "wb") as f:
        f.write(b"aaaa")
    st.sync("r8")
    mt = os.stat(path)
    with open(path, "wb") as f:          # same size, new content
        f.write(b"bbbb")
    os.utime(path, ns=(mt.st_atime_ns, mt.st_mtime_ns))  # freeze mtime
    st.sync("r8")
    assert st.obj_read("runs/r8/checkpoints/weights.bin") == b"bbbb"


def test_remote_store_fetch_rejects_escaping_keys(tmp_path):
    """Object keys are untrusted remote state: a key whose relative path
    escapes the destination must be rejected before any write."""
    from horovod_tpu.estimator import InMemoryObjectStore
    st = InMemoryObjectStore("fake://bkt-esc/pfx")
    st.obj_write("runs/r9/../../evil.bin", b"x")
    st.obj_write("runs/r9/ok.bin", b"y")
    dest = str(tmp_path / "fetched")
    with pytest.raises(ValueError, match="escapes"):
        st.fetch("r9", dest)
    assert not os.path.exists(str(tmp_path / "evil.bin"))


@pytest.mark.integration
def test_jax_estimator_fit_against_remote_store():
    # End-to-end: fit with a RemoteStore — per-epoch orbax checkpoints
    # stage locally and sync() publishes them as objects (round-4 verdict
    # ask #7: estimator fit/transform against the fake remote store).
    from horovod_tpu.estimator import InMemoryObjectStore
    import optax
    store = InMemoryObjectStore("fake://bkt-fit/artifacts")
    df = _regression_frame()
    est = JaxEstimator(model=_Linear(), feature_cols=["features"],
                       label_cols=["label"], loss="mse", batch_size=64,
                       epochs=3, seed=0, optimizer=optax.adam(0.1),
                       store=store, run_id="remote-run")
    fitted = est.fit(df)
    objs = store.obj_list("runs/remote-run/")
    assert any("checkpoints" in k for k in objs), objs
    out = fitted.transform(df)
    assert "prediction" in out.columns


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget (~54s) + RSS-delta flake under load; unit-and-rig runs it
def test_streaming_fit_peak_rss_below_materialized(tmp_path):
    """The streaming promise, measured: fitting a ~400 MB parquet through
    ParquetBatches must not grow the process by anywhere near the dataset
    size, while the materializing to_columns path must (VERDICT r3 #6:
    dataset larger than a collect must be trainable; peak-RSS asserted).

    Measured as the DELTA between each child's post-import/post-jax-warmup
    high-water mark and its post-fit high-water mark: absolute peaks vary
    by ~1 GB with system memory pressure (allocator/THP behavior when the
    suite parent is large), but the fit-phase growth is the property under
    test and is stable."""
    import subprocess
    import sys
    path = str(tmp_path / "big.parquet")
    # ~2 GB of float32 features: XLA's compile-phase RSS peak varies by
    # up to ~1.3 GB with thread timing, so the dataset must dwarf it for
    # the delta comparison to be about data and nothing else.
    _write_multi_rowgroup_parquet(path, n_rows=2_000_000, n_feat=256,
                                  rows_per_group=16384)
    # Same schema/shapes at toy size: the child fits this FIRST so the
    # train-step compile (whose XLA peak varies by hundreds of MB with
    # thread timing) lands in the baseline, not the measured delta.
    warm = str(tmp_path / "warm.parquet")
    _write_multi_rowgroup_parquet(warm, n_rows=8192, n_feat=256,
                                  rows_per_group=8192)

    def fit_rss_delta(streaming: bool) -> int:
        code = f"""
import resource, sys
import numpy as np
sys.path.insert(0, {REPO!r})
from horovod_tpu.utils.cpurig import force_cpu_platform
force_cpu_platform(1)
import jax, jax.numpy as jnp
import optax
from horovod_tpu.estimator import JaxEstimator, ParquetBatches
from tests.test_estimator import _Linear
def make_est():
    return JaxEstimator(model=_Linear(), feature_cols=["features"],
                        label_cols=["label"], loss="mse", batch_size=512,
                        epochs=1, seed=0, optimizer=optax.adam(0.1))
# Identical-shape warmup fit: the train-step compile (XLA peak varies
# hundreds of MB with thread timing) lands in the baseline.
warm_data = ParquetBatches({warm!r}, batch_rows=4096) if {streaming} \
    else {warm!r}
make_est().fit(warm_data)
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
data = ParquetBatches({path!r}, batch_rows=4096) if {streaming} \
    else {path!r}
make_est().fit(data)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print("DELTA", peak - base)
"""
        keep = ("PATH", "PYTHONPATH", "HOME", "TMPDIR",
                "LD_LIBRARY_PATH", "LANG")
        env = {k: os.environ[k] for k in keep if k in os.environ}
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=600,
                             cwd=REPO, env=env)
        assert res.returncode == 0, res.stdout + res.stderr
        line = [ln for ln in res.stdout.splitlines()
                if ln.startswith("DELTA")][-1]
        return int(line.split()[1])  # KiB on linux

    stream_kib = fit_rss_delta(True)
    full_kib = fit_rss_delta(False)
    # Dataset is ~2 GB.  Absolute ru_maxrss deltas swing with global
    # allocator/THP state (observed 1.0–7.4 GB for the SAME materialized
    # fit depending on what ran on the machine before), so the floors
    # are conservative and the load-bearing assertion is the RELATIVE
    # property: the materializing path grows by a large fraction of the
    # dataset, the streaming path by far less.
    assert full_kib > 900 * 1024, (
        f"materialized fit grew only {full_kib} KiB — dataset no longer "
        "dominates; rescale the test")
    assert stream_kib < 700 * 1024, (
        f"streaming fit grew {stream_kib} KiB (a third of the dataset) — "
        "something materialized")
    assert stream_kib < full_kib - 200 * 1024, (
        f"streaming delta {stream_kib} KiB not clearly below "
        f"materialized {full_kib} KiB")
