"""The stock examples named by BASELINE's config list, run for real via the
launcher († ``test/integration/test_static_run.py`` runs the reference's
examples under ``horovodrun`` the same way):

- ResNet-50 ImageNet, torch ``DistributedOptimizer`` data-parallel
  († ``examples/pytorch/pytorch_imagenet_resnet50.py``)
- BERT masked-LM pretraining, TF Keras callbacks
  († BASELINE config "BERT-Large pretraining (TF Keras hvd callback)")

Tiny shapes, 2 real processes, CPU platform (the dev rig).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hvdrun_example(script_args, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # workers force CPU
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--platform", "cpu", "--", sys.executable] + script_args,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


@pytest.mark.integration
def test_torch_imagenet_resnet50_example():
    res = _hvdrun_example(
        [os.path.join(REPO, "examples", "torch_imagenet_resnet50.py"),
         "--epochs", "1", "--steps-per-epoch", "1", "--image-size", "32",
         "--batch-size", "2", "--num-classes", "10",
         "--batches-per-allreduce", "2"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DONE resnet50" in res.stdout


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget (~28s): CI examples-smoke runs every example
def test_tf_keras_bert_pretrain_example():
    res = _hvdrun_example(
        [os.path.join(REPO, "examples", "tf_keras_bert_pretrain.py"),
         "--epochs", "1", "--samples", "16", "--batch-size", "8"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DONE bert" in res.stdout


@pytest.mark.integration
def test_llama_moe_example():
    """Expert-parallel MoE Llama (use_moe=True, ep=2) trains real steps
    under the launcher at np=2 — the acceptance smoke for the MoE
    workload the autoscale scenario resizes."""
    res = _hvdrun_example(
        [os.path.join(REPO, "examples", "llama_moe.py")])
    assert res.returncode == 0, res.stdout + res.stderr
    # world size = 2 procs x inherited local device count; ep stays 2.
    assert "DONE moe rank=0/" in res.stdout, res.stdout
    assert "ep=2" in res.stdout, res.stdout


@pytest.mark.integration
def test_llama_serve_example():
    """Single-process serving example: continuous batching end to end."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "llama_serve.py"),
         "--requests", "3", "--max-active", "2"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "per-request results" in res.stdout
    assert res.stdout.count("ttft") >= 3
