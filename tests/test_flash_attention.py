"""Pallas flash-attention kernel vs dense oracle (interpret mode on the CPU
rig; the same kernel runs compiled on TPU — see ops/flash_attention.py
docstring for measured speedups)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import (
    _dense_attention,
    default_blocks,
    flash_attention,
    supported,
)

INTERP = jax.default_backend() != "tpu"


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    B, S, H, D = 2, 256, 4, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    out = flash_attention(q, k, v, None, causal, 128, 128, INTERP)
    ref = _dense_attention(q, k, v, 1.0 / np.sqrt(D), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=2e-5)


def test_flash_gradients_match_dense():
    B, S, H, D = 1, 128, 2, 32
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 128, 128,
                                       INTERP) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, 1.0 / np.sqrt(D),
                                        True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("rep,causal", [(2, True), (4, True), (2, False)])
def test_flash_gqa_matches_dense(rep, causal):
    # GQA-native path: k/v carry H/rep heads; the kernel indexes kv
    # groups directly (no jnp.repeat expansion anywhere on the path).
    B, S, H, D = 2, 256, 4, 64
    KV = H // rep
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, KV, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, KV, D), jnp.float32)
    out = flash_attention(q, k, v, None, causal, 128, 128, INTERP)
    ref = _dense_attention(q, k, v, 1.0 / np.sqrt(D), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=2e-5)


def test_flash_gqa_gradients_match_dense():
    # dk/dv come back at kv_heads width: the dkv grid's innermost rep
    # dimension accumulates the group's q heads in fp32 scratch, which
    # must equal the repeat-expand oracle's sum over the group.
    B, S, H, D, KV = 1, 128, 4, 32, 2
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, KV, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, KV, D), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 128, 128,
                                       INTERP) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, 1.0 / np.sqrt(D),
                                        True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (B, S, KV, D) and gf[2].shape == (B, S, KV, D)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_rejects_bad_kv_heads():
    q = jnp.zeros((1, 128, 4, 32))
    k = jnp.zeros((1, 128, 3, 32))
    with pytest.raises(ValueError, match="kv heads"):
        flash_attention(q, k, k, None, True, 128, 128, INTERP)


def test_default_blocks_divisibility():
    # Per-length tuning from the round-4 fwd+bwd sweep (see module doc).
    # S=512 follows the committed sweep's fastest point, 256x256 (parity
    # with dense; the parity-is-the-decision rationale is in BASELINE.md).
    assert default_blocks(512) == (256, 256)
    assert default_blocks(1024) == (512, 512)
    assert default_blocks(2048) == (512, 512)
    assert default_blocks(256) == (256, 256)
    assert default_blocks(384) == (128, 128)


def test_supported_gating():
    assert supported((1, 1024, 8, 64))
    assert not supported((1, 100, 8, 64))     # not block-divisible
    assert not supported((1, 1024, 8, 512))   # head dim too large
