"""Framework elastic states (TorchState, TensorFlowKerasState), runtime
timeline control, and capability queries.

Mirrors † ``test/single/test_torch_elastic.py`` (commit/restore semantics
in-process) and the basics surface of † ``test/parallel/test_torch.py``.
"""

import json
import os

import numpy as np
import pytest
import torch

import horovod_tpu as hvd


# ---------------------------------------------------------------------------
# TorchState
# ---------------------------------------------------------------------------

def _torch_model():
    torch.manual_seed(0)
    return torch.nn.Linear(4, 2)


def test_torch_state_commit_restore():
    from horovod_tpu.torch.elastic import TorchState
    model = _torch_model()
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = TorchState(model=model, optimizer=opt, epoch=3, batch=7)

    before = {k: v.clone() for k, v in model.state_dict().items()}
    state.commit()

    # Mutate everything, then roll back.
    with torch.no_grad():
        for p in model.parameters():
            p.add_(1.0)
    state.epoch = 9
    state.batch = 0
    state.restore()

    assert state.epoch == 3 and state.batch == 7
    for k, v in model.state_dict().items():
        assert torch.allclose(v, before[k])


def test_torch_state_restore_optimizer_momentum():
    from horovod_tpu.torch.elastic import TorchState
    model = _torch_model()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    # Build momentum state with one real step.
    loss = model(torch.randn(4, 4)).sum()
    loss.backward()
    opt.step()
    state = TorchState(model=model, optimizer=opt)
    state.commit()
    saved_momenta = [
        opt.state[p]["momentum_buffer"].clone()
        for g in opt.param_groups for p in g["params"]]

    opt.zero_grad()
    model(torch.randn(4, 4)).sum().backward()
    opt.step()
    state.restore()
    restored = [
        opt.state[p]["momentum_buffer"]
        for g in opt.param_groups for p in g["params"]]
    for a, b in zip(saved_momenta, restored):
        assert torch.allclose(a, b)


def test_torch_state_sync_runs_and_keeps_values():
    from horovod_tpu.torch.elastic import TorchState
    model = _torch_model()
    state = TorchState(model=model, step=5)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    state.sync()  # single-process: broadcast is identity but must execute
    assert state.step == 5
    for k, v in model.state_dict().items():
        assert torch.allclose(v, before[k], atol=1e-6)


def test_torch_elastic_module_surface():
    import horovod_tpu.torch as hvd_torch
    assert hvd_torch.elastic.run is not None
    assert hvd_torch.elastic.TorchState is not None
    assert hvd_torch.elastic.ElasticSampler is not None


# ---------------------------------------------------------------------------
# TensorFlowKerasState
# ---------------------------------------------------------------------------

def test_tf_keras_state_commit_restore():
    keras = pytest.importorskip("keras")
    import horovod_tpu.tensorflow.elastic as tfe

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(2)])
    state = tfe.TensorFlowKerasState(model, epoch=2)
    before = [w.copy() for w in model.get_weights()]
    state.commit()

    model.set_weights([w + 1.0 for w in model.get_weights()])
    state.epoch = 5
    state.restore()

    assert state.epoch == 2
    for a, b in zip(model.get_weights(), before):
        assert np.allclose(a, b)


def test_tf_keras_state_sync():
    keras = pytest.importorskip("keras")
    import horovod_tpu.tensorflow.elastic as tfe

    model = keras.Sequential([keras.layers.Input((3,)),
                              keras.layers.Dense(1)])
    state = tfe.TensorFlowKerasState(model, batch=1)
    before = [w.copy() for w in model.get_weights()]
    state.sync()
    for a, b in zip(model.get_weights(), before):
        assert np.allclose(a, b, atol=1e-6)
    assert tfe.KerasState is tfe.TensorFlowKerasState


# ---------------------------------------------------------------------------
# Runtime timeline († start_timeline / stop_timeline)
# ---------------------------------------------------------------------------

def test_start_stop_timeline(tmp_path):
    path = str(tmp_path / "tl.json")
    hvd.start_timeline(path, mark_cycles=True)
    h = hvd.allreduce_async(
        hvd.per_rank_from_fn(lambda r: np.ones((4,), np.float32)),
        hvd.Sum, name="tl.tensor")
    hvd.synchronize(h)
    hvd.stop_timeline()
    with open(path) as fh:
        events = json.load(fh)
    names = {e.get("name") for e in events}
    assert "QUEUE" in names or any("tl.tensor" in str(e) for e in events)
    # Engine keeps running fine with no timeline.
    out = hvd.allreduce(hvd.per_rank_from_fn(
        lambda r: np.full((2,), r, np.float32)), hvd.Average)
    assert np.allclose(hvd.to_numpy(out), np.full((2,), 3.5))


# ---------------------------------------------------------------------------
# Capability queries
# ---------------------------------------------------------------------------

def test_capability_queries():
    assert hvd.xla_built() is True
    assert hvd.mpi_built() is False
    assert hvd.mpi_enabled() is False
    assert hvd.ddl_built() is False and hvd.ccl_built() is False
    assert hvd.cuda_built() is False and hvd.rocm_built() is False
    assert hvd.mpi_threads_supported() is True
    assert hvd.nccl_built() == 1
    # native .so ships in-tree; gloo-role transport mirrors its presence
    assert hvd.gloo_built() == hvd.native_built()
    assert hvd.gloo_enabled() == hvd.gloo_built()


def test_is_homogeneous(hvd_session):
    # Single-controller rig: one process drives all devices -> homogeneous.
    assert hvd.is_homogeneous() is True
