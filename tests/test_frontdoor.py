"""serving/frontdoor/: router, radix prefix cache, speculative decode.

The load-bearing assertions mirror tests/test_serving.py's contract:
greedy-token parity against batch ``generate()`` regardless of which
front-door feature is on — a prefix-hit prompt that skipped prefill and
a speculative round that drafted badly must both emit the exact tokens
the plain engine would have.  On top of that: pager refcount
interleavings (shared prefix blocks survive the owner's release),
prefix-cache match/insert/evict mechanics, router placement/failover,
and the stale-snapshot placement guard.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import llama
from horovod_tpu.serving.frontdoor import (LocalReplica, PrefixCache,
                                           Router, RouterConfig)
from horovod_tpu.serving.frontdoor.transport import (DEAD_SIGNALS,
                                                     signals_from_snapshot)
from horovod_tpu.serving.kv_pager import KVPager, PagedKVCache

N = 8


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.LlamaConfig.tiny()            # v256 d64 L2 H4 KV2 fp32
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts(rng, lens):
    return [rng.randint(0, 256, size=(n,)).astype(np.int32) for n in lens]


def _oracle(params, cfg, prompt, max_new):
    full = np.asarray(llama.generate(
        params, jnp.asarray(prompt[None]), cfg, max_new_tokens=max_new))[0]
    return [int(t) for t in full[len(prompt):]]


def _pager(num_blocks=16, block_size=4):
    return KVPager(PagedKVCache(n_layers=2, num_blocks=num_blocks,
                                block_size=block_size, kv_heads=2,
                                head_dim=8))


# ---------------------------------------------------------------------------
# pager refcounts (the substrate prefix sharing stands on)
# ---------------------------------------------------------------------------

def test_pager_shared_prefix_refcounts():
    p = _pager()
    t1 = p.allocate(1, 8)                     # 2 blocks, refcount 1 each
    p.pin(t1[0])
    assert p.refcount(t1[0]) == 2 and p.is_pinned(t1[0])
    p.check_invariants()
    # Second request adopts the pinned block as its prefix head.
    t2 = p.allocate(2, 8, prefix_blocks=[t1[0]])
    assert t2[0] == t1[0] and p.refcount(t1[0]) == 3
    assert p.shared_blocks() >= 1
    p.check_invariants()
    # Owner releases: shared block survives (cache + req 2 still hold it).
    p.release(1)
    assert p.refcount(t1[0]) == 2
    p.check_invariants()
    # Req 2 releases: only the pin holds it; still not reusable.
    free_before = p.free_blocks
    p.release(2)
    assert p.refcount(t1[0]) == 1 and p.free_blocks > free_before
    p.check_invariants()
    # Unpin drops it to the free list.
    free_before = p.free_blocks
    p.unpin(t1[0])
    assert p.refcount(t1[0]) == 0 and p.free_blocks == free_before + 1
    p.check_invariants()


def test_pager_truncate_keeps_shared_blocks():
    p = _pager()
    t1 = p.allocate(1, 8)
    for b in t1:
        p.pin(b)
    t2 = p.allocate(2, 12, prefix_blocks=t1)   # 2 shared + 1 private
    p.check_invariants()
    # Truncating below the shared region must decref, not free, the
    # shared tail block.
    remaining = p.truncate(2, 4)               # down to 1 block
    assert remaining == t2[:1]
    # Shared tail block decrefs (pin + req 1 remain) instead of freeing;
    # the private block goes straight back to the pool.
    assert p.refcount(t1[1]) == 2
    assert p.refcount(t2[2]) == 0
    p.check_invariants()
    p.release(1)
    p.release(2)
    for b in t1:
        p.unpin(b)
    p.check_invariants()
    assert p.free_blocks == p.cache.num_blocks - 1   # all but scratch


# ---------------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------------

def test_prefix_cache_match_insert():
    p = _pager()
    pc = PrefixCache(p)
    toks = np.arange(11, dtype=np.int32)       # 2 full blocks + tail
    table = p.allocate(1, 11)
    assert pc.insert(toks, table) == 2
    assert pc.resident_blocks == 2
    assert p.is_pinned(table[0]) and p.is_pinned(table[1])
    # Exact prefix hit, capped at the full blocks.
    n, blocks = pc.match(toks)
    assert n == 8 and blocks == table[:2]
    # A diverging second block only matches the first.
    other = toks.copy()
    other[5] = 99
    n, blocks = pc.match(other)
    assert n == 4 and blocks == table[:1]
    # match() never returns the whole prompt: >= 1 token must prefill.
    n, blocks = pc.match(toks[:8])
    assert n == 4 and blocks == table[:1]
    # Unrelated prompt: miss.
    n, blocks = pc.match(np.full(9, 200, np.int32))
    assert (n, blocks) == (0, [])
    # Re-inserting a matched path adds nothing.
    assert pc.insert(toks, table) == 0


def test_prefix_cache_lru_eviction():
    p = _pager()
    pc = PrefixCache(p)
    t1 = p.allocate(1, 4)
    t2 = p.allocate(2, 4)
    pc.insert(np.arange(4, dtype=np.int32), t1)
    pc.insert(np.arange(50, 54, dtype=np.int32), t2)
    p.release(1)
    p.release(2)
    # Refresh t2's stamp: t1's node becomes the LRU leaf.
    pc.match(np.arange(50, 55, dtype=np.int32))
    free_before = p.free_blocks
    assert pc.evict(1) == 1
    assert p.free_blocks == free_before + 1
    assert pc.resident_blocks == 1
    n, _ = pc.match(np.arange(5, dtype=np.int32))
    assert n == 0                              # t1's entry is gone
    n, _ = pc.match(np.arange(50, 55, dtype=np.int32))
    assert n == 4                              # t2's survived
    # Protected and still-referenced blocks are not evictable.
    assert pc.evict(1, protect=t2) == 0
    p.check_invariants()


def test_prefix_cache_respects_live_references():
    p = _pager()
    pc = PrefixCache(p)
    t1 = p.allocate(1, 4)
    pc.insert(np.arange(4, dtype=np.int32), t1)
    # Request 1 still holds the block: refcount 2, not evictable.
    assert pc.evict(1) == 0
    p.release(1)
    assert pc.evict(1) == 1
    p.check_invariants()


def test_prefix_cache_max_blocks_cap():
    p = _pager(num_blocks=32)
    pc = PrefixCache(p, max_blocks=2)
    t1 = p.allocate(1, 8)
    pc.insert(np.arange(8, dtype=np.int32), t1)
    p.release(1)
    assert pc.resident_blocks == 2
    # Inserting 2 more blocks under a 2-block cap evicts the old pair.
    t2 = p.allocate(2, 8)
    pc.insert(np.arange(100, 108, dtype=np.int32), t2)
    p.release(2)
    assert pc.resident_blocks == 2
    n, _ = pc.match(np.arange(9, dtype=np.int32))
    assert n == 0
    p.check_invariants()


# ---------------------------------------------------------------------------
# engine parity: prefix reuse and speculative decode
# ---------------------------------------------------------------------------

def test_prefix_reuse_greedy_parity(tiny):
    cfg, params = tiny
    sess = serving.serve(params, cfg, num_blocks=64, block_size=8,
                         max_active=4, use_flash="never",
                         prefix_cache=True)
    rng = np.random.RandomState(3)
    head = rng.randint(0, 256, size=(24,)).astype(np.int32)
    tails = _prompts(rng, [7, 11])
    prompts = [head] + [np.concatenate([head, t]) for t in tails]
    # First request populates the cache; the follow-ups (admitted after
    # it prefilled) hit its 3 full head blocks.
    futs = [sess.submit(prompts[0], 12)]
    sess.drain()
    futs += [sess.submit(p, 12) for p in prompts[1:]]
    sess.drain()
    for p, f in zip(prompts, futs):
        res = f.result()
        assert res.tokens == _oracle(params, cfg, p, 12), \
            "prefix-hit prompt diverged from the dense oracle"
    # The shared 24-token head (3 full blocks) was served from cache.
    m2 = futs[1].result().metrics
    assert m2["cached_tokens"] == 24
    assert futs[0].result().metrics["cached_tokens"] == 0
    sess.engine.pager.check_invariants()
    sess.close()


@pytest.mark.parametrize("k", [
    pytest.param(1, marks=pytest.mark.slow),
    2,
    pytest.param(4, marks=pytest.mark.slow),
])
def test_spec_decode_greedy_parity(tiny, k):
    """Draft == target: every draft agrees, yet emitted tokens must be
    the target's regardless (greedy spec decode is an exactness
    transform, not an approximation)."""
    cfg, params = tiny
    sess = serving.serve(params, cfg, num_blocks=64, block_size=8,
                         max_active=4, use_flash="never",
                         spec_k=k, draft_params=params, draft_cfg=cfg)
    prompts = _prompts(np.random.RandomState(4), [5, 9, 13])
    futs = [sess.submit(p, 11) for p in prompts]
    sess.drain()
    for p, f in zip(prompts, futs):
        assert f.result().tokens == _oracle(params, cfg, p, 11)
    # An identical draft must be accepted every time; anything below 1.0
    # means the draft pool diverged from the target pool (e.g. a draft
    # K/V position left unwritten after a fully-accepted round).
    spec = sess.engine.spec
    assert spec._drafted_total > 0
    assert spec._accepted_total == spec._drafted_total
    sess.engine.pager.check_invariants()
    sess.close()


@pytest.mark.slow
def test_spec_decode_weak_draft_parity(tiny):
    """A garbage draft model costs acceptance rate, never correctness."""
    cfg, params = tiny
    weak = llama.init_params(cfg, jax.random.PRNGKey(7))
    sess = serving.serve(params, cfg, num_blocks=64, block_size=8,
                         max_active=4, use_flash="never",
                         spec_k=3, draft_params=weak, draft_cfg=cfg)
    prompts = _prompts(np.random.RandomState(5), [6, 10])
    futs = [sess.submit(p, 10) for p in prompts]
    sess.drain()
    for p, f in zip(prompts, futs):
        assert f.result().tokens == _oracle(params, cfg, p, 10)
    sess.engine.pager.check_invariants()
    sess.close()


@pytest.mark.slow
def test_spec_with_prefix_cache_parity(tiny):
    cfg, params = tiny
    sess = serving.serve(params, cfg, num_blocks=64, block_size=8,
                         max_active=4, use_flash="never",
                         prefix_cache=True, spec_k=2,
                         draft_params=params, draft_cfg=cfg)
    rng = np.random.RandomState(6)
    head = rng.randint(0, 256, size=(16,)).astype(np.int32)
    prompts = [head, np.concatenate([head, _prompts(rng, [5])[0]])]
    futs = [sess.submit(prompts[0], 9)]
    sess.drain()
    futs.append(sess.submit(prompts[1], 9))
    sess.drain()
    for p, f in zip(prompts, futs):
        assert f.result().tokens == _oracle(params, cfg, p, 9)
    assert futs[1].result().metrics["cached_tokens"] == 16
    sess.engine.pager.check_invariants()
    sess.close()


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

def _local_replicas(cfg, params, n=2, **kw):
    sessions = [serving.serve(params, cfg, num_blocks=64, block_size=8,
                              max_active=4, use_flash="never", **kw)
                for _ in range(n)]
    return [LocalReplica(str(i), s) for i, s in enumerate(sessions)]


@pytest.mark.slow
def test_router_balances_and_parity(tiny):
    cfg, params = tiny
    reps = _local_replicas(cfg, params)
    router = Router(reps, RouterConfig(affinity_tokens=0))
    prompts = _prompts(np.random.RandomState(8), [5, 6, 7, 8, 9, 10])
    futs = [router.submit(p, 8) for p in prompts]
    router.drain(timeout_s=120)
    placed = {r.replica_id: 0 for r in reps}
    for p, f in zip(prompts, futs):
        res = f.result(timeout=1)
        assert res.tokens == _oracle(params, cfg, p, 8)
        assert res.metrics["finish_reason"] == "length"
        placed[res.metrics["replica"]] += 1
    # Least-loaded placement with equal replicas splits the stream.
    assert placed["0"] == 3 and placed["1"] == 3, placed
    for r in reps:
        r.session.close()


def test_router_affinity_stickiness(tiny):
    cfg, params = tiny
    reps = _local_replicas(cfg, params)
    router = Router(reps, RouterConfig(affinity_tokens=4))
    rng = np.random.RandomState(9)
    head = rng.randint(0, 256, size=(6,)).astype(np.int32)
    same = [np.concatenate([head, t]) for t in _prompts(rng, [3, 4, 5])]
    futs = [router.submit(p, 4) for p in same]
    router.drain(timeout_s=120)
    replicas = {f.result(timeout=1).metrics["replica"] for f in futs}
    assert len(replicas) == 1, \
        "shared-prefix requests should stick to one replica"
    for r in reps:
        r.session.close()


@pytest.mark.slow
def test_router_failover_completes_on_survivor(tiny):
    cfg, params = tiny
    reps = _local_replicas(cfg, params)
    router = Router(reps, RouterConfig(affinity_tokens=0))
    prompts = _prompts(np.random.RandomState(10), [5, 6, 7, 8])
    streamed: dict[int, list[int]] = {}

    def cb_for(i):
        return lambda rid, t: streamed.setdefault(i, []).append(int(t))

    futs = [router.submit(p, 10, stream_cb=cb_for(i))
            for i, p in enumerate(prompts)]
    # Let everything get placed and emit a few tokens, then crash one.
    for _ in range(6):
        router.pump()
    reps[1].kill()
    router.drain(timeout_s=120)
    assert router.failovers >= 1
    for i, (p, f) in enumerate(zip(prompts, futs)):
        res = f.result(timeout=1)
        assert res.tokens == _oracle(params, cfg, p, 10)
        assert res.metrics["finish_reason"] == "length"
        # At-least-once streaming: a failed-over request replays from
        # token 0 (greedy decode is deterministic, so the replay is
        # identical); the stream's tail is always the result tokens.
        assert streamed[i][-len(res.tokens):] == res.tokens
    moved = [f.result(timeout=1).metrics for f in futs
             if f.result(timeout=1).metrics["router_attempts"] > 1]
    assert moved and all(m["replica"] == "0" for m in moved)
    reps[0].session.close()


def test_router_all_dead_queues_then_times_out(tiny):
    """With every replica dead the router queues rather than rejects (a
    drain window should delay, not drop); drain surfaces the stall as a
    TimeoutError and the flight stays unresolved for a replica that
    might come back."""
    cfg, params = tiny
    reps = _local_replicas(cfg, params, n=1)
    router = Router(reps, RouterConfig(max_attempts=2,
                                       failover_grace_s=0.0))
    fut = router.submit(np.arange(5, dtype=np.int32), 4)
    reps[0].kill()
    with pytest.raises(TimeoutError):
        router.drain(timeout_s=0.5)
    assert not fut.done()
    assert router.failovers >= 1               # it did try to move it
    reps[0].session.close()


# ---------------------------------------------------------------------------
# placement signals: staleness guard
# ---------------------------------------------------------------------------

def _frozen_snapshot(rank, age_s, interval_s=0.5, ready=True):
    return {
        "rank": rank, "time": time.time() - age_s,
        "meta": {"interval_s": interval_s},
        "snapshot": [
            {"name": "hvd_replica_ready", "type": "gauge",
             "samples": [{"labels": {}, "value": 1.0 if ready else 0.0}]},
            {"name": "hvd_serving_queue_depth", "type": "gauge",
             "samples": [{"labels": {}, "value": 1.0}]},
        ],
    }


def test_signals_stale_snapshot_marked():
    from horovod_tpu.obs.aggregate import snapshot_is_stale
    fresh = _frozen_snapshot(0, age_s=0.1)
    stale = _frozen_snapshot(1, age_s=5.0)
    assert not snapshot_is_stale(fresh)
    assert snapshot_is_stale(stale)            # 5s >> 2 x 0.5s interval
    s = signals_from_snapshot(stale)
    assert s["stale"] and s["alive"] and s["ready"]
    assert not signals_from_snapshot(fresh)["stale"]


def test_router_skips_stale_replica():
    """A replica whose publisher froze (snapshot older than twice its
    publish interval) must not take NEW placements, even though its
    last-known signals look healthy."""

    class FakeReplica:
        def __init__(self, rid, sig):
            self.replica_id = rid
            self._sig = sig
            self.submitted = []

        def drive(self):
            pass

        def signals(self):
            return dict(self._sig)

        def submit(self, prompt, max_tokens, *, eos_token=None,
                   trace_ctx=None):
            self.submitted.append(list(prompt))
            return len(self.submitted) - 1

        def partial_tokens(self, h):
            return []

        def result(self, h):
            return {"ok": True, "tokens": [1, 2],
                    "finish_reason": "length", "metrics": {}}

    fresh = signals_from_snapshot(_frozen_snapshot(0, age_s=0.1))
    stale = signals_from_snapshot(_frozen_snapshot(1, age_s=5.0))
    stale["queue_depth"] = 0.0                 # tempting, but frozen
    r_ok = FakeReplica("0", fresh)
    r_stale = FakeReplica("1", stale)
    router = Router([r_ok, r_stale], RouterConfig(affinity_tokens=0))
    futs = [router.submit(np.arange(4, dtype=np.int32), 2)
            for _ in range(4)]
    router.drain(timeout_s=10)
    assert len(r_stale.submitted) == 0
    assert len(r_ok.submitted) == 4
    assert all(f.result(timeout=1).tokens == [1, 2] for f in futs)


def test_dead_signals_never_place():
    class DeadReplica:
        replica_id = "0"

        def drive(self):
            pass

        def signals(self):
            return dict(DEAD_SIGNALS)

        def submit(self, *a, **kw):
            raise AssertionError("placed on a dead replica")

        def partial_tokens(self, h):
            return []

        def result(self, h):
            return None

    router = Router([DeadReplica()], RouterConfig(max_attempts=1))
    fut = router.submit(np.arange(3, dtype=np.int32), 2)
    for _ in range(5):
        router.pump()
    assert not fut.done() or fut.exception() is not None


# ---------------------------------------------------------------------------
# scheduler integration: cache eviction as a pressure valve
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_scheduler_evicts_cache_under_pressure(tiny):
    """A full pool with idle cached blocks must evict them to admit new
    work instead of rejecting or preempting."""
    cfg, params = tiny
    sess = serving.serve(params, cfg, num_blocks=10, block_size=8,
                         max_active=2, use_flash="never",
                         prefix_cache=True)
    rng = np.random.RandomState(11)
    p1 = rng.randint(0, 256, size=(16,)).astype(np.int32)
    f1 = sess.submit(p1, 4)
    sess.drain()
    assert f1.result().metrics["finish_reason"] == "length"
    cache = sess.engine.prefix_cache
    assert cache.resident_blocks == 2          # p1's two full blocks
    probe = np.concatenate([p1, p1[:1]])
    assert cache.match(probe)[0] == 16
    # 9 usable blocks, 2 pinned idle: a 60-token prompt needs 8 blocks
    # (decode headroom included) — only an eviction makes it fit.
    p2 = rng.randint(0, 256, size=(60,)).astype(np.int32)
    f2 = sess.submit(p2, 4)
    sess.drain()
    assert f2.result().tokens == _oracle(params, cfg, p2, 4)
    assert cache.match(probe)[0] < 16          # p1's chain shrank
    sess.engine.pager.check_invariants()
    sess.close()
