"""Chunked+tiered hierarchical schedules (``hier:<n_local>:<k>``):
descriptor grammar, lowering structure, executor parity on a 2x4 tier
mesh, per-tier wire accounting, and the compile-warm observation fix.

Parity contract (documented, not aspirational):

- fp32: the tiered sum regroups (local, then cross) — associativity up
  to rounding, so <= 2 ulp relative vs the flat kernel, NOT bit-exact.
- int8: bit-exact vs both monolithic and flat-decomposed.  The int16
  block accumulator is exact for any summand order up to 256 ranks, and
  tier boundaries land on the same block grid, so regrouping cannot
  change a single bit.
- fp8: bounded, NOT bit-exact.  fp8 payloads accumulate in fp16
  (ops/reduction.py), exact only up to fp16 rounding; flat monolithic
  and flat rs_ag agree bit-for-bit only because they share one ring
  order, which tiering necessarily changes.  The honest contract is
  error vs the true mean comparable to flat fp8's own quantization
  error.
"""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import sched
from horovod_tpu.ops.sched import executor as SE

N = 8


@pytest.fixture
def hier_cfg():
    cfg = hvd.global_state().config
    old = (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes,
           cfg.hierarchical_allreduce, cfg.hierarchical_local_size,
           cfg.hierarchical_cross_precision)
    yield cfg
    (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes,
     cfg.hierarchical_allreduce, cfg.hierarchical_local_size,
     cfg.hierarchical_cross_precision) = old


def _parts(numel, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randn(numel).astype(np.float32) for _ in range(N)]


# ---------------------------------------------------------------------------
# Descriptor grammar
# ---------------------------------------------------------------------------

def test_hier_descriptor_grammar():
    assert sched.parse_hier_descriptor("hier:4:2") == (4, 2)
    assert sched.parse_hier_descriptor("hier:2:1") == (2, 1)
    assert sched.parse_hier_descriptor("hier:1:2") is None   # n_local < 2
    assert sched.parse_hier_descriptor("hier:4:0") is None   # k < 1
    assert sched.parse_hier_descriptor("rs_ag:2") is None
    assert sched.parse_hier_descriptor("hier:tp/dp") is None  # slash form
    assert sched.hier_descriptor(4, 2) == "hier:4:2"
    # known_descriptor accepts both families (negotiation-meta gate).
    assert sched.known_descriptor("rs_ag:3")
    assert sched.known_descriptor("hier:4:2")
    assert not sched.known_descriptor("banana")
    assert not sched.known_descriptor("")


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------

def test_lower_hierarchical_chunked_structure():
    s = sched.lower_hierarchical_chunked(
        8192, 4, 2, op_average=True, mode="fp32", cross_mode="fp32",
        chunks=2, local_axis="hvd_local", cross_axis="hvd_cross")
    assert s.descriptor == "hier:4:2"
    per_chunk = [(st.kind, st.axis) for st in s.steps
                 if st.chunk == 0 and st.kind not in ("chunk", "concat")]
    assert per_chunk == [("reduce_scatter", "hvd_local"),
                        ("all_reduce", "hvd_cross"),
                        ("combine", ""),
                        ("all_gather", "hvd_local")]
    # Deterministic: identical inputs -> identical signature.
    s2 = sched.lower_hierarchical_chunked(
        8192, 4, 2, op_average=True, mode="fp32", cross_mode="fp32",
        chunks=2, local_axis="hvd_local", cross_axis="hvd_cross")
    assert s.signature() == s2.signature()
    # Quantized cross hop changes the signature (different wire algebra).
    s3 = sched.lower_hierarchical_chunked(
        8192, 4, 2, op_average=True, mode="fp32", cross_mode="int8",
        chunks=2, local_axis="hvd_local", cross_axis="hvd_cross")
    assert s3.signature() != s.signature()
    with pytest.raises(Exception):
        sched.lower_hierarchical_chunked(
            8192, 1, 8, op_average=True, mode="fp32", cross_mode="fp32",
            chunks=2, local_axis="hvd_local", cross_axis="hvd_cross")


def test_lower_hierarchical_chunked_interleave():
    """All chunks' local reduce-scatters are dispatched before any cross
    hop: chunk c's DCN exchange is in flight under chunk c+1's ICI work."""
    s = sched.lower_hierarchical_chunked(
        1 << 14, 2, 2, op_average=False, mode="fp32", cross_mode="fp32",
        chunks=2, local_axis="hvd_local", cross_axis="hvd_cross")
    order = [(st.kind, st.chunk) for st in s.interleaved_order()]
    last_rs = max(i for i, (k, _) in enumerate(order)
                  if k == "reduce_scatter")
    first_ar = min(i for i, (k, _) in enumerate(order)
                   if k == "all_reduce")
    assert last_rs < first_ar, order


# ---------------------------------------------------------------------------
# Executor parity (single controller; negotiated transport is
# mp_sched_worker's job)
# ---------------------------------------------------------------------------

def _run(xs, op, descriptor, **kw):
    outs = SE.execute_allreduce([xs], op, descriptor=descriptor, **kw)
    return hvd.to_numpy(outs[0])


def test_hier_executor_fp32_parity(hier_cfg):
    parts = _parts(5000)
    x = hvd.per_rank(parts)
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    got = _run(x, hvd.Average, "hier:4:2")
    eps = np.finfo(np.float32).eps
    # Normwise <= 2 ulp: the tiered sum regroups terms, so elementwise
    # identity is not the contract (module docstring), but the error is
    # plain fp32 re-association noise.
    assert np.abs(got - ref).max() <= 2 * eps * np.abs(ref).max()
    # SUM + pre/postscale ride the tiers too.
    ref_s = hvd.to_numpy(hvd.allreduce(x, hvd.Sum)) * 0.5 * 2.0
    got_s = _run(x, hvd.Sum, "hier:2:2", prescale=0.5, postscale=2.0)
    assert np.abs(got_s - ref_s).max() <= 2 * eps * np.abs(ref_s).max()


def test_hier_executor_int8_bit_exact(hier_cfg):
    hier_cfg.quant_min_bytes = 0
    parts = _parts(100000, seed=3)
    x = hvd.per_rank(parts)
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression="int8"))
    flat = SE.execute_allreduce([x], hvd.Average, descriptor="rs_ag:2",
                                precision="int8")
    got = _run(x, hvd.Average, "hier:4:2", precision="int8")
    assert np.array_equal(ref, got)
    assert np.array_equal(hvd.to_numpy(flat[0]), got)
    # And the quantized path really ran (lossy vs exact numpy).
    assert np.abs(got - np.stack(parts).mean(0)).max() > 0


def test_hier_executor_fp8_bounded(hier_cfg):
    """fp8 tiers are NOT bit-exact vs flat (fp16 accumulator + regrouped
    sum, module docstring); the contract is error-vs-truth comparable to
    flat fp8's own quantization error."""
    hier_cfg.quant_min_bytes = 0
    parts = _parts(100000, seed=7)
    x = hvd.per_rank(parts)
    truth = np.stack(parts).mean(0)
    flat = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression="fp8"))
    got = _run(x, hvd.Average, "hier:4:2", precision="fp8")
    flat_err = np.abs(flat - truth).max()
    hier_err = np.abs(got - truth).max()
    assert flat_err > 0                        # fp8 really is lossy
    assert hier_err <= 2 * flat_err, (hier_err, flat_err)


def test_hier_executor_cross_precision(hier_cfg):
    """fp32 fast tier + int8 DCN hop: bounded quantization error, and the
    error really comes from the cross hop (fp32/fp32 is ulp-exact)."""
    hier_cfg.quant_min_bytes = 0
    hier_cfg.hierarchical_cross_precision = "int8"
    assert SE.resolve_cross_mode("fp32", hier_cfg) == "int8"
    assert SE.resolve_cross_mode("int8", hier_cfg) == "int8"
    assert SE.resolve_cross_mode("fp8", hier_cfg) == "fp8"
    parts = _parts(100000, seed=11)
    x = hvd.per_rank(parts)
    truth = np.stack(parts).mean(0)
    got = _run(x, hvd.Average, "hier:4:2")
    err = np.abs(got - truth).max()
    assert 0 < err < 0.1, err                  # lossy but bounded
    hier_cfg.hierarchical_cross_precision = ""
    exact = _run(x, hvd.Average, "hier:4:2")
    assert np.abs(exact - truth).max() <= \
        4 * np.finfo(np.float32).eps * np.abs(truth).max()


def test_hier_executor_grouped_and_rejections(hier_cfg):
    xs = [hvd.per_rank([np.full((97,), float(r + i), np.float32)
                        for r in range(N)]) for i in range(3)]
    outs = SE.execute_allreduce(xs, hvd.Sum, descriptor="hier:2:2")
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            hvd.to_numpy(o), np.full((97,), sum(range(N)) + N * i),
            rtol=1e-6)
    x = hvd.per_rank(_parts(4096))
    with pytest.raises(ValueError, match="cast wire mode"):
        SE.execute_allreduce([x], hvd.Sum, descriptor="hier:4:2",
                             precision="bf16")
    with pytest.raises(ValueError):
        SE.execute_allreduce([x], hvd.Sum, descriptor="hier:3:2")  # 8 % 3
    with pytest.raises(ValueError):
        SE.execute_allreduce([x], hvd.Sum, descriptor="hier:8:2")  # == n


def test_hier_executor_publishes_tier_gauges(hier_cfg):
    from horovod_tpu.obs import REGISTRY, export
    x = hvd.per_rank(_parts(4096, seed=13))
    _run(x, hvd.Average, "hier:4:2")
    text = export.to_prometheus(REGISTRY.snapshot())
    assert 'hvd_perf_efficiency{mode="fp32",schedule="hier:4:2",' \
        'tier="hier",verb="allreduce"}' in text
    assert 'hvd_perf_tier_excess_seconds{tier="local"}' in text
    assert 'hvd_perf_tier_excess_seconds{tier="cross"}' in text


# ---------------------------------------------------------------------------
# Per-tier wire accounting: the cross hop carries 1/n_local of flat
# ---------------------------------------------------------------------------

def test_cross_tier_wire_bytes_are_one_over_n_local():
    from horovod_tpu.obs import perfmodel
    from horovod_tpu.ops import reduction as R
    B, n_local, n_cross = 1 << 22, 4, 2
    n = n_local * n_cross
    for cross_mode in ("fp32", "int8", "fp8"):
        cost = perfmodel.expected_hierarchical(
            B, n_local, n_cross, mode="fp32", cross_mode=cross_mode)
        # The cross tier moves exactly what a flat ring over n_cross
        # ranks would move on a 1/n_local payload...
        assert cost.tiers["cross"].wire_bytes == pytest.approx(
            R.ring_wire_bytes(cross_mode, B // n_local, n_cross, 512,
                              itemsize=4))
        # ...i.e. 1/n_local of the same-mode flat ring at full payload,
        # up to the ring-size factor (n_cross-1)/n_cross vs (n-1)/n.
        flat = R.ring_wire_bytes(cross_mode, B, n, 512, itemsize=4)
        frac_ratio = ((n_cross - 1) / n_cross) / ((n - 1) / n)
        assert cost.tiers["cross"].wire_bytes == pytest.approx(
            flat * frac_ratio / n_local)


# ---------------------------------------------------------------------------
# Compile-warm observation (satellite: first-call jit compile must not
# pollute the observe_tiers window)
# ---------------------------------------------------------------------------

def test_hierarchical_allreduce_observation_excludes_compile(monkeypatch):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from horovod_tpu.ops import hierarchical as H
    from horovod_tpu.obs import perfmodel

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
    x = np.random.RandomState(0).randn(2, 4, 4321).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))

    clock = {"t": 0.0}
    real_compile = H._compiled_hierarchical

    def slow_compile(*a, **kw):
        clock["t"] += 100.0          # pretend the compiler took 100 s
        return real_compile(*a, **kw)

    observed = []
    monkeypatch.setattr(H, "_compiled_hierarchical", slow_compile)
    monkeypatch.setattr(H.time, "monotonic", lambda: clock["t"])
    monkeypatch.setattr(
        perfmodel.MODEL, "observe_tiers",
        lambda *a, **kw: observed.append(a[3]))

    H._COMPILE_CACHE.clear()
    out = np.asarray(H.hierarchical_allreduce(
        xs, mesh, local_axis="tp", cross_axis="dp"))
    np.testing.assert_allclose(out[0, 0], x.sum(axis=(0, 1)),
                               rtol=1e-4, atol=1e-5)
    # The fake clock only advances inside the compile step; a window
    # that included compile would observe 100 s.
    assert observed and observed[0] < 100.0, observed
    # Second call hits the program cache (no recompile).
    before = clock["t"]
    H.hierarchical_allreduce(xs, mesh, local_axis="tp", cross_axis="dp")
    assert clock["t"] == before + 100.0  # slow_compile wrapper ran...
    assert len(H._COMPILE_CACHE) == 1    # ...but the cache absorbed it
