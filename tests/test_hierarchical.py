"""Hierarchical two-level collectives († HOROVOD_HIERARCHICAL_ALLREDUCE /
ALLGATHER semantics): correctness on a 2-slice × 4-local mesh, including
padding for non-divisible payloads.
"""

import jax
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops.hierarchical import (
    hierarchical_allgather_local,
    hierarchical_allreduce,
)


@pytest.fixture
def mesh2x4():
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))


@pytest.mark.parametrize("numel", [32, 33, 7])   # incl. non-divisible
def test_hierarchical_allreduce_sum(mesh2x4, numel):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, numel).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh2x4, P("dp", "tp")))
    out = np.asarray(hierarchical_allreduce(
        xs, mesh2x4, local_axis="tp", cross_axis="dp"))
    expected = x.sum(axis=(0, 1))
    for i in range(2):
        for j in range(4):
            np.testing.assert_allclose(out[i, j], expected,
                                       rtol=1e-4, atol=1e-5)


def test_hierarchical_allreduce_average(mesh2x4):
    x = np.ones((2, 4, 16), np.float32)
    xs = jax.device_put(x, NamedSharding(mesh2x4, P("dp", "tp")))
    out = np.asarray(hierarchical_allreduce(
        xs, mesh2x4, local_axis="tp", cross_axis="dp", average=True))
    np.testing.assert_allclose(out, 1.0, rtol=1e-6)


def test_hierarchical_allgather(mesh2x4):
    y = np.arange(2 * 4 * 3, dtype=np.float32).reshape(2, 4, 3)

    def ag(v):
        return hierarchical_allgather_local(
            v[0, 0], local_axis="tp", cross_axis="dp")[None, None]

    f = jax.jit(shard_map(ag, mesh=mesh2x4, in_specs=P("dp", "tp"),
                          out_specs=P("dp", "tp"), check_vma=False))
    got = np.asarray(f(jax.device_put(
        y, NamedSharding(mesh2x4, P("dp", "tp")))))
    expected = np.concatenate(
        [np.concatenate([y[i, j] for j in range(4)]) for i in range(2)])
    np.testing.assert_allclose(got[0, 0], expected)


def test_collective_bench_harness_runs():
    from benchmarks.collective_bench import allreduce_busbw
    row = allreduce_busbw(1 << 14, iters=3, warmup=1)
    assert row["ranks"] == 8
    assert row["busbw_GBs"] > 0
    assert row["bytes"] == 1 << 14
