"""Hierarchical two-level collectives († HOROVOD_HIERARCHICAL_ALLREDUCE /
ALLGATHER semantics): correctness on a 2-slice × 4-local mesh, including
padding for non-divisible payloads.
"""

import jax
import numpy as np
import pytest
from horovod_tpu.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops.hierarchical import (
    hierarchical_allgather_local,
    hierarchical_allreduce,
)


@pytest.fixture
def mesh2x4():
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))


@pytest.mark.parametrize("numel", [32, 33, 7])   # incl. non-divisible
def test_hierarchical_allreduce_sum(mesh2x4, numel):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, numel).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh2x4, P("dp", "tp")))
    out = np.asarray(hierarchical_allreduce(
        xs, mesh2x4, local_axis="tp", cross_axis="dp"))
    expected = x.sum(axis=(0, 1))
    for i in range(2):
        for j in range(4):
            np.testing.assert_allclose(out[i, j], expected,
                                       rtol=1e-4, atol=1e-5)


def test_hierarchical_allreduce_average(mesh2x4):
    x = np.ones((2, 4, 16), np.float32)
    xs = jax.device_put(x, NamedSharding(mesh2x4, P("dp", "tp")))
    out = np.asarray(hierarchical_allreduce(
        xs, mesh2x4, local_axis="tp", cross_axis="dp", average=True))
    np.testing.assert_allclose(out, 1.0, rtol=1e-6)


def test_hierarchical_allgather(mesh2x4):
    y = np.arange(2 * 4 * 3, dtype=np.float32).reshape(2, 4, 3)

    def ag(v):
        return hierarchical_allgather_local(
            v[0, 0], local_axis="tp", cross_axis="dp")[None, None]

    f = jax.jit(shard_map(ag, mesh=mesh2x4, in_specs=P("dp", "tp"),
                          out_specs=P("dp", "tp"), check_vma=False))
    got = np.asarray(f(jax.device_put(
        y, NamedSharding(mesh2x4, P("dp", "tp")))))
    expected = np.concatenate(
        [np.concatenate([y[i, j] for j in range(4)]) for i in range(2)])
    np.testing.assert_allclose(got[0, 0], expected)


def test_collective_bench_harness_runs():
    from benchmarks.collective_bench import allreduce_busbw
    row = allreduce_busbw(1 << 14, iters=3, warmup=1)
    assert row["ranks"] == 8
    assert row["busbw_GBs"] > 0
    assert row["bytes"] == 1 << 14


def test_hierarchical_flag_routes_allreduce():
    """HVDTPU_HIERARCHICAL_ALLREDUCE wiring: flag + local-size split routes
    the public allreduce through the two-level kernel with equal results."""
    import horovod_tpu as hvd
    from horovod_tpu.ops import collectives as C
    state = hvd.global_state()
    old_flag = state.config.hierarchical_allreduce
    old_ls = state.config.hierarchical_local_size
    state.config.hierarchical_allreduce = True
    state.config.hierarchical_local_size = 4   # 2 slices x 4
    try:
        assert C._hier_split(None) == (2, 4)
        parts = [np.random.RandomState(r).randn(33).astype(np.float32)
                 for r in range(8)]
        x = hvd.per_rank(parts)
        got = np.asarray(C.allreduce(x, hvd.Sum))
        np.testing.assert_allclose(got, np.stack(parts).sum(0),
                                   rtol=1e-4, atol=1e-5)
        got_avg = np.asarray(C.allreduce(x, hvd.Average))
        np.testing.assert_allclose(got_avg, np.stack(parts).mean(0),
                                   rtol=1e-4, atol=1e-6)
        # grouped path too
        outs = C.grouped_allreduce([x, x], hvd.Sum)
        np.testing.assert_allclose(np.asarray(outs[1]),
                                   np.stack(parts).sum(0),
                                   rtol=1e-4, atol=1e-5)
        # int AVERAGE must stay on the flat path (floor semantics)
        xi = hvd.per_rank([np.full((3,), r, np.int32) for r in range(8)])
        gi = np.asarray(C.allreduce(xi, hvd.Average))
        np.testing.assert_array_equal(gi, np.full((3,), 28 // 8))
    finally:
        state.config.hierarchical_allreduce = old_flag
        state.config.hierarchical_local_size = old_ls


def test_hierarchical_split_invalid_cases():
    import horovod_tpu as hvd
    from horovod_tpu.ops import collectives as C
    state = hvd.global_state()
    old = (state.config.hierarchical_allreduce,
           state.config.hierarchical_local_size)
    try:
        state.config.hierarchical_allreduce = False
        assert C._hier_split(None) is None
        state.config.hierarchical_allreduce = True
        state.config.hierarchical_local_size = 3   # 8 % 3 != 0
        assert C._hier_split(None) is None
        state.config.hierarchical_local_size = 8   # == size
        assert C._hier_split(None) is None
    finally:
        (state.config.hierarchical_allreduce,
         state.config.hierarchical_local_size) = old


def test_hierarchical_rides_the_schedule_ir():
    """The two-level path lowers through ops/sched (ROADMAP item 3 seed):
    the IR schedule carries the tier structure, and the in-graph
    interpreter reproduces the hand-written pipeline's numbers exactly
    (default behavior unchanged)."""
    from horovod_tpu.ops import hierarchical as H
    from horovod_tpu.ops.sched import lower_hierarchical

    s = H.hierarchical_schedule("hvd_local", "hvd_cross")
    kinds = [(st.kind, st.axis) for st in s.steps if st.axis]
    assert kinds == [("reduce_scatter", "hvd_local"),
                     ("all_reduce", "hvd_cross"),
                     ("all_gather", "hvd_local")]
    # Cached + deterministic: same axes -> the same schedule object and
    # an identical signature to a fresh lowering.
    assert H.hierarchical_schedule("hvd_local", "hvd_cross") is s
    assert s.signature() == lower_hierarchical(
        "hvd_local", "hvd_cross").signature()
