"""Flagship Llama model: forward correctness properties and sharded training.

Covers the mesh layouts the multi-chip dry run exercises: dp×sp×tp,
dp×ep×tp (MoE), and dp×pp×tp (layer stack over pp).
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from horovod_tpu.jaxcompat import leaves_with_path

from horovod_tpu.models import llama
from horovod_tpu.parallel import MeshConfig, build_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _batch(cfg, B=4, S=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"tokens": jnp.asarray(
        rng.randint(0, cfg.vocab_size, size=(B, S + 1)), jnp.int32)}


def test_forward_shapes_and_finite():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _batch(cfg)["tokens"][:, :-1]
    logits, aux = llama.forward(params, tokens, cfg)
    assert logits.shape == (4, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) == 0.0


def test_forward_causality():
    # Changing a future token must not affect earlier logits.
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _batch(cfg)["tokens"][:, :-1]
    logits1, _ = llama.forward(params, tokens, cfg)
    perturbed = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
    logits2, _ = llama.forward(params, perturbed, cfg)
    np.testing.assert_allclose(np.asarray(logits1[:, :-1]),
                               np.asarray(logits2[:, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(logits1[:, -1]),
                           np.asarray(logits2[:, -1]))


def test_gqa_forward():
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=1)
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    tokens = _batch(cfg)["tokens"][:, :-1]
    logits, _ = llama.forward(params, tokens, cfg)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(dp=2, sp=2, tp=2),
    MeshConfig(dp=2, pp=2, tp=2),
    MeshConfig(dp=4, tp=2),
])
def test_train_step_sharded(mesh_cfg):
    mesh = build_mesh(mesh_cfg)
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx)
    batch = jax.device_put(_batch(cfg, B=8, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


def test_train_step_moe_ep():
    mesh = build_mesh(MeshConfig(dp=2, ep=2, tp=2))
    cfg = llama.LlamaConfig.tiny(use_moe=True, n_experts=4,
                                 capacity_factor=2.0)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx)
    batch = jax.device_put(_batch(cfg, B=8, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_pp_pipeline_matches_dp_oracle(schedule):
    """pp>1 runs a real pipeline schedule (stage-resident params,
    ppermute'd activations) and must be loss-equivalent to plain DP —
    both the GPipe autodiff path and the explicit-gradient 1F1B path."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=3)
    pp_losses, _, _ = _train_losses(MeshConfig(pp=2, dp=2, tp=2), n_steps=3,
                                    schedule=schedule)
    np.testing.assert_allclose(dp_losses, pp_losses, rtol=1e-4)


def test_pp_1f1b_activation_memory_below_gpipe():
    """The 1F1B selling point, asserted on the compiled step: with many
    microbatches the GPipe step's temporary-buffer footprint grows with M
    while 1F1B's stays bounded by 2*(pp-1) in-flight microbatches."""
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    cfg = llama.LlamaConfig.tiny(n_layers=4, remat=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    batch = jax.device_put(_batch(cfg, B=32, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))

    def temp_bytes(schedule):
        step = llama.make_train_step(cfg, mesh, tx,
                                     pipeline_schedule=schedule)
        comp = step.lower(params, opt_state, batch).compile()
        return comp.memory_analysis().temp_size_in_bytes

    t_1f1b, t_gpipe = temp_bytes("1f1b"), temp_bytes("gpipe")
    assert t_1f1b < t_gpipe, (
        f"1f1b temp {t_1f1b} not below gpipe temp {t_gpipe}")


def test_pp_pipeline_no_per_layer_param_gather():
    """The pp axis must never all-gather stage parameters: the compiled
    step shows collective-permutes (pipeline handoffs) and no all-gather
    whose result is a full stacked layer weight (the anti-pattern where
    scanning a pp-sharded stack makes GSPMD fetch every layer's params)."""
    import re
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    cfg = llama.LlamaConfig.tiny(n_layers=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx)
    batch = jax.device_put(_batch(cfg, B=8, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))
    txt = step.lower(params, opt_state, batch).compile().as_text()
    assert "collective-permute" in txt, "no pipeline handoffs compiled"
    # Full stacked weight shapes (w_gate/w_up [L,D,F], w_down [L,F,D],
    # wq/wo [L,D,H,Dh]-ish): no all-gather may produce them.
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    banned = {f"[{L},{D},{F}]", f"[{L},{F},{D}]",
              f"[{L},{D},{cfg.n_heads},{cfg.head_dim}]"}
    for line in txt.splitlines():
        if "all-gather" in line:
            for shape in banned:
                assert shape not in line.replace(" ", ""), (
                    f"per-layer param gather over pp: {line[:160]}")


@pytest.mark.integration
def test_multichip_dryrun_no_involuntary_remat():
    """The full dp/tp/pp, sp/tp/dp and ep/fsdp/dp dryrun compiles must
    emit zero SPMD 'Involuntary full rematerialization' warnings — each
    one means XLA is replicating a tensor (HBM + ICI cost) because our
    sharding annotations left a gap (round-2 verdict finding; fixed by
    pinning scanned layer slices, gradient accumulators, and vocab-row
    embedding sharding)."""
    import subprocess
    import sys as _sys
    res = subprocess.run(
        [_sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "import __graft_entry__ as g; g.dryrun_multichip(8)" % REPO],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    bad = [ln for ln in res.stderr.splitlines()
           if "Involuntary full rematerialization" in ln]
    assert not bad, "involuntary resharding in flagship:\n" + "\n".join(
        ln[:200] for ln in bad)


def test_flash_model_path_matches_dense_on_mesh():
    """The TPU-gated flash branch of the model's sharded attention (the
    dp/fsdp/tp shard_map in ``_attention``) must produce the same loss
    and gradients as the dense path — exercised on the CPU rig through
    the Pallas interpreter via the ``_FORCE_FLASH_INTERPRET`` hook.
    (The pp-mesh counterpart is ``test_pp_flash_attention_matches_dense``.)"""
    from horovod_tpu.models import llama as L

    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    # Shapes satisfying FA.supported on the LOCAL view: S=256 (block
    # 256), heads 4 / tp 2, head_dim 64.
    cfg = llama.LlamaConfig.tiny(
        n_layers=2, d_model=256, n_heads=4, n_kv_heads=4,
        d_ff=256, vocab_size=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(8, 257))
    batch = jax.device_put(
        {"tokens": jnp.asarray(tokens, jnp.int32)},
        NamedSharding(mesh, P(("dp", "fsdp"))))

    def loss_and_grads(force_flash):
        old = L._FORCE_FLASH_INTERPRET
        L._FORCE_FLASH_INTERPRET = force_flash
        try:
            fn = jax.jit(jax.value_and_grad(
                lambda p: llama.loss_fn(p, batch, cfg, mesh=mesh)))
            loss, grads = fn(params)
            return float(loss), jax.device_get(grads)
        finally:
            L._FORCE_FLASH_INTERPRET = old

    loss_f, grads_f = loss_and_grads(True)
    loss_d, grads_d = loss_and_grads(False)
    np.testing.assert_allclose(loss_f, loss_d, rtol=1e-5)
    flat_f = {jax.tree_util.keystr(k): v
              for k, v in leaves_with_path(grads_f)}
    flat_d = {jax.tree_util.keystr(k): v
              for k, v in leaves_with_path(grads_d)}
    assert flat_f.keys() == flat_d.keys()
    for key in flat_f:
        np.testing.assert_allclose(
            np.asarray(flat_f[key]), np.asarray(flat_d[key]),
            rtol=2e-3, atol=2e-4, err_msg=key)


def test_flash_kept_when_tp_exceeds_kv_heads():
    """GQA config where tp divides H but NOT KV (n_kv_heads=2, tp=4):
    the flash path must survive by expanding K/V (round-5 review: the
    grouped-KV dispatch silently dropped to dense here, a 2-5x
    regression), and the result must match the dense oracle."""
    from horovod_tpu.models import llama as L

    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    cfg = llama.LlamaConfig.tiny(
        n_layers=2, d_model=256, n_heads=4, n_kv_heads=2,
        d_ff=256, vocab_size=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(1), mesh)
    tokens = np.random.RandomState(2).randint(
        0, cfg.vocab_size, size=(8, 257))
    batch = jax.device_put(
        {"tokens": jnp.asarray(tokens, jnp.int32)},
        NamedSharding(mesh, P(("dp", "fsdp"))))

    def loss_of(force_flash):
        old = L._FORCE_FLASH_INTERPRET
        L._FORCE_FLASH_INTERPRET = force_flash
        try:
            return float(jax.jit(
                lambda p: llama.loss_fn(p, batch, cfg, mesh=mesh))(params))
        finally:
            L._FORCE_FLASH_INTERPRET = old

    np.testing.assert_allclose(loss_of(True), loss_of(False), rtol=1e-5)


def test_pp_sp_matches_dp_oracle():
    """pp×sp composition: ring attention inside the fully-manual pipeline
    region must be loss-equivalent to plain DP (round-3 verdict gap —
    long-context on pipeline meshes)."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=3)
    ppsp_losses, _, _ = _train_losses(MeshConfig(pp=2, sp=2, dp=2),
                                      n_steps=3)
    np.testing.assert_allclose(dp_losses, ppsp_losses, rtol=1e-3)


def test_pp_ep_moe_trains():
    """pp×ep composition: MoE a2a dispatch inside the pipeline region.
    Capacity dropping depends on token sharding, so exact oracle equality
    is not defined — assert stable learning like the ep-only MoE test."""
    mesh = build_mesh(MeshConfig(pp=2, ep=2, dp=2))
    cfg = llama.LlamaConfig.tiny(use_moe=True, n_experts=4,
                                 capacity_factor=2.0)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx)
    batch = jax.device_put(_batch(cfg, B=8, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(pp=2, ep=2, dp=2),
    MeshConfig(pp=2, ep=2, tp=2),
])
def test_pp_moe_1f1b_matches_gpipe(mesh_cfg):
    """Gradient-correctness oracle for MoE on pp meshes: the 1F1B explicit-
    gradient path must produce the same loss TRAJECTORY as the GPipe
    autodiff path (same params, same batch, same routing) — with a large
    aux weight so any aux-gradient mis-scaling diverges by step 2 (the
    round-4 review found exactly that: an n_data-times aux overcount that
    'loss decreases' tests cannot catch)."""
    mesh = build_mesh(mesh_cfg)
    cfg = llama.LlamaConfig.tiny(use_moe=True, n_experts=4,
                                 capacity_factor=2.0, moe_aux_weight=0.5)
    tx = optax.adam(1e-2)
    batch = jax.device_put(_batch(cfg, B=8, S=32),
                           NamedSharding(mesh, P(("dp", "fsdp"))))

    def run(schedule):
        params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
        opt_state = jax.jit(tx.init)(params)
        step = llama.make_train_step(cfg, mesh, tx,
                                     pipeline_schedule=schedule)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run("1f1b"), run("gpipe"), rtol=1e-4)


def test_pp_flash_attention_matches_dense():
    """Flash attention under pp (direct kernel call in the fully-manual
    pipeline region — the round-3 1.4x-gradient bug is gone): loss AND
    grads must match the dense path on the same pp mesh."""
    from horovod_tpu.models import llama as L

    from horovod_tpu.ops import flash_attention as FA

    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    cfg = llama.LlamaConfig.tiny(
        n_layers=2, d_model=256, n_heads=4, n_kv_heads=4,
        d_ff=256, vocab_size=128)
    # Guard against vacuity: the LOCAL shard shape (mb/dpf, S, H/tp, Dh)
    # must actually take the flash branch, or both runs silently go dense.
    assert FA.supported((1, 256, 2, 64), itemsize=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tokens = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                              size=(8, 257))
    batch = jax.device_put(
        {"tokens": jnp.asarray(tokens, jnp.int32)},
        NamedSharding(mesh, P(("dp", "fsdp"))))

    def loss_and_grads(force_flash):
        old = L._FORCE_FLASH_INTERPRET
        L._FORCE_FLASH_INTERPRET = force_flash
        try:
            fn = jax.jit(jax.value_and_grad(
                lambda p: llama.loss_fn(p, batch, cfg, mesh=mesh)))
            loss, grads = fn(params)
            return float(loss), jax.device_get(grads)
        finally:
            L._FORCE_FLASH_INTERPRET = old

    loss_f, grads_f = loss_and_grads(True)
    loss_d, grads_d = loss_and_grads(False)
    np.testing.assert_allclose(loss_f, loss_d, rtol=1e-5)
    flat_f = {jax.tree_util.keystr(k): v
              for k, v in leaves_with_path(grads_f)}
    flat_d = {jax.tree_util.keystr(k): v
              for k, v in leaves_with_path(grads_d)}
    assert flat_f.keys() == flat_d.keys()
    for key in flat_f:
        np.testing.assert_allclose(
            np.asarray(flat_f[key]), np.asarray(flat_d[key]),
            rtol=5e-3, atol=5e-4, err_msg=key)


def _train_losses(mesh_cfg, n_steps=4, seed=0, schedule="1f1b", cfg=None):
    mesh = build_mesh(mesh_cfg)
    cfg = cfg or llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(seed), mesh)
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx,
                                 pipeline_schedule=schedule)
    batch = jax.device_put(_batch(cfg, B=8, S=32, seed=seed),
                           NamedSharding(mesh, P(("dp", "fsdp"))))
    losses = []
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    return losses, params, opt_state


def test_fsdp_matches_dp_oracle():
    # ZeRO-3 (params sharded over fsdp, gathered on use, grads
    # reduce-scattered by GSPMD) must train identically to plain DP.
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8))
    fsdp_losses, _, _ = _train_losses(MeshConfig(fsdp=8))
    np.testing.assert_allclose(dp_losses, fsdp_losses, rtol=1e-4)


def test_fsdp_mixed_mesh_matches_dp_oracle():
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8))
    mixed_losses, _, _ = _train_losses(MeshConfig(dp=2, fsdp=2, tp=2))
    np.testing.assert_allclose(dp_losses, mixed_losses, rtol=1e-3)


def test_fsdp_params_at_rest_are_sharded():
    """ZeRO-3 memory property: every matmul weight (embed-dim params)
    lives sharded over fsdp at rest — per-device bytes are 1/fsdp of the
    leaf, not a full replica."""
    mesh = build_mesh(MeshConfig(fsdp=8))
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    for name in ("embed", "lm_head"):
        leaf = params[name]
        shard = leaf.addressable_shards[0].data
        assert shard.size == leaf.size // 8, (
            f"{name} not memory-sharded: shard {shard.shape} of {leaf.shape}")
    for name in ("wq", "wo", "w_gate", "w_down"):
        leaf = params["layers"][name]
        shard = leaf.addressable_shards[0].data
        assert shard.size == leaf.size // 8, (
            f"layers/{name} not memory-sharded: "
            f"shard {shard.shape} of {leaf.shape}")


def test_fsdp_optimizer_state_is_sharded():
    # The ZeRO property: optimizer moments live sharded over fsdp, not
    # replicated — each device holds 1/fsdp of mu/nu for embed-dim params.
    _, params, opt_state = _train_losses(MeshConfig(fsdp=8), n_steps=1)
    mu_wq = opt_state[0].mu["layers"]["wq"]
    spec = mu_wq.sharding.spec
    assert "fsdp" in jax.tree.leaves(list(spec)), (
        f"optimizer state not fsdp-sharded: {spec}")
    # And a shard really is 1/8 of the tensor's rows.
    shard = mu_wq.addressable_shards[0].data
    assert shard.shape[1] == mu_wq.shape[1] // 8


def test_ring_vs_dense_attention_in_model():
    # Same params, same tokens: sp-sharded ring attention must match the
    # dense single-axis forward.
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(2))
    tokens = _batch(cfg, B=2, S=32)["tokens"][:, :-1]
    dense_logits, _ = llama.forward(params, tokens, cfg)

    mesh = build_mesh(MeshConfig(sp=8))
    params_s = jax.device_put(params, llama.param_shardings(cfg, mesh))
    ring_logits, _ = jax.jit(
        lambda p, t: llama.forward(p, t, cfg, mesh=mesh))(params_s, tokens)
    np.testing.assert_allclose(np.asarray(dense_logits),
                               np.asarray(ring_logits),
                               rtol=5e-3, atol=5e-4)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_pp_fsdp_matches_dp_oracle(schedule):
    """pp×fsdp composition: ZeRO-3 all_gathers inside the manual pipeline
    region (and, on the 1F1B path, the lm_head grad reduce-scatter over
    fsdp) must be loss-equivalent to plain DP."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=3)
    pf_losses, _, _ = _train_losses(MeshConfig(pp=2, fsdp=2, tp=2),
                                    n_steps=3, schedule=schedule)
    np.testing.assert_allclose(dp_losses, pf_losses, rtol=1e-3)


def test_ulysses_vs_dense_attention_in_model():
    """sp_attention="ulysses": the all_to_all heads<->sequence swap in the
    model's sp path must match the dense single-axis forward (the ring
    counterpart is test_ring_vs_dense_attention_in_model)."""
    cfg = llama.LlamaConfig.tiny(sp_attention="ulysses",
                                 n_heads=8, n_kv_heads=8, d_model=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(2))
    tokens = _batch(cfg, B=2, S=32)["tokens"][:, :-1]
    dense_logits, _ = llama.forward(
        params, tokens, dataclasses.replace(cfg, sp_attention="ring"))

    mesh = build_mesh(MeshConfig(sp=8))
    params_s = jax.device_put(params, llama.param_shardings(cfg, mesh))
    uly_logits, _ = jax.jit(
        lambda p, t: llama.forward(p, t, cfg, mesh=mesh))(params_s, tokens)
    np.testing.assert_allclose(np.asarray(dense_logits),
                               np.asarray(uly_logits),
                               rtol=5e-3, atol=5e-4)


def test_pp_sp_ulysses_matches_dp_oracle():
    """pp x sp with Ulysses attention inside the manual pipeline region."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=3)
    uly_losses, _, _ = _train_losses(
        MeshConfig(pp=2, sp=2, dp=2), n_steps=3,
        cfg=llama.LlamaConfig.tiny(sp_attention="ulysses"))
    np.testing.assert_allclose(dp_losses, uly_losses, rtol=1e-3)


def test_sp_ulysses_training_matches_dp_oracle():
    """Ulysses BACKWARD on a plain sp mesh (the tiled all_to_all transpose
    — the block form's vjp came back mis-shaped; forward-only tests never
    caught it)."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=3)
    uly_losses, _, _ = _train_losses(
        MeshConfig(sp=4, dp=2), n_steps=3,
        cfg=llama.LlamaConfig.tiny(sp_attention="ulysses"))
    np.testing.assert_allclose(dp_losses, uly_losses, rtol=1e-3)


def test_pp_microbatches_knob():
    """cfg.pp_microbatches overrides the auto microbatch count (bubble
    tuning; 1F1B memory is flat in M) and validates divisibility."""
    dp_losses, _, _ = _train_losses(MeshConfig(dp=8), n_steps=2)
    m4_losses, _, _ = _train_losses(
        MeshConfig(pp=2, dp=2, tp=2), n_steps=2,
        cfg=llama.LlamaConfig.tiny(pp_microbatches=4))  # local batch 4
    np.testing.assert_allclose(dp_losses, m4_losses, rtol=1e-4)

    with pytest.raises(ValueError, match="pp_microbatches"):
        _train_losses(MeshConfig(pp=2, dp=2, tp=2), n_steps=1,
                      cfg=llama.LlamaConfig.tiny(pp_microbatches=3))


def test_generate_matches_full_forward_greedy():
    """KV-cache decoding oracle: generate() must emit exactly the tokens
    that greedy decoding via repeated FULL forwards produces (prefill +
    cached single-token steps = recompute-everything, token for token)."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(3))
    rng = np.random.RandomState(5)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 8)), jnp.int32)

    out = llama.generate(params, prompt, cfg, max_new_tokens=6)
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out[:, :8]),
                                  np.asarray(prompt))

    seq = prompt
    for _ in range(6):
        logits, _ = llama.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generate_gqa_and_mesh():
    """generate with GQA heads and under a dp/tp GSPMD mesh; manual-axis
    meshes are rejected."""
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=2)
    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    rng = np.random.RandomState(1)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 5)), jnp.int32)
    out = llama.generate(params, prompt, cfg, max_new_tokens=4, mesh=mesh)
    assert out.shape == (4, 9)
    assert np.isfinite(np.asarray(out)).all()

    with pytest.raises(NotImplementedError, match="sp/ep"):
        llama.generate(params, prompt, cfg, max_new_tokens=2,
                       mesh=build_mesh(MeshConfig(sp=8)))


def test_generate_tp_sharded_cache_matches_oracle():
    """generate on a tp=2 mesh (KV cache constrained to kv_heads-over-tp)
    must emit exactly the mesh=None tokens (round-4 verdict ask #6)."""
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=2)
    oracle_params = llama.init_params(cfg, jax.random.PRNGKey(7))
    rng = np.random.RandomState(9)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 6)), jnp.int32)
    oracle = llama.generate(oracle_params, prompt, cfg, max_new_tokens=5)
    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    params = jax.device_put(oracle_params,
                            llama.param_shardings(cfg, mesh))
    out = llama.generate(params, prompt, cfg, max_new_tokens=5, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(oracle))


@pytest.mark.parametrize("mesh_kw", [dict(pp=2, dp=4), dict(pp=2, tp=2, dp=2),
                                     dict(pp=2, fsdp=2, dp=2)])
def test_generate_pp_matches_oracle(mesh_kw):
    """generate on pp meshes: stage-resident layers, sharded KV cache,
    ppermute chain — token-exact vs the single-device oracle (round-4
    verdict ask #6: the models/llama.py:669 restriction lifted)."""
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=2)
    oracle_params = llama.init_params(cfg, jax.random.PRNGKey(4))
    rng = np.random.RandomState(6)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 5)), jnp.int32)
    oracle = llama.generate(oracle_params, prompt, cfg, max_new_tokens=4)
    mesh = build_mesh(MeshConfig(**mesh_kw))
    params = jax.device_put(oracle_params,
                            llama.param_shardings(cfg, mesh))
    out = llama.generate(params, prompt, cfg, max_new_tokens=4, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(oracle))


def test_generate_pp_temperature_sampling_reproducible():
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=2)
    mesh = build_mesh(MeshConfig(pp=2, tp=2, dp=2))
    params = llama.init_params(cfg, jax.random.PRNGKey(2), mesh)
    prompt = jnp.asarray(np.random.RandomState(3).randint(
        0, cfg.vocab_size, (2, 4)), jnp.int32)
    k = jax.random.PRNGKey(21)
    s1 = llama.generate(params, prompt, cfg, max_new_tokens=4,
                        temperature=0.7, key=k, mesh=mesh)
    s2 = llama.generate(params, prompt, cfg, max_new_tokens=4,
                        temperature=0.7, key=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert (np.asarray(s1) >= 0).all()
    assert (np.asarray(s1) < cfg.vocab_size).all()


def test_generate_temperature_sampling():
    """temperature=0 is greedy; temperature>0 samples reproducibly from
    the key and stays in-vocab."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(3))
    prompt = jnp.asarray(np.random.RandomState(5).randint(
        0, cfg.vocab_size, (2, 8)), jnp.int32)
    greedy = llama.generate(params, prompt, cfg, max_new_tokens=5)
    greedy0 = llama.generate(params, prompt, cfg, max_new_tokens=5,
                             temperature=0.0)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(greedy0))
    k = jax.random.PRNGKey(11)
    s1 = llama.generate(params, prompt, cfg, max_new_tokens=5,
                        temperature=1.0, key=k)
    s2 = llama.generate(params, prompt, cfg, max_new_tokens=5,
                        temperature=1.0, key=k)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert (np.asarray(s1) < cfg.vocab_size).all()
    assert (np.asarray(s1) >= 0).all()
    with pytest.raises(ValueError, match="PRNG key"):
        llama.generate(params, prompt, cfg, max_new_tokens=2,
                       temperature=0.8)
