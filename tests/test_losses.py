"""Blockwise cross-entropy (ops/losses.py) vs the dense log_softmax
oracle: values and gradients must agree to fp32 tolerance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.losses import blockwise_cross_entropy


def _dense_nll(x, w, targets):
    logits = (x @ w).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("block", [128, 256, None])
def test_blockwise_matches_dense(dtype, block):
    T, D, V = 48, 32, 512
    k = jax.random.PRNGKey(0)
    kx, kw, kt = jax.random.split(k, 3)
    x = jax.random.normal(kx, (T, D), dtype)
    w = jax.random.normal(kw, (D, V), dtype) * 0.1
    targets = jax.random.randint(kt, (T,), 0, V, jnp.int32)

    got = blockwise_cross_entropy(x, w, targets, block)
    want = _dense_nll(x, w, targets)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_blockwise_grads_match_dense(dtype):
    T, D, V = 32, 16, 256
    k = jax.random.PRNGKey(1)
    kx, kw, kt = jax.random.split(k, 3)
    x = jax.random.normal(kx, (T, D), dtype)
    w = jax.random.normal(kw, (D, V), dtype) * 0.1
    targets = jax.random.randint(kt, (T,), 0, V, jnp.int32)

    def loss_b(x, w):
        return blockwise_cross_entropy(x, w, targets, 64).mean()

    def loss_d(x, w):
        return _dense_nll(x, w, targets).mean()

    gb = jax.grad(loss_b, argnums=(0, 1))(x, w)
    gd = jax.grad(loss_d, argnums=(0, 1))(x, w)
    tol = 1e-5 if dtype == jnp.float32 else 3e-2
    for a, b in zip(gb, gd):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=tol, atol=tol)


def test_blockwise_under_jit_and_vocab_not_power_of_two():
    T, D, V = 16, 8, 320   # V = 320 -> block picks 128? 320 % 128 != 0
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (T, D), jnp.float32)
    w = jax.random.normal(k, (D, V), jnp.float32) * 0.1
    targets = jnp.zeros((T,), jnp.int32)
    got = jax.jit(lambda x, w, t: blockwise_cross_entropy(x, w, t))(
        x, w, targets)
    want = _dense_nll(x, w, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_blockwise_pads_awkward_vocab():
    """A vocab with no usable divisor (e.g. GPT-2's prime 50257) must be
    padded to big blocks and masked — never hundreds of 1-column scan
    iterations (code-review finding)."""
    from horovod_tpu.ops.losses import _pick_block
    assert _pick_block(32000, None) == 8000      # clean divisor
    assert _pick_block(50257, None) == 1733      # largest usable divisor
    assert _pick_block(1031, None) == 1031       # small vocab: one block
    assert _pick_block(50026, None) == 4096      # 2 x prime -> pad path
    T, D, V = 16, 8, 50026                       # exercises the padding
    k = jax.random.PRNGKey(3)
    x = jax.random.normal(k, (T, D), jnp.float32)
    w = jax.random.normal(k, (D, V), jnp.float32) * 0.1
    targets = jax.random.randint(k, (T,), 0, V, jnp.int32)
    got = blockwise_cross_entropy(x, w, targets)
    want = _dense_nll(x, w, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    gb = jax.grad(lambda x, w: blockwise_cross_entropy(
        x, w, targets).mean(), argnums=(0, 1))(x, w)
    gd = jax.grad(lambda x, w: _dense_nll(x, w, targets).mean(),
                  argnums=(0, 1))(x, w)
    for a, b in zip(gb, gd):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_llama_blockwise_ce_trains_on_fsdp_mesh():
    """blockwise_ce composes with dp/fsdp sharding (the documented
    support surface): training losses must match the dense path."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import llama
    from horovod_tpu.parallel import MeshConfig, build_mesh

    def losses(blockwise):
        mesh = build_mesh(MeshConfig(dp=4, fsdp=2))
        cfg = llama.LlamaConfig.tiny(vocab_size=512,
                                     blockwise_ce=blockwise)
        params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
        tx = optax.adam(1e-2)
        opt_state = jax.jit(tx.init)(params)
        step = llama.make_train_step(cfg, mesh, tx)
        tokens = np.random.RandomState(0).randint(0, 512, size=(8, 17))
        batch = jax.device_put(
            {"tokens": jnp.asarray(tokens, jnp.int32)},
            NamedSharding(mesh, P(("dp", "fsdp"))))
        out = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, batch)
            out.append(float(loss))
        return out

    np.testing.assert_allclose(losses(True), losses(False),
                               rtol=1e-4, atol=1e-5)


def test_llama_loss_paths_agree():
    """The flagship loss with blockwise_ce forced on must match the dense
    path (same params/batch)."""
    from horovod_tpu.models import llama
    cfg_d = llama.LlamaConfig.tiny(vocab_size=512, blockwise_ce=False)
    cfg_b = llama.LlamaConfig.tiny(vocab_size=512, blockwise_ce=True)
    params = llama.init_params(cfg_d, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 512, size=(2, 17)), jnp.int32)
    batch = {"tokens": tokens}
    ld = float(llama.loss_fn(params, batch, cfg_d))
    lb = float(llama.loss_fn(params, batch, cfg_b))
    np.testing.assert_allclose(lb, ld, rtol=1e-5, atol=1e-5)
