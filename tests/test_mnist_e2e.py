"""End-to-end: MNIST ConvNet trained data-parallel on 8 fake devices.

The SURVEY §7 phase-1 milestone (reference config 1, †
``examples/pytorch/pytorch_mnist.py`` run under ``horovodrun``): model
replicated, batch sharded across the hvd axis, gradients averaged by
``DistributedOptimizer``, loss must decrease and parameters must stay
identical across ranks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from horovod_tpu.jaxcompat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.mnist import ConvNet

N = 8
BATCH = 32  # global; 4 per rank


def _synthetic_mnist(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(n,)).astype(np.int32)
    return x, y


def test_mnist_convnet_trains():
    model = ConvNet()
    x_host, y_host = _synthetic_mnist(BATCH)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    opt_state = tx.init(params)
    mesh = hvd.mesh()

    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, opt_state2, jax.lax.pmean(loss, "hvd")

    sharded_step = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()),
        check_vma=False))

    x = jax.device_put(x_host, NamedSharding(mesh, P("hvd")))
    y = jax.device_put(y_host, NamedSharding(mesh, P("hvd")))

    losses = []
    for _ in range(30):
        params, opt_state, loss = sharded_step(params, opt_state, x, y)
        losses.append(float(loss))

    # Overfits the fixed batch: loss must drop substantially.
    assert losses[-1] < losses[0] * 0.5, f"loss did not decrease: {losses}"

    # Parameters must be replicated (identical on every device).
    leaf = jax.tree.leaves(params)[0]
    assert leaf.sharding.is_fully_replicated

    # Inference path produces a valid distribution.
    logits = model.apply(params, jnp.asarray(x_host[:4]))
    assert logits.shape == (4, 10)
    assert np.isfinite(np.asarray(logits)).all()
