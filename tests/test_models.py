"""Model zoo: ResNet, BERT, DLRM — forward correctness + data-parallel
training (the BASELINE configs 2, 3, 5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import bert as bert_mod
from horovod_tpu.models import dlrm as dlrm_mod
from horovod_tpu.models.resnet import resnet18_thin, resnet50

N = 8


# ---------------------------------------------------------------------------
# ResNet
# ---------------------------------------------------------------------------

def test_resnet50_builds():
    model = resnet50(dtype=jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)), train=False))
    n_params = sum(np.prod(x.shape) for x in
                   jax.tree.leaves(variables["params"]))
    # ResNet-50 has ~25.6M params; sanity window.
    assert 24e6 < n_params < 27e6, n_params


def test_resnet_thin_trains_dp():
    model = resnet18_thin(num_classes=10, dtype=jnp.float32)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(16,))
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = hvd.DistributedOptimizer(optax.sgd(0.05))
    opt_state = tx.init(params)
    mesh = hvd.mesh()

    def step(params, batch_stats, opt_state, xb, yb):
        def loss_fn(p):
            logits, new_vars = model.apply(
                {"params": p, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
            return loss, new_vars["batch_stats"]
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        params2 = optax.apply_updates(params, updates)
        # batch_stats averaged across replicas (cross-replica running stats).
        new_bs = jax.tree.map(lambda a: jax.lax.pmean(a, "hvd"), new_bs)
        return params2, new_bs, opt_state2, jax.lax.pmean(loss, "hvd")

    sharded = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    xb = jax.device_put(x, NamedSharding(mesh, P("hvd")))
    yb = jax.device_put(y, NamedSharding(mesh, P("hvd")))
    losses = []
    for _ in range(6):
        params, batch_stats, opt_state, loss = sharded(
            params, batch_stats, opt_state, xb, yb)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_resnet_syncbn_matches_global_bn():
    """SyncBatchNorm via axis_name: per-shard BN statistics psum'd across
    the axis must equal single-device BN over the full batch
    († sync_batch_norm.py semantics)."""
    model_sync = resnet18_thin(num_classes=4, dtype=jnp.float32,
                               axis_name="hvd")
    model_plain = resnet18_thin(num_classes=4, dtype=jnp.float32)
    rng = np.random.RandomState(1)
    x = rng.rand(16, 16, 16, 3).astype(np.float32)
    variables = model_plain.init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 16, 16, 3)), train=False)
    mesh = hvd.mesh()

    ref, _ = model_plain.apply(variables, jnp.asarray(x), train=True,
                               mutable=["batch_stats"])

    def fwd(v, xb):
        out, _ = model_sync.apply(v, xb, train=True, mutable=["batch_stats"])
        return out

    sharded = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(P(), P("hvd")), out_specs=P("hvd"),
        check_vma=False))
    got = sharded(variables, jax.device_put(x, NamedSharding(mesh, P("hvd"))))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------

def test_bert_large_param_count():
    cfg = bert_mod.BertConfig.bert_large()
    model = bert_mod.Bert(cfg)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32)))
    n_params = sum(np.prod(x.shape) for x in jax.tree.leaves(variables))
    # BERT-Large ≈ 335M (tied MLM head).
    assert 300e6 < n_params < 360e6, n_params


def test_bert_mlm_trains_dp():
    cfg = bert_mod.BertConfig.tiny()
    model = bert_mod.Bert(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))
    batch = bert_mod.synthetic_mlm_batch(cfg, batch=16, seq=32)
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    opt_state = tx.init(params)
    mesh = hvd.mesh()

    def step(params, opt_state, tokens, labels):
        def loss_fn(p):
            return bert_mod.mlm_loss(
                p, {"tokens": tokens, "labels": labels}, model)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state2,
                jax.lax.pmean(loss, "hvd"))

    sharded = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P(), P()), check_vma=False))
    tok = jax.device_put(batch["tokens"], NamedSharding(mesh, P("hvd")))
    lab = jax.device_put(batch["labels"], NamedSharding(mesh, P("hvd")))
    losses = []
    for _ in range(10):
        params, opt_state, loss = sharded(params, opt_state, tok, lab)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# DLRM
# ---------------------------------------------------------------------------

def test_dlrm_sharded_embedding_matches_dense_lookup():
    cfg = dlrm_mod.DlrmConfig.tiny()
    mesh = hvd.mesh()
    tables = dlrm_mod.init_embedding_tables(cfg, jax.random.PRNGKey(0))
    batch = dlrm_mod.synthetic_batch(cfg, batch=16)
    # Oracle: direct gather.
    idx = np.asarray(batch["sparse"])
    expected = np.stack([np.asarray(tables)[t, idx[:, t]]
                         for t in range(cfg.n_sparse)], axis=1)
    got = dlrm_mod.sharded_embedding_lookup(
        jax.device_put(tables, NamedSharding(mesh, P("hvd"))),
        jax.device_put(batch["sparse"], NamedSharding(mesh, P("hvd"))),
        mesh)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-6)


def test_dlrm_trains_end_to_end():
    cfg = dlrm_mod.DlrmConfig.tiny()
    mesh = hvd.mesh()
    dense_model = dlrm_mod.DlrmDense(cfg)
    batch = dlrm_mod.synthetic_batch(cfg, batch=16)
    tables = dlrm_mod.init_embedding_tables(cfg, jax.random.PRNGKey(1))
    demb0 = np.zeros((1, cfg.n_sparse, cfg.embed_dim), np.float32)
    params = dense_model.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, cfg.n_dense)), jnp.asarray(demb0))
    tx = optax.adam(1e-2)
    opt_state = tx.init((params, tables))

    t_sh = NamedSharding(mesh, P("hvd"))
    b_sh = NamedSharding(mesh, P("hvd"))
    repl = NamedSharding(mesh, P())

    def step(params, tables, opt_state, dense, sparse, label):
        def loss_fn(pt):
            p, tb = pt
            # Embedding exchange via shard_map nested under jit.
            from functools import partial
            emb = shard_map(
                partial(dlrm_mod.sharded_embedding_lookup_local,
                        axis_name="hvd"),
                mesh=mesh, in_specs=(P("hvd"), P("hvd")),
                out_specs=P("hvd"), check_vma=False)(tb, sparse)
            logit = dense_model.apply(p, dense, emb)
            return optax.sigmoid_binary_cross_entropy(logit, label).mean()
        loss, grads = jax.value_and_grad(loss_fn)((params, tables))
        updates, opt_state2 = tx.update(grads, opt_state, (params, tables))
        params2, tables2 = optax.apply_updates((params, tables), updates)
        return params2, tables2, opt_state2, loss

    jstep = jax.jit(step,
                    in_shardings=(repl, t_sh, None, b_sh, b_sh, b_sh),
                    out_shardings=(repl, t_sh, None, repl))
    dense = jax.device_put(batch["dense"], b_sh)
    sparse = jax.device_put(batch["sparse"], b_sh)
    label = jax.device_put(batch["label"], b_sh)
    tables = jax.device_put(tables, t_sh)
    losses = []
    for _ in range(15):
        params, tables, opt_state, loss = jstep(
            params, tables, opt_state, dense, sparse, label)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_dlrm_interaction_shape():
    cfg = dlrm_mod.DlrmConfig.tiny()
    B, T, D = 4, cfg.n_sparse, cfg.embed_dim
    out = dlrm_mod.interact_features(
        jnp.zeros((B, D)), jnp.zeros((B, T, D)))
    assert out.shape == (B, D + (T + 1) * T // 2)
