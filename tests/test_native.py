"""Native core: rendezvous KV store + coordinator negotiation protocol.

Mirrors the reference's controller/rendezvous behavior († ``controller.cc``
``ComputeResponseList``, † ``gloo/http_store.cc``, † ``response_cache.cc``):
- a tensor is executed only once every rank has submitted it;
- all ranks receive the identical ordered response list;
- steady-state rounds hit the name→id cache;
- lagging ranks produce stall warnings.

Threads stand in for ranks here (same-protocol, in-process); a subprocess
test exercises true multi-process negotiation.
"""

import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu._native import (
    ControllerClient,
    ControllerServer,
    KvClient,
    KvServer,
)


# ---------------------------------------------------------------------------
# KV store
# ---------------------------------------------------------------------------

def test_kv_set_get_roundtrip():
    with KvServer() as srv:
        c = KvClient("127.0.0.1", srv.port)
        c.set("rank/0/addr", b"10.0.0.1:1234")
        assert c.wait("rank/0/addr") == b"10.0.0.1:1234"
        assert c.get("nonexistent") is None
        c.close()


def test_server_port_after_stop_raises():
    # Regression: reading .port after stop() dereferenced the freed native
    # handle and segfaulted; it must raise instead.
    srv = KvServer()
    srv.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        srv.port
    ctrl = ControllerServer(size=1)
    ctrl.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        ctrl.port


def test_kv_wait_blocks_until_set():
    with KvServer() as srv:
        reader = KvClient("127.0.0.1", srv.port)
        writer = KvClient("127.0.0.1", srv.port)
        result = {}

        def wait_side():
            result["val"] = reader.wait("late-key", timeout_ms=5000)

        t = threading.Thread(target=wait_side)
        t.start()
        time.sleep(0.2)
        writer.set("late-key", b"hello")
        t.join(timeout=5)
        assert result["val"] == b"hello"
        reader.close()
        writer.close()


def test_kv_wait_timeout():
    with KvServer() as srv:
        c = KvClient("127.0.0.1", srv.port)
        with pytest.raises(TimeoutError):
            c.wait("never", timeout_ms=200)
        c.close()


def test_kv_delete():
    with KvServer() as srv:
        c = KvClient("127.0.0.1", srv.port)
        c.set("k", b"v")
        c.delete("k")
        assert c.get("k") is None
        c.close()


def test_kv_large_value():
    with KvServer() as srv:
        c = KvClient("127.0.0.1", srv.port)
        big = bytes(range(256)) * 4096  # 1 MB
        c.set("big", big)
        assert c.wait("big") == big
        c.close()


# ---------------------------------------------------------------------------
# Controller negotiation
# ---------------------------------------------------------------------------

def _run_ranks(port, size, submissions_per_rank, rounds):
    """Drive `size` rank clients through `rounds` negotiation rounds.

    submissions_per_rank: list (per round) of dict rank -> [names].
    Returns list (per round) of dict rank -> ready list.
    """
    clients = [ControllerClient("127.0.0.1", port, r) for r in range(size)]
    results = []
    for rnd in range(rounds):
        out = {}
        barrier = threading.Barrier(size)

        def go(r):
            barrier.wait()
            res = clients[r].negotiate(
                submissions_per_rank[rnd].get(r, []))
            out[r] = (res.ready, res.stalled)

        threads = [threading.Thread(target=go, args=(r,))
                   for r in range(size)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        results.append(out)
    for c in clients:
        c.close()
    return results


def test_negotiate_all_ready():
    with ControllerServer(size=4) as srv:
        res = _run_ranks(srv.port, 4,
                         [{r: ["grad.a", "grad.b"] for r in range(4)}], 1)
        for r in range(4):
            ready, stalled = res[0][r]
            assert ready == ["grad.a", "grad.b"]
            assert stalled == []


def test_negotiate_waits_for_all_ranks():
    # Rank 3 submits grad.x one round late: nobody executes it until then.
    with ControllerServer(size=4) as srv:
        rounds = [
            {0: ["grad.x"], 1: ["grad.x"], 2: ["grad.x"], 3: []},
            {0: [], 1: [], 2: [], 3: ["grad.x"]},
        ]
        res = _run_ranks(srv.port, 4, rounds, 2)
        for r in range(4):
            assert res[0][r][0] == []          # not ready yet
            assert res[1][r][0] == ["grad.x"]  # ready once rank 3 joined


def test_negotiate_order_is_identical_despite_submission_order():
    # Ranks submit the same tensors in different orders; the agreed order
    # must be identical everywhere (fusion determinism invariant).
    with ControllerServer(size=3) as srv:
        rounds = [{
            0: ["t.a", "t.b", "t.c"],
            1: ["t.c", "t.a", "t.b"],
            2: ["t.b", "t.c", "t.a"],
        }]
        res = _run_ranks(srv.port, 3, rounds, 1)
        orders = {tuple(res[0][r][0]) for r in range(3)}
        assert len(orders) == 1
        assert set(next(iter(orders))) == {"t.a", "t.b", "t.c"}


def test_negotiate_cache_fast_path():
    # Second round with the same names must use cached ids.
    with ControllerServer(size=2) as srv:
        c0 = ControllerClient("127.0.0.1", srv.port, 0)
        c1 = ControllerClient("127.0.0.1", srv.port, 1)

        def both(names):
            out = {}
            def go(c, r):
                out[r] = c.negotiate(names)
            ts = [threading.Thread(target=go, args=(c, r))
                  for r, c in ((0, c0), (1, c1))]
            for t in ts: t.start()
            for t in ts: t.join(timeout=30)
            return out

        out1 = both(["g.1", "g.2"])
        assert out1[0][0] == ["g.1", "g.2"]
        assert c0.cache_size == 2
        # Steady state: same names next step ride the id fast path and are
        # re-negotiated as a fresh cycle (every training step re-reduces
        # the same gradients).
        out2 = both(["g.1", "g.2"])
        assert c0.cache_size == 2
        assert out2[0][0] == ["g.1", "g.2"]
        assert out2[0][0] == out2[1][0]
        c0.close()
        c1.close()


def test_stall_warning_reported():
    with ControllerServer(size=2, stall_warn_ms=100) as srv:
        c0 = ControllerClient("127.0.0.1", srv.port, 0)
        c1 = ControllerClient("127.0.0.1", srv.port, 1)
        out = {}

        def go(c, r, names):
            out[r] = c.negotiate(names)

        # Round 1: only rank 0 submits grad.s; rank 1 empty.
        ts = [threading.Thread(target=go, args=(c0, 0, ["grad.s"])),
              threading.Thread(target=go, args=(c1, 1, []))]
        for t in ts: t.start()
        for t in ts: t.join(timeout=30)
        assert out[0][0] == []
        time.sleep(0.3)  # exceed stall_warn_ms
        # Round 2: rank 1 still hasn't submitted -> stall warning.
        ts = [threading.Thread(target=go, args=(c0, 0, [])),
              threading.Thread(target=go, args=(c1, 1, []))]
        for t in ts: t.start()
        for t in ts: t.join(timeout=30)
        assert "grad.s" in out[0][1]
        # Straggler attribution: the stall record names the withholding
        # rank (1 never submitted) and carries the stall age.
        info = out[0].stall_info["grad.s"]
        assert info.missing_ranks == (1,), info
        assert info.age_ms >= 100, info
        c0.close()
        c1.close()


_WORKER = r"""
import sys
from horovod_tpu._native import ControllerClient
rank, port = int(sys.argv[1]), int(sys.argv[2])
c = ControllerClient("127.0.0.1", port, rank)
res = c.negotiate([f"grad.{i}" for i in range(3)])
print(",".join(res.ready))
c.close()
"""


def test_negotiate_multiprocess():
    """True multi-process negotiation († multi-rank rig, SURVEY §4)."""
    with ControllerServer(size=3) as srv:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(r), str(srv.port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd="/root/repo")
            for r in range(3)]
        outs = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=60)
            assert p.returncode == 0, stderr
            outs.append(stdout.strip())
        assert len(set(outs)) == 1
        assert outs[0] == "grad.0,grad.1,grad.2"


# ---------------------------------------------------------------------------
# JOIN protocol († message.h RequestType::JOIN): a joined rank counts as an
# implicit submitter for every tensor; all-joined is reported with the last
# rank to join (the hvd.join() return value).
# ---------------------------------------------------------------------------

def _round(clients, subs, joined=()):
    """One synchronized negotiation round; subs: rank -> [names or pairs]."""
    out = {}
    barrier = threading.Barrier(len(clients))

    def go(r):
        barrier.wait()
        out[r] = clients[r].negotiate(subs.get(r, []), joined=r in joined)

    ts = [threading.Thread(target=go, args=(r,)) for r in range(len(clients))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    return out


def test_join_makes_tensor_ready_with_metadata():
    with ControllerServer(size=2) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(2)]
        # Rank 1 joined; rank 0 submits a tensor with metadata — it must be
        # ready immediately, and rank 1 must receive the metadata to build
        # its zero participation.
        out = _round(clients, {0: [("grad.a", '{"v":"allreduce"}')]},
                     joined={1})
        for r in range(2):
            assert out[r].ready == ["grad.a"]
            assert out[r].metas["grad.a"] == '{"v":"allreduce"}'
            assert not out[r].all_joined
        for c in clients:
            c.close()


def test_join_all_joined_reports_last_rank():
    with ControllerServer(size=3) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(3)]
        out = _round(clients, {}, joined={2})
        assert not out[0].all_joined
        out = _round(clients, {}, joined={2, 0})
        assert not out[0].all_joined
        out = _round(clients, {}, joined={2, 0, 1})
        for r in range(3):
            assert out[r].all_joined
            assert out[r].last_join_rank == 1
        # Join state resets: a later phase can run another uneven epoch.
        out = _round(clients, {r: ["t.next"] for r in range(3)})
        assert out[0].ready == ["t.next"]
        assert not out[0].all_joined
        for c in clients:
            c.close()


def test_join_coverage_flag_marks_fabricated_readiness():
    """A tensor ready only because a joined rank implicitly covers it must
    carry the join-coverage flag on every rank — the signal engines use to
    error non-allreduce verbs consistently († the reference errors
    non-allreduce ops while any rank is joined)."""
    with ControllerServer(size=2) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(2)]
        out = _round(clients, {0: [("grad.c", '{"v":"allreduce"}')]},
                     joined={1})
        for r in range(2):
            assert out[r].ready == ["grad.c"]
            assert "grad.c" in out[r].join_covered
        for c in clients:
            c.close()


def test_join_coverage_flag_absent_when_all_submit():
    """A joined rank that still submits a tensor provides real (not
    fabricated) participation, so the coverage flag must stay clear."""
    with ControllerServer(size=2) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(2)]
        out = _round(clients, {0: [("t.real", "")], 1: [("t.real", "")]},
                     joined={1})
        for r in range(2):
            assert out[r].ready == ["t.real"]
            assert out[r].join_covered == frozenset()
        for c in clients:
            c.close()


def test_join_meta_cleared_by_empty_resubmission():
    """An 'N' resubmission carrying an empty meta must replace the stored
    one — live and joined ranks decide joinability from the same
    descriptor, so a stale non-empty meta would split the mesh."""
    with ControllerServer(size=2) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(2)]
        subs = {r: [("t.m", '{"v":"allreduce"}')] for r in range(2)}
        out = _round(clients, subs)
        assert out[0].metas.get("t.m") == '{"v":"allreduce"}'
        # Same name resubmitted with empty meta (e.g. now a process-set
        # entry): the echoed meta must be empty, not the stale allreduce
        # descriptor.
        subs = {r: [("t.m", "")] for r in range(2)}
        out = _round(clients, subs)
        assert out[0].ready == ["t.m"]
        assert "t.m" not in out[0].metas
        for c in clients:
            c.close()


def test_join_metadata_survives_cache_fast_path():
    # Meta travels on first sighting; later id-cached rounds must still
    # deliver it to a rank that joins afterwards.
    with ControllerServer(size=2) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(2)]
        subs = {r: [("g", '{"d":"float32"}')] for r in range(2)}
        out = _round(clients, subs)
        assert out[0].ready == ["g"]
        # Round 2: rank 1 joins; rank 0 resubmits via the id fast path.
        out = _round(clients, {0: [("g", '{"d":"float32"}')]}, joined={1})
        assert out[1].ready == ["g"]
        assert out[1].metas["g"] == '{"d":"float32"}'
        for c in clients:
            c.close()


def test_process_set_readiness_counts_members_only():
    """A subgroup tensor is ready once its MEMBER ranks submitted — the
    rest of the world never does († process_set.cc); without per-tensor
    membership the round would wait forever."""
    with ControllerServer(size=4) as srv:
        clients = [ControllerClient("127.0.0.1", srv.port, r)
                   for r in range(4)]
        out = _round(clients, {0: [("ps.t", "", "0,2")],
                               2: [("ps.t", "", "0,2")]})
        for r in range(4):
            assert out[r].ready == ["ps.t"], (r, out[r])
        # A world tensor still needs everyone.
        out = _round(clients, {0: ["t.w"], 2: ["t.w"]})
        assert out[0].ready == []
        out = _round(clients, {r: ["t.w"] for r in range(4)})
        assert out[0].ready == ["t.w"]
        for c in clients:
            c.close()


def test_round_abort_releases_waiting_rank():
    """With round_abort_ms set, a rank whose peer never checks in gets an
    abort error instead of blocking in the barrier forever (the escape
    hatch that lets its engine fail pending work † error Response)."""
    import time as _time
    with ControllerServer(size=2, round_abort_ms=300) as srv:
        c0 = ControllerClient("127.0.0.1", srv.port, 0)
        t0 = _time.monotonic()
        with pytest.raises(ConnectionError, match="aborted"):
            c0.negotiate(["t0"])
        assert _time.monotonic() - t0 < 5.0
        c0.close()


# ---------------------------------------------------------------------------
# HMAC-authenticated control plane († runner/common/util/secret.py: per-job
# shared secret signs every driver<->task RPC)
# ---------------------------------------------------------------------------

def test_kv_auth_roundtrip():
    with KvServer(secret="s3cr3t") as srv:
        c = KvClient("127.0.0.1", srv.port, secret="s3cr3t")
        c.set("k", b"v")
        assert c.wait("k") == b"v"
        c.close()


def test_kv_auth_wrong_secret_rejected():
    with KvServer(secret="right") as srv:
        c = KvClient("127.0.0.1", srv.port, secret="wrong")
        with pytest.raises(OSError):
            c.set("k", b"v")
        c.close()
        # The server must still serve properly-authed clients afterwards.
        good = KvClient("127.0.0.1", srv.port, secret="right")
        good.set("k", b"v2")
        assert good.wait("k") == b"v2"
        good.close()


def test_kv_auth_unauthenticated_client_rejected():
    with KvServer(secret="right") as srv:
        c = KvClient("127.0.0.1", srv.port, secret="")
        with pytest.raises(OSError):
            c.set("k", b"v")
        c.close()


def test_kv_secret_from_env(monkeypatch):
    monkeypatch.setenv("HVDTPU_SECRET", "env-secret")
    with KvServer() as srv:                      # picks up env
        c = KvClient("127.0.0.1", srv.port)      # picks up env
        c.set("k", b"v")
        assert c.wait("k") == b"v"
        c.close()


def test_ctrl_auth_negotiation():
    with ControllerServer(size=2, secret="job") as srv:
        results = {}

        def rank_fn(r):
            c = ControllerClient("127.0.0.1", srv.port, r, secret="job")
            results[r] = c.negotiate(["t0"]).ready
            c.close()

        ts = [threading.Thread(target=rank_fn, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert results[0] == results[1] == ["t0"]


def test_ctrl_auth_wrong_secret_fails():
    with ControllerServer(size=1, secret="job") as srv:
        c = ControllerClient("127.0.0.1", srv.port, 0, secret="nope")
        with pytest.raises(ConnectionError):
            c.negotiate(["t0"])
        c.close()


def _recvn(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return buf
        buf += chunk
    return buf


def _mac_frame(secret, nonce, direction, seq, body):
    """Mirror of the native wire format: tag = HMAC(secret,
    nonce || dir || seq_be64 || body); frame = u32 len || tag || body."""
    import hashlib
    import hmac as pyhmac
    import struct
    m = nonce + direction + struct.pack(">Q", seq) + body
    tag = pyhmac.new(secret, m, hashlib.sha256).digest()
    payload = tag + body
    return struct.pack(">I", len(payload)) + payload


def _recv_auth_reply(sock, secret, nonce, seq):
    import hashlib
    import hmac as pyhmac
    import struct
    hdr = _recvn(sock, 4)
    if len(hdr) < 4:
        return None  # connection closed
    ln = struct.unpack(">I", hdr)[0]
    payload = _recvn(sock, ln)
    tag, body = payload[:32], payload[32:]
    m = nonce + b"S" + struct.pack(">Q", seq) + body
    assert pyhmac.new(secret, m, hashlib.sha256).digest() == tag
    return body


def test_kv_auth_replay_and_reflection_rejected():
    import socket
    import struct
    with KvServer(secret="job") as srv:
        s = socket.create_connection(("127.0.0.1", srv.port))
        nonce = _recvn(s, struct.unpack(">I", _recvn(s, 4))[0])
        assert len(nonce) == 16
        body = b"S" + struct.pack(">I", 1) + b"k" + b"v"
        frame0 = _mac_frame(b"job", nonce, b"C", 0, body)
        s.sendall(frame0)
        assert _recv_auth_reply(s, b"job", nonce, 0) == b"K"
        # In-connection replay: same frame again (stale seq) -> dropped.
        s.sendall(frame0)
        assert _recv_auth_reply(s, b"job", nonce, 1) in (None, b"")
        s.close()

        # Cross-connection replay: frame MAC'd under the old nonce -> dropped.
        s2 = socket.create_connection(("127.0.0.1", srv.port))
        nonce2 = _recvn(s2, struct.unpack(">I", _recvn(s2, 4))[0])
        assert nonce2 != nonce
        s2.sendall(frame0)
        assert _recv_auth_reply(s2, b"job", nonce2, 0) in (None, b"")
        s2.close()

        # Reflection: a server-direction frame sent as a client frame.
        s3 = socket.create_connection(("127.0.0.1", srv.port))
        nonce3 = _recvn(s3, struct.unpack(">I", _recvn(s3, 4))[0])
        reflected = _mac_frame(b"job", nonce3, b"S", 0, body)
        s3.sendall(reflected)
        assert _recv_auth_reply(s3, b"job", nonce3, 0) in (None, b"")
        s3.close()

        # Honest clients still work after all that.
        good = KvClient("127.0.0.1", srv.port, secret="job")
        assert good.wait("k") == b"v"
        good.close()
